"""Elastic membership for the socket backend (ISSUE 10).

PR 5's epoch-fenced abort/retry deliberately kept the roster fixed, so
a permanently dead rank was a job-wide :class:`Mp4jFatalError` — the
one failure class the chaos grid could not recover from. This module
holds the membership layer's shared vocabulary: the master's warm-spare
pool and membership event log, and the pure functions both sides of the
protocol derive their decisions from (mp4j-lint R1/R8 discipline: a
membership decision is a pure function of the shared round state, never
of anything rank-local).

Two modes, selected by ``MP4J_ELASTIC`` (validated in
``utils.tuning.elastic_mode``; default ``off`` keeps the pre-elastic
fail-fatal contract bit-for-bit):

**replace** — bit-exact continuation from a warm spare::

    spare: registers with the master at startup ({"spare": True} in the
           REGISTER payload), holds the control channel, pings, idles
    rank r dies (connection lost / stalled ack / escalated barrier)
    master: opens (or upgrades) an abort round -> epoch e
            requests a MANIFEST from the lowest live survivor:
              columnar keycodec vocabularies (pinned at the pre-attempt
              sizes every survivor's retry truncates back to), the
              outermost-collective ordinal, the barrier generation
    every survivor: tears down the old epoch's data plane, acks
    master: all acks + manifest -> sends the spare ("adopt", manifest
            + rank r + new roster + the audit watermark); the spare
            seeds its epoch/ordinal/vocabulary/barrier state, starts
            its control/accept threads, acks
    master: installs the spare's channel at rank r, swaps the roster,
            fans ("abort_go", e, {"replaced": ..., "roster": ...})
    survivors: swap the roster, restore their preserved inputs and
            re-run; the joiner's first collective enters at the SAME
            ordinal — the retry pairs bit-exactly, zero survivor errors

**shrink** — degraded continuation for reduction-only workloads::

    master: same round, no spare; survivors renumber contiguously
            (old ranks sorted ascending -> 0..n-2, a pure function of
            the survivor set), the roster drops the dead entry, and
            ("abort_go", e, {"shrink": ...}) ships the mapping
    survivors: adopt their new rank/slave_num, rebuild topology
            (host groups included) at n-1, and the fenced retry
            re-runs the collective over the surviving inputs

Shrink loses the dead rank's contribution by construction — correct
n-1 results, not bit-exact continuation — and it renumbers ranks, so
only workloads whose collective arguments do not bake in the original
rank count (allreduce/reduce/broadcast families; not caller-provided
``ranges``) survive it. mp4j-lint R15 polices the code-level half of
that hazard: topology derived from the roster must be read through the
roster-versioned accessor (``ProcessCommSlave._set_roster``), never
cached in long-lived attributes a renumbering silently strands.
"""

from __future__ import annotations

import collections
import time

from ytk_mp4j_tpu.comm import keycodec
from ytk_mp4j_tpu.exceptions import Mp4jError


# ----------------------------------------------------------------------
# pure protocol functions (both sides must derive identical answers)
# ----------------------------------------------------------------------
def joiner_seq(progress: dict[int, tuple[int, bool]]) -> int:
    """The collective ordinal a joining spare must resume AT (i.e. the
    count of collectives it should consider completed), from the
    survivors' abort-ack progress samples ``{rank: (seq, inflight)}``.

    In-flight survivors are retrying ordinal ``m = max(inflight
    seqs)``; idle survivors sit at ``m - 1`` (the master's
    ``_mixed_progress`` check enforces exactly this shape before any
    release). The joiner must behave like an idle rank — enter ``m``
    fresh — so it resumes at ``m - 1``. With nobody in flight (the
    death was detected between collectives) everyone sits at the same
    seq and the joiner matches it."""
    if not progress:
        return 0
    inflight = [s for s, f in progress.values() if f]
    if inflight:
        return max(inflight) - 1
    return max(s for s, _ in progress.values())


def shrink_mapping(slave_num: int, dead: set[int]) -> dict[int, int]:
    """Contiguous renumbering of the survivors: old rank -> new rank,
    survivors ordered by old rank. A pure function of (slave_num,
    dead) so the master and every survivor derive the identical map."""
    survivors = [r for r in range(slave_num) if r not in dead]
    return {old: new for new, old in enumerate(survivors)}


def swap_roster(roster: list, replacements: dict[int, tuple]) -> list:
    """A new roster with ``replacements[rank]`` entries swapped in —
    the replace-mode roster (same length, dead entries now point at
    the adopted spares' listen sockets / host fingerprints)."""
    out = list(roster)
    for rank, entry in replacements.items():
        out[rank] = entry
    return out


def shrink_roster(roster: list, mapping: dict[int, int]) -> list:
    """The n-1 roster: surviving entries in new-rank order."""
    out: list = [None] * len(mapping)
    for old, new in mapping.items():
        out[new] = roster[old]
    return out


def grow_roster(roster: list, entries: list[tuple]) -> list:
    """The n+k roster (ISSUE 13 grow mode): the adopted spares'
    (host, port, fp) entries appended in NEW-rank order — existing
    ids never move, so survivors keep every peer channel they hold."""
    return list(roster) + list(entries)


# ----------------------------------------------------------------------
# vocabulary replay (the manifest's columnar half)
# ----------------------------------------------------------------------
def export_vocab(codecs: dict, pin: dict | None) -> dict[str, list]:
    """Export the columnar key vocabularies for the adoption manifest:
    per key kind, the key list in CODE order. ``pin`` (the surviving
    donor's pre-attempt codec sizes, captured by the recovery wrapper's
    ``preserve``) truncates the export to the state every survivor's
    retry rolls back to — a failed map attempt may have tentatively
    grown the donor's codec, and shipping that growth would hand the
    joiner codes the retry's sync round is about to reassign.

    A kind ABSENT from a non-None pin did not exist at attempt entry
    (the codec was created by the in-flight attempt — the job's FIRST
    map of that kind, killed mid-sync): every survivor's retry
    truncates it to 0 (``sizes.get(kind, 0)`` in the wrapper's
    restore), so the export must ship it EMPTY too. Shipping the
    tentative growth instead hands the joiner a code table no survivor
    holds — its unique keys are silently absent from the retry's
    novelty round (already encoded locally, so never offered), and the
    job's code->key tables diverge permanently: the mid-map-sync
    replay gap of ISSUE 10's follow-up, closed in ISSUE 11."""
    out: dict[str, list] = {}
    for kind, codec in codecs.items():
        size = codec.size if pin is None else pin.get(kind, 0)
        keys = codec.export(size)
        if keys:
            out[kind] = keys
    return out


def import_vocab(target: dict, vocab: dict) -> None:
    """Rebuild a joiner's (empty) codec table from an exported
    manifest: code i maps to ``vocab[kind][i]``, exactly the
    assignment every survivor holds."""
    for kind, keys in (vocab or {}).items():
        if kind in target:
            raise Mp4jError(
                f"import_vocab: codec for kind {kind!r} already exists")
        codec = keycodec.codec_for_kind(kind)
        codec.import_keys(keys)
        target[kind] = codec


# ----------------------------------------------------------------------
# master-side bookkeeping (owned by Master, guarded by its lock)
# ----------------------------------------------------------------------
class SpareRecord:
    """One registered warm spare: its control channel, roster entry
    (host, listen_port, fp) and lifecycle flags."""

    __slots__ = ("idx", "ch", "entry", "alive", "adopting_rank",
                 "adopt_since", "last_ping", "grow")

    def __init__(self, idx: int, ch, entry: tuple):
        self.idx = idx
        self.ch = ch
        self.entry = entry
        self.alive = True
        self.adopting_rank: int | None = None   # mid-adoption target
        self.adopt_since: float | None = None   # mono ts of adopt send
        self.last_ping = time.monotonic()
        # ISSUE 13: this adoption EXPANDS the roster (a NEW rank id at
        # a resize_point boundary) instead of replacing a casualty
        self.grow = False


class MembershipLog:
    """Counters + bounded event history for the membership plane —
    the source of the Prometheus series (``mp4j_replacements_total``,
    ``mp4j_shrinks_total``, ``mp4j_spares_available``), the
    ``mp4j-scope live`` badges, and the postmortem manifest's
    membership section. Guarded by the owner's (master's) lock."""

    def __init__(self, mode: str):
        self.mode = mode
        self.replacements = 0
        self.shrinks = 0
        # ISSUE 13: planned (autoscaler-driven) evictions and grow
        # rounds, counted apart from death-driven replacements — the
        # operator must be able to tell recovery from actuation
        self.planned_evictions = 0
        self.grows = 0
        self.events: collections.deque = collections.deque(maxlen=64)
        # rank -> current badge ("REPLACED@e1", "SHRUNK 3->2@e1")
        self.badges: dict[int, str] = {}

    def note_replace(self, rank: int, epoch: int, spare_idx: int,
                     why: str) -> None:
        self.replacements += 1
        self.badges[rank] = f"REPLACED@e{epoch}"
        self.events.append({
            "kind": "replace", "rank": rank, "epoch": epoch,
            "spare": spare_idx, "why": why,
            "mono": time.monotonic()})

    def note_evict(self, rank: int, epoch: int, spare_idx: int,
                   why: str) -> None:
        """A LIVE rank was proactively evicted and replaced (ISSUE 13
        planned eviction) — the autoscaler polls for this event kind
        to confirm its action landed."""
        self.planned_evictions += 1
        self.replacements += 1
        self.badges[rank] = f"EVICTED@e{epoch}"
        self.events.append({
            "kind": "planned_evict", "rank": rank, "epoch": epoch,
            "spare": spare_idx, "why": why,
            "mono": time.monotonic()})

    def note_spare(self, idx: int) -> None:
        """A warm spare registered. The autoscaler resolves a pending
        ``provision`` action on this event — observing the
        ``spares_available`` gauge alone is race-prone: a waiting
        membership round can claim the fresh spare synchronously at
        registration, so the gauge never visibly leaves 0 even though
        the provision succeeded (and saved the job)."""
        self.events.append({
            "kind": "spare_registered", "spare": idx,
            "mono": time.monotonic()})

    def note_evict_cancel(self, rank: int, token: int,
                          why: str) -> None:
        """An eviction FENCE was canceled before anything was torn
        down — zero disruption, the victim stays a member. The
        autoscaler reads this as a benign RETRY (budget refunded),
        never a circuit-breaker failure."""
        self.events.append({
            "kind": "evict_fence_cancel", "rank": rank,
            "token": token, "why": why, "mono": time.monotonic()})

    def note_evict_abort(self, ranks: list[int], epoch: int,
                         why: str) -> None:
        """A planned-eviction round could not complete (spare pool
        exhausted mid-round): the round was released as a plain abort
        with the victim still a member. The autoscaler reads this
        event as a FAILED action (circuit-breaker input)."""
        self.events.append({
            "kind": "evict_abort", "ranks": list(ranks),
            "epoch": epoch, "why": why, "mono": time.monotonic()})

    def note_grow(self, new_ranks: list[int], epoch: int,
                  gen: int) -> None:
        """Registered spares were adopted into NEW rank ids at a
        ``resize_point()`` boundary (ISSUE 13 grow mode)."""
        self.grows += 1
        for r in new_ranks:
            self.badges[r] = f"GROWN@z{gen}"
        self.events.append({
            "kind": "grow", "ranks": list(new_ranks), "epoch": epoch,
            "gen": gen, "mono": time.monotonic()})

    def note_grow_cancel(self, gen: int, why: str) -> None:
        """An approved grow was dropped BEFORE any adoption was
        dispatched (revalidation under the lock found the spare gone
        or a round open): zero disruption — the autoscaler settles
        its pending action as a benign retry, mirroring
        :meth:`note_evict_cancel`."""
        self.events.append({
            "kind": "grow_cancel", "gen": gen, "why": why,
            "mono": time.monotonic()})

    def note_grow_abort(self, ranks: list[int], gen: int,
                        why: str) -> None:
        """A grow round failed mid-adoption and was rolled back: the
        resize released unchanged, any seeded joiners were released
        with ``Mp4jEvicted``. A FAILED action for the autoscaler."""
        self.events.append({
            "kind": "grow_abort", "ranks": list(ranks), "gen": gen,
            "why": why, "mono": time.monotonic()})

    def note_shrink(self, dead: list[int], mapping: dict[int, int],
                    epoch: int, why: str) -> None:
        self.shrinks += 1
        self.badges = {new: f"SHRUNK {old}->{new}@e{epoch}"
                       for old, new in mapping.items() if old != new}
        self.events.append({
            "kind": "shrink", "dead": list(dead),
            "ranks": dict(mapping), "epoch": epoch, "why": why,
            "mono": time.monotonic()})

    def status(self, spares_available: int, spares_total: int) -> dict:
        """The membership document (metrics doc / postmortem manifest):
        plain JSON-ready values only."""
        return {
            "mode": self.mode,
            "replacements": self.replacements,
            "shrinks": self.shrinks,
            "planned_evictions": self.planned_evictions,
            "grows": self.grows,
            "spares_available": spares_available,
            "spares_total": spares_total,
            "badges": {str(r): b for r, b in self.badges.items()},
            "events": [dict(e) for e in self.events],
        }
