"""Deterministic fault injection for the socket data plane.

A :class:`FaultPlan` is a list of directives parsed from the
``MP4J_FAULT_PLAN`` grammar (or built programmatically) and evaluated
by a per-rank :class:`FaultInjector` that the slave installs on its
peer channels. Determinism is the point: a chaos test must fail the
same way every run, so directives trigger on the slave's collective
ordinal (the Nth outermost collective this rank enters), never on wall
time, and any probabilistic directive draws from an RNG seeded by
``(plan seed, rank, directive index)``.

Grammar (``;``-separated directives, ``:``-separated ``key=value``
fields after the action; whitespace ignored)::

    seed=42; reset:rank=1:nth=3:peer=2; delay:rank=0:nth=2:secs=0.2
    slow:rank=3:secs=0.01; kill:rank=2:nth=5

Actions:

- ``delay`` — sleep ``secs`` once, before the first channel I/O of
  collective ``nth`` on ``rank``.
- ``slow``  — sleep ``secs`` before EVERY channel I/O from collective
  ``nth`` onward (a persistently slow rank).
- ``reset`` — close the peer connection (to ``peer`` if given, else
  whichever peer channel does I/O first) at collective ``nth``,
  mid-frame: the hook fires between a frame's header and payload, so
  the remote side observes a torn frame, not a clean boundary.
- ``kill``  — at the entry of collective ``nth``, abruptly close every
  socket this slave owns (peers, master, listen) and raise
  :class:`FaultKill` — the closest a thread-hosted test rank can get
  to ``kill -9``. The master sees the control connection die and fans
  out the terminal abort.
- ``corrupt`` — flip one byte of the next payload frame (>=
  ``CORRUPT_MIN`` bytes, so frame headers and tiny control tuples are
  never hit — a desynced frame stream would be a crash, not the
  silent corruption this directive exists to simulate) sent at
  collective ``nth``. The flip happens in a COPY below the audit
  plane's sender-side digests, never in the caller's buffer, so the
  wire carries corrupted bytes while the sender's records stay clean —
  exactly the shape ``MP4J_AUDIT=verify`` must detect (ISSUE 8).
  Hooked at the same channel primitives as ``reset`` (and at the raw
  exchange for the native/shm data planes). The flipped byte is the
  frame's middle byte XOR 0xFF — deterministic, like every directive.

Every directive fires at most once except ``slow``, which persists
once armed. ``prob`` (0..1, default 1) gates arming through the seeded
RNG.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError

_ACTIONS = ("delay", "slow", "reset", "kill", "corrupt")
_ONCE = ("delay", "reset", "kill")

# a corrupt directive only fires on buffers at least this large:
# payload frames, never the u8/u64 frame headers or small pickled
# control tuples whose corruption would desync the framing (a crash,
# not a silent wrong answer)
CORRUPT_MIN = 4096


class FaultKill(Mp4jError):
    """An injected slave death. Deliberately NOT a transport error:
    the dying rank must not retry its own murder — it propagates out
    of the collective while the survivors' recovery engines handle the
    fallout."""


@dataclasses.dataclass
class Fault:
    """One parsed directive (see the module grammar)."""

    action: str
    rank: int
    nth: int = 1
    secs: float = 0.0
    peer: int | None = None
    prob: float = 1.0

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise Mp4jError(
                f"fault plan: unknown action {self.action!r} "
                f"(expected one of {_ACTIONS})")
        if self.rank < 0 or self.nth < 1:
            raise Mp4jError(
                f"fault plan: rank must be >= 0 and nth >= 1 "
                f"(got rank={self.rank}, nth={self.nth})")
        if self.action in ("delay", "slow") and self.secs <= 0:
            raise Mp4jError(
                f"fault plan: {self.action} needs secs > 0")
        if not 0.0 <= self.prob <= 1.0:
            raise Mp4jError(
                f"fault plan: prob={self.prob} outside [0, 1]")


_FIELD_TYPES = {"rank": int, "nth": int, "secs": float, "peer": int,
                "prob": float}


@dataclasses.dataclass
class FaultPlan:
    """A parsed, validated plan — the same object on every rank of a
    job (the injector filters by rank locally)."""

    faults: list[Fault] = dataclasses.field(default_factory=list)
    seed: int = 0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``MP4J_FAULT_PLAN`` grammar; garbage raises
        ``Mp4jError`` at slave setup, not mid-collective."""
        faults: list[Fault] = []
        seed = 0
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                try:
                    seed = int(part[len("seed="):])
                except ValueError:
                    raise Mp4jError(
                        f"fault plan: bad seed in {part!r}") from None
                continue
            fields = [f.strip() for f in part.split(":")]
            action, kvs = fields[0], fields[1:]
            kwargs: dict = {}
            for kv in kvs:
                key, sep, val = kv.partition("=")
                key = key.strip()
                if not sep or key not in _FIELD_TYPES:
                    raise Mp4jError(
                        f"fault plan: bad field {kv!r} in {part!r} "
                        f"(expected one of {sorted(_FIELD_TYPES)})")
                try:
                    kwargs[key] = _FIELD_TYPES[key](val.strip())
                except ValueError:
                    raise Mp4jError(
                        f"fault plan: {key}={val!r} is not a "
                        f"{_FIELD_TYPES[key].__name__}") from None
            if "rank" not in kwargs:
                raise Mp4jError(
                    f"fault plan: directive {part!r} needs rank=")
            faults.append(Fault(action=action, **kwargs))
        return cls(faults=faults, seed=seed)

    def for_rank(self, rank: int) -> list[Fault]:
        return [f for f in self.faults if f.rank == rank]


def corrupt_copy(buf):
    """A COPY of ``buf`` with its middle byte flipped (XOR 0xFF) —
    deterministic, and never mutating the caller's buffer: the frame
    on the wire lies while every local record stays truthful, the
    exact hazard shape the audit plane must catch. Accepts bytes-likes
    and numpy arrays; returns the matching kind."""
    import numpy as np

    if isinstance(buf, np.ndarray):
        out = buf.copy()
        flat = out.view(np.uint8).reshape(-1)
        flat[flat.size // 2] ^= 0xFF
        return out
    out = bytearray(buf)
    out[len(out) // 2] ^= 0xFF
    return bytes(out)


class FaultInjector:
    """Per-rank evaluator of a :class:`FaultPlan`.

    The slave calls :meth:`on_collective` at every OUTERMOST collective
    entry (arming directives whose ordinal matched, executing kills)
    and installs the injector on its peer channels, whose I/O
    primitives call :meth:`on_io` — where armed delays/slows sleep and
    armed resets cut the connection. Thread-safe: channel I/O may run
    on the send-helper thread.
    """

    def __init__(self, plan: FaultPlan, rank: int):
        self._rank = rank
        self._lock = threading.Lock()
        self._armed: list[Fault] = []
        self._pending: list[Fault] = []
        for i, f in enumerate(plan.faults):
            if f.rank != rank:
                continue
            if f.prob < 1.0:
                rng = random.Random(f"{plan.seed}:{rank}:{i}")
                if rng.random() >= f.prob:
                    continue
            self._pending.append(f)

    @property
    def empty(self) -> bool:
        return not self._pending and not self._armed

    def on_collective(self, ordinal: int, kill_cb=None) -> None:
        """Arm directives whose ``nth`` equals this collective ordinal;
        execute kills. ``kill_cb(fault)`` performs the slave-side
        socket teardown before this raises :class:`FaultKill`. Retried
        attempts keep the first attempt's ordinal, so a one-shot fault
        does not re-fire into its own recovery."""
        kill: Fault | None = None
        with self._lock:
            # a one-shot directive belongs to ONE ordinal: if its
            # peer= filter saw no matching I/O during its collective,
            # it must disarm, not leak into a later collective the
            # plan never targeted
            self._armed = [f for f in self._armed
                           if f.action == "slow" or f.nth == ordinal]
            still: list[Fault] = []
            for f in self._pending:
                if f.nth == ordinal or (f.action == "slow"
                                        and f.nth <= ordinal):
                    if f.action == "kill":
                        kill = f
                    else:
                        self._armed.append(f)
                else:
                    still.append(f)
            self._pending = still
        if kill is not None:
            if kill_cb is not None:
                kill_cb(kill)
            raise FaultKill(
                f"fault injection: rank {self._rank} killed at "
                f"collective {ordinal}")

    def on_collective_window(self, ordinal: int, kill_cb=None) -> None:
        """Concurrent-batch variant of :meth:`on_collective`
        (ISSUE 11): the nonblocking scheduler admits SEVERAL ordinals
        back-to-back before any of their I/O moves, so arming ordinal
        k+1 must not disarm ordinal k's still-unfired directives (the
        per-ordinal prune assumes sequential collectives). Arms
        ``nth == ordinal`` directives and executes kills; stale armed
        directives are pruned at the next batch boundary
        (:meth:`prune_below`)."""
        kill: Fault | None = None
        with self._lock:
            still: list[Fault] = []
            for f in self._pending:
                if f.nth == ordinal or (f.action == "slow"
                                        and f.nth <= ordinal):
                    if f.action == "kill":
                        kill = f
                    else:
                        self._armed.append(f)
                else:
                    still.append(f)
            self._pending = still
        if kill is not None:
            if kill_cb is not None:
                kill_cb(kill)
            raise FaultKill(
                f"fault injection: rank {self._rank} killed at "
                f"collective {ordinal}")

    def prune_below(self, ordinal: int) -> None:
        """Disarm one-shot directives armed for ordinals before
        ``ordinal`` — the batch-boundary half of
        :meth:`on_collective_window`: those collectives completed
        without matching I/O, so their directives must not leak into a
        later batch the plan never targeted."""
        with self._lock:
            self._armed = [f for f in self._armed
                           if f.action == "slow" or f.nth >= ordinal]

    def take_corrupt(self, channel, nbytes: int):
        """Pop one armed ``corrupt`` directive for this channel's peer
        if ``nbytes`` clears :data:`CORRUPT_MIN`; returns the
        :class:`Fault` or ``None``. Separate from :meth:`on_io`
        because the caller must know BEFORE the write whether to
        substitute a flipped copy — and only payload-sized buffers are
        eligible (see the grammar note)."""
        if nbytes < CORRUPT_MIN:
            return None
        with self._lock:
            for f in self._armed:
                if f.action == "corrupt" and (
                        f.peer is None or f.peer == channel.peer_rank):
                    self._armed.remove(f)
                    return f
        return None

    def on_io(self, channel, op: str) -> None:
        """Channel I/O hook (``op`` is ``"send"`` or ``"recv"``). At
        most ONE armed one-shot directive fires per I/O, so a plan
        carrying N resets at the same ordinal cuts N successive
        attempts (one per recovery round) — the lever for
        retry-exhaustion chaos tests — instead of burning all N on a
        single operation."""
        with self._lock:
            def match(f):
                return f.peer is None or f.peer == channel.peer_rank
            fire = [f for f in self._armed
                    if f.action == "slow" and match(f)]
            once = next((f for f in self._armed
                         if f.action in _ONCE and match(f)), None)
            if once is not None:
                self._armed.remove(once)
                fire.append(once)
        for f in fire:
            if f.action in ("delay", "slow"):
                time.sleep(f.secs)
            elif f.action == "reset":
                # cut the connection where we stand — between a frame's
                # header and payload when called from _send_all — so
                # both ends observe a mid-frame tear. shutdown WITHOUT
                # close: the paired helper-thread send (or the native
                # poll loop) may still hold this raw fd number, and
                # freeing it here would let a re-dial recycle it into
                # the wrong exchange — the exact hazard the recovery
                # teardown's invalidate()/deferred-close discipline
                # exists for. The tear triggers that teardown, which
                # owns the eventual close.
                channel.invalidate()
