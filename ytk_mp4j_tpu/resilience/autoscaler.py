"""mp4j-autopilot — the closed-loop elastic autoscaler (ISSUE 13).

PR 10 built the membership MECHANISM (adopt a warm spare into a rank
id, bit-exact) and PR 12 built the DECISION substrate
(``Master.health_status()`` per-rank verdicts, with
``MP4J_HEALTH_DOMINATOR_ORDINALS`` driving ``EVICT_RECOMMENDED``).
This module is the ACTING side the ROADMAP names: a master-owned
controller that reads the verdicts and drives the membership machinery
— turning elastic membership from a failure-recovery feature into a
self-healing substrate. Four actions:

1. **Planned eviction / replace** (``evict_replace``): a rank the
   health plane marks ``EVICT_RECOMMENDED`` — but which is still
   *alive* — is proactively replaced at the next collective boundary:
   :meth:`Master.request_planned_evict` quiesces the job through the
   epoch-fenced abort round, adopts a spare into the slow rank's id
   via the existing manifest path, and releases the evicted rank with
   a clean :class:`~ytk_mp4j_tpu.exceptions.Mp4jEvicted`.
2. **Spare auto-provisioning** (``provision``): when
   ``mp4j_spares_available`` hits 0 the operator hook fires —
   ``Master(provision_hook=)`` (a callable) or ``MP4J_PROVISION_CMD``
   (a shell command run with ``MP4J_MASTER_HOST``/``MP4J_MASTER_PORT``
   in its environment) — to spawn a fresh ``spare=True`` process.
3. **Grow** (``grow``): under ``MP4J_ELASTIC=grow`` the master adopts
   registered spares into NEW rank ids when every rank reaches an
   explicit app epoch boundary (``ProcessCommSlave.resize_point()``).
   The app paces this action; the controller only gates it
   (:meth:`Autoscaler.approve_grow`) behind the same safety rails.
4. **Safety rails** — the robustness heart, all enforced in
   :func:`gate` (a pure function, testable without sockets):
   per-action cooldowns (``MP4J_AUTOSCALE_COOLDOWN_SECS``), a
   job-lifetime action budget (``MP4J_AUTOSCALE_BUDGET``), ONE action
   in flight at a time, an audit-green precondition (no action while
   the cross-rank digest grid holds unresolved divergence), the
   ``MP4J_AUTOSCALE=off|observe|act`` ladder (``observe`` logs every
   would-be action without acting), and a **circuit breaker**: two
   consecutive failed actions (adoption timeout burning the pool,
   eviction/grow round abort, provision that never registers) trip the
   controller back to recommend-only with a structured alert —
   degraded advice is strictly safer than a flapping actuator.

ISSUE 19 adds **load-following** over the serve plane
(:func:`decide_load`): ``serve_shrink`` when serve QPS stays under
``MP4J_SERVE_IDLE_QPS`` for ``MP4J_SERVE_IDLE_SECS`` straight,
``serve_grow`` (pace a spare in at the app's next ``resize_point()``)
when QPS crosses ``MP4J_SERVE_BUSY_QPS`` with spares registered. Both
ship OBSERVE-FIRST: they ride the gate (pacing) and the alert pipe,
and never the actuator — even under ``act`` — until the
recommendations prove out in the field.

The policy core — :func:`decide`, :func:`gate`, :func:`resolve_pending`
— is pure functions over ``health_status()`` / ``membership_status()``
/ ``audit_status()`` snapshots (the health-engine convention: tests
drive them without sockets). :class:`Autoscaler` is the thin stateful
shell: a control thread that samples the master's documents, runs the
policy, and executes — waking on an ``Event`` (mp4j-lint R18: a
sleeping controller could neither shut down promptly nor notice its
own trip).

Lock discipline: the controller NEVER holds its own lock while calling
into the master (the master's document methods take the master lock,
and the master renders :meth:`status` into its metrics document while
holding it — holding both in the other order would deadlock).

Every action (and every trip) lands everywhere at once, the repo
precedent: master log, the subject rank's recovery log and durable
sink (via the ``health_alert`` control push — ``mp4j-scope health``
timelines interleave actions with verdict transitions), Prometheus
(``mp4j_autoscale_actions_total{action}``, ``mp4j_autoscale_tripped``),
``mp4j-scope live``'s ``autoscale:`` head-line, and the postmortem
manifest's autoscaler section.
"""

from __future__ import annotations

import collections
import os
import socket
import subprocess
import threading
import time

from ytk_mp4j_tpu.utils import tuning

# the controller's action vocabulary (the Prometheus `action` label).
# serve_shrink / serve_grow are the load-following pair (ISSUE 19):
# OBSERVE-FIRST by design — even under MP4J_AUTOSCALE=act they route
# through the alert pipe only, never the actuator, until the
# recommendations prove trustworthy in the field
ACTIONS = ("evict_replace", "provision", "grow",
           "serve_shrink", "serve_grow")

# how long a dispatched action may stay pending before it counts as
# FAILED, as a multiple of the adoption deadline (the slowest step an
# action waits on is a spare acking its adoption; one retry spare is
# in budget before the controller calls it)
_DEADLINE_ADOPTS = 2.5
_DEADLINE_FLOOR = 5.0


def _wall() -> float:
    # autoscaler events ride the same durable alert pipe as health
    # alerts and are rendered in cross-host timelines next to them
    # mp4j-lint: disable=R11 (artifact timestamp, not a duration)
    return time.time()


class ControllerState:
    """The controller's mutable ledger — plain fields so the pure
    policy functions can read it like a snapshot. Owned by
    :class:`Autoscaler` under its lock; tests build one directly."""

    def __init__(self):
        self.actions: dict[str, int] = {a: 0 for a in ACTIONS}
        self.observed: dict[str, int] = {a: 0 for a in ACTIONS}
        self.failures: dict[str, int] = {a: 0 for a in ACTIONS}
        self.retried: dict[str, int] = {a: 0 for a in ACTIONS}
        self.last_action: dict[str, float] = {}   # action -> mono ts
        self.budget_used = 0
        self.consecutive_failures = 0
        self.tripped = False
        self.tripped_why = ""
        # the ONE in-flight action: {"action", "rank"?, "since" (mono),
        # "deadline" (mono), "baseline" (membership counter snapshot)}
        self.pending: dict | None = None
        # monotonic instant the serve plane's QPS first dipped under
        # the idle threshold; None while busy (load-following hysteresis)
        self.serve_idle_since: float | None = None
        self.events: collections.deque = collections.deque(maxlen=64)


def audit_green(audit: dict | None) -> bool:
    """The audit-green precondition: the cross-rank digest grid holds
    ZERO divergences. A divergence means some rank's content is
    suspect — acting on membership while the data plane may be
    corrupt can launder corruption into a 'recovered' roster."""
    return not audit or int(audit.get("divergences", 0) or 0) == 0


def gate(state: ControllerState, now: float, action: str, *,
         cooldown_secs: float, budget: int,
         audit: dict | None) -> tuple[bool, str]:
    """Whether ``action`` may fire NOW — every safety rail in one pure
    function. Returns ``(allowed, reason)``; the reason names the
    specific rail so observe-mode logs read like a decision trace.
    The breaker is checked LAST: a tripped controller still runs the
    pacing rails (cooldown/pending/budget) so its recommend-only
    would-act trace stays paced instead of firing every tick."""
    if state.pending is not None:
        return False, (f"action '{state.pending.get('action')}' still "
                       "in flight (one at a time)")
    if state.budget_used >= budget:
        return False, (f"job-lifetime action budget exhausted "
                       f"({state.budget_used}/{budget})")
    last = state.last_action.get(action)
    if last is not None and now - last < cooldown_secs:
        return False, (f"cooldown: last '{action}' "
                       f"{now - last:.1f}s ago "
                       f"(< {cooldown_secs:.1f}s)")
    if not audit_green(audit):
        return False, ("audit divergence unresolved "
                       f"({int((audit or {}).get('divergences', 0))} "
                       "flagged) — no membership action while content "
                       "is suspect")
    if state.tripped:
        return False, ("circuit breaker tripped (recommend-only): "
                       + state.tripped_why)
    return True, ""


def decide(health: dict | None, membership: dict | None,
           *, provisionable: bool) -> list[dict]:
    """The policy core: what the controller WANTS to do, given the
    verdict and membership documents — before any safety rail. Pure
    function; the master's :meth:`request_planned_evict` re-validates
    everything under its lock (single source of truth), so a stale
    snapshot here costs a refused request, never a wrong action.

    Returns proposals ``[{"action", "rank"?, "why"}, ...]``, most
    urgent first. ONE eviction per tick (lowest recommended rank):
    serial actions keep every intermediate state observable."""
    out: list[dict] = []
    ms = membership or {}
    mode = ms.get("mode", "off")
    spares = int(ms.get("spares_available", 0) or 0)
    if mode in ("replace", "grow"):
        evict = sorted(int(r) for r in
                       (health or {}).get("evict_recommended") or ())
        if evict and spares >= 1:
            rank = evict[0]
            ev = ((health or {}).get("ranks") or {}).get(str(rank), {})
            out.append({
                "action": "evict_replace", "rank": rank,
                "why": (f"health verdict EVICT_RECOMMENDED: "
                        f"{ev.get('why') or 'sustained pressure'}")})
        if spares == 0 and provisionable:
            out.append({
                "action": "provision",
                "why": "warm-spare pool drained to 0"})
    return out


def decide_load(serve: dict | None, membership: dict | None,
                idle_since: float | None, now: float, *,
                idle_qps: float, busy_qps: float,
                idle_secs: float) -> tuple[list[dict], float | None]:
    """The load-following policy over the serve plane (ISSUE 19) —
    pure, like :func:`decide`. Two proposals:

    - ``serve_shrink`` when the inference plane's QPS has stayed at or
      under ``idle_qps`` for ``idle_secs`` straight (the sustained-idle
      window is the hysteresis: a single quiet scrape proposes
      nothing);
    - ``serve_grow`` the moment QPS reaches ``busy_qps`` while warm
      spares are registered — growth happens at the app's next
      :meth:`resize_point`, so the proposal is the *recommendation to
      pace one in*, not an adoption.

    Returns ``(proposals, new_idle_since)``; the caller stores the
    second element back into :class:`ControllerState` — the function
    itself owns no clock and no state."""
    if not serve or not serve.get("active"):
        return [], None
    qps = float(serve.get("qps", 0.0) or 0.0)
    if qps >= busy_qps:
        out = []
        if int((membership or {}).get("spares_available", 0) or 0) > 0:
            out.append({
                "action": "serve_grow",
                "why": (f"serve QPS {qps:.1f} >= busy threshold "
                        f"{busy_qps:.1f} — pace a spare in at the "
                        "next resize_point()")})
        return out, None
    if qps > idle_qps:
        return [], None
    if idle_since is None:
        return [], now
    if now - idle_since >= idle_secs:
        return [{
            "action": "serve_shrink",
            "why": (f"serve QPS {qps:.1f} <= idle threshold "
                    f"{idle_qps:.1f} for {now - idle_since:.0f}s — "
                    "the replica set is over-provisioned")}], idle_since
    return [], idle_since


def resolve_pending(pending: dict, membership: dict | None,
                    now: float) -> tuple[str, str]:
    """Resolve the in-flight action against the latest membership
    document: ``("ok", detail)`` when the matching success event
    landed after dispatch, ``("failed", detail)`` on a matching abort
    event or a blown deadline, ``("pending", "")`` otherwise. Pure
    function of its inputs."""
    action = pending.get("action")
    since = float(pending.get("since", 0.0))
    for ev in reversed((membership or {}).get("events") or []):
        if float(ev.get("mono", 0.0)) < since:
            break
        kind = ev.get("kind")
        if action == "evict_replace":
            if (kind == "planned_evict"
                    and ev.get("rank") == pending.get("rank")):
                return "ok", (f"rank {ev.get('rank')} evicted and "
                              f"replaced from spare #{ev.get('spare')}"
                              f" @ epoch {ev.get('epoch')}")
            if (kind == "evict_abort"
                    and pending.get("rank") in (ev.get("ranks") or ())):
                return "failed", (f"eviction round aborted: "
                                  f"{ev.get('why')}")
            if (kind == "evict_fence_cancel"
                    and ev.get("rank") == pending.get("rank")):
                # the fence canceled before anything was torn down —
                # zero disruption, so this is a benign RETRY (budget
                # refunded), never a breaker failure
                return "retry", (f"eviction fence canceled: "
                                 f"{ev.get('why')}")
        elif action == "grow":
            if kind == "grow":
                return "ok", (f"grew by rank(s) {ev.get('ranks')} "
                              f"@ resize {ev.get('gen')}")
            if kind == "grow_abort":
                return "failed", f"grow aborted: {ev.get('why')}"
            if kind == "grow_cancel":
                # dropped before any adoption was dispatched: benign
                return "retry", f"grow canceled: {ev.get('why')}"
        elif action == "provision":
            if kind == "spare_registered":
                # the registration event, not the pool gauge: a
                # waiting membership round may claim the fresh spare
                # synchronously, so `spares_available` can stay 0
                # through a provision that succeeded
                return "ok", (f"spare #{ev.get('spare')} registered")
    if action == "provision":
        if int((membership or {}).get("spares_available", 0) or 0) > 0:
            return "ok", "a fresh spare registered"
    if now > float(pending.get("deadline", now)):
        return "failed", (f"'{action}' not confirmed within "
                          f"{now - since:.1f}s (adoption timeout / "
                          "spare never registered)")
    return "pending", ""


class Autoscaler:
    """The controller shell around the pure policy core. Owned by the
    master; one background thread, started from ``Master._serve`` and
    stopped by the master's stop event."""

    def __init__(self, master, *, mode: str,
                 cooldown_secs: float | None = None,
                 budget: int | None = None,
                 provision_hook=None,
                 provision_cmd: str | None = None,
                 tick_secs: float = 0.25):
        self._master = master
        self.mode = mode
        self.cooldown_secs = tuning.autoscale_cooldown_secs(
            cooldown_secs)
        self.budget = tuning.autoscale_budget(budget)
        self._provision_hook = provision_hook
        self._provision_cmd = (tuning.provision_cmd()
                               if provision_cmd is None
                               else str(provision_cmd))
        self._tick = max(0.05, min(float(tick_secs), 1.0))
        self._deadline_secs = max(
            _DEADLINE_FLOOR, _DEADLINE_ADOPTS * master._adopt_secs)
        # load-following thresholds (ISSUE 19), frozen at construction
        # like the cooldown/budget knobs
        self._serve_idle_qps = tuning.serve_idle_qps()
        self._serve_busy_qps = tuning.serve_busy_qps()
        self._serve_idle_secs = tuning.serve_idle_secs()
        self._lock = threading.Lock()
        self.state = ControllerState()
        self._alert_seq = 0
        # events minted under the controller lock, dispatched OUTSIDE
        # it (the master push path and status() render compose with
        # the master lock in both orders — dispatching while holding
        # the controller lock would complete a deadlock cycle)
        self._outbox: list[tuple[dict, str]] = []
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------
    def start(self, stop: threading.Event) -> "Autoscaler":
        with self._lock:
            self._stop = stop
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mp4j-autoscaler")
        self._thread.start()
        return self

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _loop(self) -> None:
        # snapshot the stop event once under the controller lock: it
        # is published by start() on the spawning thread and never
        # rebound afterwards
        with self._lock:
            stop = self._stop
        # Event.wait, never time.sleep (mp4j-lint R18): the master's
        # stop event ends the loop within one tick, and a trip takes
        # effect on the very next evaluation
        while not stop.wait(self._tick):
            try:
                self.tick()
            # the controller must outlive any single bad tick (a
            # half-shut-down master mid-sample, a hook raising): a
            # dead controller is a silent loss of the whole plane
            # mp4j-lint: disable=R5 (controller isolation; logged)
            except Exception as e:
                try:
                    self._master._log("M", "ERROR",
                                      f"autoscale: tick failed: {e!r}")
                except Exception:
                    pass

    # -- one evaluation -------------------------------------------------
    def tick(self) -> None:
        """Sample the decision substrate, resolve the in-flight
        action, and dispatch at most one new one. Public so tests can
        single-step the controller deterministically."""
        try:
            self._tick_once()
        finally:
            self._flush_events()

    def _tick_once(self) -> None:
        m = self._master
        health = m.health_status()
        membership = m.membership_status()
        audit = m.audit_status()
        now = time.monotonic()

        with self._lock:
            st = self.state
            if st.pending is not None:
                verdict, detail = resolve_pending(
                    st.pending, membership, now)
                if verdict != "pending":
                    self._settle_locked(verdict, detail, now)
        # load-following (ISSUE 19): sample the serve section and run
        # the pure policy; proposals route through the SAME gate (so a
        # persistent verdict is one line per cooldown) and then through
        # _observe UNCONDITIONALLY — serve actions ship observe-first,
        # even in act mode (module docstring / ACTIONS comment)
        serve_fn = getattr(m, "serve_status", None)
        serve = serve_fn() if serve_fn is not None else None
        with self._lock:
            idle_since = self.state.serve_idle_since
        load_props, idle_since = decide_load(
            serve, membership, idle_since, now,
            idle_qps=self._serve_idle_qps,
            busy_qps=self._serve_busy_qps,
            idle_secs=self._serve_idle_secs)
        with self._lock:
            self.state.serve_idle_since = idle_since
        for prop in load_props:
            with self._lock:
                allowed, _ = gate(
                    self.state, now, prop["action"],
                    cooldown_secs=self.cooldown_secs,
                    budget=self.budget, audit=audit)
            if allowed:
                self._observe(prop["action"], prop, now)
        provisionable = (self._provision_hook is not None
                         or bool(self._provision_cmd))
        for prop in decide(health, membership,
                           provisionable=provisionable):
            action = prop["action"]
            with self._lock:
                allowed, why_not = gate(
                    self.state, now, action,
                    cooldown_secs=self.cooldown_secs,
                    budget=self.budget, audit=audit)
                tripped = self.state.tripped
            if self.mode != "act" or tripped:
                # recommend-only (observe mode, or a tripped act
                # mode): log the would-be action through the full
                # alert pipe, paced by the SAME rails — a persistent
                # verdict is one line per cooldown, never per tick
                if allowed or why_not.startswith("circuit breaker"):
                    self._observe(action, prop, now)
                continue
            if not allowed:
                continue
            self._execute(action, prop, now)
            return          # one dispatch per tick, by design

    def _settle_locked(self, verdict: str, detail: str,
                       now: float) -> None:
        """Close the in-flight action (caller holds the lock); trips
        the breaker on the second consecutive failure."""
        st = self.state
        pending, st.pending = st.pending, None
        action = pending.get("action", "?")
        if verdict == "ok":
            st.consecutive_failures = 0
            self._emit_locked("action_ok", action,
                             rank=pending.get("rank"),
                             msg=detail, level="WARN")
            return
        if verdict == "retry":
            # nothing was disturbed (a canceled fence): refund the
            # budget and keep the cooldown stamp (pacing). The
            # per-action DISPATCH counter is NOT rolled back — it
            # feeds the Prometheus counter, which must stay monotone
            # (a 1 -> 0 step reads as a counter reset to rate());
            # the retried dict tells the two apart
            st.budget_used = max(0, st.budget_used - 1)
            st.retried[action] = st.retried.get(action, 0) + 1
            self._emit_locked("action_retry", action,
                             rank=pending.get("rank"), msg=detail,
                             level="WARN")
            return
        st.failures[action] = st.failures.get(action, 0) + 1
        st.consecutive_failures += 1
        self._emit_locked("action_failed", action,
                         rank=pending.get("rank"), msg=detail,
                         level="ERROR")
        if st.consecutive_failures >= 2 and not st.tripped:
            st.tripped = True
            st.tripped_why = (f"{st.consecutive_failures} consecutive "
                              f"failed action(s); last: {detail}")
            # the breaker alert is the structured headline: the
            # controller is now recommend-only for the job's lifetime
            self._emit_locked(
                "tripped", action, rank=pending.get("rank"),
                msg=("circuit breaker tripped -> recommend-only: "
                     + st.tripped_why),
                level="ERROR")

    def _observe(self, action: str, prop: dict,
                 now: float | None = None) -> None:
        """``observe`` mode (and a tripped ``act`` mode): log the
        would-be action through the full alert pipe, act on nothing.
        Stamps the cooldown like a real dispatch — a verdict that
        persists through the cooldown produces ONE line per window,
        not one per controller tick."""
        with self._lock:
            st = self.state
            st.observed[action] = st.observed.get(action, 0) + 1
            st.last_action[action] = (time.monotonic()
                                      if now is None else now)
            self._emit_locked(
                "would_act", action, rank=prop.get("rank"),
                msg=f"would {action}: {prop.get('why', '')}",
                level="WARN")

    def _execute(self, action: str, prop: dict, now: float) -> None:
        m = self._master
        if action == "evict_replace":
            rank = int(prop["rank"])
            if not m.request_planned_evict(rank, prop.get("why", "")):
                # refused (round open / spare died / rank gone since
                # the snapshot): not a failed action — the next tick
                # re-proposes from fresh documents
                return
            self._dispatched(action, prop, now, rank=rank)
        elif action == "provision":
            try:
                self._run_provision_hook()
            except Exception as e:
                # a hook that cannot even launch is an immediate
                # failure — there is nothing to wait for
                with self._lock:
                    self.state.actions[action] += 1
                    self.state.budget_used += 1
                    self.state.last_action[action] = now
                    self.state.pending = {
                        "action": action, "since": now,
                        "deadline": now}
                    self._settle_locked(
                        "failed", f"provision hook failed: {e!r}", now)
                return
            self._dispatched(action, prop, now)

    def _dispatched(self, action: str, prop: dict, now: float,
                    rank: int | None = None) -> None:
        with self._lock:
            st = self.state
            st.actions[action] = st.actions.get(action, 0) + 1
            st.budget_used += 1
            st.last_action[action] = now
            st.pending = {"action": action, "rank": rank,
                          "since": now,
                          "deadline": now + self._deadline_secs}
            self._emit_locked(
                "action", action, rank=rank,
                msg=f"{action}: {prop.get('why', '')}", level="WARN")

    def _run_provision_hook(self) -> None:
        """Fire the operator hook: the callable seam, else the
        ``MP4J_PROVISION_CMD`` subprocess (detached — the spawned
        process is expected to register as a spare, not to exit)."""
        if self._provision_hook is not None:
            self._provision_hook(self._master)
            return
        # advertise a REACHABLE master address: the explicit bind
        # host when the master has one, else this machine's hostname
        # (a provisioner spawning the spare on another host must not
        # be handed its own loopback)
        host = getattr(self._master, "host", "") or ""
        if not host or host == "0.0.0.0":
            try:
                host = socket.gethostname() or "127.0.0.1"
            except OSError:
                host = "127.0.0.1"
        env = {**os.environ,
               "MP4J_MASTER_HOST": host,
               "MP4J_MASTER_PORT": str(self._master.port)}
        subprocess.Popen(self._provision_cmd, shell=True, env=env,
                         start_new_session=True,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)

    # -- grow gating (called by the master at resize completion) --------
    def approve_grow(self, spares_available: int,
                     audit: dict | None) -> int:
        """How many spares a completed ``resize_point()`` round may
        adopt into new ranks: all available ones when the rails allow,
        0 otherwise. ``observe`` logs the would-be growth. Called by
        the master WITHOUT the master lock held (lock discipline in
        the module docstring); counts as a dispatched action — the
        master confirms it via the membership ``grow``/``grow_abort``
        event like every other action."""
        if spares_available <= 0:
            return 0
        now = time.monotonic()
        try:
            with self._lock:
                allowed, why_not = gate(
                    self.state, now, "grow",
                    cooldown_secs=self.cooldown_secs,
                    budget=self.budget, audit=audit)
            if not allowed:
                with self._lock:
                    self._emit_locked(
                        "skipped", "grow",
                        msg=f"grow skipped: {why_not}", level="WARN")
                return 0
            if self.mode != "act":
                self._observe("grow", {
                    "why": (f"adopt {spares_available} spare(s) into "
                            "new rank ids at this resize point")})
                return 0
            self._dispatched("grow", {
                "why": (f"adopting {spares_available} spare(s) into "
                        "new rank ids at a resize point")}, now)
            return spares_available
        finally:
            self._flush_events()

    # -- alerts + status ------------------------------------------------
    def _emit_locked(self, kind: str, action: str, *,
                     msg: str, rank: int | None = None,
                     level: str = "WARN") -> None:
        """Record + dispatch one structured autoscaler event (caller
        holds the controller lock). Events ride the health-alert
        control pipe so they land in the durable sink's ``alerts``
        records and interleave with verdict transitions in every
        timeline. Ids are NEGATIVE so they can never collide with the
        health engine's positive monotone ids in the dedup/sort."""
        self._alert_seq += 1
        ev = {"id": -self._alert_seq, "wall": _wall(),
              "kind": "autoscale", "event": kind, "action": action,
              "rank": rank, "mode": self.mode, "msg": msg}
        self.state.events.append(ev)
        self._outbox.append((ev, level))

    def _flush_events(self) -> None:
        """Dispatch every event minted since the last flush — called
        with the controller lock NOT held (lock discipline)."""
        with self._lock:
            out, self._outbox = self._outbox, []
        for ev, level in out:
            self._master._autoscale_event(ev, level=level)

    def status(self) -> dict:
        """The autoscaler document: ``mp4j-scope live``'s head-line,
        the metrics doc's ``cluster.autoscale`` section (Prometheus
        ``mp4j_autoscale_actions_total{action}`` /
        ``mp4j_autoscale_tripped``), and the postmortem manifest's
        autoscaler section. Plain JSON-ready values."""
        with self._lock:
            st = self.state
            return {
                "mode": self.mode,
                "tripped": st.tripped,
                "tripped_why": st.tripped_why,
                "actions": dict(st.actions),
                "observed": dict(st.observed),
                "failures": dict(st.failures),
                "retried": dict(st.retried),
                "consecutive_failures": st.consecutive_failures,
                "budget": {"limit": self.budget,
                           "used": st.budget_used},
                "cooldown_secs": self.cooldown_secs,
                "pending": (dict(st.pending)
                            if st.pending is not None else None),
                "events": [dict(e) for e in st.events],
            }
