// Native socket data plane for the CPU reference path.
//
// The reference's slaves move primitive-array segments over raw JVM
// socket streams (SURVEY.md section 2 "Serialization": raw
// DataOutputStream writes, no Kryo, for the primitive fast path). The
// Python framed path (transport/channel.py) pays per-frame pickle +
// per-call interpreter overhead and needs a helper thread to overlap
// the send and receive sides of a ring/halving exchange. This file is
// the native equivalent: a poll()-driven full-duplex raw exchange --
// both directions progress in one thread, no framing, no copies.
//
// ABI: plain C. Sizes are NOT sent on the wire -- both peers derive
// them from the collective's metadata (segment math), exactly like the
// reference's primitive fast path. Callers must keep the raw/framed
// decision a pure function of job-wide parameters so ranks never
// disagree about the wire format.
//
// Return codes: 0 ok, -1 syscall error, -2 peer closed early,
// -3 timeout. timeout_ms is an IDLE timeout, matching the framed
// path's per-recv socket timeout: the deadline resets whenever bytes
// move in either direction, so a slow-but-progressing transfer never
// times out — only a stalled peer does.

#include <cerrno>
#include <cstdint>
#include <ctime>

#include <fcntl.h>
#include <poll.h>
#include <unistd.h>

namespace {

constexpr int64_t kChunk = 1 << 20;  // per-syscall cap, keeps poll honest

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

class NonblockGuard {
 public:
  explicit NonblockGuard(int fd) : fd_(fd), flags_(fcntl(fd, F_GETFL, 0)) {
    if (flags_ >= 0) fcntl(fd_, F_SETFL, flags_ | O_NONBLOCK);
  }
  ~NonblockGuard() {
    if (flags_ >= 0) fcntl(fd_, F_SETFL, flags_);
  }
  bool ok() const { return flags_ >= 0; }

 private:
  int fd_;
  int flags_;
};

// One progress attempt on a ready direction; updates *done.
// Returns 0 on progress/EAGAIN, else a negative error code.
int try_send(int fd, const char* buf, int64_t nbytes, int64_t* done) {
  int64_t want = nbytes - *done;
  if (want > kChunk) want = kChunk;
  ssize_t w = write(fd, buf + *done, static_cast<size_t>(want));
  if (w >= 0) {
    *done += w;
    return 0;
  }
  return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) ? 0 : -1;
}

int try_recv(int fd, char* buf, int64_t nbytes, int64_t* done) {
  int64_t want = nbytes - *done;
  if (want > kChunk) want = kChunk;
  ssize_t r = read(fd, buf + *done, static_cast<size_t>(want));
  if (r > 0) {
    *done += r;
    return 0;
  }
  if (r == 0) return -2;  // orderly shutdown with bytes still pending
  return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) ? 0 : -1;
}

}  // namespace

extern "C" {

// Full-duplex raw exchange: send sbytes from sbuf on send_fd while
// receiving rbytes into rbuf from recv_fd. send_fd may equal recv_fd
// (partner exchange on one socket) or differ (ring step).
// timeout_ms < 0 means block forever (the reference's fail-stop mode).
int mp4j_sendrecv_raw(int send_fd, int recv_fd, const void* sbuf,
                      int64_t sbytes, void* rbuf, int64_t rbytes,
                      int64_t timeout_ms) {
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  int64_t sdone = 0, rdone = 0;
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : -1;

  NonblockGuard sg(send_fd);
  if (!sg.ok()) return -1;
  const bool same = send_fd == recv_fd;
  NonblockGuard rg(same ? -1 : recv_fd);  // fcntl(-1) fails harmlessly
  if (!same && !rg.ok()) return -1;

  while (sdone < sbytes || rdone < rbytes) {
    pollfd fds[2];
    int nfds = 0;
    if (same) {
      fds[0].fd = send_fd;
      fds[0].events = static_cast<short>(
          (sdone < sbytes ? POLLOUT : 0) | (rdone < rbytes ? POLLIN : 0));
      nfds = 1;
    } else {
      if (sdone < sbytes) {
        fds[nfds].fd = send_fd;
        fds[nfds].events = POLLOUT;
        ++nfds;
      }
      if (rdone < rbytes) {
        fds[nfds].fd = recv_fd;
        fds[nfds].events = POLLIN;
        ++nfds;
      }
    }
    int wait = -1;
    if (deadline >= 0) {
      int64_t left = deadline - now_ms();
      if (left <= 0) return -3;
      wait = left > 1000000000 ? 1000000000 : static_cast<int>(left);
    }
    int pr = poll(fds, static_cast<nfds_t>(nfds), wait);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return -3;
    const int64_t before = sdone + rdone;
    for (int i = 0; i < nfds; ++i) {
      short rev = fds[i].revents;
      if (rev == 0) continue;
      const bool is_send =
          fds[i].fd == send_fd && (fds[i].events & POLLOUT) != 0;
      const bool is_recv =
          fds[i].fd == recv_fd && (fds[i].events & POLLIN) != 0;
      if (is_recv && (rev & (POLLIN | POLLHUP | POLLERR)) &&
          rdone < rbytes) {
        int rc = try_recv(recv_fd, rp, rbytes, &rdone);
        if (rc < 0) return rc;
      }
      if (is_send && (rev & POLLOUT) && sdone < sbytes) {
        int rc = try_send(send_fd, sp, sbytes, &sdone);
        if (rc < 0) return rc;
      }
      // POLLERR/POLLHUP with nothing readable: surface as closed/error
      if ((rev & (POLLERR | POLLNVAL)) && !(rev & POLLIN)) return -1;
      if ((rev & POLLHUP) && !(rev & POLLIN) && is_recv &&
          rdone < rbytes) {
        return -2;
      }
    }
    if (deadline >= 0 && sdone + rdone > before) {
      deadline = now_ms() + timeout_ms;  // progress resets idle timer
    }
  }
  return 0;
}

// One-directional steps (fold/unfold) call mp4j_sendrecv_raw with a
// null buffer on the inactive side; no separate entry points needed.

}  // extern "C"
