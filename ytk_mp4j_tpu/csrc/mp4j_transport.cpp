// Native socket data plane for the CPU reference path.
//
// The reference's slaves move primitive-array segments over raw JVM
// socket streams (SURVEY.md section 2 "Serialization": raw
// DataOutputStream writes, no Kryo, for the primitive fast path). The
// Python framed path (transport/channel.py) pays per-frame pickle +
// per-call interpreter overhead and needs a helper thread to overlap
// the send and receive sides of a ring/halving exchange. This file is
// the native equivalent: a poll()-driven full-duplex raw exchange --
// both directions progress in one thread, no framing, no copies.
//
// ABI: plain C. Sizes are NOT sent on the wire -- both peers derive
// them from the collective's metadata (segment math), exactly like the
// reference's primitive fast path. Callers must keep the raw/framed
// decision a pure function of job-wide parameters so ranks never
// disagree about the wire format.
//
// Return codes: 0 ok, -1 syscall error, -2 peer closed early,
// -3 timeout. timeout_ms is an IDLE timeout, matching the framed
// path's per-recv socket timeout: the deadline resets whenever bytes
// move in either direction, so a slow-but-progressing transfer never
// times out — only a stalled peer does.

#include <cerrno>
#include <cstdint>
#include <ctime>

#include <fcntl.h>
#include <poll.h>
#include <unistd.h>

namespace {

constexpr int64_t kChunk = 1 << 20;  // per-syscall cap, keeps poll honest

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

class NonblockGuard {
 public:
  explicit NonblockGuard(int fd) : fd_(fd), flags_(fcntl(fd, F_GETFL, 0)) {
    if (flags_ >= 0) fcntl(fd_, F_SETFL, flags_ | O_NONBLOCK);
  }
  ~NonblockGuard() {
    if (flags_ >= 0) fcntl(fd_, F_SETFL, flags_);
  }
  bool ok() const { return flags_ >= 0; }

 private:
  int fd_;
  int flags_;
};

// One progress attempt on a ready direction; updates *done.
// Returns 0 on progress/EAGAIN, else a negative error code.
int try_send(int fd, const char* buf, int64_t nbytes, int64_t* done) {
  int64_t want = nbytes - *done;
  if (want > kChunk) want = kChunk;
  ssize_t w = write(fd, buf + *done, static_cast<size_t>(want));
  if (w >= 0) {
    *done += w;
    return 0;
  }
  return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) ? 0 : -1;
}

int try_recv(int fd, char* buf, int64_t nbytes, int64_t* done) {
  int64_t want = nbytes - *done;
  if (want > kChunk) want = kChunk;
  ssize_t r = read(fd, buf + *done, static_cast<size_t>(want));
  if (r > 0) {
    *done += r;
    return 0;
  }
  if (r == 0) return -2;  // orderly shutdown with bytes still pending
  return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) ? 0 : -1;
}

}  // namespace

extern "C" {

// Full-duplex raw exchange: send sbytes from sbuf on send_fd while
// receiving rbytes into rbuf from recv_fd. send_fd may equal recv_fd
// (partner exchange on one socket) or differ (ring step).
// timeout_ms < 0 means block forever (the reference's fail-stop mode).
int mp4j_sendrecv_raw(int send_fd, int recv_fd, const void* sbuf,
                      int64_t sbytes, void* rbuf, int64_t rbytes,
                      int64_t timeout_ms) {
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  int64_t sdone = 0, rdone = 0;
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : -1;

  NonblockGuard sg(send_fd);
  if (!sg.ok()) return -1;
  const bool same = send_fd == recv_fd;
  NonblockGuard rg(same ? -1 : recv_fd);  // fcntl(-1) fails harmlessly
  if (!same && !rg.ok()) return -1;

  while (sdone < sbytes || rdone < rbytes) {
    pollfd fds[2];
    int nfds = 0;
    if (same) {
      fds[0].fd = send_fd;
      fds[0].events = static_cast<short>(
          (sdone < sbytes ? POLLOUT : 0) | (rdone < rbytes ? POLLIN : 0));
      nfds = 1;
    } else {
      if (sdone < sbytes) {
        fds[nfds].fd = send_fd;
        fds[nfds].events = POLLOUT;
        ++nfds;
      }
      if (rdone < rbytes) {
        fds[nfds].fd = recv_fd;
        fds[nfds].events = POLLIN;
        ++nfds;
      }
    }
    int wait = -1;
    if (deadline >= 0) {
      int64_t left = deadline - now_ms();
      if (left <= 0) return -3;
      wait = left > 1000000000 ? 1000000000 : static_cast<int>(left);
    }
    int pr = poll(fds, static_cast<nfds_t>(nfds), wait);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return -3;
    const int64_t before = sdone + rdone;
    for (int i = 0; i < nfds; ++i) {
      short rev = fds[i].revents;
      if (rev == 0) continue;
      const bool is_send =
          fds[i].fd == send_fd && (fds[i].events & POLLOUT) != 0;
      const bool is_recv =
          fds[i].fd == recv_fd && (fds[i].events & POLLIN) != 0;
      if (is_recv && (rev & (POLLIN | POLLHUP | POLLERR)) &&
          rdone < rbytes) {
        int rc = try_recv(recv_fd, rp, rbytes, &rdone);
        if (rc < 0) return rc;
      }
      if (is_send && (rev & POLLOUT) && sdone < sbytes) {
        int rc = try_send(send_fd, sp, sbytes, &sdone);
        if (rc < 0) return rc;
      }
      // POLLERR/POLLHUP with nothing readable: surface as closed/error
      if ((rev & (POLLERR | POLLNVAL)) && !(rev & POLLIN)) return -1;
      if ((rev & POLLHUP) && !(rev & POLLIN) && is_recv &&
          rdone < rbytes) {
        return -2;
      }
    }
    if (deadline >= 0 && sdone + rdone > before) {
      deadline = now_ms() + timeout_ms;  // progress resets idle timer
    }
  }
  return 0;
}

// One-directional steps (fold/unfold) call mp4j_sendrecv_raw with a
// null buffer on the inactive side; no separate entry points needed.

// ---------------------------------------------------------------------
// Multi-leg progress driver (ISSUE 11): the nonblocking-collective
// scheduler's byte mover. The Python engine hands down the set of
// RUNNABLE legs — the head of each per-(peer, direction) FIFO queue,
// so at most one send and one recv leg per fd — and this drives them
// all through ONE poll loop, moving bytes on whichever fd is ready.
// Cross-collective burst coalescing falls out: when collective k's
// send leg to a peer completes, k+1's leg enters the set on the next
// call and its bytes stream back-to-back into the same socket buffer,
// so the peer drains large bursts instead of ping-ponging per
// exchange — the mechanism that makes k outstanding collectives
// cheaper per byte than k sequential ones on a CPU-bound host.
//
// Contract: sockets are ALREADY nonblocking (the Python engine owns
// the mode for the batch). dones[i] is in-out progress. Returns the
// number of legs that newly completed (>= 1), 0 when timeout_ms
// elapsed without a completion (the Python side's fence-poll tick),
// or a negative error (-1 syscall, -2 peer closed) with status[i] set
// on the failing leg.
// ---------------------------------------------------------------------
int mp4j_progress_multi(const int32_t* fds, const int32_t* dirs,
                        void** bufs, const int64_t* lens,
                        int64_t* dones, int8_t* status, int32_t nlegs,
                        int64_t timeout_ms) {
  const int64_t deadline = now_ms() + (timeout_ms < 0 ? 0 : timeout_ms);
  for (int i = 0; i < nlegs; ++i) status[i] = 0;
  constexpr int kMaxFds = 256;  // the Python side slices leg sets to
                                // this bound per pass (FIFO-fair), so
                                // the cap is never an error in practice
  while (true) {
    // poll set: unique fds of incomplete legs, events OR-combined
    pollfd pfds[kMaxFds];
    int leg_of_pfd_send[kMaxFds];
    int leg_of_pfd_recv[kMaxFds];
    int npfd = 0;
    int pending = 0;
    for (int i = 0; i < nlegs; ++i) {
      if (dones[i] >= lens[i]) continue;
      ++pending;
      int slot = -1;
      for (int j = 0; j < npfd; ++j) {
        if (pfds[j].fd == fds[i]) {
          slot = j;
          break;
        }
      }
      if (slot < 0) {
        if (npfd >= kMaxFds) {
          status[i] = -1;  // name the overflowing leg for diagnostics
          return -1;
        }
        slot = npfd++;
        pfds[slot].fd = fds[i];
        pfds[slot].events = 0;
        leg_of_pfd_send[slot] = -1;
        leg_of_pfd_recv[slot] = -1;
      }
      if (dirs[i] == 0) {
        pfds[slot].events = static_cast<short>(pfds[slot].events | POLLOUT);
        leg_of_pfd_send[slot] = i;
      } else {
        pfds[slot].events = static_cast<short>(pfds[slot].events | POLLIN);
        leg_of_pfd_recv[slot] = i;
      }
    }
    if (pending == 0) return 0;
    int64_t left = deadline - now_ms();
    if (left < 0) left = 0;
    int pr = poll(pfds, static_cast<nfds_t>(npfd),
                  left > 1000000000 ? 1000000000 : static_cast<int>(left));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return 0;  // tick: the Python side polls the fence
    int completed = 0;
    for (int j = 0; j < npfd; ++j) {
      short rev = pfds[j].revents;
      if (rev == 0) continue;
      int ri = leg_of_pfd_recv[j];
      if (ri >= 0 && (rev & (POLLIN | POLLHUP | POLLERR)) &&
          dones[ri] < lens[ri]) {
        int rc = try_recv(pfds[j].fd, static_cast<char*>(bufs[ri]),
                          lens[ri], &dones[ri]);
        if (rc < 0) {
          status[ri] = static_cast<int8_t>(rc);
          return rc;
        }
        if (dones[ri] >= lens[ri]) ++completed;
      }
      int si = leg_of_pfd_send[j];
      if (si >= 0 && (rev & POLLOUT) && dones[si] < lens[si]) {
        int rc = try_send(pfds[j].fd, static_cast<const char*>(bufs[si]),
                          lens[si], &dones[si]);
        if (rc < 0) {
          status[si] = static_cast<int8_t>(rc);
          return rc;
        }
        if (dones[si] >= lens[si]) ++completed;
      }
      if ((rev & (POLLERR | POLLNVAL)) && !(rev & POLLIN)) {
        int bad = si >= 0 ? si : ri;
        if (bad >= 0) status[bad] = -1;
        return -1;
      }
      if ((rev & POLLHUP) && !(rev & POLLIN) && ri >= 0 &&
          dones[ri] < lens[ri]) {
        status[ri] = -2;
        return -2;
      }
    }
    if (completed > 0) return completed;
  }
}

// ---------------------------------------------------------------------
// Batch leg-graph driver (ISSUE 11): runs a WHOLE engine batch — every
// leg of every outstanding collective, with its dependency gates and
// reduce-merges — inside one native call, so the Python scheduler pays
// one call per batch instead of one per leg completion. Gates encode
// both orderings the engine needs: the per-(peer, direction) FIFO (a
// leg's queue predecessor) and the per-collective op sequence (the
// previous op's legs); a leg joins the poll set only once every gate
// leg has completed. A recv leg with a merge spec reduces natively
// (mp4j_reduce) CHUNK-GRANULARLY as bytes arrive: mchunk[i] is the
// merge step in elements (the per-link tuner-adapted chunk schedule;
// 0 = whole buffer), melems[i] the in-out merge cursor — every fully
// received chunk merges in the same pass, so the tail chunk's merge
// is all that remains at leg completion and dependents still only
// unblock behind a fully merged accumulator. mp4j_reduce is
// element-wise, so any chunk partition is bit-identical to the
// whole-buffer merge.
//
// Returns: 1 = every leg complete; 0 = timeout tick (caller polls the
// epoch fence and re-enters); 2 = wake_fd readable (new submissions to
// admit — the byte(s) are drained here); negative = error with
// status[i] set on the failing leg. dones[] is in-out, so the call is
// re-entrant across ticks/wakes.
// ---------------------------------------------------------------------
extern "C" int mp4j_reduce(int32_t dtype, int32_t op, void* acc,
                           const void* src, int64_t n);

static void merge_avail(void** mdst, void** msrc, const int32_t* mdtype,
                        const int32_t* mopcode, const int64_t* mcount,
                        const int64_t* mchunk, int64_t* melems,
                        const int64_t* lens, const int64_t* dones,
                        int ri) {
  if (mdst[ri] == nullptr || melems[ri] >= mcount[ri]) return;
  const int64_t isz = mcount[ri] > 0 ? lens[ri] / mcount[ri] : 0;
  if (isz <= 0) return;
  const int64_t avail = dones[ri] / isz;
  const int64_t step = mchunk[ri] > 0 ? mchunk[ri] : mcount[ri];
  while (melems[ri] < mcount[ri]) {
    int64_t hi = melems[ri] + step;
    if (hi > mcount[ri]) hi = mcount[ri];
    if (avail < hi) break;
    mp4j_reduce(mdtype[ri], mopcode[ri],
                static_cast<char*>(mdst[ri]) + melems[ri] * isz,
                static_cast<const char*>(msrc[ri]) + melems[ri] * isz,
                hi - melems[ri]);
    melems[ri] = hi;
  }
}

extern "C" int mp4j_run_legs(const int32_t* fds, const int32_t* dirs,
                             void** bufs, const int64_t* lens,
                             int64_t* dones, const int32_t* gates,
                             void** mdst, void** msrc,
                             const int32_t* mdtype,
                             const int32_t* mopcode,
                             const int64_t* mcount,
                             const int64_t* mchunk, int64_t* melems,
                             int8_t* status, int32_t nlegs,
                             int32_t wake_fd, int64_t timeout_ms) {
  const int64_t deadline = now_ms() + (timeout_ms < 0 ? 0 : timeout_ms);
  constexpr int kMax = 256;
  if (nlegs > kMax) return -1;
  while (true) {
    pollfd pfds[kMax + 1];
    int leg_send[kMax];
    int leg_recv[kMax];
    int npfd = 0;
    int pending = 0;
    for (int i = 0; i < nlegs; ++i) {
      if (dones[i] >= lens[i]) continue;
      ++pending;
      bool gated = false;
      for (int g = 0; g < 3; ++g) {
        int32_t pre = gates[i * 3 + g];
        if (pre >= 0 && dones[pre] < lens[pre]) {
          gated = true;
          break;
        }
      }
      if (gated) continue;
      int slot = -1;
      for (int j = 0; j < npfd; ++j) {
        if (pfds[j].fd == fds[i]) {
          slot = j;
          break;
        }
      }
      if (slot < 0) {
        slot = npfd++;
        pfds[slot].fd = fds[i];
        pfds[slot].events = 0;
        leg_send[slot] = -1;
        leg_recv[slot] = -1;
      }
      if (dirs[i] == 0) {
        pfds[slot].events = static_cast<short>(pfds[slot].events | POLLOUT);
        leg_send[slot] = i;
      } else {
        pfds[slot].events = static_cast<short>(pfds[slot].events | POLLIN);
        leg_recv[slot] = i;
      }
    }
    if (pending == 0) return 1;
    if (wake_fd >= 0) {
      pfds[npfd].fd = wake_fd;
      pfds[npfd].events = POLLIN;
      ++npfd;
    }
    int64_t left = deadline - now_ms();
    if (left < 0) left = 0;
    int pr = poll(pfds, static_cast<nfds_t>(npfd),
                  left > 1000000000 ? 1000000000 : static_cast<int>(left));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (pr == 0) return 0;  // tick: the caller polls the fence
    const int last = wake_fd >= 0 ? npfd - 1 : npfd;
    if (wake_fd >= 0 && (pfds[npfd - 1].revents & POLLIN)) {
      char sink[64];
      while (read(wake_fd, sink, sizeof(sink)) > 0) {
      }
      return 2;  // new submissions to admit
    }
    for (int j = 0; j < last; ++j) {
      short rev = pfds[j].revents;
      if (rev == 0) continue;
      int ri = leg_recv[j];
      if (ri >= 0 && (rev & (POLLIN | POLLHUP | POLLERR)) &&
          dones[ri] < lens[ri]) {
        int rc = try_recv(pfds[j].fd, static_cast<char*>(bufs[ri]),
                          lens[ri], &dones[ri]);
        if (rc < 0) {
          status[ri] = static_cast<int8_t>(rc);
          return rc;
        }
        merge_avail(mdst, msrc, mdtype, mopcode, mcount, mchunk,
                    melems, lens, dones, ri);
      }
      int si = leg_send[j];
      if (si >= 0 && (rev & POLLOUT) && dones[si] < lens[si]) {
        int rc = try_send(pfds[j].fd, static_cast<const char*>(bufs[si]),
                          lens[si], &dones[si]);
        if (rc < 0) {
          status[si] = static_cast<int8_t>(rc);
          return rc;
        }
      }
      if ((rev & (POLLERR | POLLNVAL)) && !(rev & POLLIN)) {
        int bad = si >= 0 ? si : ri;
        if (bad >= 0) status[bad] = -1;
        return -1;
      }
      if ((rev & POLLHUP) && !(rev & POLLIN) && ri >= 0 &&
          dones[ri] < lens[ri]) {
        status[ri] = -2;
        return -2;
      }
    }
  }
}

}  // extern "C"
