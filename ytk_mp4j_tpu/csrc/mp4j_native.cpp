// Native hot loops for the CPU socket reference path.
//
// The reference's per-round element-wise merge (operator.apply over the
// received segment, SURVEY.md section 3b step 2) is its CPU hot loop; here
// it is a templated C++ kernel driven through ctypes. A sorted-u64 merge
// kernel supports the sparse map path's key-union step.
//
// ABI: plain C, dispatch by (dtype code, op code). Codes must match
// ytk_mp4j_tpu/operators.py and ytk_mp4j_tpu/utils/native.py.

#include <cstdint>
#include <cstddef>
#include <algorithm>

namespace {

enum DType : int32_t {
  F64 = 0,
  F32 = 1,
  I32 = 2,
  I64 = 3,
  I16 = 4,
  I8 = 5,
};

enum OpCode : int32_t {
  SUM = 0,
  PROD = 1,
  MAX = 2,
  MIN = 3,
};

template <typename T, OpCode OP>
void reduce_loop(T* __restrict acc, const T* __restrict src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    if constexpr (OP == SUM) acc[i] += src[i];
    else if constexpr (OP == PROD) acc[i] *= src[i];
    else if constexpr (OP == MAX) acc[i] = std::max(acc[i], src[i]);
    else acc[i] = std::min(acc[i], src[i]);
  }
}

template <typename T>
int dispatch_op(int32_t op, T* acc, const T* src, int64_t n) {
  switch (op) {
    case SUM:  reduce_loop<T, SUM>(acc, src, n); return 0;
    case PROD: reduce_loop<T, PROD>(acc, src, n); return 0;
    case MAX:  reduce_loop<T, MAX>(acc, src, n); return 0;
    case MIN:  reduce_loop<T, MIN>(acc, src, n); return 0;
    default: return -1;
  }
}

}  // namespace

extern "C" {

// acc[i] = op(acc[i], src[i]) for i in [0, n). Returns 0 on success,
// -1 on unknown op, -2 on unknown dtype.
int mp4j_reduce(int32_t dtype, int32_t op, void* acc, const void* src,
                int64_t n) {
  switch (dtype) {
    case F64:
      return dispatch_op<double>(op, static_cast<double*>(acc),
                                 static_cast<const double*>(src), n);
    case F32:
      return dispatch_op<float>(op, static_cast<float*>(acc),
                                static_cast<const float*>(src), n);
    case I32:
      return dispatch_op<int32_t>(op, static_cast<int32_t*>(acc),
                                  static_cast<const int32_t*>(src), n);
    case I64:
      return dispatch_op<int64_t>(op, static_cast<int64_t*>(acc),
                                  static_cast<const int64_t*>(src), n);
    case I16:
      return dispatch_op<int16_t>(op, static_cast<int16_t*>(acc),
                                  static_cast<const int16_t*>(src), n);
    case I8:
      return dispatch_op<int8_t>(op, static_cast<int8_t*>(acc),
                                 static_cast<const int8_t*>(src), n);
    default:
      return -2;
  }
}

}  // extern "C"
