// Native libsvm/libffm chunk parser — the framework's data-loader hot
// path (configs[4]: ytk-learn streams 1TB of libsvm text; SURVEY.md
// section 1 flagship consumer). The Python per-token parser measured
// ~100k rows/s on the bench host and numpy string->number casts are no
// faster than Python's (~95 ns/item both ways, BASELINE.md round 5);
// this kernel parses the raw chunk bytes in one pass with hand-rolled
// int/float scanners and no intermediate strings.
//
// STRICT-SUBSET contract: this parser accepts exactly the common shape
// of what utils/libsvm.parse_line accepts (decimal int ids, ordinary
// float literals). Anything else — over-long lines, mixed widths,
// underscore literals, hex floats, inf/nan, out-of-int32 ids — returns
// a negative code and the Python caller replays the chunk through
// parse_line, which raises the exact diagnostic (or accepts the exotic
// valid forms at Python speed). It must NEVER accept what parse_line
// rejects.
//
// ABI: plain C via ctypes (see utils/native.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>

namespace {

// strtof is LC_NUMERIC-sensitive: under a comma-decimal locale it would
// refuse every "0.5" and silently push all parsing onto the Python
// replay path. Pin the C locale once (POSIX strtof_l).
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

inline bool is_space(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\f' || c == '\v';
}

// Python int() literal semantics, minus underscores: optional sign then
// decimal digits only. Overflow returns false (caller falls back).
bool parse_i64(const char* b, const char* e, int64_t* out) {
  if (b == e) return false;
  bool neg = false;
  if (*b == '+' || *b == '-') {
    neg = (*b == '-');
    ++b;
  }
  if (b == e) return false;
  int64_t v = 0;
  for (; b != e; ++b) {
    if (*b < '0' || *b > '9') return false;
    if (v > (INT64_MAX - (*b - '0')) / 10) return false;
    v = v * 10 + (*b - '0');
  }
  *out = neg ? -v : v;
  return true;
}

// Ordinary float literals only. The charset gate rejects C-only forms
// (hex floats "0x1p3") and word forms ("inf", "nan") BEFORE strtof can
// accept them — those must go through the Python float() path so the
// two parsers never disagree on acceptance.
bool parse_f32(const char* b, const char* e, float* out) {
  if (b == e) return false;
  for (const char* p = b; p != e; ++p) {
    char c = *p;
    if (!((c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.' ||
          c == 'e' || c == 'E'))
      return false;
  }
  char tmp[64];
  size_t n = (size_t)(e - b);
  if (n >= sizeof tmp) return false;
  memcpy(tmp, b, n);
  tmp[n] = '\0';
  char* endp = nullptr;
  // parse at DOUBLE precision then cast, matching the Python path's
  // float() -> np.float32 double rounding exactly: strtof's single
  // rounding diverges by 1 ulp on some literals (e.g.
  // "0.0000180163488039397634566"), which would make fast-path and
  // replay-path training bytes differ. Overflow -> +-inf, like float().
  double v = strtod_l(tmp, &endp, c_locale());
  if (endp != tmp + n) return false;
  *out = (float)v;
  return true;
}

}  // namespace

extern "C" {

// Parse a chunk of whole lines (newline-separated; blank lines are
// skipped). Output buffers are [max_rows, max_nnz] row-major and
// zero-filled by the caller (absent slots keep field/feat/val = 0, the
// padding convention of utils/libsvm.read_libsvm). libsvm tokens
// (feat:val) leave fields at 0; libffm tokens are field:feat:val; a
// line may use either width but not both (parse_line's rule).
// Returns 0 with *out_rows = parsed row count, or -1 on any refused
// line (caller replays in Python for diagnostics), or -2 if more than
// max_rows non-blank lines arrive.
int64_t mp4j_parse_libsvm(const char* buf, int64_t len, int32_t max_nnz,
                          int64_t max_rows, int32_t* feats,
                          int32_t* fields, float* vals, float* labels,
                          int64_t* out_rows) {
  int64_t row = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* eol = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!eol) eol = end;
    const char* q = p;
    while (q < eol && is_space(*q)) ++q;
    if (q == eol) {  // blank line
      p = eol + 1;
      continue;
    }
    if (row >= max_rows) return -2;
    const char* ts = q;
    while (q < eol && !is_space(*q)) ++q;
    if (!parse_f32(ts, q, &labels[row])) return -1;
    int32_t slot = 0;
    int width = 0;  // 0 until the line's first token decides
    for (;;) {
      while (q < eol && is_space(*q)) ++q;
      if (q == eol) break;
      ts = q;
      while (q < eol && !is_space(*q)) ++q;
      const char* c1 = (const char*)memchr(ts, ':', (size_t)(q - ts));
      if (!c1) return -1;
      const char* c2 =
          (const char*)memchr(c1 + 1, ':', (size_t)(q - c1 - 1));
      int w = c2 ? 3 : 2;
      if (c2 && memchr(c2 + 1, ':', (size_t)(q - c2 - 1))) return -1;
      if (width == 0) width = w;
      if (w != width) return -1;       // mixed widths on one line
      if (slot >= max_nnz) return -1;  // over-long line
      int64_t feat, field = 0;
      float v;
      if (w == 2) {
        if (!parse_i64(ts, c1, &feat)) return -1;
        if (!parse_f32(c1 + 1, q, &v)) return -1;
      } else {
        if (!parse_i64(ts, c1, &field)) return -1;
        if (!parse_i64(c1 + 1, c2, &feat)) return -1;
        if (!parse_f32(c2 + 1, q, &v)) return -1;
      }
      if (feat < INT32_MIN || feat > INT32_MAX || field < INT32_MIN ||
          field > INT32_MAX)
        return -1;  // replay raises OverflowError like the old path
      int64_t off = row * (int64_t)max_nnz + slot;
      feats[off] = (int32_t)feat;
      fields[off] = (int32_t)field;
      vals[off] = v;
      ++slot;
    }
    ++row;
    p = eol + 1;
  }
  *out_rows = row;
  return 0;
}

}  // extern "C"
