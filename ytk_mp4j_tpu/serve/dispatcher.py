"""Serve dispatch over the collective substrate (ISSUE 19).

Two dispatch shapes cover the four model families, chosen by the
servable's ``kind``:

- **pull** (linear / FM / FFM): the parameter table is sharded by
  ``row_id % size`` across the job's ranks (the serve mirror of the
  AOT ``ffm/sharded_serve`` owner-routed row fetch). Per batch the
  frontend broadcasts the list of row ids its cache is missing
  (``LONG`` header + ids on the binomial tree) and the rows come back
  in ONE ``allreduce_map`` on the columnar keycodec plane — owners
  contribute their rows, ownership is disjoint, so SUM is identity
  and every rank pays one vectorized merge. A warm cache means zero
  collectives for the batch.

- **reduce** (GBDT): every example visits every tree, so the ENSEMBLE
  is sharded (round ``t % size``) and the batch itself rides the
  wire: one fixed-shape float64 ``allreduce`` announces the batch
  (the frontend's request region sums against zeros), the next one
  collects it (every rank contributes its partial margins plus a
  contributor-bitmap bit). Every round is exactly ONE allreduce of
  ONE agreed shape, which is what makes the chaos story honest — a
  replacement rank adopted mid-stream (PR 10 machinery) just joins
  the next round; the batch it could not score shows up as a bitmap
  gap, is counted ``serve/degraded_batches``, and is still DELIVERED
  (status DEGRADED), never hung.

The frontend is rank 0: it owns the :class:`MicroBatcher` (whose one
dispatch thread is the only caller of the comm — collectives are
ordered, so request concurrency must be funneled), the hot-key cache
and the latency/QPS metrics. All other ranks run :func:`serve_worker`
until the frontend's STOP round.
"""

from __future__ import annotations

import time

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.serve.batcher import MicroBatcher
from ytk_mp4j_tpu.serve.cache import HotKeyCache, validate_version
from ytk_mp4j_tpu.serve import framing
from ytk_mp4j_tpu.utils import tuning

# pull-plane round ops (header slot 0)
OP_STOP = 0
OP_PULL = 1
# reduce-plane round ops (buffer slot 0; the frontend is the only
# writer of the op slot, so the summed value IS the op)
OP_BATCH = 1
OP_FLUSH = 2

_HDR = 4          # reduce buffer header slots: [op, n, seq, reserved]
_QPS_WINDOW_SECS = 5.0


class _ReduceLayout:
    """The agreed reduce-round buffer layout — a pure function of
    (max_batch, req_width, resp_width, size), identical on every rank
    (mp4j-lint R8 discipline: the shape IS the wire protocol)."""

    def __init__(self, max_batch: int, req_width: int,
                 resp_width: int, size: int):
        self.max_batch = max_batch
        self.req_width = req_width
        self.resp_width = resp_width
        self.size = size
        self.off_req = _HDR
        self.off_resp = self.off_req + max_batch * req_width
        self.off_bm = self.off_resp + max_batch * resp_width
        self.total = self.off_bm + size

    def new_buf(self) -> np.ndarray:
        return np.zeros(self.total, np.float64)

    def put_batch(self, buf, bins: np.ndarray) -> None:
        n = bins.shape[0]
        buf[self.off_req:self.off_req + n * self.req_width] = \
            bins.astype(np.float64).ravel()

    def get_batch(self, buf, n: int) -> np.ndarray:
        flat = buf[self.off_req:self.off_req + n * self.req_width]
        return np.rint(flat).astype(np.int64).reshape(
            n, self.req_width)

    def put_partials(self, buf, part: np.ndarray, rank: int) -> None:
        n = part.shape[0]
        buf[self.off_resp:self.off_resp + n * self.resp_width] = \
            part.ravel()
        buf[self.off_bm + rank] = 1.0

    def get_margins(self, buf, n: int) -> np.ndarray:
        flat = buf[self.off_resp:self.off_resp + n * self.resp_width]
        return flat.reshape(n, self.resp_width)

    def contributors(self, buf) -> int:
        return int(np.rint(
            buf[self.off_bm:self.off_bm + self.size]).sum())


class ServeFrontend:
    """Rank 0's serve plane: micro-batcher + hot-key cache + sharded
    dispatch + first-class latency/QPS/hit-rate metrics.

    ``deadline_ms`` / ``max_batch`` / ``cache_rows`` /
    ``stale_versions`` fall back to the ``MP4J_SERVE_*`` knobs.
    ``max_batch`` is JOB-wide for reduce-kind servables (it sizes the
    agreed allreduce buffer): run every rank's :func:`serve_worker`
    with the same value.
    """

    def __init__(self, comm, servable, deadline_ms=None,
                 max_batch=None, cache_rows=None, stale_versions=None,
                 version: int = 0):
        if comm.rank != 0:
            raise Mp4jError(
                f"ServeFrontend must run on rank 0, got rank "
                f"{comm.rank}")
        self._comm = comm
        self._servable = servable
        self._size = comm.slave_num
        self.version = validate_version(version)
        self._metrics = comm.metrics_registry()
        self._cache = (HotKeyCache(cache_rows, stale_versions)
                       if servable.kind == "pull" else None)
        self._layout = None
        if servable.kind == "reduce":
            self._layout = _ReduceLayout(
                tuning.serve_max_batch(max_batch),
                servable.req_width, servable.resp_width, self._size)
        self._seq = 0
        self._requests = 0
        self._stale_prev = 0
        self.degraded_batches = 0
        self._qps_win = metrics_mod.RateWindow(_QPS_WINDOW_SECS)
        self._closed = False
        self._batcher = MicroBatcher(
            self._dispatch, deadline_ms=deadline_ms,
            max_batch=max_batch, on_batch=self._note_batch,
            on_latency=self._note_latency)

    # -- request side ---------------------------------------------------
    def submit(self, req):
        """Enqueue one request payload (the family's array triplet /
        binned vector); returns a ``ServeFuture`` resolving to the
        float64 prediction vector."""
        return self._batcher.submit(req)

    def predict(self, req, timeout: float = 60.0) -> np.ndarray:
        """Blocking single-request convenience: submit + wait."""
        return self.submit(req).wait(timeout)

    def submit_frame(self, frame: bytes):
        """Framed entry (``serve/framing``): decode one request frame,
        enqueue it; returns ``(req_id, future)``."""
        family, req_id, ids, fields, vals = framing.decode_request(
            frame)
        if family != self._servable.family:
            raise Mp4jError(
                f"frame family {family!r} does not match servable "
                f"{self._servable.family!r}")
        if family == "gbdt":
            return req_id, self._batcher.submit(ids)
        return req_id, self._batcher.submit((ids, fields, vals))

    def bump_version(self) -> int:
        """Advance the live model version (a table republish): cached
        rows stamped more than ``stale_versions`` bumps ago become
        misses from here on."""
        self.version += 1
        return self.version

    def cache_stats(self) -> dict:
        return self._cache.stats() if self._cache is not None else {}

    def close(self, timeout: float = 30.0) -> None:
        """Drain the batcher, then fan the STOP round out to the
        workers (idempotent)."""
        if self._closed:
            return
        self._batcher.close(timeout=timeout)
        self._closed = True
        if self._size > 1:
            if self._servable.kind == "pull":
                header = np.asarray([OP_STOP, 0], np.int64)
                self._comm.broadcast_array(header, Operands.LONG,
                                           root=0)
            else:
                buf = self._layout.new_buf()
                buf[0] = OP_STOP
                self._comm.allreduce_array(buf, Operands.DOUBLE,
                                           Operators.SUM)

    # -- dispatch thread ------------------------------------------------
    def _dispatch(self, reqs: list) -> list:
        if self._servable.kind == "pull":
            preds = self._dispatch_pull(reqs)
        else:
            preds = self._dispatch_reduce(reqs)
        self._requests += len(reqs)
        self._qps_win.note(time.monotonic(),
                           {"requests": self._requests})
        qps = self._qps_win.rates().get("requests_per_sec", 0.0)
        self._metrics.set_gauge("serve/qps", qps)
        self._metrics.inc("serve/requests", len(reqs))
        return preds

    def _dispatch_pull(self, reqs: list) -> list:
        need: dict[int, np.ndarray | None] = {}
        for req in reqs:
            for rid in self._servable.row_ids(req):
                need.setdefault(int(rid), None)
        miss = []
        for rid in need:
            row = self._cache.lookup(rid, self.version)
            if row is None:
                miss.append(rid)
            else:
                need[rid] = row
        self._metrics.inc("serve/cache_hits",
                          len(need) - len(miss))
        self._metrics.inc("serve/cache_misses", len(miss))
        if miss:
            ids = np.asarray(sorted(miss), np.int64)
            if self._size > 1:
                header = np.asarray([OP_PULL, ids.shape[0]], np.int64)
                self._comm.broadcast_array(header, Operands.LONG,
                                           root=0)
                self._comm.broadcast_array(ids, Operands.LONG, root=0)
            pulled = _owned_rows(self._servable, ids, 0, self._size)
            if self._size > 1:
                pulled = self._comm.allreduce_map(
                    pulled, Operands.DOUBLE, Operators.SUM)
            self._metrics.inc("serve/pull_rows", len(pulled))
            for rid in miss:
                row = pulled.get(rid)
                if row is not None:
                    need[rid] = row
                    self._cache.insert(rid, row, self.version)
        self._metrics.set_gauge("serve/cache_rows", len(self._cache))
        rowmap = {k: v for k, v in need.items() if v is not None}
        if len(rowmap) != len(need):
            # rows nobody owns (out-of-vocabulary ids): delivered as
            # zero-contribution, surfaced as a degraded batch
            self.degraded_batches += 1
            self._metrics.inc("serve/degraded_batches")
        return self._servable.predict_sharded(reqs, rowmap)

    def _dispatch_reduce(self, reqs: list) -> list:
        lay = self._layout
        n = len(reqs)
        bins = np.stack([np.asarray(r, np.int64).reshape(-1)
                         for r in reqs])
        if bins.shape[1] != lay.req_width:
            raise Mp4jError(
                f"gbdt serve request width {bins.shape[1]} != "
                f"n_features {lay.req_width}")
        self._seq += 1
        if self._size > 1:
            # round 1: announce the batch
            buf = lay.new_buf()
            buf[0] = OP_BATCH
            buf[1] = float(n)
            buf[2] = float(self._seq)
            lay.put_batch(buf, bins)
            self._comm.allreduce_array(buf, Operands.DOUBLE,
                                       Operators.SUM)
        # round 2: collect — the frontend contributes its own shard
        buf = lay.new_buf()
        buf[0] = OP_FLUSH
        buf[1] = float(n)
        buf[2] = float(self._seq)
        lay.put_partials(
            buf, self._servable.partial_margins(bins, 0, self._size),
            0)
        if self._size > 1:
            self._comm.allreduce_array(buf, Operands.DOUBLE,
                                       Operators.SUM)
        if lay.contributors(buf) != self._size:
            # a replacement rank joined mid-batch and could not score
            # it: deliver the partial margin, say so
            self.degraded_batches += 1
            self._metrics.inc("serve/degraded_batches")
        return self._servable.link(lay.get_margins(buf, n))

    # -- metrics hooks (called from the batcher's dispatch thread) ------
    def _note_batch(self, n: int, reason: str, wait_secs: float) -> None:
        self._metrics.inc("serve/batches")
        if reason == "full":
            self._metrics.inc("serve/batch_full")
        elif reason == "deadline":
            self._metrics.inc("serve/batch_deadline")
        if self._cache is not None:
            # registry counters take deltas; the cache keeps lifetimes
            d = self._cache.stale - self._stale_prev
            if d:
                self._metrics.inc("serve/cache_stale", d)
                self._stale_prev = self._cache.stale

    def _note_latency(self, secs: float) -> None:
        self._metrics.observe("latency/serve_request", secs,
                              metrics_mod.LATENCY_LO,
                              metrics_mod.LATENCY_BUCKETS)


def _owned_rows(servable, ids: np.ndarray, rank: int,
                size: int) -> dict:
    """This rank's contribution to a pull round: the rows it OWNS
    (``row_id % size == rank``), fetched in one vectorized lookup.
    Ids outside the servable's table are nobody's (the frontend
    reports the batch degraded), never an exception mid-collective."""
    owned = ids[(ids % size) == rank]
    owned = owned[(owned >= 0) & (owned < servable.n_rows)]
    if owned.shape[0] == 0:
        return {}
    mat = servable.rows(owned)
    return {int(rid): mat[j] for j, rid in enumerate(owned)}


def serve_worker(comm, servable, max_batch=None) -> dict:
    """Every non-frontend rank's serve loop: answer pull / reduce
    rounds until the frontend's STOP. Returns the worker's round
    counters (handy for tests and bench bodies).

    ``max_batch`` must match the frontend's for reduce-kind servables
    (it sizes the agreed buffer) — both default to
    ``MP4J_SERVE_MAX_BATCH``, so env-configured jobs agree for free.
    """
    metrics = comm.metrics_registry()
    rank, size = comm.rank, comm.slave_num
    rounds = pulls = 0
    if servable.kind == "pull":
        while True:
            header = np.zeros(2, np.int64)
            comm.broadcast_array(header, Operands.LONG, root=0)
            if int(header[0]) == OP_STOP:
                break
            nids = int(header[1])
            ids = np.zeros(nids, np.int64)
            comm.broadcast_array(ids, Operands.LONG, root=0)
            contrib = _owned_rows(servable, ids, rank, size)
            comm.allreduce_map(contrib, Operands.DOUBLE,
                               Operators.SUM)
            rounds += 1
            pulls += nids
            metrics.inc("serve/worker_rounds")
    else:
        lay = _ReduceLayout(tuning.serve_max_batch(max_batch),
                            servable.req_width, servable.resp_width,
                            size)
        pending = None        # (bins of the announced batch)
        while True:
            buf = lay.new_buf()
            if pending is not None:
                lay.put_partials(
                    buf,
                    servable.partial_margins(pending, rank, size),
                    rank)
            comm.allreduce_array(buf, Operands.DOUBLE, Operators.SUM)
            op = int(np.rint(buf[0]))
            if op == OP_STOP:
                break
            if op == OP_BATCH:
                pending = lay.get_batch(buf, int(np.rint(buf[1])))
            else:                               # OP_FLUSH
                pending = None
            rounds += 1
            metrics.inc("serve/worker_rounds")
    return {"rounds": rounds, "pull_ids": pulls, "rank": rank}
