"""Serve request/response wire framing (ISSUE 19).

The external face of the serve plane: a compact little-endian binary
frame an edge proxy can speak without importing this package. One
request shape covers all four model families — three parallel arrays
(``ids``/``fields``/``vals``) whose meaning the family tag fixes:

- ``linear``: ids = feature indices, vals = feature values (fields
  unused, all zero);
- ``fm`` / ``ffm``: the padded-sparse instance triplet the trainers
  stage (``_stage_instances``): feature ids, field ids, values;
- ``gbdt``: ids = the binned feature vector (one bin per feature, in
  feature order; fields/vals unused).

Responses carry float64 predictions (length 1, or ``n_classes`` for
softmax families) plus a status byte — ``DEGRADED`` is a real,
deliverable outcome (a reduce-mode batch scored while a replacement
rank was still warming up), distinct from ``ERROR``.

Framing is PURE bytes <-> arrays: no sockets live here. The dispatch
plane rides the collective substrate's own channels; this module is
what a TCP/HTTP front door would wrap, and what the round-trip tests
pin so the layout cannot drift silently.
"""

from __future__ import annotations

import struct

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError

FAMILIES = ("linear", "fm", "ffm", "gbdt")

STATUS_OK = 0
STATUS_ERROR = 1
STATUS_DEGRADED = 2

_REQ_MAGIC = b"Mq"
_RSP_MAGIC = b"Mr"
_VERSION = 1
# magic[2] ver u8 family u8 req_id u64 n u32
_REQ_HEAD = struct.Struct("<2sBBQI")
# magic[2] ver u8 status u8 req_id u64 n u32
_RSP_HEAD = struct.Struct("<2sBBQI")
# frame sanity bound: a request is a single instance, a response a
# single prediction vector — megabytes mean a corrupt length field,
# not a real payload
_MAX_ITEMS = 1 << 20


def encode_request(family: str, req_id: int, ids, fields=None,
                   vals=None) -> bytes:
    """One instance -> one request frame."""
    if family not in FAMILIES:
        raise Mp4jError(f"unknown serve family {family!r}")
    ids = np.ascontiguousarray(np.asarray(ids, np.int64))
    n = ids.shape[0]
    fields = (np.zeros(n, np.int32) if fields is None
              else np.ascontiguousarray(np.asarray(fields, np.int32)))
    vals = (np.zeros(n, np.float32) if vals is None
            else np.ascontiguousarray(np.asarray(vals, np.float32)))
    if fields.shape != (n,) or vals.shape != (n,):
        raise Mp4jError(
            f"request arrays must share length: ids[{n}], "
            f"fields{list(fields.shape)}, vals{list(vals.shape)}")
    head = _REQ_HEAD.pack(_REQ_MAGIC, _VERSION,
                          FAMILIES.index(family), int(req_id), n)
    return head + ids.tobytes() + fields.tobytes() + vals.tobytes()


def decode_request(buf: bytes):
    """Request frame -> ``(family, req_id, ids, fields, vals)``;
    raises ``Mp4jError`` on anything malformed (bad magic/version,
    truncated arrays, absurd lengths)."""
    if len(buf) < _REQ_HEAD.size:
        raise Mp4jError(f"request frame truncated at {len(buf)} bytes")
    magic, ver, fam, req_id, n = _REQ_HEAD.unpack_from(buf)
    if magic != _REQ_MAGIC or ver != _VERSION:
        raise Mp4jError(
            f"bad request frame header {magic!r} v{ver}")
    if fam >= len(FAMILIES) or n > _MAX_ITEMS:
        raise Mp4jError(f"bad request frame: family {fam}, n {n}")
    need = _REQ_HEAD.size + n * (8 + 4 + 4)
    if len(buf) != need:
        raise Mp4jError(
            f"request frame length {len(buf)} != expected {need}")
    off = _REQ_HEAD.size
    ids = np.frombuffer(buf, np.int64, n, off).copy()
    off += 8 * n
    fields = np.frombuffer(buf, np.int32, n, off).copy()
    off += 4 * n
    vals = np.frombuffer(buf, np.float32, n, off).copy()
    return FAMILIES[fam], req_id, ids, fields, vals


def encode_response(req_id: int, preds,
                    status: int = STATUS_OK) -> bytes:
    """One prediction vector -> one response frame."""
    if status not in (STATUS_OK, STATUS_ERROR, STATUS_DEGRADED):
        raise Mp4jError(f"bad response status {status}")
    preds = np.ascontiguousarray(
        np.atleast_1d(np.asarray(preds, np.float64)))
    head = _RSP_HEAD.pack(_RSP_MAGIC, _VERSION, status, int(req_id),
                          preds.shape[0])
    return head + preds.tobytes()


def decode_response(buf: bytes):
    """Response frame -> ``(req_id, preds, status)``."""
    if len(buf) < _RSP_HEAD.size:
        raise Mp4jError(
            f"response frame truncated at {len(buf)} bytes")
    magic, ver, status, req_id, n = _RSP_HEAD.unpack_from(buf)
    if magic != _RSP_MAGIC or ver != _VERSION:
        raise Mp4jError(
            f"bad response frame header {magic!r} v{ver}")
    if n > _MAX_ITEMS:
        raise Mp4jError(f"bad response frame: n {n}")
    need = _RSP_HEAD.size + 8 * n
    if len(buf) != need:
        raise Mp4jError(
            f"response frame length {len(buf)} != expected {need}")
    preds = np.frombuffer(buf, np.float64, n, _RSP_HEAD.size).copy()
    return req_id, preds, status
