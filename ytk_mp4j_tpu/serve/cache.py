"""Hot-key row cache for the serve plane (ISSUE 19).

An LRU of pulled parameter rows keyed by the SAME integer row ids the
``comm/keycodec`` vocabularies carry on the columnar map plane — a
cache hit means one fewer id in the next pull round's key-union, so
under a zipf-ish request mix the steady state is zero collectives per
batch (every hot row resident) and the pull plane only moves tail
keys.

Staleness is a FIRST-CLASS bound, not a hope: every row is stamped
with the model version it was pulled under, and a lookup whose stamp
lags the frontend's live version by more than ``stale_versions`` bumps
is a MISS (counted separately as ``serve/cache_stale``), so the
operator-facing guarantee is "a served row is at most N versions
behind the table" — with the default bound of 0, a version bump
atomically invalidates everything older.

Single-owner by design: only the frontend's dispatch thread touches
the cache (the batcher serializes dispatches), so there is no lock —
adding one here would be the start of a lock-order story the serve
plane doesn't need (mp4j-lint R19/R20 keep it honest).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.utils import tuning


class HotKeyCache:
    """LRU of ``{row_id: (version_stamp, row vector)}`` with hit /
    miss / eviction / staleness accounting.

    ``capacity_rows == 0`` disables the cache (every lookup is a miss,
    nothing is retained) — the bench A/B knob, so the amortization
    figure measures batching alone.
    """

    def __init__(self, capacity_rows: int | None = None,
                 stale_versions: int | None = None):
        self.capacity = tuning.serve_cache_rows(capacity_rows)
        self.stale_versions = tuning.serve_stale_versions(stale_versions)
        self._rows: OrderedDict[int, tuple[int, np.ndarray]] = \
            OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stale = 0

    def __len__(self) -> int:
        return len(self._rows)

    def lookup(self, row_id: int, version: int):
        """The cached row vector, or ``None`` on a miss. A resident
        row whose stamp lags ``version`` past the staleness bound is
        dropped and counted BOTH stale and miss — the staleness figure
        explains the miss, it does not replace it."""
        ent = self._rows.get(row_id)
        if ent is None:
            self.misses += 1
            return None
        stamp, row = ent
        if version - stamp > self.stale_versions:
            del self._rows[row_id]
            self.stale += 1
            self.misses += 1
            return None
        self._rows.move_to_end(row_id)
        self.hits += 1
        return row

    def insert(self, row_id: int, row: np.ndarray, version: int) -> None:
        """Stamp + retain a pulled row; evicts the least recently used
        row when full. A no-op at capacity 0."""
        if self.capacity == 0:
            return
        if row_id in self._rows:
            self._rows.move_to_end(row_id)
        self._rows[row_id] = (version, row)
        while len(self._rows) > self.capacity:
            self._rows.popitem(last=False)
            self.evictions += 1

    def hit_rate(self) -> float:
        """Lifetime hit fraction (0.0 before any lookup)."""
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        """Counter snapshot for the metrics plane / tests."""
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "stale": self.stale,
                "rows": len(self._rows), "capacity": self.capacity,
                "stale_versions": self.stale_versions,
                "hit_rate": self.hit_rate()}


def validate_version(version: int) -> int:
    """Model versions are monotone non-negative ints — the staleness
    bound's arithmetic depends on it."""
    v = int(version)
    if v < 0:
        raise Mp4jError(f"model version={version} must be >= 0")
    return v
