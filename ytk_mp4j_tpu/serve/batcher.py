"""Micro-batch accumulation under a latency deadline (ISSUE 19).

The serve front end's core tradeoff: one collective round per REQUEST
is latency-optimal and throughput-terrible (every round pays the full
substrate latency for one example); one round per large batch is the
reverse. The :class:`MicroBatcher` buys amortization without an
unbounded tail — the OLDEST queued request waits at most
``deadline_ms`` (``MP4J_SERVE_DEADLINE_MS``) before whatever has
accumulated dispatches, and a batch that reaches ``max_batch``
(``MP4J_SERVE_MAX_BATCH``) dispatches immediately without waiting the
deadline out.

One dispatch thread owns every downstream collective: the substrate's
collectives are ordered per comm, so request concurrency MUST be
funneled through a single caller — callers enqueue under the
condition variable and block on a :class:`ServeFuture`, never on the
comm itself. All deadline arithmetic is on the monotonic clock and
every blocking wait in here carries an explicit timeout (mp4j-lint
R28 — authored alongside this module — flags anything else in
``serve/``).
"""

from __future__ import annotations

import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.utils import tuning

# idle-poll backstop for the dispatch thread's condition waits: the
# notify on submit()/close() is the real wakeup, the timeout only
# bounds a lost-wakeup pathology (and satisfies the R28 contract that
# no serve-path wait is unbounded)
_IDLE_WAIT_SECS = 0.2
# join budget for close(): generous vs any single dispatch (which is
# itself deadline-bounded), tiny vs a hang
_CLOSE_JOIN_SECS = 30.0


class ServeFuture:
    """Deferred prediction for one enqueued request — the serve twin
    of ``comm/progress.CollectiveFuture`` (same Event-publication
    shape, same wait-without-consuming timeout contract)."""

    __slots__ = ("_done", "_result", "_exc")

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None):
        """Block until the batch containing this request completes;
        returns the prediction or re-raises the dispatch failure. A
        ``timeout`` expiry raises ``Mp4jError`` without consuming the
        future (wait again)."""
        if not self._done.wait(timeout):
            raise Mp4jError(
                f"serve future not complete after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    # the concurrent.futures-familiar spelling
    def result(self, timeout: float | None = None):
        return self.wait(timeout)

    def _resolve(self, value) -> None:
        self._result = value
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()


class MicroBatcher:
    """Accumulate requests into micro-batches and hand them to
    ``dispatch_fn(requests) -> results`` on a single owned thread.

    ``dispatch_fn`` receives the batched request payloads in enqueue
    order and must return one result per request (or raise — the
    failure fans out to every future of the batch, and the batcher
    keeps serving subsequent batches: one poisoned batch is not a
    dead plane).
    """

    def __init__(self, dispatch_fn, deadline_ms=None, max_batch=None,
                 on_batch=None, on_latency=None,
                 name: str = "mp4j-serve-batcher"):
        self.deadline_secs = tuning.serve_deadline_ms(deadline_ms) / 1e3
        self.max_batch = tuning.serve_max_batch(max_batch)
        self._dispatch_fn = dispatch_fn
        self._on_batch = on_batch     # (n, reason, wait_secs) observer
        self._on_latency = on_latency  # per-request enqueue->resolve secs
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # [(payload, future, t_enqueue_monotonic)], enqueue order
        self._queue: list = []
        self._closed = False
        self.batches = 0
        self.batch_full = 0           # dispatched because max_batch hit
        self.batch_deadline = 0       # dispatched because deadline hit
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._thread.start()

    # -- caller side ----------------------------------------------------
    def submit(self, payload) -> ServeFuture:
        """Enqueue one request; returns its :class:`ServeFuture`."""
        fut = ServeFuture()
        with self._cond:
            if self._closed:
                raise Mp4jError("serve batcher is closed")
            self._queue.append((payload, fut, time.monotonic()))
            self._cond.notify()
        return fut

    def close(self, timeout: float = _CLOSE_JOIN_SECS) -> None:
        """Stop accepting requests, drain what is queued, join the
        dispatch thread (bounded), fail anything still undelivered."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        with self._cond:
            leftovers = [f for _p, f, _t in self._queue]
            self._queue.clear()
        for fut in leftovers:
            fut._fail(Mp4jError("serve batcher closed before dispatch"))

    # -- dispatch thread ------------------------------------------------
    def _collect(self):
        """Block (bounded waits only) until a batch is due; pops and
        returns ``(entries, reason)`` — ``reason`` is ``"full"``,
        ``"deadline"`` or ``"drain"`` — or ``(None, "")`` at shutdown
        with an empty queue."""
        with self._cond:
            while True:
                if self._queue and self._closed:
                    # drain mode: no more arrivals are possible, so
                    # waiting out the deadline buys nothing
                    return self._pop_locked(), "drain"
                if len(self._queue) >= self.max_batch:
                    return self._pop_locked(), "full"
                if self._queue:
                    due = self._queue[0][2] + self.deadline_secs
                    remaining = due - time.monotonic()
                    if remaining <= 0:
                        return self._pop_locked(), "deadline"
                    self._cond.wait(timeout=remaining)
                elif self._closed:
                    return None, ""
                else:
                    self._cond.wait(timeout=_IDLE_WAIT_SECS)

    def _pop_locked(self) -> list:
        batch = self._queue[:self.max_batch]
        del self._queue[:self.max_batch]
        return batch

    def _run(self) -> None:
        while True:
            entries, reason = self._collect()
            if entries is None:
                return
            self.batches += 1
            if reason == "full":
                self.batch_full += 1
            elif reason == "deadline":
                self.batch_deadline += 1
            # oldest request's accumulation wait — the deadline the
            # batcher is accountable for (dispatch latency downstream
            # of here belongs to the collective substrate)
            wait_secs = time.monotonic() - entries[0][2]
            payloads = [p for p, _f, _t in entries]
            try:
                results = self._dispatch_fn(payloads)
            except BaseException as exc:  # fan the failure out
                for _p, fut, _t in entries:
                    fut._fail(exc)
                if self._on_batch is not None:
                    self._on_batch(len(entries), "error", wait_secs)
                continue
            if len(results) != len(entries):
                exc = Mp4jError(
                    f"dispatch returned {len(results)} results for "
                    f"{len(entries)} requests")
                for _p, fut, _t in entries:
                    fut._fail(exc)
                continue
            for (_p, fut, _t), res in zip(entries, results):
                fut._resolve(res)
            if self._on_latency is not None:
                now = time.monotonic()
                for _p, _f, t_enq in entries:
                    self._on_latency(now - t_enq)
            if self._on_batch is not None:
                self._on_batch(len(entries), reason, wait_secs)
