"""mp4j-serve (ISSUE 19): the sharded low-latency inference plane.

The first workload after 18 PRs of training substrate: a micro-
batching front end (``batcher``), a hot-key row cache keyed through
the persistent keycodec vocabularies (``cache``), binary
request/response framing (``framing``) and the collective-substrate
dispatch planes (``dispatcher`` — pull rows for the embedding
families, reduce margins for GBDT). See README "Serving".
"""

from ytk_mp4j_tpu.serve.batcher import MicroBatcher, ServeFuture
from ytk_mp4j_tpu.serve.cache import HotKeyCache
from ytk_mp4j_tpu.serve.dispatcher import ServeFrontend, serve_worker
from ytk_mp4j_tpu.serve.framing import (STATUS_DEGRADED, STATUS_ERROR,
                                        STATUS_OK, decode_request,
                                        decode_response, encode_request,
                                        encode_response)

__all__ = [
    "MicroBatcher", "ServeFuture", "HotKeyCache", "ServeFrontend",
    "serve_worker", "encode_request", "decode_request",
    "encode_response", "decode_response", "STATUS_OK", "STATUS_ERROR",
    "STATUS_DEGRADED",
]
