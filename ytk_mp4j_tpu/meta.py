"""Segment / partition metadata math.

The reference carries per-rank segment descriptors (``ArrayMetaData`` /
``MapMetaData``, SURVEY.md section 2, expected path ``meta/`` [U]) that
describe how an array range ``[from, to)`` is split across ranks for
reduce-scatter / scatter / gather, and how map keys are partitioned.

Both the TPU path and the CPU socket reference path in this rebuild share
THIS module's partition math so that differential tests compare
bit-identical segment layouts.

Block distribution rule: for ``n`` elements over ``p`` ranks, ranks
``0..(n % p - 1)`` get ``ceil(n / p)`` elements and the rest get
``floor(n / p)``, in rank order. This is the standard MPI block
distribution; the reference's exact rule is unverified (mount empty), so
this is a pinned free choice — documented here as the single source of
truth.
"""

from __future__ import annotations

from ytk_mp4j_tpu.exceptions import Mp4jError


def partition_sizes(length: int, parts: int) -> list[int]:
    """Sizes of each rank's block for ``length`` elements over ``parts``."""
    if parts <= 0:
        raise Mp4jError(f"parts must be positive, got {parts}")
    if length < 0:
        raise Mp4jError(f"length must be non-negative, got {length}")
    base, rem = divmod(length, parts)
    return [base + 1 if r < rem else base for r in range(parts)]


def partition_range(lo: int, hi: int, parts: int) -> list[tuple[int, int]]:
    """Split ``[lo, hi)`` into ``parts`` contiguous blocks (block rule above).

    Returns a list of ``(start, end)`` half-open ranges, one per rank.
    Empty ranges (``start == end``) are legal when ``hi - lo < parts``.
    """
    if hi < lo:
        raise Mp4jError(f"invalid range [{lo}, {hi})")
    sizes = partition_sizes(hi - lo, parts)
    out = []
    start = lo
    for s in sizes:
        out.append((start, start + s))
        start += s
    return out


def owner_of(index: int, lo: int, hi: int, parts: int) -> int:
    """Rank owning ``index`` under ``partition_range(lo, hi, parts)``."""
    if not (lo <= index < hi):
        raise Mp4jError(f"index {index} outside [{lo}, {hi})")
    length = hi - lo
    base, rem = divmod(length, parts)
    off = index - lo
    cut = rem * (base + 1)
    if off < cut:
        return off // (base + 1)
    if base == 0:
        raise Mp4jError(f"index {index} beyond last non-empty block")
    return rem + (off - cut) // base


def key_partition(key, parts: int) -> int:
    """Stable hash partition of a map key across ranks.

    Used by scatter_map / reduce_scatter_map on BOTH backends so
    differential tests see identical key placement. Python's builtin
    ``hash`` is salted per-process (PYTHONHASHSEED), so a keyed-stable
    blake2b digest of the key's string form is used instead.

    Integral keys are canonicalized through ``__index__`` first:
    ``repr(np.int64(5))`` is ``"np.int64(5)"`` on numpy >= 2, which
    would place the same logical key differently than python ``5`` (and
    differently than the key codecs, which decode to python ints).
    bool is deliberately NOT canonicalized — it would collide with 0/1.
    """
    import hashlib

    if not isinstance(key, bool):
        try:
            key = key.__index__()
        except (AttributeError, TypeError):
            pass
    h = hashlib.blake2b(repr(key).encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "little") % parts


def check_partition_rank(p: int, parts: int, key) -> int:
    """Validate a user ``partitioner``'s placement. Shared by every
    backend's ``scatter_map`` so they agree on bad output: without
    this, a buggy partitioner returning -1 would silently wrap to the
    last rank via Python negative indexing on one backend and raise on
    another."""
    if not (0 <= p < parts):
        raise Mp4jError(
            f"partitioner placed key {key!r} on rank {p}, outside "
            f"[0, {parts})")
    return p


def padded_block(length: int, parts: int) -> int:
    """Per-rank block size when padding ``length`` up to a multiple of
    ``parts`` (used by the TPU path, which needs equal static shapes)."""
    if parts <= 0:
        raise Mp4jError(f"parts must be positive, got {parts}")
    return -(-length // parts)
