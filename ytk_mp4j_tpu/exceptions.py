"""Library-wide exception type.

Mirrors the reference's single checked exception ``Mp4jException``
(SURVEY.md section 2, expected path ``exception/Mp4jException.java`` [U]).
"""


class Mp4jError(Exception):
    """Raised for any mp4j-level failure (rendezvous, transport, shape/type
    mismatches, collective misuse)."""
