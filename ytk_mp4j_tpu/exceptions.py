"""Library-wide exception types.

Mirrors the reference's single checked exception ``Mp4jException``
(SURVEY.md section 2, expected path ``exception/Mp4jException.java`` [U]),
refined into a small hierarchy for the resilience subsystem (ISSUE 5):
recovery must retry a torn socket but never a caller mistake, so the
two kinds are distinct types, not string matches.
"""


class Mp4jError(Exception):
    """Raised for any mp4j-level failure (rendezvous, transport, shape/type
    mismatches, collective misuse)."""


class Mp4jTransportError(Mp4jError):
    """A wire/socket-level failure (timeout, reset, torn frame, failed
    dial). The RECOVERABLE class: the epoch-fenced abort/retry engine
    (``resilience/recovery.py``) may re-run the collective after one of
    these. Validation and protocol-misuse failures stay plain
    :class:`Mp4jError` — retrying a duplicate gather key or an
    out-of-range root would re-fail deterministically while dragging
    every healthy rank through a pointless abort round."""


class Mp4jAbortError(Mp4jTransportError):
    """The epoch fence tripped: a job-wide abort round targeting a
    newer epoch is in flight, so this rank must stop touching the torn
    data plane and join the round. Always recoverable — raised *by* the
    recovery machinery to reroute a collective attempt, never a final
    verdict."""


class Mp4jFatalError(Mp4jError):
    """A terminal, cluster-wide abort: the master has declared the job
    unrecoverable (dead rank, exhausted retry budget, stalled recovery
    round) and fanned the SAME message out to every surviving rank.
    Deliberately not a transport error — nothing retries it."""


class Mp4jEvicted(Mp4jFatalError):
    """This rank was PROACTIVELY evicted by the elastic autoscaler
    (ISSUE 13): the health plane recommended replacing it, the
    controller quiesced the job at a collective boundary, a warm spare
    was adopted into this rank's id, and the job continues without this
    process. A clean release, not a failure — the hosting process
    should treat it like :class:`Mp4jSpareReleased` (exit 0). Subclass
    of :class:`Mp4jFatalError` so every wait that a terminal abort
    breaks also breaks for an eviction, and nothing ever retries it."""


class Mp4jSpareReleased(Mp4jError):
    """A warm spare (ISSUE 10, ``ProcessCommSlave(spare=True)``) was
    released without ever being adopted: the job completed (or died)
    while the spare idled. Not a defect — the spare existing unused is
    the success case of elastic provisioning — but the blocked
    constructor has nothing to return, so it raises this distinct type
    for the hosting process to treat as a clean exit."""
