"""Shared-memory intra-host transport behind the Channel SPI (ISSUE 7).

Co-located slave PROCESSES were paying full TCP + frame-codec tax for
what can be a memcpy. This transport keeps the pair's rendezvous TCP
connection as a **carrier** (control, small transfers, synchronization,
liveness) and moves BULK raw payloads through two lock-free SPSC ring
buffers in a ``multiprocessing.shared_memory`` segment; the frame
codec, stats attribution, fault hooks and epoch fencing all ride the
SPI base unchanged.

Layout per segment (one per peer pair, created by the DIALER)::

    ring A header (64 B) | ring A data (ring_bytes)   dialer -> accepter
    ring B header (64 B) | ring B data (ring_bytes)   accepter -> dialer

Ring header: ``u64 head`` (total bytes written), ``u64 tail`` (total
bytes read), ``u32 poison``. Head/tail are monotone cursors (position =
cursor % capacity), single-writer each — the classic SPSC design
needing no lock: the writer only advances ``head`` after the bytes are
in place, the reader only advances ``tail`` after copying out. 8-byte
aligned loads/stores are atomic on every platform this repo targets.

**Hybrid routing** (the load-bearing design decision, measured on the
bench host): a raw transfer rides the ring only when its byte count
clears ``_RING_MIN``; smaller transfers — and the whole framed plane
(headers, objects, compressed streams, map columns) — ride the carrier
socket directly. Both ends derive the routing from the SAME transfer
size (raw sizes come from the collective's segment metadata; framed
traffic is a byte stream on one vehicle), so the split can never
desync. Rationale: a user-space ring must solve WAKEUP — and every
user-space discipline loses to the kernel's on an oversubscribed host.
Measured on the 1-core bench host: spin/yield ladders burn the peer's
whole scheduler quantum (4x slower than loopback TCP end to end);
select()-parked doorbells fix the median but keep multi-millisecond
scheduler tails (~7ms per small tree collective vs TCP's 1.1ms at the
same ~1.6 context switches — the wakee just isn't run). Small
transfers therefore belong ON the kernel path. Large transfers ride
the ring in **pieces**: the writer copies a piece into the ring and
sends ONE sync byte on the carrier; the reader blocks in a normal
kernel ``recv`` for the sync (TCP-grade wakeup), then copies the piece
straight into the destination array (the zero-copy receive: no staging
buffer). The carrier byte stream per direction is just
[small payloads | sync bytes] in protocol order — both ends agree on
every op, so the streams stay framed without any extra protocol.
Stats attribution: everything a ShmChannel moves — ring bytes AND its
carrier traffic — books under the ``shm`` transport tag; the carrier
is a component of this transport (like TCP's ACKs), not a separate
plane.

Poison/teardown: the header's POISON flag is this transport's
``invalidate()`` — visible to BOTH processes at once — and the carrier
shutdown that accompanies it wakes any blocked kernel recv with EOF,
exactly like a TCP teardown. The segment itself is only released
later, by the owner, from the collective thread
(``_drain_dead_channels``), mirroring the deferred-close discipline
that keeps fd/segment reuse out of still-unwinding operations.
Peer-death detection rides the carrier for free: a SIGKILLed peer's
socket closes and every blocked op errors out.

Knobs (README "Transport tuning"): ``MP4J_SHM`` gates the transport
(default on — rendezvous falls back to TCP for cross-host pairs
automatically); ``MP4J_SHM_RING_BYTES`` sizes each direction's ring.
Segment backing is ``memfd_create`` where available (see
:class:`Segment` — attached via ``/proc/<pid>/fd``, freed by the
kernel on the last close, so even a SIGKILLed job leaks nothing); the
``shm_open`` fallback names segments
``mp4j-<job>-<lo>x<hi>-e<epoch>-<nonce>`` in ``/dev/shm``, unlinked at
close by whichever side closes first (POSIX keeps the memory alive for
the other side's mapping) — there a SIGKILLed job can leak names until
reboot, greppable by prefix.
"""

from __future__ import annotations

import hashlib
import os
import secrets
import select
import socket
import struct
import time
import uuid

from multiprocessing import shared_memory

from ytk_mp4j_tpu.transport.channel import Channel, _raw_view
from ytk_mp4j_tpu.transport.tcp import (
    drain_half_close as tcp_drain_half_close,
    recv_into_checked as tcp_recv_into_checked,
    sendall_checked as tcp_sendall_checked,
)
from ytk_mp4j_tpu.utils import tuning
from ytk_mp4j_tpu.exceptions import Mp4jTransportError

_HDR_BYTES = 64              # one cache line per ring header
_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_OFF_HEAD = 0
_OFF_TAIL = 8
_OFF_POISON = 16

# Hybrid routing thresholds (see module docstring). _RING_MIN is the
# smallest raw transfer that rides the ring (smaller ones take the
# carrier's kernel path, whose wakeup latency no user-space wait can
# match on an oversubscribed host); pieces are sized so the reader's
# first kernel wakeup arrives after a fraction of the transfer and the
# two sides stream in parallel through the ring. The value itself
# lives in utils.tuning (ISSUE 15's R22 knob discipline: size
# literals feeding transport decisions are centralized there).
_RING_MIN = tuning.SHM_RING_MIN_BYTES
_POLL_SLEEP = 50e-6          # writer's ring-space poll (reader active)
_PARK_TICK = 0.05            # duplex select tick (poison/deadline checks)


def host_fingerprint() -> str:
    """An identifier two slave processes share IFF they can attach each
    other's shared-memory segments: the kernel boot id (same machine,
    same boot) plus the identity of the ``/dev/shm`` tmpfs instance
    (containers share a kernel but usually NOT a /dev/shm mount — the
    device/inode pair tells them apart). Falls back to hostname+MAC on
    systems without either. Rendezvous ships this in the roster; only
    pairs with EQUAL fingerprints negotiate shm."""
    parts = []
    try:
        with open("/proc/sys/kernel/random/boot_id") as fh:
            parts.append(fh.read().strip())
    except OSError:
        parts.append(f"{socket.gethostname()}-{uuid.getnode():x}")
    try:
        st = os.stat("/dev/shm")
        parts.append(f"{st.st_dev:x}.{st.st_ino:x}")
    except OSError:
        pass
    # memfd attach reopens /proc/<pid>/fd/<fd>, which needs a shared
    # PID namespace — containers on one kernel get distinct ns inodes
    try:
        parts.append(f"{os.stat('/proc/self/ns/pid').st_ino:x}")
    except OSError:
        pass
    return hashlib.blake2s("|".join(parts).encode(),
                           digest_size=8).hexdigest()


def segment_name(job: str, lo: int, hi: int, epoch: int) -> str:
    """Segment name for one peer pair: job id + (lo, hi) rank pair +
    epoch + a dialer-chosen nonce (the nonce rides the handshake, so
    the name never needs to be re-derived — a backoff re-dial at the
    same epoch simply mints a fresh segment)."""
    return (f"mp4j-{job}-{lo}x{hi}-e{epoch}-{secrets.token_hex(4)}")


class Segment:
    """One peer pair's shared mapping, behind a uniform handle.

    Preferred backing is ``memfd_create`` + ``mmap``: the attacher
    reopens the creator's fd through ``/proc/<pid>/fd/<fd>`` (the
    ``token`` that rides the peer handshake), the kernel frees the
    memory on the last close (a SIGKILLed job leaks NOTHING), and —
    decisive on this bench host — the mapping stays off the mounted
    ``/dev/shm`` tmpfs: a file mapped from that mount was measured to
    degrade the whole process's SOCKET latencies ~20x (4-proc tree
    exchange 0.10 -> 2.4 ms/iter with one dormant 64 KiB mapping;
    anonymous and memfd mappings are clean — some supervisor watches
    the mount). ``multiprocessing.shared_memory`` remains the fallback
    for kernels without memfd or /proc fd reopen.
    """

    def __init__(self, buf: memoryview, token, closer) -> None:
        self.buf = buf
        self.token = token          # handshake form; see module doc
        self._closer = closer

    def close(self) -> None:
        """Release the mapping (callers release ring views first)."""
        try:
            self.buf.release()
        except (BufferError, ValueError):
            pass
        try:
            self._closer()
        except (OSError, BufferError, ValueError):
            pass


def _memfd_supported() -> bool:
    """One-time probe: memfd + /proc/self/fd reopen + mmap."""
    try:
        fd = os.memfd_create("mp4j-probe")
    except (AttributeError, OSError):
        return False
    try:
        os.ftruncate(fd, 4096)
        ofd = os.open(f"/proc/{os.getpid()}/fd/{fd}", os.O_RDWR)
        os.close(ofd)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


_MEMFD_OK = _memfd_supported()


def _tracker_unregister(name: str) -> None:
    """Drop a segment from THIS process's resource tracker. The stdlib
    registers on BOTH create and attach (bpo-39959) into one per-
    process set, so exact register/unregister pairing is impossible
    when creator and attacher share a process (the thread-hosted test
    harness) — instead the transport owns cleanup outright: unregister
    immediately on create/attach and unlink via :func:`_unlink_quiet`,
    which never touches the tracker. Cost: a SIGKILLed process loses
    the tracker's exit-time sweep — the documented ``/dev/shm`` leak
    window, bounded by the greppable name prefix."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(f"/{name.lstrip('/')}",
                                    "shared_memory")
    # Not a data path: tracker bookkeeping only — a failed unregister
    # costs at worst a stale tracker entry at process exit, never a
    # byte of the collective; tracker internals vary across Pythons.
    # mp4j-lint: disable=R5 (best-effort resource-tracker bookkeeping)
    except Exception:   # pragma: no cover
        pass


def _unlink_quiet(seg_name: str) -> None:
    """Unlink the segment NAME (memory survives for open mappings);
    tracker-free (see :func:`_tracker_unregister`) and idempotent —
    both sides call this at close and the second call finds nothing."""
    try:
        shared_memory._posixshmem.shm_unlink(
            seg_name if seg_name.startswith("/") else "/" + seg_name)
    except (FileNotFoundError, OSError):
        pass


def create_segment(name: str, ring_bytes: int) -> Segment:
    """Create one peer pair's segment (two rings); dialer side. The
    returned handle's ``token`` rides the peer handshake and is all
    the accepter needs to attach."""
    size = 2 * (_HDR_BYTES + ring_bytes)
    if _MEMFD_OK:
        import mmap as mmap_mod

        fd = os.memfd_create(name)
        try:
            os.ftruncate(fd, size)
            mm = mmap_mod.mmap(fd, size)
        except OSError:
            os.close(fd)
            raise
        token = ("memfd", os.getpid(), fd, size)
        # fd stays open for the channel's lifetime: it IS the name the
        # attacher reopens through /proc; the kernel frees the memory
        # when the last of {creator fd+map, attacher map} closes

        def closer(fd=fd, mm=mm):
            mm.close()
            os.close(fd)

        return Segment(memoryview(mm), token, closer)
    seg = shared_memory.SharedMemory(name=name, create=True, size=size)
    _tracker_unregister(name)

    def closer(seg=seg, name=name):
        seg.close()
        _unlink_quiet(name)

    return Segment(seg.buf, ("shm", name), closer)


def attach_segment(token, timeout: float = 5.0) -> Segment:
    """Attach the dialer's segment (accepter side) from its handshake
    token. The creator creates BEFORE sending the handshake, so a miss
    is a narrow race at most — surfaced as a transport error (recovery
    treats it like any torn dial)."""
    if isinstance(token, tuple) and token and token[0] == "memfd":
        import mmap as mmap_mod

        _, pid, fd, size = token
        try:
            ofd = os.open(f"/proc/{pid}/fd/{fd}", os.O_RDWR)
        except OSError as e:
            raise Mp4jTransportError(
                f"cannot attach peer memfd segment (pid {pid} fd "
                f"{fd}): {e} — peer died mid-handshake, or the pid "
                "namespace is not shared (host fingerprint "
                "collision?)") from None
        try:
            mm = mmap_mod.mmap(ofd, size)
        finally:
            os.close(ofd)
        return Segment(memoryview(mm), token, mm.close)
    name = token[1] if isinstance(token, tuple) else str(token)
    deadline = time.monotonic() + timeout
    while True:
        try:
            seg = shared_memory.SharedMemory(name=name)
            _tracker_unregister(name)

            def closer(seg=seg, name=name):
                seg.close()
                _unlink_quiet(name)

            return Segment(seg.buf, ("shm", name), closer)
        except FileNotFoundError:
            if time.monotonic() > deadline:
                raise Mp4jTransportError(
                    f"shm segment {name!r} never appeared (peer died "
                    "mid-handshake, or /dev/shm is not shared — host "
                    "fingerprint collision?)") from None
            time.sleep(0.002)


class _Ring:
    """One direction of the channel: an SPSC byte ring over a slice of
    the shared segment. Each side constructs its own ``_Ring`` views;
    the roles (who writes, who reads) are fixed by the channel."""

    def __init__(self, buf: memoryview, base: int, cap: int):
        self._hdr = buf[base:base + _HDR_BYTES]
        self._data = buf[base + _HDR_BYTES:base + _HDR_BYTES + cap]
        self._cap = cap

    # cursor accessors (single 8-byte aligned load/store each)
    def _head(self) -> int:
        return _U64.unpack_from(self._hdr, _OFF_HEAD)[0]

    def _tail(self) -> int:
        return _U64.unpack_from(self._hdr, _OFF_TAIL)[0]

    def _set_head(self, v: int) -> None:
        _U64.pack_into(self._hdr, _OFF_HEAD, v)

    def _set_tail(self, v: int) -> None:
        _U64.pack_into(self._hdr, _OFF_TAIL, v)

    @property
    def poisoned(self) -> bool:
        return _U32.unpack_from(self._hdr, _OFF_POISON)[0] != 0

    def poison(self) -> None:
        _U32.pack_into(self._hdr, _OFF_POISON, 1)

    def release(self) -> None:
        """Drop the memoryview slices so the segment's mmap can close
        (an exported buffer would make SharedMemory.close raise)."""
        self._hdr.release()
        self._data.release()

    # -- data movement (bounded attempts; callers own waits) ------------
    def write_some(self, src: memoryview, off: int, limit: int) -> int:
        """ONE bounded copy attempt: move up to ``limit`` bytes of
        ``src[off:]`` into the ring (0 = full). Data lands before the
        head advances — the SPSC publication order."""
        cap, data = self._cap, self._data
        head = self._head()
        free = cap - (head - self._tail())
        if free <= 0:
            return 0
        take = min(free, limit, len(src) - off)
        pos = head % cap
        first = min(take, cap - pos)
        data[pos:pos + first] = src[off:off + first]
        if take > first:
            data[:take - first] = src[off + first:off + take]
        self._set_head(head + take)
        return take

    def read_exact(self, dst: memoryview, off: int, n: int) -> None:
        """Copy EXACTLY ``n`` available bytes into ``dst[off:]``
        DIRECTLY (the zero-copy receive — no staging buffer between
        the ring and the caller's array). The caller guarantees
        availability (a sync byte arrived for this piece)."""
        cap, data = self._cap, self._data
        tail = self._tail()
        pos = tail % cap
        first = min(n, cap - pos)
        dst[off:off + first] = data[pos:pos + first]
        if n > first:
            dst[off + first:off + n] = data[:n - first]
        self._set_tail(tail + n)


class ShmChannel(Channel):
    """The Channel SPI over one shared-memory segment (two rings) plus
    the pair's TCP carrier socket (framed plane, small raw transfers,
    ring sync bytes, liveness).

    ``owner`` marks the segment's creator (the dialer): ownership only
    decides who created; BOTH sides attempt the unlink at close (the
    first wins, POSIX keeps the memory mapped for the laggard), so a
    one-sided crash-free shutdown never leaks the name.
    """

    transport = "shm"

    def __init__(self, sock: socket.socket, seg: Segment,
                 ring_bytes: int, owner: bool):
        self.sock = sock
        self.stats = None
        self.peer_rank = None
        self.faults = None
        self.epoch = 0
        self._chunk_bytes = tuning.chunk_bytes()
        self._seg = seg
        self._owner = owner
        self._timeout: float | None = None
        self._closed = False
        # frame-level ring routing (ISSUE 15): framed payload units at
        # or above this threshold stream through the ring; 0 keeps the
        # whole framed plane on the carrier (the pre-ISSUE-15 layout)
        self._frame_min = tuning.shm_frame_min()
        self._tx_stream: dict | None = None
        self._rx_stream: dict | None = None
        # piece size: reader's first wakeup lands after a fraction of
        # a large transfer; half-ring keeps writer and reader streaming
        # in parallel through the same ring
        self._piece = max(ring_bytes // 2, tuning.SHM_RING_FLOOR)
        ring_a = _Ring(seg.buf, 0, ring_bytes)
        ring_b = _Ring(seg.buf, _HDR_BYTES + ring_bytes, ring_bytes)
        # ring A is dialer->accepter by convention
        self._tx, self._rx = (ring_a, ring_b) if owner else (ring_b,
                                                             ring_a)
        sock.settimeout(None)

    # -- carrier primitives (kernel path; shm-flavored diagnostics) -----
    def set_timeout(self, timeout: float | None) -> None:
        self._timeout = timeout
        try:
            self.sock.settimeout(timeout)
        except OSError:
            pass

    # carrier I/O rides THE shared socket loops (transport/tcp.py) —
    # one place to fix socket semantics for both transports; the only
    # shm flavor is the poison-aware EOF upgrade (an invalidated
    # channel must say so, not "peer closed"). Since ISSUE 15 the
    # framing layer's route hooks may arm a FRAME STREAM, steering a
    # payload unit's bytes through the ring while its header (and the
    # sync bytes) keep the carrier.
    def _io_send(self, buf) -> None:
        st = self._tx_stream
        if st is not None:
            # wire-ready byte buffers only: _send_all's callers pin
            # contiguity/dtype before framing (channel.py discipline)
            # mp4j-lint: disable=R13 (already-serialized frame bytes)
            view = memoryview(buf)
            if view.ndim != 1 or view.format != "B":
                view = view.cast("B")
            take = min(len(view), st["end"] - st["pos"])
            self._stream_send(view[:take], st)
            if take < len(view):
                tcp_sendall_checked(self.sock, view[take:])
            return
        tcp_sendall_checked(self.sock, buf)

    def _io_recv_into(self, view: memoryview) -> None:
        st = self._rx_stream
        if st is not None:
            take = min(len(view), st["end"] - st["pos"])
            self._stream_recv(view[:take], st)
            if take < len(view):
                self._carrier_recv_into(view[take:])
            return
        self._carrier_recv_into(view)

    def _carrier_recv_into(self, view: memoryview) -> None:
        try:
            tcp_recv_into_checked(self.sock, view, self._whom(),
                                  what="shm carrier")
        except Mp4jTransportError:
            if self._tx.poisoned or self._rx.poisoned:
                raise Mp4jTransportError(
                    f"shm channel invalidated{self._whom()} "
                    f"({len(view)} byte receive torn)") from None
            raise

    # -- frame-level ring routing (ISSUE 15) ----------------------------
    # The framing layer announces each payload unit whose length the
    # peer already knows (frame header / chunk length prefix). Units
    # clearing MP4J_SHM_FRAME_MIN become a RING STREAM: the unit's
    # bytes move through the SPSC ring in the same piece schedule the
    # raw plane uses — a pure function of (unit length, ring size), so
    # both ends agree without 1:1 buffer pairing: the sender may write
    # in any granularity (u32 prefix, pickle header, array body) and
    # the receiver may read in any other (header peek, chunked fills);
    # the stream serves both against the shared piece/sync schedule.
    def _route_send(self, n: int) -> None:
        if 0 < self._frame_min <= n:
            self._check_poison("send")
            self._tx_stream = {"end": n, "pos": 0, "idx": 0,
                               "pieces": self._pieces(n),
                               "bound": 0}
            self._tx_stream["bound"] = self._tx_stream["pieces"][0]

    def _route_recv(self, n: int) -> None:
        if 0 < self._frame_min <= n:
            self._check_poison("recv")
            self._rx_stream = {"end": n, "pos": 0, "idx": 0,
                               "pieces": self._pieces(n),
                               "synced": 0}

    def _stream_send(self, src: memoryview, st: dict) -> None:
        """Move ``src`` into the tx ring as part of the armed frame
        stream, publishing one carrier sync byte per completed piece
        (the kernel-grade wakeup the reader blocks on)."""
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        off, n = 0, len(src)
        while off < n:
            moved = self._tx.write_some(src, off, st["bound"] - st["pos"])
            if moved:
                off += moved
                st["pos"] += moved
                if st["pos"] == st["bound"]:
                    # piece complete -> ONE kernel wakeup; sync bytes
                    # bypass _io_send (the stream must not recurse)
                    tcp_sendall_checked(self.sock, b"\x01")
                    st["idx"] += 1
                    if st["idx"] < len(st["pieces"]):
                        st["bound"] += st["pieces"][st["idx"]]
                continue
            if self._tx.poisoned or self._rx.poisoned:
                self._raise_poisoned("send", n - off)
            if deadline is not None and time.monotonic() > deadline:
                raise Mp4jTransportError(
                    f"shm frame-stream send timed out with {n - off} "
                    f"bytes pending{self._whom()} (peer dead or "
                    "stalled?)")
            time.sleep(_POLL_SLEEP)
        if st["pos"] >= st["end"]:
            self._tx_stream = None
        if self.stats is not None and n:
            self.stats.add("wire_bytes_shm_ring", n)

    def _stream_recv(self, view: memoryview, st: dict) -> None:
        """Fill ``view`` from the rx ring's armed frame stream,
        blocking in a normal kernel recv for each piece's sync byte
        (TCP-grade wakeup) — after which the piece's bytes are
        GUARANTEED present in the ring."""
        sync = bytearray(1)
        off, n = 0, len(view)
        while off < n:
            if st["pos"] == st["synced"]:
                self._carrier_recv_into(memoryview(sync))
                if self._tx.poisoned or self._rx.poisoned:
                    self._raise_poisoned("recv", n - off)
                st["synced"] += st["pieces"][st["idx"]]
                st["idx"] += 1
            take = min(n - off, st["synced"] - st["pos"])
            self._rx.read_exact(view, off, take)
            off += take
            st["pos"] += take
        if st["pos"] >= st["end"]:
            self._rx_stream = None
        if self.stats is not None and n:
            self.stats.add("wire_bytes_shm_ring", n)

    # -- raw plane: hybrid ring/carrier routing -------------------------
    def _check_poison(self, op: str) -> None:
        """Fail FAST on a poisoned channel, not only when blocked: an
        invalidated ring may still have free space (writes) or stale
        bytes (reads), and letting an operation 'succeed' against a
        torn epoch is exactly what invalidate exists to prevent."""
        if self._tx.poisoned or self._rx.poisoned:
            raise Mp4jTransportError(
                f"shm channel invalidated{self._whom()} "
                f"(attempted {op} on a torn-down ring)")

    def _pieces(self, n: int) -> list[int]:
        """Piece sizes for an ``n``-byte ring transfer — a pure
        function of (n, ring size), so sender and receiver always
        agree on the sync-byte count."""
        p = self._piece
        return [min(p, n - off) for off in range(0, n, p)]

    def send_raw(self, arr) -> None:
        src = memoryview(_raw_view(arr)).cast("B")
        n = len(src)
        if n < _RING_MIN:
            self._io_send(src)
            return
        self._check_poison("send")
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        off = 0
        for size in self._pieces(n):
            end = off + size
            while off < end:
                moved = self._tx.write_some(src, off, end - off)
                if moved:
                    off += moved
                    continue
                # ring full: the reader is behind but AWAKE (its sync
                # for the previous piece was sent) — a short poll is
                # cheap relative to the memcpy it waits on
                if self._tx.poisoned or self._rx.poisoned:
                    self._raise_poisoned("send", n - off)
                if deadline is not None and time.monotonic() > deadline:
                    raise Mp4jTransportError(
                        f"shm send timed out with {n - off} bytes "
                        f"pending{self._whom()} (peer dead or stalled?)")
                time.sleep(_POLL_SLEEP)
            # piece complete -> ONE kernel-grade wakeup on the carrier
            # (direct: sync bytes must never enter a frame stream)
            tcp_sendall_checked(self.sock, b"\x01")
        if self.stats is not None:
            self.stats.add("wire_bytes_shm_ring", n)

    def recv_raw_into(self, arr) -> None:
        dst = memoryview(_raw_view(arr)).cast("B")
        n = len(dst)
        if n < _RING_MIN:
            self._io_recv_into(dst)
            return
        self._check_poison("recv")
        sync = bytearray(1)
        off = 0
        for size in self._pieces(n):
            # block in a normal kernel recv for the piece's sync byte
            # (TCP-grade wakeup), then the piece is GUARANTEED present
            self._carrier_recv_into(memoryview(sync))
            if self._tx.poisoned or self._rx.poisoned:
                self._raise_poisoned("recv", n - off)
            self._rx.read_exact(dst, off, size)
            off += size
        if self.stats is not None:
            self.stats.add("wire_bytes_shm_ring", n)

    def _raise_poisoned(self, op: str, pending: int) -> None:
        raise Mp4jTransportError(
            f"shm ring poisoned mid-{op}{self._whom()} "
            f"({pending} bytes pending; channel invalidated)")

    # -- lifecycle ------------------------------------------------------
    def invalidate(self) -> None:
        """Poison both rings (shared state: the REMOTE side's blocked
        ring waits observe it too) and shut the carrier down — which
        wakes every blocked kernel recv/sync wait on BOTH ends with
        EOF, like a TCP teardown. The segment itself stays mapped —
        the owner frees it later via :meth:`close` from the collective
        thread (``_drain_dead_channels``), the same deferred-release
        discipline the TCP transport applies to fds."""
        try:
            self._tx.poison()
            self._rx.poison()
        except ValueError:
            pass    # already released by close
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self, graceful: bool = False) -> None:
        """Release the mapping and unlink the segment name. ``graceful``
        skips the poison: a finishing rank's final bytes live in the
        carrier/ring, and POSIX keeps the ring memory alive for the
        peer's mapping until it closes too; an abrupt close poisons
        first so a blocked peer errors instead of waiting on a
        corpse."""
        if self._closed:
            return
        self._closed = True
        if graceful:
            # the carrier carries REAL bytes (framed plane, small raw
            # transfers, ring syncs): the same half-close + bounded
            # drain as TCP, or closing with unread inbound data RSTs
            # away our queued final bytes under a slower peer
            tcp_drain_half_close(self.sock)
        else:
            try:
                self._tx.poison()
                self._rx.poison()
            except ValueError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._tx.release()
        self._rx.release()
        # memfd: the kernel frees the memory with the last close; shm
        # fallback: the Segment closer also unlinks the name (both
        # sides attempt it; the second finds nothing — no coordination
        # needed)
        self._seg.close()


def duplex_exchange(send_ch: ShmChannel | None, sarr,
                    recv_ch: ShmChannel | None, rarr) -> None:
    """Full-duplex raw exchange over shm channels in ONE thread — the
    shm analogue of the native C++ socket poll loop (a helper-thread
    send would ping-pong the GIL around user-space memcpys and pay a
    pool-future handoff per pipeline chunk). Interleaves the hybrid
    send/recv plans (ring pieces + carrier sync bytes, or small
    payloads on the carrier) against nonblocking carrier I/O, parking
    in ``select()`` only when NEITHER direction can move. ``send_ch``
    and ``recv_ch`` may be the same channel (partner exchange) or
    different (ring step); either side may be absent (None array)."""
    if sarr is None and rarr is None:
        return
    if sarr is None:
        recv_ch.recv_raw_into(rarr)
        return
    if rarr is None:
        send_ch.send_raw(sarr)
        return
    sv = memoryview(_raw_view(sarr)).cast("B")
    rv = memoryview(_raw_view(rarr)).cast("B")
    sn, rn = len(sv), len(rv)
    s_ring = sn >= _RING_MIN
    r_ring = rn >= _RING_MIN
    if s_ring:
        send_ch._check_poison("send")
    if r_ring:
        recv_ch._check_poison("recv")
    deadline = (None if send_ch._timeout is None
                else time.monotonic() + send_ch._timeout)
    # plans: sender side emits [pieces -> sync bytes] or raw payload
    # into the carrier; receiver side consumes the mirror stream
    s_pieces = send_ch._pieces(sn) if s_ring else []
    r_pieces = recv_ch._pieces(rn) if r_ring else []
    soff = roff = 0               # payload progress
    s_piece_end = (soff + s_pieces[0]) if s_pieces else 0
    s_piece_idx = 0
    s_sync_due = 0                # sync bytes owed to the carrier
    r_piece_idx = 0
    r_sync_got = 0                # sync bytes received, pieces unread
    ssock, rsock = send_ch.sock, recv_ch.sock
    ssock.setblocking(False)
    if rsock is not ssock:
        rsock.setblocking(False)
    try:
        while soff < sn or roff < rn or s_sync_due:
            progressed = False
            # 1) sender: ring pieces
            if s_ring and soff < sn:
                moved = send_ch._tx.write_some(sv, soff,
                                               s_piece_end - soff)
                if moved:
                    progressed = True
                    soff += moved
                    if soff == s_piece_end:
                        s_sync_due += 1
                        s_piece_idx += 1
                        if s_piece_idx < len(s_pieces):
                            s_piece_end += s_pieces[s_piece_idx]
            # 2) sender: carrier bytes (sync bytes, or the small
            #    payload itself)
            try:
                if s_sync_due:
                    sent = ssock.send(b"\x01" * s_sync_due)
                    if sent:
                        progressed = True
                        s_sync_due -= sent
                elif not s_ring and soff < sn:
                    sent = ssock.send(sv[soff:])
                    if sent:
                        progressed = True
                        soff += sent
            except (BlockingIOError, InterruptedError):
                pass
            except OSError as e:
                raise Mp4jTransportError(
                    f"shm carrier failed mid-send"
                    f"{send_ch._whom()}: {e}") from None
            # 3) receiver: carrier bytes (sync bytes or payload)
            if roff < rn:
                try:
                    if r_ring:
                        data = rsock.recv(len(r_pieces) - r_piece_idx
                                          - r_sync_got)
                        if data:
                            progressed = True
                            r_sync_got += len(data)
                        elif data == b"":
                            _eof(recv_ch, rn - roff)
                    else:
                        got = rsock.recv_into(rv[roff:], rn - roff)
                        if got:
                            progressed = True
                            roff += got
                        else:
                            _eof(recv_ch, rn - roff)
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError as e:
                    raise Mp4jTransportError(
                        f"shm carrier failed mid-receive"
                        f"{recv_ch._whom()}: {e}") from None
            # 4) receiver: drain synced ring pieces
            while r_sync_got:
                size = r_pieces[r_piece_idx]
                recv_ch._rx.read_exact(rv, roff, size)
                roff += size
                r_piece_idx += 1
                r_sync_got -= 1
                progressed = True
            if progressed:
                continue
            if (send_ch._tx.poisoned or send_ch._rx.poisoned
                    or recv_ch._tx.poisoned or recv_ch._rx.poisoned):
                raise Mp4jTransportError(
                    f"shm ring poisoned mid-exchange"
                    f"{send_ch._whom()} ({sn - soff + rn - roff} "
                    "bytes pending; channel invalidated)")
            if deadline is not None and time.monotonic() > deadline:
                raise Mp4jTransportError(
                    f"shm exchange timed out ({sn - soff} send / "
                    f"{rn - roff} recv bytes pending; peer dead "
                    "or stalled?)")
            # nothing moved: park until the peer's carrier traffic
            # (sync/payload/EOF) or until our carrier drains
            rlist = [rsock] if roff < rn else []
            wlist = [ssock] if (s_sync_due
                               or (not s_ring and soff < sn)) else []
            if not rlist and not wlist:
                # waiting on ring SPACE only (peer reader behind)
                time.sleep(_POLL_SLEEP)
                continue
            try:
                select.select(rlist, wlist, [], _PARK_TICK)
            except (OSError, ValueError):
                pass    # torn carrier: the next recv/send adjudicates
        ring_bytes_moved = (sn if s_ring else 0) + (rn if r_ring else 0)
        if ring_bytes_moved and send_ch.stats is not None:
            send_ch.stats.add("wire_bytes_shm_ring", ring_bytes_moved)
    finally:
        try:
            ssock.settimeout(send_ch._timeout)
            if rsock is not ssock:
                rsock.settimeout(recv_ch._timeout)
        except OSError:
            pass



def _eof(ch: ShmChannel, pending: int) -> None:
    if ch._tx.poisoned or ch._rx.poisoned:
        ch._raise_poisoned("exchange", pending)
    raise Mp4jTransportError(
        f"peer closed shm carrier mid-exchange{ch._whom()} "
        f"({pending} bytes pending; peer process dead?)")


# ----------------------------------------------------------------------
# engine-leg pumps (ISSUE 17): ONE DIRECTION of the async engine's
# chunk-granular shm schedule, nonblocking. The async raw engine
# (comm/progress.py) decouples an exchange into independent send/recv
# legs with per-(peer, direction) FIFO queues; these pumps give a shm
# leg the same incremental, never-blocking contract a nonblocking TCP
# socket gives a tcp leg — so shm-paired collectives can interleave on
# the engine instead of executing as one atomic blocking step.
#
# Wire contract: the per-direction byte streams are IDENTICAL to the
# blocking chunked exchange's. The leg's payload splits at the same
# chunk boundaries (`_chunk_for(peer)` element ranges, passed in as
# byte bounds), and each chunk routes exactly like one
# `_exchange_raw` step: below `_RING_MIN` the chunk's raw bytes ride
# the carrier; at or above, the chunk moves through the SPSC ring in
# the shared `_pieces` schedule with ONE carrier sync byte per
# completed piece. Chunks complete strictly in order — chunk k's
# carrier traffic (payload or sync bytes) fully precedes chunk k+1's,
# which is the per-direction stream order the blocking twin emits — so
# a mixed engine/blocking pair can never desync.
# ----------------------------------------------------------------------
class SendPump:
    """Nonblocking chunk-granular sender for one engine leg.

    ``pump()`` moves whatever can move RIGHT NOW (ring space, carrier
    writability) and returns the payload bytes shipped; ``done`` flips
    only once the payload AND every owed sync byte are flushed —
    retiring a leg with syncs pending would let the next leg on the
    same (peer, send) queue jump the carrier stream. The caller owns
    waits (select on ``want_carrier``, short ticks for ``ring_wait``)
    and stall deadlines."""

    __slots__ = ("ch", "view", "bounds", "ci", "off", "sync_due",
                 "ring", "pieces", "piece_idx", "piece_end")

    def __init__(self, ch: ShmChannel, view: memoryview,
                 bounds: list[tuple[int, int]]):
        self.ch = ch
        self.view = view
        self.bounds = bounds      # ascending byte (lo, hi) chunk bounds
        self.ci = -1
        self.off = 0              # payload bytes shipped (ring+carrier)
        self.sync_due = 0         # sync bytes owed to the carrier
        self.ring = False
        self.pieces: list[int] = []
        self.piece_idx = 0
        self.piece_end = 0
        self._next_chunk()

    def _next_chunk(self) -> None:
        self.ci += 1
        if self.ci >= len(self.bounds):
            return
        lo, hi = self.bounds[self.ci]
        self.ring = hi - lo >= _RING_MIN
        if self.ring:
            self.ch._check_poison("send")
            self.pieces = self.ch._pieces(hi - lo)
            self.piece_idx = 0
            self.piece_end = lo + self.pieces[0]

    @property
    def done(self) -> bool:
        return self.ci >= len(self.bounds) and self.sync_due == 0

    @property
    def want_carrier(self) -> bool:
        """Parking hint: carrier writability would unblock us."""
        return self.sync_due > 0 or (self.ci < len(self.bounds)
                                     and not self.ring)

    @property
    def ring_wait(self) -> bool:
        """Parking hint: blocked on ring SPACE only (peer reader
        behind) — nothing selectable; the caller should tick short."""
        return (self.sync_due == 0 and self.ci < len(self.bounds)
                and self.ring)

    def _flush_syncs(self) -> int:
        try:
            sent = self.ch.sock.send(b"\x01" * self.sync_due)
        except (BlockingIOError, InterruptedError):
            return 0
        except OSError as e:
            raise Mp4jTransportError(
                f"shm carrier failed mid-send{self.ch._whom()}: {e}"
            ) from None
        self.sync_due -= sent
        return sent

    def pump(self) -> int:
        ch = self.ch
        moved = 0
        while True:
            # owed sync bytes first: they precede every later chunk's
            # bytes in this direction's carrier stream
            if self.sync_due:
                if not self._flush_syncs():
                    if ch._tx.poisoned or ch._rx.poisoned:
                        ch._raise_poisoned(
                            "send", self.bounds[-1][1] - self.off)
                    return moved
                if self.sync_due:
                    return moved
                continue
            if self.ci >= len(self.bounds):
                return moved
            lo, hi = self.bounds[self.ci]
            if not self.ring:
                try:
                    sent = ch.sock.send(self.view[self.off:hi])
                except (BlockingIOError, InterruptedError):
                    return moved
                except OSError as e:
                    raise Mp4jTransportError(
                        f"shm carrier failed mid-send"
                        f"{ch._whom()}: {e}") from None
                if not sent:
                    return moved
                self.off += sent
                moved += sent
                if self.off >= hi:
                    self._next_chunk()
                continue
            if self.off >= hi and self.piece_idx >= len(self.pieces):
                # every piece written and synced: chunk complete
                if ch.stats is not None:
                    ch.stats.add("wire_bytes_shm_ring", hi - lo)
                self._next_chunk()
                continue
            w = ch._tx.write_some(self.view, self.off,
                                  self.piece_end - self.off)
            if not w:
                if ch._tx.poisoned or ch._rx.poisoned:
                    ch._raise_poisoned("send",
                                       self.bounds[-1][1] - self.off)
                return moved      # ring full: reader behind but awake
            self.off += w
            moved += w
            if self.off == self.piece_end:
                # piece complete -> ONE kernel-grade wakeup owed
                self.sync_due += 1
                self.piece_idx += 1
                if self.piece_idx < len(self.pieces):
                    self.piece_end += self.pieces[self.piece_idx]


class RecvPump:
    """Nonblocking chunk-granular receiver for one engine leg (the
    :class:`SendPump` mirror). Payload lands in ``view`` in ascending
    contiguous order — ring pieces copy straight into the destination
    (the zero-copy receive), so the caller can fold/merge the
    ``[prev, off)`` delta after every ``pump()``. Never reads past the
    current chunk's carrier traffic: a greedy read could swallow the
    NEXT chunk's raw payload along with this one's sync bytes."""

    __slots__ = ("ch", "view", "bounds", "ci", "off",
                 "ring", "pieces", "piece_idx", "sync_got")

    def __init__(self, ch: ShmChannel, view: memoryview,
                 bounds: list[tuple[int, int]]):
        self.ch = ch
        self.view = view
        self.bounds = bounds
        self.ci = -1
        self.off = 0              # payload bytes landed in view
        self.ring = False
        self.pieces: list[int] = []
        self.piece_idx = 0
        self.sync_got = 0         # synced pieces not yet drained
        self._next_chunk()

    def _next_chunk(self) -> None:
        self.ci += 1
        if self.ci >= len(self.bounds):
            return
        lo, hi = self.bounds[self.ci]
        self.ring = hi - lo >= _RING_MIN
        if self.ring:
            self.ch._check_poison("recv")
            self.pieces = self.ch._pieces(hi - lo)
            self.piece_idx = 0
            self.sync_got = 0

    @property
    def done(self) -> bool:
        return self.ci >= len(self.bounds)

    def pump(self) -> int:
        ch = self.ch
        moved = 0
        while True:
            if self.ci >= len(self.bounds):
                return moved
            lo, hi = self.bounds[self.ci]
            if not self.ring:
                try:
                    got = ch.sock.recv_into(self.view[self.off:hi],
                                            hi - self.off)
                except (BlockingIOError, InterruptedError):
                    return moved
                except OSError as e:
                    raise Mp4jTransportError(
                        f"shm carrier failed mid-receive"
                        f"{ch._whom()}: {e}") from None
                if got == 0:
                    _eof(ch, self.bounds[-1][1] - self.off)
                self.off += got
                moved += got
                if self.off >= hi:
                    self._next_chunk()
                continue
            # drain every synced ring piece straight into the view
            while self.sync_got:
                size = self.pieces[self.piece_idx]
                ch._rx.read_exact(self.view, self.off, size)
                self.off += size
                self.piece_idx += 1
                self.sync_got -= 1
                moved += size
            if self.off >= hi:
                if ch.stats is not None:
                    ch.stats.add("wire_bytes_shm_ring", hi - lo)
                self._next_chunk()
                continue
            # sync bytes: bounded to THIS chunk's remaining pieces
            want = len(self.pieces) - self.piece_idx - self.sync_got
            try:
                data = ch.sock.recv(want)
            except (BlockingIOError, InterruptedError):
                if ch._tx.poisoned or ch._rx.poisoned:
                    ch._raise_poisoned(
                        "recv", self.bounds[-1][1] - self.off)
                return moved
            except OSError as e:
                raise Mp4jTransportError(
                    f"shm carrier failed mid-receive"
                    f"{ch._whom()}: {e}") from None
            if not data:
                _eof(ch, self.bounds[-1][1] - self.off)
            self.sync_got += len(data)
