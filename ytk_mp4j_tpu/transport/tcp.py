"""TCP transport — the reference socket plane behind the Channel SPI.

The reference frames messages over raw ``java.net.Socket`` streams with
Kryo for objects and raw ``DataOutputStream`` writes for primitive
arrays (SURVEY.md section 2 "Serialization" [U]). All framing lives in
the SPI base (:mod:`ytk_mp4j_tpu.transport.channel`); this module
contributes only the socket primitives: timeout-translated
``sendall`` / ``recv_into`` loops, the ``connect()`` dialer, kernel
socket-buffer sizing, the graceful half-close discipline, and the
``invalidate()`` = shutdown-without-close teardown the recovery plane's
deferred fd release relies on.

Env knobs applied at channel setup (see :mod:`ytk_mp4j_tpu.utils.tuning`
— JOB-wide settings, every rank must agree): ``MP4J_SO_SNDBUF`` /
``MP4J_SO_RCVBUF`` size the kernel socket buffers (unset keeps kernel
defaults); ``MP4J_CHUNK_BYTES`` sizes the streaming-compression chunks.
"""

from __future__ import annotations

import socket

from ytk_mp4j_tpu.transport.channel import Channel
from ytk_mp4j_tpu.utils import tuning
from ytk_mp4j_tpu.exceptions import Mp4jError, Mp4jTransportError


def apply_socket_buf_sizes(sock: socket.socket,
                           so_bufs: tuple[int, int] | None = None
                           ) -> None:
    """Apply ``MP4J_SO_SNDBUF`` / ``MP4J_SO_RCVBUF`` (validated; unset
    keeps the kernel's autotuned defaults). ``so_bufs`` is a PER-LINK
    ``(sndbuf, rcvbuf)`` override (ISSUE 15: ``MP4J_SO_BUF_MAP`` or a
    tuner decision) taking precedence over the job-wide knobs; 0 in
    either slot falls back to that direction's job-wide value. Must
    run BEFORE ``connect()`` on dialing sockets and before
    ``listen()`` on server sockets (accepted sockets inherit): TCP
    fixes the window-scale factor at the SYN/SYN-ACK from the buffer
    size at that moment, so a post-handshake resize cannot widen the
    advertised window."""
    for i, (env, opt) in enumerate(
            (("MP4J_SO_SNDBUF", socket.SO_SNDBUF),
             ("MP4J_SO_RCVBUF", socket.SO_RCVBUF))):
        size = tuning.env_bytes(env, 0, minimum=0)
        if so_bufs is not None and so_bufs[i] > 0:
            size = so_bufs[i]
        if size > 0:
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, size)
            except OSError as e:
                raise Mp4jError(f"{env}={size} rejected by the "
                                f"kernel: {e}") from None


def set_so_bufs(sock: socket.socket, snd: int | None,
                rcv: int | None) -> None:
    """Per-link buffer resize on a LIVE socket (ISSUE 15: the tuner's
    boundary application). Post-handshake, so it cannot widen the
    negotiated window scale — it still sizes the kernel's queue
    (useful shrinking, or growing within the scale factor)."""
    if snd:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, int(snd))
    if rcv:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, int(rcv))


def applied_buf_sizes(sock: socket.socket) -> tuple[int, int]:
    """The kernel's ACTUAL (sndbuf, rcvbuf) for this socket — what
    ``comm.link_stats()`` records per link (the kernel may round or
    double requested sizes, so the readback is the truth)."""
    return (sock.getsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF),
            sock.getsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF))


def sendall_checked(sock: socket.socket, buf) -> None:
    """THE socket send loop (shared with the shm transport's carrier):
    a socket timeout surfaces as a transport error — a peer that stops
    draining must fail like a dead receiver, not as raw
    socket.timeout. Raw OSErrors propagate (the recovery engine treats
    them as recoverable transport failures)."""
    try:
        sock.sendall(buf)
    except socket.timeout:
        raise Mp4jTransportError(
            "send timed out (peer dead or not draining?)") from None


def recv_into_checked(sock: socket.socket, view: memoryview,
                      whom: str = "", what: str = "connection") -> None:
    """THE socket exact-fill loop (shared with the shm carrier):
    timeout-aware, fail-stop on EOF. ``what`` names the wire in
    diagnostics ("connection" / "shm carrier")."""
    n = len(view)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            raise Mp4jTransportError(
                f"receive timed out with {n - got} bytes pending"
                f"{whom} (peer dead or stalled?)") from None
        if r == 0:
            raise Mp4jTransportError(
                f"peer closed {what} mid-message{whom} "
                f"({n - got}/{n} bytes short)")
        got += r


def drain_half_close(sock: socket.socket) -> None:
    """The graceful-close discipline (shared with the shm carrier):
    FIN after flushing our send queue, then a bounded drain of inbound
    bytes until the peer's FIN — a close with unread inbound data
    would otherwise turn into a TCP RST that discards our queued send
    bytes and truncates the peer's stream mid-message."""
    try:
        sock.shutdown(socket.SHUT_WR)
        sock.settimeout(1.0)
        while sock.recv(65536):
            pass
    except OSError:
        pass   # timeout/reset: the caller falls through to hard close


class TcpChannel(Channel):
    """The Channel SPI over one connected TCP (or UNIX-pair) socket."""

    transport = "tcp"

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.stats = None
        self.peer_rank = None
        self.faults = None
        self.epoch = 0
        self._chunk_bytes = tuning.chunk_bytes()
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transport (e.g. a UNIX socketpair)
        # also applied here for non-TCP/odd sockets; for TCP the
        # load-bearing application happens BEFORE connect()/listen()
        # (see apply_socket_buf_sizes) — the window scale is fixed at
        # the handshake, so a post-connect resize cannot widen it
        apply_socket_buf_sizes(sock)

    # -- SPI primitives -------------------------------------------------
    def _io_send(self, buf) -> None:
        sendall_checked(self.sock, buf)

    def _io_recv_into(self, view: memoryview) -> None:
        recv_into_checked(self.sock, view, self._whom())

    def set_timeout(self, timeout: float | None) -> None:
        """Transfer timeout, both directions: receives AND sends (a
        peer that stops draining stalls sendall the same way a dead
        sender stalls recv). ``None`` (default) is the reference's
        fail-stop behavior — a dead peer blocks forever; a finite value
        turns that hang into a diagnosable Mp4jError."""
        self.sock.settimeout(timeout)

    def native_fd(self) -> int | None:
        return self.sock.fileno()

    # (the raw plane rides the base's send_raw/recv_raw_into, which
    # delegate to the _io primitives above — one socket loop to fix)

    # -- lifecycle ------------------------------------------------------
    def invalidate(self) -> None:
        """Shut the connection down WITHOUT releasing the fd. The
        recovery teardown runs on the control thread while the
        collective thread may sit inside the native poll loop on this
        channel's raw fd number: ``shutdown`` wakes that poller with
        EOF/HUP, but an immediate ``close`` would free the fd number
        for reuse — a re-dialed channel could then recycle it and the
        still-unwinding native call would poll (or read!) the wrong
        socket. The owner closes invalidated channels later, from the
        collective thread, once no native call can be in flight
        (:meth:`ProcessCommSlave._drain_dead_channels`)."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self, graceful: bool = False) -> None:
        """Close the channel. ``graceful`` half-closes first (FIN after
        flushing our send queue, then a bounded drain of inbound bytes
        until the peer's FIN): a rank finishing its LAST collective
        must not hard-close while a slower peer is still reading our
        buffered bytes — a close with unread inbound data turns into a
        TCP RST that discards our send queue and truncates the peer's
        stream mid-message. Recovery teardown keeps the abrupt default:
        there the hard cut IS the drain (stale frames must die)."""
        if graceful:
            drain_half_close(self.sock)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect(host: str, port: int, timeout: float | None = None,
            so_bufs: tuple[int, int] | None = None) -> TcpChannel:
    # buffer sizes must be in place before the TCP handshake (window
    # scale negotiation) — so no create_connection() shortcut here;
    # so_bufs is the per-link override (ISSUE 15)
    err: Exception | None = None
    for family, socktype, proto, _, addr in socket.getaddrinfo(
            host, port, type=socket.SOCK_STREAM):
        sock = socket.socket(family, socktype, proto)
        try:
            apply_socket_buf_sizes(sock, so_bufs)
            sock.settimeout(timeout)
            sock.connect(addr)
            sock.settimeout(None)
            return TcpChannel(sock)
        except OSError as e:
            sock.close()
            err = e
    raise Mp4jTransportError(f"cannot connect to {host}:{port}: {err}")
