"""Transport SPI: the abstract ``Channel`` contract + shared framing.

ytk-mp4j's design premise is that ONE comm API spans in-process
threads, co-located processes and cross-machine sockets. This module is
the seam that makes that true below the collective layer: a
:class:`Channel` is a framed, blocking, bidirectional, order-preserving
byte channel to one peer, and everything the collectives need — object
frames, array frames, paired columnar map frames, the unframed raw
plane, epoch pinning, fault hooks, stats attribution — is implemented
HERE, once, against two transport primitives:

- ``_io_send(buf)`` — blocking write of one buffer, honoring the
  channel's transfer timeout; raises ``Mp4jTransportError`` on a dead
  or stalled peer;
- ``_io_recv_into(view)`` — blocking exact fill of ``view``, same
  contract.

Concrete transports implement just those plus lifecycle
(``set_timeout`` / ``invalidate`` / ``close``):

- :mod:`ytk_mp4j_tpu.transport.tcp` — the reference socket transport
  (framing over ``java.net.Socket`` streams in the reference, SURVEY.md
  section 2);
- :mod:`ytk_mp4j_tpu.transport.shm` — the intra-host shared-memory
  ring transport (ISSUE 7): same frames, but the "wire" is a lock-free
  ring in a ``multiprocessing.shared_memory`` segment.

Faults, epoch fencing, stats/metrics attribution and recovery compose
as LAYERS over this contract instead of special cases per transport:
the fault injector hooks ride ``_send_all`` / ``_recv_into`` (shared),
``invalidate()`` has one meaning everywhere (wake every blocked
operation with a transport error WITHOUT releasing OS resources — the
owner frees them later, from the collective thread, mirroring the
deferred-close discipline of ``_drain_dead_channels``), and wire stats
carry the channel's ``transport`` tag (tcp|shm) so every byte is
attributable to the plane it rode.

Frame layout (identical on every transport): ``u8 tag | u64
payload_len | payload``. Numeric numpy arrays take the fast path (a
small dtype/shape header, then the raw buffer — no pickling; zero-copy
on receive into a preallocated array); everything else (maps, strings,
objects, control tuples) is pickled — pickle stands in for Kryo. Either
kind may be zlib-compressed on the wire (``compress=True`` on send; the
receiver auto-detects by frame tag). Compressed ARRAYS stream in
``MP4J_CHUNK_BYTES`` pieces (``TAG_ARRAY_ZC``) so the sender's zlib
work on chunk k+1 overlaps the transfer of chunk k; the chunk stream is
self-delimiting (``u32 clen | cbytes`` repeated, ``u32 0`` terminator),
so compressed sizes never need to be known up front.

SPI enforcement: constructing a concrete channel (or a raw
``socket.socket``) outside ``transport/`` is an mp4j-lint R12 error —
rendezvous code paths hold the only baselined exceptions.
"""

from __future__ import annotations

import abc
import pickle
import struct
import time
import zlib

import numpy as np

from ytk_mp4j_tpu.utils import tuning
from ytk_mp4j_tpu.exceptions import Mp4jError

TAG_OBJ = 0
TAG_ARRAY = 1
TAG_OBJ_Z = 2      # zlib-compressed pickle
TAG_ARRAY_Z = 3    # header pickle | zlib-compressed raw buffer
TAG_ARRAY_ZC = 4   # header pickle | streamed compressed chunks

_ZLEVEL = 1  # fast; the trade is wire bytes vs CPU, not ratio records

_HDR = struct.Struct("<BQ")
_U32 = struct.Struct("<I")


def _dtype_token(dt: np.dtype) -> str:
    """Wire name for a dtype. ``dt.str`` for standard numpy dtypes;
    extension float dtypes (ml_dtypes, kind 'V') go by NAME because
    their ``str`` ('<V2') decodes as raw void."""
    return dt.name if dt.kind == "V" else dt.str


def _raw_view(arr: np.ndarray):
    """The array's bytes as a buffer; extension dtypes lack buffer
    support, so reinterpret as uint8."""
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError):
        return arr.view(np.uint8)


class Channel(abc.ABC):
    """A framed, blocking, bidirectional message channel to one peer —
    THE transport contract (see the module docstring).

    ``stats`` (optional, set by the owning slave on peer channels) is a
    :class:`ytk_mp4j_tpu.utils.stats.CommStats`; when present the
    channel books wire seconds/bytes and serialize (pickle/zlib)
    seconds into the current collective's bucket, tagged with this
    channel's ``transport``. ``peer_rank`` (likewise set by the owning
    slave) tags the booked wire spans with the remote rank, so a
    timeline span reads "wire recv<-2" instead of an anonymous
    transfer. ``faults`` is the resilience fault injector; ``epoch``
    the job-wide recovery epoch the channel was established in.
    """

    # class-level defaults so partially-constructed channels (tests
    # build bare instances around transport stand-ins) still frame
    stats = None
    peer_rank = None
    faults = None     # resilience.faults.FaultInjector on peer channels
    epoch = 0         # the job-wide epoch this channel was dialed in
    transport = "?"   # wire-plane tag for stats/metrics (tcp|shm)
    _chunk_bytes = tuning.DEFAULT_CHUNK_BYTES

    # -- transport primitives (the whole SPI surface) -------------------
    @abc.abstractmethod
    def _io_send(self, buf) -> None:
        """Blocking write of one buffer (bytes/memoryview), honoring
        the transfer timeout; ``Mp4jTransportError`` on a dead peer."""

    @abc.abstractmethod
    def _io_recv_into(self, view: memoryview) -> None:
        """Blocking exact fill of ``view``, honoring the transfer
        timeout; ``Mp4jTransportError`` on EOF/teardown/expiry."""

    @abc.abstractmethod
    def set_timeout(self, timeout: float | None) -> None:
        """Transfer timeout, both directions. ``None`` (default) is the
        reference's fail-stop behavior — a dead peer blocks forever; a
        finite value turns that hang into a diagnosable error."""

    @abc.abstractmethod
    def invalidate(self) -> None:
        """Tear the channel down WITHOUT releasing OS resources: every
        blocked (and future) operation on either end must fail with a
        transport error, but fds / shared segments stay allocated — the
        recovery teardown runs on the control thread while the
        collective thread may still sit inside an I/O primitive, and
        releasing a resource under a live operation lets a re-dial
        recycle it into the wrong exchange. The owner frees invalidated
        channels later, from the collective thread, once no operation
        can be in flight (``ProcessCommSlave._drain_dead_channels``)."""

    @abc.abstractmethod
    def close(self, graceful: bool = False) -> None:
        """Release the channel's resources. ``graceful`` flushes and
        drains first where the transport needs it (TCP must not RST a
        slower peer mid-read; the shm ring's bytes outlive the name, so
        graceful is free there)."""

    def native_fd(self) -> int | None:
        """The raw socket fd for the native C++ poll loop, or ``None``
        when this transport has no socket data plane (the caller falls
        back to the Python raw path, which is wire-identical)."""
        return None

    # -- frame-level route hooks (ISSUE 15) -----------------------------
    # The framing layer announces, just before moving a payload unit
    # whose byte length the OTHER end already knows (it traveled in the
    # frame header or a chunk length prefix), how many bytes follow.
    # Transports with more than one wire (the shm ring + carrier pair)
    # override these to steer large units onto the fast plane; both
    # ends derive the same route from the same announced length, so
    # the split can never desync. Base/TCP: one wire, no-ops.
    def _route_send(self, n: int) -> None:
        pass

    def _route_recv(self, n: int) -> None:
        pass

    def set_chunk_bytes(self, n: int) -> None:
        """Per-link pipeline chunk size (ISSUE 15): sizes this
        channel's streamed-compression pieces and chunked framed
        receives. Receiver-local on a byte-stream transport — the
        peer never needs to agree — which is exactly why the tuner
        may adapt it per link."""
        self._chunk_bytes = max(64, int(n))

    def _audit(self):
        """The owning slave's audit ring when wire folds are armed
        (``MP4J_AUDIT=verify|capture``), else None — rides the stats
        attachment so every peer channel (tcp AND shm) gets per-frame
        wire digests for free, with transport attribution (ISSUE 8)."""
        st = self.stats
        if st is not None:
            audit = st.audit
            if audit is not None and audit.wire_on:
                return audit
        return None

    # -- shared low level -----------------------------------------------
    def _send_all(self, *bufs: bytes | memoryview) -> None:
        t0 = time.perf_counter() if self.stats is not None else 0.0
        audit = self._audit()
        if audit is not None:
            # fold BEFORE any fault injection: the sender's record
            # must describe what it MEANT to send, so a flipped byte
            # below shows up as a sender/receiver digest mismatch
            audit.on_wire(self.peer_rank, "send", bufs, self.transport)
        for b in bufs:
            # per-buffer hook so an injected cut lands BETWEEN the
            # header and payload of one frame — a true mid-frame
            # tear, the hardest drain case for the receiver
            if self.faults is not None:
                self.faults.on_io(self, "send")
                # mp4j-lint: disable=R13 (length read, not a byte serialization)
                f = self.faults.take_corrupt(self, memoryview(b).nbytes)
                if f is not None:
                    from ytk_mp4j_tpu.resilience import faults as _fm

                    b = _fm.corrupt_copy(b)
            self._io_send(b)
        if self.stats is not None:
            self.stats.add_wire(sum(len(b) for b in bufs), 0,
                                time.perf_counter() - t0, chunks=0,
                                peer=self.peer_rank,
                                transport=self.transport)

    def _whom(self) -> str:
        """Peer tag for error messages (empty off the peer plane)."""
        return f" (peer {self.peer_rank})" if self.peer_rank is not None \
            else ""

    def _recv_into(self, view: memoryview) -> None:
        """Fill ``view`` (timeout-aware, fail-stop on a closed peer);
        the building block of every framed receive."""
        t0 = time.perf_counter() if self.stats is not None else 0.0
        if self.faults is not None:
            self.faults.on_io(self, "recv")
        self._io_recv_into(view)
        audit = self._audit()
        if audit is not None:
            # fold AFTER the fill: the receiver's record describes
            # what actually arrived; crc composability makes the
            # chunked receive boundaries irrelevant vs the sender's
            # per-buffer folds
            audit.on_wire(self.peer_rank, "recv", (view,),
                          self.transport)
        if self.stats is not None:
            self.stats.add_wire(0, len(view), time.perf_counter() - t0,
                                chunks=0, peer=self.peer_rank,
                                transport=self.transport)

    def _recv_exact(self, n: int) -> bytearray:
        out = bytearray(n)
        self._recv_into(memoryview(out))
        return out

    def _recv_payload(self, n: int) -> np.ndarray:
        """Large-payload receive buffer: ``np.empty`` skips bytearray's
        zero-fill pass (a whole extra memory write per received MB)."""
        out = np.empty(n, np.uint8)
        self._recv_into(memoryview(out))
        return out

    def _add_serialize(self, t0: float) -> None:
        if self.stats is not None:
            self.stats.add("serialize_seconds", time.perf_counter() - t0)

    def _add_compress(self, raw: int, wire: int) -> None:
        """Book one compression outcome (raw payload bytes -> wire
        bytes) on this link's rolling stats — the observed-ratio
        evidence the tuner's per-link compression policy consumes
        (ISSUE 15)."""
        if self.stats is not None and self.peer_rank is not None:
            self.stats.add_compress(self.peer_rank, raw, wire)

    # -- objects --------------------------------------------------------
    def send_obj(self, obj, compress: bool = False) -> None:
        t0 = time.perf_counter()
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        tag = TAG_OBJ
        if compress:
            raw_len = len(payload)
            payload = zlib.compress(payload, _ZLEVEL)
            tag = TAG_OBJ_Z
            self._add_compress(raw_len, len(payload))
        self._add_serialize(t0)
        # header first, then the payload as one announced route unit:
        # the header carries the payload length, so a multi-wire
        # transport (shm ring + carrier) steers the payload while both
        # ends agree on the route from the same number (ISSUE 15)
        self._send_all(_HDR.pack(tag, len(payload)))
        self._route_send(len(payload))
        self._send_all(payload)

    # -- arrays (fast path) --------------------------------------------
    def send_array(self, arr: np.ndarray, compress: bool = False) -> None:
        t0 = time.perf_counter()
        arr = np.ascontiguousarray(arr)
        header = pickle.dumps((_dtype_token(arr.dtype), arr.shape))
        self._add_serialize(t0)
        if compress:
            return self._send_array_zc(arr, header)
        ln = len(header) + 4 + arr.nbytes
        self._send_all(_HDR.pack(TAG_ARRAY, ln))
        self._route_send(ln)
        self._send_all(
            struct.pack("<I", len(header)),
            header,
            _raw_view(arr),
        )

    def _send_array_zc(self, arr: np.ndarray, header: bytes) -> None:
        """Streamed compressed array send (TAG_ARRAY_ZC): compress in
        ``MP4J_CHUNK_BYTES`` pieces and put each on the wire as soon as
        it exists, so zlib work on chunk k+1 overlaps the transfer of
        chunk k (and the peer's inflate of chunk k). The declared frame
        payload covers only the header; the chunk stream is
        self-delimiting (u32 length prefixes, 0 terminator), so the
        total compressed size never needs to be known up front."""
        self._send_all(_HDR.pack(TAG_ARRAY_ZC, len(header) + 4))
        self._route_send(len(header) + 4)
        self._send_all(struct.pack("<I", len(header)), header)
        comp = zlib.compressobj(_ZLEVEL)
        view = memoryview(_raw_view(arr)).cast("B")
        step = self._chunk_bytes
        wire_total = 0

        def _ship(piece: bytes) -> None:
            # each compressed piece is its own announced route unit:
            # its length travels on the carrier ahead of it, so both
            # ends route it the same way (ISSUE 15)
            self._send_all(_U32.pack(len(piece)))
            self._route_send(len(piece))
            self._send_all(piece)

        for off in range(0, len(view), step):
            t0 = time.perf_counter()
            piece = comp.compress(view[off:off + step])
            self._add_serialize(t0)
            if piece:
                wire_total += len(piece)
                _ship(piece)
        t0 = time.perf_counter()
        piece = comp.flush()
        self._add_serialize(t0)
        if piece:
            wire_total += len(piece)
            _ship(piece)
        self._send_all(_U32.pack(0))
        self._add_compress(len(view), wire_total)

    # -- paired columnar map frames ------------------------------------
    # The socket map plane's wire unit (ISSUE 4): a map travels as its
    # int32 code column followed by its value column, two back-to-back
    # array frames forming ONE protocol unit — the receiver always
    # drains both. Riding the array frames (rather than a pickled dict)
    # buys the columnar plane everything the framed path already has:
    # streaming compression (TAG_ARRAY_ZC), no-zero-fill receives, and
    # wire/serialize stats attribution.
    def send_map_columns(self, codes: np.ndarray, values: np.ndarray,
                         compress: bool = False) -> None:
        """Send one (codes, values) column pair. ``compress`` applies
        to the VALUE column only (codes are near-random int32s that
        zlib cannot help; the value column is the bulk of the bytes) —
        a fixed rule, so both ends derive the same wire format from the
        call's operand alone."""
        self.send_array(codes)
        self.send_array(values, compress=compress)

    def recv_map_columns(self) -> tuple[np.ndarray, np.ndarray]:
        """Receive one (codes, values) column pair (protocol-checked:
        a malformed pair is a wire violation, not a recoverable
        condition — both ends derive the pairing from the same
        collective call)."""
        codes = self.recv_array()
        values = self.recv_array()
        if (codes.dtype != np.int32 or codes.ndim != 1
                or values.shape[:1] != codes.shape):
            raise Mp4jError(
                f"malformed map column pair: codes {codes.dtype}"
                f"{codes.shape} vs values {values.shape} (operand "
                "disagreement between sender and receiver?)")
        return codes, values

    # -- raw (unframed) fast path --------------------------------------
    # Sizes never travel on the wire: both peers derive them from the
    # collective's segment metadata, like the reference's primitive
    # DataOutputStream fast path. Used by ProcessCommSlave's numeric
    # collectives (native poll loop when available, these when not).
    # No injector hook here: the raw plane hooks at EXCHANGE
    # granularity (_exchange_raw) so the native poll loop and these
    # fallbacks see identical fault schedules — a second hook here
    # would double-fire slow directives on fallback transports only.
    def send_raw(self, arr: np.ndarray) -> None:
        self._io_send(_raw_view(arr))

    def recv_raw_into(self, arr: np.ndarray) -> None:
        self._io_recv_into(memoryview(_raw_view(arr)))

    # -- unified receive ------------------------------------------------
    @staticmethod
    def _decode_dtype(dtype_str) -> np.dtype:
        try:
            return np.dtype(dtype_str)
        except TypeError:
            import ml_dtypes  # noqa: F401 - registers extension names

            return np.dtype(dtype_str)

    def _recv_zc_into(self, view: memoryview, itemsize: int = 1,
                      on_chunk=None) -> None:
        """Drain a TAG_ARRAY_ZC chunk stream, inflating into ``view``
        as compressed pieces arrive (decompress of chunk k overlaps the
        sender's compress+send of chunk k+1). ``on_chunk(lo, hi)``
        reports progress on ``itemsize``-aligned element boundaries so
        a merge callback only ever sees whole elements."""
        decomp = zlib.decompressobj()
        done = 0          # bytes written
        reported = 0      # elements handed to on_chunk
        chunks = 0

        def _write(piece: bytes):
            nonlocal done
            if done + len(piece) > len(view):
                raise Mp4jError(
                    "compressed stream inflates past the declared "
                    "array size (wire protocol violation)")
            view[done:done + len(piece)] = piece
            done += len(piece)

        def _report():
            nonlocal reported
            ready = done // itemsize
            if on_chunk is not None and ready > reported:
                on_chunk(reported, ready)
                reported = ready

        while True:
            (clen,) = _U32.unpack(bytes(self._recv_exact(4)))
            if clen == 0:
                break
            self._route_recv(clen)
            piece = self._recv_payload(clen)
            t0 = time.perf_counter()
            _write(decomp.decompress(piece))
            self._add_serialize(t0)
            chunks += 1
            _report()
        t0 = time.perf_counter()
        _write(decomp.flush())
        self._add_serialize(t0)
        if done != len(view):
            raise Mp4jError(
                f"compressed stream ended {len(view) - done} bytes "
                "short of the declared array size")
        if self.stats is not None and chunks:
            self.stats.add("chunks", chunks)
        _report()

    def recv(self):
        hdr = self._recv_exact(_HDR.size)
        tag, ln = _HDR.unpack(bytes(hdr))
        # the mirror of the send-side _route_send: the header told us
        # the payload length, so route the same unit the sender did
        if tag in (TAG_OBJ, TAG_OBJ_Z, TAG_ARRAY, TAG_ARRAY_Z,
                   TAG_ARRAY_ZC):
            self._route_recv(ln)
        if tag in (TAG_OBJ, TAG_OBJ_Z):
            payload = self._recv_exact(ln)
            t0 = time.perf_counter()
            if tag == TAG_OBJ_Z:
                payload = zlib.decompress(payload)
            out = pickle.loads(payload)
            self._add_serialize(t0)
            return out
        if tag in (TAG_ARRAY, TAG_ARRAY_Z, TAG_ARRAY_ZC):
            (hlen,) = struct.unpack("<I", bytes(self._recv_exact(4)))
            dtype_str, shape = pickle.loads(self._recv_exact(hlen))
            dt = self._decode_dtype(dtype_str)
            if tag == TAG_ARRAY_ZC:
                arr = np.empty(shape, dtype=dt)
                self._recv_zc_into(memoryview(_raw_view(arr)).cast("B"))
                return arr
            buf = self._recv_payload(ln - 4 - hlen)
            if tag == TAG_ARRAY_Z:
                t0 = time.perf_counter()
                # bytearray keeps the received array writable, like the
                # uncompressed path's recv_into buffer
                buf = bytearray(zlib.decompress(buf))
                self._add_serialize(t0)
            return np.frombuffer(buf, dtype=dt).reshape(shape)
        raise Mp4jError(f"unknown frame tag {tag}")

    def recv_array_into(self, out: np.ndarray, on_chunk=None) -> None:
        """Receive one array frame directly into ``out`` (a contiguous
        writable array of the exact dtype/size the sender framed — both
        ends derive it from the collective's segment metadata, so any
        mismatch is a wire-protocol violation, not a recoverable
        condition).

        ``on_chunk(lo, hi)`` (element range) fires as each
        ``MP4J_CHUNK_BYTES`` piece lands, so the caller's merge of
        chunk k runs cache-hot and overlaps the transfer of chunk k+1 —
        the framed path's half of the pipelined collective engine.
        Uncompressed frames are received in chunked pieces; compressed
        frames inflate piece-by-piece and report progress on element
        boundaries.
        """
        hdr = self._recv_exact(_HDR.size)
        tag, ln = _HDR.unpack(bytes(hdr))
        if tag not in (TAG_ARRAY, TAG_ARRAY_Z, TAG_ARRAY_ZC):
            raise Mp4jError(
                f"expected an array frame, got tag {tag} (operand "
                "disagreement between sender and receiver?)")
        self._route_recv(ln)
        (hlen,) = struct.unpack("<I", bytes(self._recv_exact(4)))
        dtype_str, shape = pickle.loads(self._recv_exact(hlen))
        dt = self._decode_dtype(dtype_str)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if dt != out.dtype or size != out.size:
            raise Mp4jError(
                f"array frame {dt}[{size}] does not match the expected "
                f"{out.dtype}[{out.size}] (segment metadata drift)")
        view = memoryview(_raw_view(out)).cast("B")
        itemsize = out.dtype.itemsize
        if tag == TAG_ARRAY:
            nbody = ln - 4 - hlen
            if nbody != len(view):
                raise Mp4jError(
                    f"array frame carries {nbody} bytes for a "
                    f"{len(view)}-byte destination")
            chunks = 0
            for lo, hi in tuning.chunk_ranges(out.size, itemsize,
                                              self._chunk_bytes):
                self._recv_into(view[lo * itemsize:hi * itemsize])
                chunks += 1
                if on_chunk is not None:
                    on_chunk(lo, hi)
            if self.stats is not None and chunks:
                self.stats.add("chunks", chunks)
            return
        if tag == TAG_ARRAY_Z:
            buf = self._recv_payload(ln - 4 - hlen)
            t0 = time.perf_counter()
            raw = zlib.decompress(buf)
            if len(raw) != len(view):
                raise Mp4jError(
                    f"compressed frame inflates to {len(raw)} bytes "
                    f"for a {len(view)}-byte destination (wire "
                    "protocol violation)")
            view[:] = raw
            self._add_serialize(t0)
            if self.stats is not None:
                self.stats.add("chunks", 1)
            if on_chunk is not None and out.size:
                on_chunk(0, out.size)
            return
        # TAG_ARRAY_ZC: shared streamed-inflate path (same protocol
        # enforcement as the generic recv)
        self._recv_zc_into(view, itemsize=itemsize, on_chunk=on_chunk)

    def recv_array(self) -> np.ndarray:
        out = self.recv()
        if not isinstance(out, np.ndarray):
            raise Mp4jError(f"expected array frame, got {type(out)}")
        return out
