"""Socket message framing for the CPU reference path.

The reference frames messages over raw ``java.net.Socket`` streams with
Kryo for objects and raw ``DataOutputStream`` writes for primitive arrays
(SURVEY.md section 2 "Serialization" [U]). Here:

- numeric numpy arrays take the fast path: a small dtype/shape header,
  then the raw buffer (no pickling; zero-copy on receive into a
  preallocated array),
- everything else (maps, strings, objects, control tuples) is pickled —
  pickle stands in for Kryo,
- either kind may be zlib-compressed on the wire (``compress=True`` on
  send; the receiver auto-detects by frame tag). Compression is
  per-operand (``Operands.compressed(...)``): a bandwidth/CPU trade the
  caller makes for highly-compressible payloads.

Frame layout: ``u8 tag | u64 payload_len | payload``.
"""

from __future__ import annotations

import pickle
import socket
import struct
import zlib

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError

TAG_OBJ = 0
TAG_ARRAY = 1
TAG_OBJ_Z = 2      # zlib-compressed pickle
TAG_ARRAY_Z = 3    # header pickle | zlib-compressed raw buffer

_ZLEVEL = 1  # fast; the trade is wire bytes vs CPU, not ratio records

_HDR = struct.Struct("<BQ")


def _dtype_token(dt: np.dtype) -> str:
    """Wire name for a dtype. ``dt.str`` for standard numpy dtypes;
    extension float dtypes (ml_dtypes, kind 'V') go by NAME because
    their ``str`` ('<V2') decodes as raw void."""
    return dt.name if dt.kind == "V" else dt.str


def _raw_view(arr: np.ndarray):
    """The array's bytes as a buffer; extension dtypes lack buffer
    support, so reinterpret as uint8."""
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError):
        return arr.view(np.uint8)


class Channel:
    """A framed, blocking, bidirectional message channel over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # non-TCP transport (e.g. a UNIX socketpair)

    # -- low level ------------------------------------------------------
    def _send_all(self, *bufs: bytes | memoryview):
        # a socket timeout (set_timeout) applies to sends too: a peer
        # that stops draining must surface as Mp4jError like a dead
        # receiver does, not as a raw socket.timeout
        try:
            for b in bufs:
                self.sock.sendall(b)
        except socket.timeout:
            raise Mp4jError(
                "send timed out (peer dead or not draining?)") from None

    def set_timeout(self, timeout: float | None) -> None:
        """Transfer timeout, both directions: receives AND sends (a
        peer that stops draining stalls sendall the same way a dead
        sender stalls recv). ``None`` (default) is the reference's
        fail-stop behavior — a dead peer blocks forever; a finite value
        turns that hang into a diagnosable Mp4jError."""
        self.sock.settimeout(timeout)

    def _recv_exact(self, n: int) -> bytearray:
        out = bytearray(n)
        view = memoryview(out)
        got = 0
        while got < n:
            try:
                r = self.sock.recv_into(view[got:], n - got)
            except socket.timeout:
                raise Mp4jError(
                    f"receive timed out with {n - got} bytes pending "
                    "(peer dead or stalled?)") from None
            if r == 0:
                raise Mp4jError("peer closed connection mid-message")
            got += r
        return out

    # -- objects --------------------------------------------------------
    def send_obj(self, obj, compress: bool = False) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        tag = TAG_OBJ
        if compress:
            payload = zlib.compress(payload, _ZLEVEL)
            tag = TAG_OBJ_Z
        self._send_all(_HDR.pack(tag, len(payload)), payload)

    # -- arrays (fast path) --------------------------------------------
    def send_array(self, arr: np.ndarray, compress: bool = False) -> None:
        arr = np.ascontiguousarray(arr)
        header = pickle.dumps((_dtype_token(arr.dtype), arr.shape))
        if compress:
            body: bytes | memoryview = zlib.compress(_raw_view(arr), _ZLEVEL)
            tag = TAG_ARRAY_Z
            nbody = len(body)
        else:
            body = _raw_view(arr)
            tag = TAG_ARRAY
            nbody = arr.nbytes
        self._send_all(
            _HDR.pack(tag, len(header) + 4 + nbody),
            struct.pack("<I", len(header)),
            header,
            body,
        )

    # -- raw (unframed) fast path ----------------------------------------
    # Sizes never travel on the wire: both peers derive them from the
    # collective's segment metadata, like the reference's primitive
    # DataOutputStream fast path. Used by ProcessCommSlave's numeric
    # collectives (native poll loop when available, these when not).
    def send_raw(self, arr: np.ndarray) -> None:
        try:
            self.sock.sendall(_raw_view(arr))
        except socket.timeout:
            raise Mp4jError(
                "raw send timed out (peer dead or not draining?)") from None

    def recv_raw_into(self, arr: np.ndarray) -> None:
        view = memoryview(_raw_view(arr))
        n = len(view)
        got = 0
        while got < n:
            try:
                r = self.sock.recv_into(view[got:], n - got)
            except socket.timeout:
                raise Mp4jError(
                    f"receive timed out with {n - got} raw bytes pending "
                    "(peer dead or stalled?)") from None
            if r == 0:
                raise Mp4jError("peer closed connection mid-message")
            got += r

    # -- unified receive ------------------------------------------------
    def recv(self):
        hdr = self._recv_exact(_HDR.size)
        tag, ln = _HDR.unpack(bytes(hdr))
        if tag in (TAG_OBJ, TAG_OBJ_Z):
            payload = self._recv_exact(ln)
            if tag == TAG_OBJ_Z:
                payload = zlib.decompress(payload)
            return pickle.loads(payload)
        if tag in (TAG_ARRAY, TAG_ARRAY_Z):
            (hlen,) = struct.unpack("<I", bytes(self._recv_exact(4)))
            dtype_str, shape = pickle.loads(self._recv_exact(hlen))
            buf = self._recv_exact(ln - 4 - hlen)
            if tag == TAG_ARRAY_Z:
                # bytearray keeps the received array writable, like the
                # uncompressed path's recv_into buffer
                buf = bytearray(zlib.decompress(buf))
            try:
                dt = np.dtype(dtype_str)
            except TypeError:
                import ml_dtypes  # noqa: F401 - registers extension names

                dt = np.dtype(dtype_str)
            return np.frombuffer(buf, dtype=dt).reshape(shape)
        raise Mp4jError(f"unknown frame tag {tag}")

    def recv_array(self) -> np.ndarray:
        out = self.recv()
        if not isinstance(out, np.ndarray):
            raise Mp4jError(f"expected array frame, got {type(out)}")
        return out

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect(host: str, port: int, timeout: float | None = None) -> Channel:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    return Channel(sock)
