"""Socket message framing for the CPU reference path.

The reference frames messages over raw ``java.net.Socket`` streams with
Kryo for objects and raw ``DataOutputStream`` writes for primitive arrays
(SURVEY.md section 2 "Serialization" [U]). Here:

- numeric numpy arrays take the fast path: a small dtype/shape header,
  then the raw buffer (no pickling; zero-copy on receive into a
  preallocated array),
- everything else (maps, strings, objects, control tuples) is pickled —
  pickle stands in for Kryo.

Frame layout: ``u8 tag | u64 payload_len | payload``.
"""

from __future__ import annotations

import pickle
import socket
import struct

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError

TAG_OBJ = 0
TAG_ARRAY = 1

_HDR = struct.Struct("<BQ")


class Channel:
    """A framed, blocking, bidirectional message channel over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # -- low level ------------------------------------------------------
    def _send_all(self, *bufs: bytes | memoryview):
        for b in bufs:
            self.sock.sendall(b)

    def _recv_exact(self, n: int) -> bytearray:
        out = bytearray(n)
        view = memoryview(out)
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:], n - got)
            if r == 0:
                raise Mp4jError("peer closed connection mid-message")
            got += r
        return out

    # -- objects --------------------------------------------------------
    def send_obj(self, obj) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        self._send_all(_HDR.pack(TAG_OBJ, len(payload)), payload)

    # -- arrays (fast path) --------------------------------------------
    def send_array(self, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        header = pickle.dumps((arr.dtype.str, arr.shape))
        payload_len = len(header) + 4 + arr.nbytes
        self._send_all(
            _HDR.pack(TAG_ARRAY, payload_len),
            struct.pack("<I", len(header)),
            header,
            memoryview(arr).cast("B"),
        )

    # -- unified receive ------------------------------------------------
    def recv(self):
        hdr = self._recv_exact(_HDR.size)
        tag, ln = _HDR.unpack(bytes(hdr))
        if tag == TAG_OBJ:
            return pickle.loads(self._recv_exact(ln))
        if tag == TAG_ARRAY:
            (hlen,) = struct.unpack("<I", bytes(self._recv_exact(4)))
            dtype_str, shape = pickle.loads(self._recv_exact(hlen))
            nbytes = ln - 4 - hlen
            buf = self._recv_exact(nbytes)
            return np.frombuffer(buf, dtype=np.dtype(dtype_str)).reshape(shape)
        raise Mp4jError(f"unknown frame tag {tag}")

    def recv_array(self) -> np.ndarray:
        out = self.recv()
        if not isinstance(out, np.ndarray):
            raise Mp4jError(f"expected array frame, got {type(out)}")
        return out

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect(host: str, port: int, timeout: float | None = None) -> Channel:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    return Channel(sock)
