from ytk_mp4j_tpu.transport.channel import Channel

__all__ = ["Channel"]
