"""Transport SPI package: the abstract :class:`Channel` contract plus
the concrete transports — TCP (:mod:`.tcp`) and intra-host shared
memory (:mod:`.shm`). Constructing a concrete channel (or a raw
socket) outside this package is an mp4j-lint R12 violation; rendezvous
holds the only baselined sites."""

from ytk_mp4j_tpu.transport.channel import Channel
from ytk_mp4j_tpu.transport.shm import ShmChannel
from ytk_mp4j_tpu.transport.tcp import TcpChannel, connect

__all__ = ["Channel", "TcpChannel", "ShmChannel", "connect"]
