from ytk_mp4j_tpu.ops import collectives, ring, ring_kernel

__all__ = ["collectives", "ring", "ring_kernel"]
