from ytk_mp4j_tpu.ops import collectives, ring

__all__ = ["collectives", "ring"]
