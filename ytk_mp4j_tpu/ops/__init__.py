from ytk_mp4j_tpu.ops import collectives

__all__ = ["collectives"]
