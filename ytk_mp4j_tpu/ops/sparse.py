"""Device-native sparse collectives — the TPU replacement for the
reference's ``Map<K, V>`` Kryo path.

The reference's sparse allreduce serializes whole hash maps with Kryo and
merges key-wise per socket round — an allocation-heavy host loop
(SURVEY.md section 3c). The TPU-native design packs each rank's sparse
contribution into dense ``(index, value)`` buffers of STATIC capacity and
rides XLA collectives:

    all_gather(idx), all_gather(val)      # one ICI collective each
    sort by idx                           # XLA sort, fused
    segment-reduce runs of equal idx      # jax.ops.segment_*
    compact to static out-capacity        # scatter into [capacity]

Everything is static-shaped (XLA requirement): unused slots carry a
SENTINEL index and the operator's identity value, so padding never
perturbs results. Host-side key<->code translation (for string keys)
lives in ``comm.tpu_comm``; this module is pure device code usable inside
``shard_map`` (e.g. embedding-gradient aggregation inside a jitted train
step — the FFM workload of BASELINE.json configs[4]).
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops.collectives import _axis_size, flat_index

# Index sentinel for padding slots. int32 max keeps sorts stable (padding
# sorts to the end) and is never a legal key code.
SENTINEL = jnp.iinfo(jnp.int32).max

# keyed by the BUILTIN Operator objects (frozen dataclass equality),
# not by name: a user-defined Operator.custom("MAX", fn, ...) must take
# the generic reduction with ITS OWN fn, not silently inherit the
# builtin segment_max
_SEGMENT_REDUCERS = {
    Operators.SUM: jax.ops.segment_sum,
    Operators.PROD: jax.ops.segment_prod,
    Operators.MAX: jax.ops.segment_max,
    Operators.MIN: jax.ops.segment_min,
}


def sort_by_key(idx, val):
    """Jointly sort ``(idx, val)`` ascending by ``idx`` with ONE
    multi-operand ``lax.sort`` — key and payload ride the same sort
    network, so there is no post-sort gather.

    The previous formulation (``order = argsort(idx); idx[order],
    val[order]``) routed the payload through a fancy-index row gather;
    the v5e-8 AOT compile of the FFM sparse step costed that program at
    180.5 GB bytes-accessed (AOT_r02) for 16 MB of live data — the
    gather's multi-chip lowering is pathological. The multi-operand
    sort carries each payload column through the sort comparators
    instead (see BASELINE.md round-3 A/B for the measured delta).

    ``val`` may be [L] or [L, ...]; trailing dims ride as extra static
    payload columns. Beyond ``_MAX_SORT_PAYLOAD_COLS`` columns the
    comparator payload would dominate the sort network, so wide rows
    fall back to sorting (key, iota) pairs and gathering rows once.
    """
    if val.ndim == 1:
        si, sv = lax.sort((idx, val), dimension=0, num_keys=1)
        return si, sv
    L = idx.shape[0]
    cols = math.prod(val.shape[1:])
    if cols == 0:
        # zero-width payload carries no data; only the keys need sorting
        return lax.sort(idx, dimension=0), val
    flat = val.reshape(L, cols)
    if cols > _MAX_SORT_PAYLOAD_COLS:
        order = jnp.argsort(idx)
        return idx[order], val[order]
    out = lax.sort((idx,) + tuple(flat[:, j] for j in range(cols)),
                   dimension=0, num_keys=1)
    return out[0], jnp.stack(out[1:], axis=1).reshape(val.shape)


# Widest value row that still rides the sort network as payload; wider
# rows fall back to argsort + one row gather (the comparator cost grows
# linearly with payload width while the gather cost is width-invariant).
_MAX_SORT_PAYLOAD_COLS = 128


def pad_to(idx, val, capacity: int, operator: Operator = Operators.SUM):
    """Pad/truncate ``(idx, val)`` to static ``capacity`` slots, filling
    with SENTINEL / the operator identity."""
    L = idx.shape[0]
    if L > capacity:
        raise ValueError(f"{L} entries exceed capacity {capacity}")
    ident = jnp.asarray(operator.identity(val.dtype), dtype=val.dtype)
    pad_i = jnp.full((capacity - L,), SENTINEL, dtype=jnp.int32)
    pad_v = jnp.full((capacity - L,) + val.shape[1:], ident, dtype=val.dtype)
    return (jnp.concatenate([idx.astype(jnp.int32), pad_i]),
            jnp.concatenate([val, pad_v]))


def segment_reduce_sorted(idx, val, capacity: int,
                          operator: Operator = Operators.SUM):
    """Reduce runs of equal index in an idx-sorted stream into at most
    ``capacity`` unique (idx, val) slots. Returns (out_idx, out_val) with
    SENTINEL/identity padding; unique entries are packed at the front in
    ascending idx order."""
    # run starts -> segment ids (cumsum of boundary flags)
    first = jnp.ones((1,), dtype=jnp.int32)
    bounds = jnp.concatenate([first, (idx[1:] != idx[:-1]).astype(jnp.int32)])
    # padding slots (SENTINEL) must not open new live segments; they sort
    # to the end so they share one trailing segment region
    seg = jnp.cumsum(bounds) - 1
    reducer = _SEGMENT_REDUCERS.get(operator)
    if reducer is not None:
        out_val = reducer(val, seg, num_segments=capacity)
    else:
        # generic associative op: log-step doubling combine over the
        # sorted stream (scan-free, static shapes)
        out_val = _generic_segment_reduce(val, seg, capacity, operator)
    # mode="drop": with a full union the sentinel segment id equals
    # `capacity` and must be discarded, not clipped onto the last slot
    out_idx = (jnp.full((capacity,), SENTINEL, dtype=jnp.int32)
               .at[seg].set(idx, mode="drop"))
    # overwrite segments that only contain sentinel slots; values may be
    # N-D (map-of-arrays operands) — broadcast the liveness mask
    ident = jnp.asarray(operator.identity(val.dtype), dtype=val.dtype)
    live = (out_idx != SENTINEL).reshape(
        (capacity,) + (1,) * (out_val.ndim - 1))
    out_val = jnp.where(live, out_val, ident)
    return out_idx, out_val


def _generic_segment_reduce(val, seg, capacity: int, operator: Operator):
    """Segment reduction for user-defined operators via a segmented
    suffix scan (Hillis-Steele): after round k, acc[i] covers elements
    [i, i+2^k) of i's segment; segment contiguity in the sorted stream
    makes the same-segment test sufficient. O(log L) rounds, static."""
    L = val.shape[0]
    acc = val
    stride = 1
    idxs = jnp.arange(L)
    expand = (L,) + (1,) * (val.ndim - 1)
    while stride < L:
        partner = idxs + stride
        partner_ok = partner < L
        p = jnp.clip(partner, 0, L - 1)
        same = ((seg[p] == seg) & partner_ok).reshape(expand)
        merged = operator.jnp_fn(acc, acc[p])
        acc = jnp.where(same, merged, acc)
        stride *= 2
    # heads of segments carry the full reduction
    head = jnp.concatenate([jnp.ones((1,), bool), seg[1:] != seg[:-1]])
    out = jnp.full((capacity,) + val.shape[1:],
                   operator.identity(val.dtype), dtype=val.dtype)
    out = out.at[jnp.where(head, seg, capacity)].set(acc, mode="drop")
    return out


def sparse_allreduce(idx, val, capacity: int,
                     operator: Operator = Operators.SUM,
                     axis_name: str = "mp4j"):
    """Key-union sparse allreduce inside ``shard_map``.

    Each member contributes up to ``local_capacity`` (= idx.shape[0])
    entries (SENTINEL-padded). Every member receives the union of keys
    with values reduced by ``operator``, packed ascending into
    ``capacity`` static slots (SENTINEL/identity padding).
    """
    gi = lax.all_gather(idx, axis_name, axis=0, tiled=True)
    gv = lax.all_gather(val, axis_name, axis=0, tiled=True)
    si, sv = sort_by_key(gi, gv)
    return segment_reduce_sorted(si, sv, capacity, operator)


def block_owner(codes, size: int, n: int):
    """Owning member of each key code under the BLOCK partition of the
    key space ``[0, size)`` — jit-side twin of :func:`meta.owner_of`
    (ranks ``0..size%n-1`` own ``ceil(size/n)`` codes, the rest
    ``floor``). SENTINEL (or any out-of-range) codes map to ``n`` so
    callers can mask them with one compare."""
    base, rem = divmod(size, n)
    cut = rem * (base + 1)
    small = codes // max(base + 1, 1)
    big = rem + (codes - cut) // max(base, 1)
    owner = jnp.where(codes < cut, small, big)
    return jnp.where((codes >= 0) & (codes < size), owner, n)


def sparse_reduce_scatter(idx, val, capacity: int, size: int,
                          operator: Operator = Operators.SUM,
                          axis_name: str = "mp4j"):
    """Key-union sparse reduce-scatter inside ``shard_map``: the union
    is reduced exactly like :func:`sparse_allreduce`, then each member
    KEEPS only the keys it owns under the block partition of the key
    space ``[0, size)`` (:func:`block_owner`), packed ascending into
    ``capacity`` SENTINEL/identity-padded slots.

    The placement rule is block-by-code, not the host backends'
    blake2b ``meta.key_partition``: in-jit there is no original key to
    hash, only its int code — and block ownership is exactly what a
    mesh-sharded parameter table (member r owns rows
    ``[r*V/n, (r+1)*V/n)``) needs from its gradient reduce-scatter.
    """
    oi, ov = sparse_allreduce(idx, val, capacity, operator, axis_name)
    me = flat_index(axis_name)
    mine = block_owner(oi, size, _axis_size(axis_name)) == me
    ident = jnp.asarray(operator.identity(ov.dtype), dtype=ov.dtype)
    keep_i = jnp.where(mine, oi, SENTINEL)
    keep_v = jnp.where(
        mine.reshape((capacity,) + (1,) * (ov.ndim - 1)), ov, ident)
    # repack the surviving entries to the front: dropped slots carry
    # SENTINEL and sort to the end (stably, preserving ascending order)
    return sort_by_key(keep_i, keep_v)


def sparse_allgather(idx, val, axis_name: str = "mp4j"):
    """Concatenate every member's (idx, val) entries and sort them by
    key code: the disjoint-union gather of the map family, in-jit.
    Output is ``[n * L]`` with all live entries ascending and SENTINEL
    padding at the end. Duplicate codes across members are RETAINED as
    adjacent entries (static shapes cannot raise data-dependently; feed
    the result to :func:`segment_reduce_sorted` to merge, which is
    exactly :func:`sparse_allreduce`)."""
    gi = lax.all_gather(idx, axis_name, axis=0, tiled=True)
    gv = lax.all_gather(val, axis_name, axis=0, tiled=True)
    return sort_by_key(gi, gv)


# ----------------------------------------------------------------------
# Host-side numpy twins of the segment-reduce kernels.
#
# The socket backend's columnar map plane (process_comm) merges
# (codes:int32, values:[n, *vshape]) column pairs with these instead of
# the per-key dict loop: same sorted-union + segment-reduce shape as the
# device kernels above, expressed over numpy so the CPU reference path
# and the TPU path share one merge algorithm. Bit-exactness contract:
# for two per-map-unique sorted streams concatenated LEFT column first,
# the stable sort keeps equal codes in (left, right) order and
# ``ufunc.reduceat`` applies the operator left-to-right — exactly
# ``op(acc[k], src[k])``, the dict loop's operand order, so the two
# paths agree bit-for-bit on every dtype.
# ----------------------------------------------------------------------
def np_sort_columns(codes, val):
    """Host twin of :func:`sort_by_key`: jointly sort ``(codes, val)``
    ascending by code with one stable argsort (payload rows ride a
    single take)."""
    order = np.argsort(codes, kind="stable")
    return codes[order], val[order]


def np_segment_reduce_sorted(codes, val, np_fn):
    """Host twin of :func:`segment_reduce_sorted` over a code-sorted
    stream: reduce runs of equal code with ``np_fn`` (a binary numpy
    ufunc — ``Operator.np_fn`` for the builtins), packing unique codes
    ascending. No sentinel padding: host shapes are dynamic."""
    if codes.size == 0:
        return codes, val
    head = np.empty(codes.size, bool)
    head[0] = True
    np.not_equal(codes[1:], codes[:-1], out=head[1:])
    starts = np.flatnonzero(head)
    if starts.size == codes.size:       # all unique: nothing to reduce
        return codes, val
    # dtype pinned: reduceat otherwise promotes narrow ints to the
    # platform int (np.sum rules), which would break the bit-exactness
    # contract with the per-key scalar merge (int32+int32 -> int32)
    return codes[starts], np_fn.reduceat(val, starts, axis=0,
                                         dtype=val.dtype)


def np_merge_sorted_columns(ca, va, cb, vb, np_fn):
    """Sorted-union merge of two code-sorted column pairs (each with
    unique codes): the vectorized replacement for the socket map path's
    per-key dict merge. ``(ca, va)`` is the ACCUMULATOR side — it is
    concatenated first, so shared codes reduce as ``np_fn(acc, src)``
    (see the section comment's bit-exactness contract)."""
    if ca.size == 0:
        return cb, vb
    if cb.size == 0:
        return ca, va
    codes = np.concatenate([ca, cb])
    val = np.concatenate([va, vb])
    return np_segment_reduce_sorted(*np_sort_columns(codes, val), np_fn)


def sparse_to_dense(idx, val, size: int,
                    operator: Operator = Operators.SUM):
    """Scatter (idx, val) into a dense [size] vector (identity-filled);
    SENTINEL slots are dropped."""
    ident = jnp.asarray(operator.identity(val.dtype), dtype=val.dtype)
    out = jnp.full((size,) + val.shape[1:], ident, dtype=val.dtype)
    safe = jnp.where(idx == SENTINEL, size, idx)
    return out.at[safe].set(val, mode="drop")
