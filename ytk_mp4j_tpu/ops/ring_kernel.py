"""Pallas ring collectives — explicit inter-chip RDMA, one level below XLA.

Where ``ops/ring.py`` hand-schedules the ring as ``lax.ppermute`` steps
(XLA still owns the transfers), this module writes the transport itself:
``pltpu.make_async_remote_copy`` moves each chunk over the ICI ring with
double-buffered communication slots and DMA-semaphore synchronization —
the closest TPU analogue of the reference's hand-written socket rounds
(SURVEY.md section 3b), where every send/recv and every merge is
explicit in user code.

Three entry points (all run inside ``shard_map`` over a 1-D mesh axis):

- :func:`ring_allreduce_kernel` — reduce-scatter + allgather fused in
  one kernel (2(n-1) steps, Rabenseifner's 2(n-1)/n bandwidth bound);
  ANY length (identity-padded internally to lane-aligned chunks) and
  any element-wise operator (the merge is fused into the ring step on
  the VPU).
- :func:`ring_reduce_scatter_kernel` — n-1 steps; member r ends with
  chunk r of the reduction (the ``coll.reduce_scatter`` contract, so
  the driver backend can substitute it directly).
- :func:`ring_allgather_kernel` — n-1 steps of forwarding this
  member's shard around the ring.

Data layout: chunks travel as 2-D ``[rows, 128]`` tiles (Mosaic's
native (sublane x lane) tiling — 1-D dynamic slices would need
start-alignment proofs the compiler cannot make), so compiled chunk
sizes are multiples of 128 x sublane(dtype) elements; the allreduce
entry pads internally, the reduce-scatter/allgather entries require it
(their chunk boundaries are the caller's contract). Interpret mode
uses ``[c, 1]`` tiles — no alignment, tiny test shapes stay tiny.

Protocol, in three layers:

1. ENTRY BARRIER (compiled path): a remote DMA must not land on a
   device that has not entered the kernel yet, so every member signals
   both ring neighbors on the Mosaic barrier semaphore
   (``get_barrier_semaphore``, keyed by ``collective_id``) and waits
   for both of its own signals before any transfer.
2. SLOT DISCIPLINE: separate send/recv buffers, alternating slots per
   global step, DMA send/recv semaphores per slot.
3. CREDIT-BASED BACKPRESSURE: the DMA waits alone do not bound ring
   skew (sends go right but a member's waits are satisfied by its LEFT
   neighbor, so a delayed rank's upstream can run ahead and overwrite
   an unconsumed receive slot). After consuming a receive slot, a
   member signals a credit to its left neighbor on a regular semaphore;
   the sender waits for that credit before reusing the slot (first use
   of each slot needs none — the buffer starts free). Residual credits
   are drained at kernel exit so every semaphore returns to zero. The
   accounting is property-tested host-side against a skew-adversarial
   scheduler in ``tests/test_ring_kernel.py``.

Tested in Pallas interpret mode on multi-device CPU meshes (the
driver's virtual-pod pattern; the interpreter serializes members and
has no remote semaphores, so barrier+credits are compiled-path only)
and AOT-compiled for a real v5e-8 TPU topology by
``check/checkaot.py`` (barrier + credit path included).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators

_LANES = 128
# minimum sublane count per dtype byte-width (Mosaic tiling table)
_SUBLANE = {8: 8, 4: 8, 2: 16, 1: 32}

# Every barrier-using kernel variant gets its OWN Mosaic barrier
# semaphore: two variants in one program (examples/08 runs the uni- and
# bidirectional kernels in one jit) would otherwise share collective_id
# 0's semaphore, which is safe only because SPMD sequences side-
# effecting calls identically — distinct ids remove the reliance on
# that (ADVICE round-2).
_COLLECTIVE_IDS = {
    ("uni", "allreduce"): 0,
    ("uni", "reduce_scatter"): 1,
    ("uni", "allgather"): 2,
    ("bidir", "allreduce"): 3,
    ("bidir", "reduce_scatter"): 4,
    ("bidir", "allgather"): 5,
}


def min_chunk_elems(dtype) -> int:
    """Compiled-path chunk-size granule: one full (sublane x lane)
    tile of ``dtype``. Callers padding for ``algo='rdma'`` align to
    this. Byte widths outside the Mosaic tiling table are rejected
    here rather than failing later with an opaque Mosaic error."""
    itemsize = jnp.dtype(dtype).itemsize
    if itemsize not in _SUBLANE:
        raise Mp4jError(
            f"dtype {jnp.dtype(dtype).name} (itemsize {itemsize}) has no "
            "entry in the Mosaic sublane tiling table; the RDMA ring "
            f"kernels support itemsizes {sorted(_SUBLANE)}")
    return _LANES * _SUBLANE[itemsize]


def round_up_chunk(n_elems: int, dtype, interpret: bool = False) -> int:
    """``n_elems`` rounded up to the compiled-path chunk granule (the
    ONE place the Mosaic tiling rule turns into a padding amount);
    identity in interpret mode."""
    if interpret:
        return max(n_elems, 1)
    g = min_chunk_elems(dtype)
    return -(-max(n_elems, 1) // g) * g


def _neighbor_barrier(left, right):
    """Block until both ring neighbors entered the kernel: remote DMA
    may not target a device still outside its pallas_call (Mosaic
    requires the collective_id barrier semaphore for this)."""
    bar = pltpu.get_barrier_semaphore()
    for nb in (left, right):
        pltpu.semaphore_signal(
            bar, inc=1, device_id=nb,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(bar, 2)


def _direction(sbuf, rbuf, send_sem, recv_sem, credit_sem, dst,
               credit_to, use_credits):
    """ONE direction's slot/DMA/credit protocol (the unit the host-side
    property model verifies). Returns (begin, finish, drain):

    - ``begin(g, value)`` waits the slot-free credit (reuse only — the
      buffer starts free), stages the send, starts the DMA toward
      ``dst``, and returns the in-flight descriptor;
    - ``finish(g, rdma)`` waits it, reads the receive slot, and signals
      the slot-free credit to ``credit_to`` (the upstream sender whose
      copy we just consumed);
    - ``drain(steps)`` absorbs the final credit per used slot so every
      semaphore exits at zero.

    The begin/finish split lets the bidirectional kernel start both
    directions' DMAs before waiting on either."""
    def begin(g, value):
        slot = g % 2
        if use_credits and g >= 2:
            # slot reuse: the downstream must have consumed its copy
            pltpu.semaphore_wait(credit_sem.at[slot], 1)
        sbuf[slot] = value
        rdma = pltpu.make_async_remote_copy(
            src_ref=sbuf.at[slot],
            dst_ref=rbuf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        return rdma

    def finish(g, rdma):
        rdma.wait()
        slot = g % 2
        got = rbuf[slot]
        if use_credits:
            pltpu.semaphore_signal(
                credit_sem.at[slot], inc=1, device_id=credit_to,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        return got

    def drain(steps):
        if use_credits:
            for slot in range(min(2, steps)):
                pltpu.semaphore_wait(credit_sem.at[slot], 1)

    return begin, finish, drain


def _ring_kernel(x_ref, out_ref, sbuf, rbuf, send_sem, recv_sem,
                 credit_sem, *, n, rows, axis_name, mode, op_fn,
                 use_credits, use_barrier):
    me = lax.axis_index(axis_name)
    right = jnp.mod(me + 1, n)
    left = jnp.mod(me - 1, n)

    if use_barrier:
        _neighbor_barrier(left, right)

    # clockwise: send right, consume what the LEFT neighbor sent, so
    # the slot-free credit goes back to the left
    begin, finish, drain = _direction(sbuf, rbuf, send_sem, recv_sem,
                                      credit_sem, right, left,
                                      use_credits)

    def exchange(g, value):
        return finish(g, begin(g, value))

    # chunk index shift: 0 makes member r finish the reduce-scatter
    # holding chunk (r+1)%n (the classic ring layout); -1 shifts every
    # selection one chunk left so member r finishes holding chunk r
    # (the coll.reduce_scatter contract)
    shift = -1 if mode == "reduce_scatter" else 0

    def rds(idx):
        """Row slice of chunk ``(idx + shift) % n``; the dynamic start
        is a multiple of the static ``rows``, which Mosaic can prove
        tile-aligned."""
        return pl.ds(jnp.mod(idx + shift, n) * rows, rows)

    steps = 0
    if mode in ("allreduce", "reduce_scatter"):
        # ---- reduce-scatter: n-1 partial-merge pushes ----------------
        acc = x_ref[rds(me), :]               # running partial
        for s in range(n - 1):
            got = exchange(steps, acc)
            acc = op_fn(got, x_ref[rds(me - s - 1), :])
            steps += 1
        if mode == "reduce_scatter":
            out_ref[...] = acc                # chunk me, fully reduced
        else:
            # acc holds chunk (me + 1) % n fully reduced
            out_ref[rds(me + 1), :] = acc
            # ---- allgather: forward the newest chunk -----------------
            # the global step index continues across the phase boundary
            # so successive transfers always alternate slots
            cur = acc
            for s in range(n - 1):
                cur = exchange(steps, cur)
                out_ref[rds(me - s), :] = cur       # owner of arrival
                steps += 1
    else:  # pure allgather of this member's shard
        out_ref[rds(me), :] = x_ref[...]
        cur = x_ref[...]
        for s in range(n - 1):
            cur = exchange(steps, cur)
            out_ref[rds(me - s - 1), :] = cur
            steps += 1

    # final credits: one per used slot, granted by the right neighbor's
    # last consumptions
    drain(steps)


def _pallas_ring(x2d, out_rows, mode, op_fn, n, rows, axis_name,
                 interpret):
    lanes = x2d.shape[1]
    vma = getattr(jax.typeof(x2d), "vma", None)
    shape = (out_rows, lanes)
    out_shape = (jax.ShapeDtypeStruct(shape, x2d.dtype, vma=vma) if vma
                 else jax.ShapeDtypeStruct(shape, x2d.dtype))
    # the interpreter serializes members (races are impossible) and
    # does not implement REMOTE semaphores, so the entry barrier and
    # the credit protocol are compiled-path only
    return pl.pallas_call(
        functools.partial(_ring_kernel, n=n, rows=rows,
                          axis_name=axis_name, mode=mode, op_fn=op_fn,
                          use_credits=not interpret,
                          use_barrier=not interpret),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, lanes), x2d.dtype),   # send slots
            pltpu.VMEM((2, rows, lanes), x2d.dtype),   # recv slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),  # slot-free credits
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=_COLLECTIVE_IDS[("uni", mode)]),
        interpret=interpret,
    )(x2d)


def _ring_kernel_bidir(x_ref, out_ref, sbufR, rbufR, sbufL, rbufL,
                       send_semR, recv_semR, send_semL, recv_semL,
                       credit_semR, credit_semL, *, n, rows2, axis_name,
                       op_fn, use_credits, use_barrier, mode):
    """Bidirectional ring collectives: two independent rings at once —
    one half of the payload clockwise (send right), the other half
    counter-clockwise (send left) — so BOTH directions of each
    full-duplex ICI link carry payload and each link direction moves
    (n-1)/n of HALF the buffer: ~half the unidirectional ring's wall
    clock (~2x throughput) on hardware where the reverse direction
    would otherwise idle. Each direction runs exactly the
    :func:`_direction` protocol the host-side property model verifies
    (slots, DMA semaphores, credits — mirrored).

    Payload split by mode: "allreduce" rings the BUFFER's halves (n
    chunks each, halves laid out [n*rows2 | n*rows2]); "reduce_scatter"
    and "allgather" ring each CHUNK's halves (chunk i occupies rows
    [i*2*rows2, (i+1)*2*rows2), its half A clockwise and half B
    counter-clockwise), matching the unidirectional chunk layout so
    the finished output is identical."""
    me = lax.axis_index(axis_name)
    right = jnp.mod(me + 1, n)
    left = jnp.mod(me - 1, n)

    if use_barrier:
        _neighbor_barrier(left, right)

    # clockwise: send right, credit the left (our upstream); counter-
    # clockwise: mirrored
    beginR, finishR, drainR = _direction(
        sbufR, rbufR, send_semR, recv_semR, credit_semR, right, left,
        use_credits)
    beginL, finishL, drainL = _direction(
        sbufL, rbufL, send_semL, recv_semL, credit_semL, left, right,
        use_credits)

    def exchange2(g, valR, valL):
        """Send valR right and valL left concurrently (both DMAs start
        before either wait); return what arrived (from the left and the
        right respectively)."""
        dmaR = beginR(g, valR)
        dmaL = beginL(g, valL)
        return finishR(g, dmaR), finishL(g, dmaL)

    if mode == "allreduce":
        def blkR(i):                  # half-0 chunk i (clockwise ring)
            return pl.ds(jnp.mod(i, n) * rows2, rows2)

        def blkL(i):                  # half-1 chunk i (counter-clockwise)
            return pl.ds((n + jnp.mod(i, n)) * rows2, rows2)
    else:
        def blkR(i):                  # chunk i's half A
            return pl.ds(jnp.mod(i, n) * 2 * rows2, rows2)

        def blkL(i):                  # chunk i's half B
            return pl.ds(jnp.mod(i, n) * 2 * rows2 + rows2, rows2)

    steps = 0
    if mode == "allgather":
        # forward this member's chunk halves in opposite directions
        out_ref[blkR(me), :] = x_ref[pl.ds(0, rows2), :]
        out_ref[blkL(me), :] = x_ref[pl.ds(rows2, rows2), :]
        curR = x_ref[pl.ds(0, rows2), :]
        curL = x_ref[pl.ds(rows2, rows2), :]
        for s in range(n - 1):
            curR, curL = exchange2(steps, curR, curL)
            out_ref[blkR(me - s - 1), :] = curR
            out_ref[blkL(me + s + 1), :] = curL
            steps += 1
        drainR(steps)
        drainL(steps)
        return

    # ---- reduce-scatter phase, both directions ----------------------
    # chunk-index shifts make member r finish holding: allreduce —
    # chunk (r+1) CW / (r-1) CCW (any layout works, the allgather phase
    # restores order); reduce_scatter — chunk r in BOTH directions (the
    # coll.reduce_scatter contract): CW shift -1, CCW shift +1
    shR = -1 if mode == "reduce_scatter" else 0
    shL = +1 if mode == "reduce_scatter" else 0
    accR = x_ref[blkR(me + shR), :]
    accL = x_ref[blkL(me + shL), :]
    for s in range(n - 1):
        gotR, gotL = exchange2(steps, accR, accL)
        accR = op_fn(gotR, x_ref[blkR(me - s - 1 + shR), :])
        accL = op_fn(gotL, x_ref[blkL(me + s + 1 + shL), :])
        steps += 1
    if mode == "reduce_scatter":
        out_ref[pl.ds(0, rows2), :] = accR      # chunk me, half A
        out_ref[pl.ds(rows2, rows2), :] = accL  # chunk me, half B
    else:
        out_ref[blkR(me + 1), :] = accR   # mirrored finishing chunks
        out_ref[blkL(me - 1), :] = accL

        # ---- allgather phase, both directions -----------------------
        curR, curL = accR, accL
        for s in range(n - 1):
            curR, curL = exchange2(steps, curR, curL)
            out_ref[blkR(me - s), :] = curR
            out_ref[blkL(me + s), :] = curL
            steps += 1

    drainR(steps)
    drainL(steps)


def _pallas_ring_bidir(x2d, out_rows, mode, op_fn, n, rows2, axis_name,
                       interpret):
    lanes = x2d.shape[1]
    vma = getattr(jax.typeof(x2d), "vma", None)
    shape = (out_rows, lanes)
    out_shape = (jax.ShapeDtypeStruct(shape, x2d.dtype, vma=vma) if vma
                 else jax.ShapeDtypeStruct(shape, x2d.dtype))
    buf = lambda: pltpu.VMEM((2, rows2, lanes), x2d.dtype)  # noqa: E731
    return pl.pallas_call(
        functools.partial(_ring_kernel_bidir, n=n, rows2=rows2,
                          axis_name=axis_name, op_fn=op_fn,
                          use_credits=not interpret,
                          use_barrier=not interpret, mode=mode),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            buf(), buf(),                       # CW send/recv slots
            buf(), buf(),                       # CCW send/recv slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),  # CW slot-free credits
            pltpu.SemaphoreType.REGULAR((2,)),  # CCW slot-free credits
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=_COLLECTIVE_IDS[("bidir", mode)]),
        interpret=interpret,
    )(x2d)


def _check_1d(x, what: str):
    if x.ndim != 1:
        raise Mp4jError(f"{what} needs a 1-D array, got shape {x.shape}")


def _tile(c: int, dtype, interpret: bool, what: str):
    """(rows, lanes) layout of a c-element chunk: full Mosaic tiles on
    the compiled path, [c, 1] in interpret mode."""
    if interpret:
        return c, 1
    granule = min_chunk_elems(dtype)
    if c % granule:
        raise Mp4jError(
            f"{what}: compiled chunks must be multiples of {granule} "
            f"elements for {jnp.dtype(dtype).name} (Mosaic tiling); "
            f"got {c} (see min_chunk_elems)")
    return c // _LANES, _LANES


def ring_allreduce_kernel(x, operator: Operator = Operators.SUM,
                          axis_name="mp4j", interpret: bool = False,
                          bidirectional: bool = False,
                          force_kernel: bool = False):
    """Allreduce of a per-member [L] array via explicit ICI RDMA.

    Any element-wise associative+commutative ``operator`` (the merge
    runs on the VPU inside the ring step); ANY length L — the buffer is
    padded with the operator identity to equal tile-aligned chunks and
    sliced back, so padding never perturbs the result.

    ``bidirectional=True`` splits the buffer in half and rings the
    halves in opposite directions simultaneously (see
    ``_ring_kernel_bidir``): each full-duplex ICI link direction
    carries (n-1)/n of HALF the buffer — ~half the unidirectional
    wall clock (~2x throughput) on real hardware. Same results either
    way.

    ``force_kernel=True`` runs the pallas_call even on a 1-member axis
    (normally an identity fast path): zero ring steps, but the Mosaic
    codegen, VMEM slot allocation, semaphore allocation and the
    collective_id entry barrier all execute — the real-chip hardware
    smoke ``check/checktpu.py`` records when only one chip exists.
    """
    n = lax.axis_size(axis_name)
    _check_1d(x, "ring allreduce kernel")
    if n == 1 and not force_kernel:
        return x
    L = x.shape[0]
    parts = 2 * n if bidirectional else n
    c = round_up_chunk(-(-L // parts), x.dtype, interpret)
    pad = parts * c - L
    if pad:
        ident = jnp.asarray(operator.identity(x.dtype), dtype=x.dtype)
        x = jnp.concatenate([x, jnp.full((pad,), ident, x.dtype)])
    rows, lanes = _tile(c, x.dtype, interpret, "ring allreduce kernel")
    if bidirectional:
        out = _pallas_ring_bidir(x.reshape(parts * rows, lanes),
                                 parts * rows, "allreduce",
                                 operator.jnp_fn, n, rows, axis_name,
                                 interpret)
    else:
        out = _pallas_ring(x.reshape(parts * rows, lanes), parts * rows,
                           "allreduce", operator.jnp_fn, n, rows,
                           axis_name, interpret)
    out = out.reshape(parts * c)
    return out[:L] if pad else out


def _bidir_rows2(rows: int, what: str) -> int:
    """Per-direction row count when a chunk's halves ride opposite
    directions; the chunk must split into two tile-aligned halves."""
    if rows % 2:
        raise Mp4jError(
            f"{what}: bidirectional chunks must split into two "
            f"tile-aligned halves; got {rows} rows (double the chunk "
            "granule, see min_chunk_elems)")
    return rows // 2


def ring_reduce_scatter_kernel(x, operator: Operator = Operators.SUM,
                               axis_name="mp4j", interpret: bool = False,
                               bidirectional: bool = False,
                               force_kernel: bool = False):
    """Member r ends with chunk r ([L/n]) of the element-wise reduction
    (the ``coll.reduce_scatter`` layout). L must be divisible by the
    axis size, and compiled chunks by ``min_chunk_elems`` (pad outside
    — the chunk boundaries are the caller's contract).
    ``bidirectional`` rings each chunk's halves in opposite directions
    (chunks must split into two tile-aligned halves). ``force_kernel``:
    see :func:`ring_allreduce_kernel`."""
    n = lax.axis_size(axis_name)
    _check_1d(x, "ring reduce-scatter kernel")
    if x.shape[0] % n:
        raise Mp4jError(
            f"ring reduce-scatter kernel needs a length divisible by "
            f"{n}, got shape {x.shape}")
    if n == 1 and not force_kernel:
        return x
    c = x.shape[0] // n
    rows, lanes = _tile(c, x.dtype, interpret,
                        "ring reduce-scatter kernel")
    if bidirectional:
        rows2 = _bidir_rows2(rows, "ring reduce-scatter kernel")
        out = _pallas_ring_bidir(x.reshape(n * rows, lanes), rows,
                                 "reduce_scatter", operator.jnp_fn, n,
                                 rows2, axis_name, interpret)
    else:
        out = _pallas_ring(x.reshape(n * rows, lanes), rows,
                           "reduce_scatter", operator.jnp_fn, n, rows,
                           axis_name, interpret)
    return out.reshape(c)


def ring_allgather_kernel(x, axis_name="mp4j", interpret: bool = False,
                          bidirectional: bool = False,
                          force_kernel: bool = False):
    """Every member ends with [n * c]: member q's [c] shard at block q
    (the ``ring.ring_allgather`` layout). Compiled shards must be
    multiples of ``min_chunk_elems``. ``bidirectional`` forwards each
    shard's halves in opposite directions (shards must split into two
    tile-aligned halves). ``force_kernel``: see
    :func:`ring_allreduce_kernel`."""
    n = lax.axis_size(axis_name)
    _check_1d(x, "ring allgather kernel")
    if n == 1 and not force_kernel:
        return x
    c = x.shape[0]
    rows, lanes = _tile(c, x.dtype, interpret, "ring allgather kernel")
    if bidirectional:
        rows2 = _bidir_rows2(rows, "ring allgather kernel")
        out = _pallas_ring_bidir(x.reshape(rows, lanes), n * rows,
                                 "allgather", None, n, rows2,
                                 axis_name, interpret)
    else:
        out = _pallas_ring(x.reshape(rows, lanes), n * rows, "allgather",
                           None, n, rows, axis_name, interpret)
    return out.reshape(n * c)
