"""Pallas ring allreduce — explicit inter-chip RDMA, one level below XLA.

Where ``ops/ring.py`` hand-schedules the ring as ``lax.ppermute`` steps
(XLA still owns the transfers), this module writes the transport itself:
``pltpu.make_async_remote_copy`` moves each chunk over the ICI ring with
double-buffered communication slots and DMA-semaphore synchronization —
the closest TPU analogue of the reference's hand-written socket rounds
(SURVEY.md section 3b), where every send/recv and every merge is
explicit in user code.

Algorithm (n = ring size, input [L] split into n chunks of c):

- reduce-scatter, n-1 steps: at step s each member sends its running
  partial sum (of chunk ``(me - s) % n``) to the right neighbor and
  merges the incoming partial (chunk ``(me - s - 1) % n``) with its
  local copy. After n-1 steps member r holds chunk ``(r + 1) % n``
  fully reduced. Each step moves c elements per link.
- allgather, n-1 steps: forward the newest finished chunk around the
  ring. Total wire traffic: 2 (n-1)/n of the buffer per member —
  Rabenseifner's bandwidth bound, the same the reference's
  halving/doubling pays over sockets.

Slot discipline: separate send/recv buffers, alternating slots per
global step, plus CREDIT-BASED BACKPRESSURE. The DMA waits alone do
not bound ring skew (sends go right but a member's waits are satisfied
by its LEFT neighbor, so a delayed rank's upstream can run ahead and
overwrite an unconsumed receive slot). After consuming a receive slot,
a member signals a credit to its left neighbor on a regular semaphore;
the sender waits for that credit before reusing the slot (first use of
each slot needs none — the buffer starts free). Residual credits are
drained at kernel exit so every semaphore returns to zero.

Tested in Pallas interpret mode on multi-device CPU meshes (the
driver's virtual-pod pattern); on real hardware the kernel compiles for
a multi-chip mesh (chunk size must then be lane-aligned; single-chip
rings are a no-op).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ytk_mp4j_tpu.exceptions import Mp4jError


def _ring_kernel(x_ref, out_ref, sbuf, rbuf, send_sem, recv_sem,
                 credit_sem, *, n, c, axis_name, use_credits):
    me = lax.axis_index(axis_name)
    right = jnp.mod(me + 1, n)
    left = jnp.mod(me - 1, n)

    def exchange(g, value):
        """Global step g: send ``value`` right, return what arrived from
        the left. Credit flow: wait for the right neighbor's
        slot-free credit before reusing a slot (first use exempt);
        after consuming our own receive slot, credit the left."""
        slot = g % 2
        if use_credits and g >= 2:
            # slot reuse: right must have consumed its copy
            pltpu.semaphore_wait(credit_sem.at[slot], 1)
        sbuf[slot] = value
        rdma = pltpu.make_async_remote_copy(
            src_ref=sbuf.at[slot],
            dst_ref=rbuf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        got = rbuf[slot]
        if use_credits:
            pltpu.semaphore_signal(
                credit_sem.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        return got

    def chunk(idx):
        return x_ref[pl.ds(idx * c, c)]

    # ---- reduce-scatter: n-1 partial-sum pushes (steps 0..n-2) -----
    acc = chunk(me)                           # running partial, [c]
    for s in range(n - 1):
        got = exchange(s, acc)
        acc = got + chunk(jnp.mod(me - s - 1, n))

    # acc now holds chunk (me + 1) % n fully reduced
    mine = jnp.mod(me + 1, n)
    out_ref[pl.ds(mine * c, c)] = acc

    # ---- allgather: forward the newest chunk (steps n-1..2n-3) -----
    # the global step index continues across the phase boundary so
    # successive transfers always alternate slots
    cur = acc
    for s in range(n - 1):
        cur = exchange(n - 1 + s, cur)
        src = jnp.mod(me - s, n)      # owner of the arrival
        out_ref[pl.ds(src * c, c)] = cur

    # drain the final credits (one per slot, granted by the right
    # neighbor's last consumptions) so every semaphore exits at zero
    if use_credits:
        total = 2 * (n - 1)
        for slot in range(min(2, total)):
            pltpu.semaphore_wait(credit_sem.at[slot], 1)


def ring_allreduce_kernel(x, axis_name="mp4j", interpret: bool = False):
    """SUM-allreduce of a per-member [L] array via explicit ICI RDMA.

    Runs inside ``shard_map`` over a 1-D mesh axis; L must be divisible
    by the axis size. SUM only: the merge is fused into the ring step
    (other operators belong to the ppermute ring in ops/ring.py).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if x.ndim != 1 or x.shape[0] % n:
        raise Mp4jError(
            f"ring kernel needs a 1-D length divisible by {n}, "
            f"got shape {x.shape}")
    L = x.shape[0]
    c = L // n
    vma = getattr(jax.typeof(x), "vma", None)
    if vma:
        out_shape = jax.ShapeDtypeStruct((L,), x.dtype, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct((L,), x.dtype)
    # the interpreter serializes members (races are impossible) and
    # does not implement REMOTE semaphore signals, so the credit
    # protocol is compiled-path only
    return pl.pallas_call(
        functools.partial(_ring_kernel, n=n, c=c, axis_name=axis_name,
                          use_credits=not interpret),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, c), x.dtype),      # send slots
            pltpu.VMEM((2, c), x.dtype),      # recv slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),  # slot-free credits
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=0),
        interpret=interpret,
    )(x)
