"""Pallas TPU kernel for (node x feature x bin) gradient histograms.

The GBDT hot op (SURVEY.md section 6: "GBDT histogram allreduce —
Higgs 11Mx28, 256 bins"). The XLA "matmul" strategy in models/gbdt.py
routes the histogram onto the MXU via a one-hot matmul, but XLA
materializes the per-tile one-hot and the hi/lo-split A operand through
HBM between the compare and the dot. This kernel fuses the whole
per-tile pipeline in VMEM:

  1. build A = [g_hi | g_lo | h_hi | h_lo] x node-one-hot, a
     [tile, 4*n_nodes] bf16 operand, from g/h/node_ids tiles
     (hi/lo mantissa bit-split for near-f32 accuracy);
  2. for each feature, generate the [tile, B] bin one-hot in VMEM and
     feed the MXU directly (contraction over the tile axis);
  3. accumulate the [4*n_nodes, F*B] f32 output across grid steps
     (constant out index_map -> the accumulator stays resident in VMEM).

Measured on v5e (N=1M, F=28, B=256, amortized over 30 dispatches):
14.5 / 16.0 / 20.2 ms per level at n_nodes = 1 / 8 / 32, vs
19.2 / 20.3 / 25.4 ms for the XLA matmul mode — ~25% faster, close to
the VPU floor of the one-hot generation itself (~15 ms: compare +
select over N*F*B lanes at ~1e12 lane-ops/s; element throughput is
dtype-independent, so the remaining cost is algorithmic, not layout).

Constraints (checked by ``pallas_hist_supported``): B and F*B must be
lane-aligned (multiples of 128) for the compiled path; any shape works
in interpret mode (used by the CPU test suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE = 1024  # contraction tile (samples per grid step)

# The [4*n_nodes, F*B] f32 accumulator stays pinned in VMEM for the
# whole grid (constant out index_map); leave headroom for the input
# blocks, the A operand and the per-feature one-hot within ~16 MB/core.
_MAX_ACC_BYTES = 8 * 2 ** 20


def split_bf16(a):
    """Split f32 ``a`` into bf16 (hi, lo) with ``hi + lo ~= a`` to ~24
    bits. ``hi`` zeroes the low 16 mantissa bits via bit-masking — NOT
    ``a - f32(bf16(a))``, which XLA's algebraic simplifier folds to
    zero — so ``lo = a - hi`` is exact in f32 and only rounds at the
    final bf16 cast (<= 2^-17 relative). Shared by this kernel and the
    XLA matmul strategy in models/gbdt.py."""
    hi = lax.bitcast_convert_type(
        lax.bitcast_convert_type(a, jnp.uint32) & jnp.uint32(0xFFFF0000),
        jnp.float32)
    return hi.astype(jnp.bfloat16), (a - hi).astype(jnp.bfloat16)


def pallas_hist_supported(n_bins: int, n_features: int,
                          n_nodes: int = 1) -> bool:
    """Compiled-path constraints: lane-aligned bin rows (static lane
    slices at multiples of B must be 128-aligned) and a VMEM-resident
    accumulator small enough to leave room for the operand buffers."""
    acc_bytes = 4 * n_nodes * n_features * n_bins * 4
    return n_bins % 128 == 0 and acc_bytes <= _MAX_ACC_BYTES


def _hist_kernel(bins_ref, g_ref, h_ref, nid_ref, out_ref, *, tile, F, B,
                 n_nodes):
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # A: [tile, 4*n_nodes] bf16 = [g_hi | g_lo | h_hi | h_lo] per node
    nid = nid_ref[:]                                      # [tile] i32
    iota_n = lax.broadcasted_iota(jnp.int32, (tile, n_nodes), 1)
    noh = nid[:, None] == iota_n                          # [tile, n]

    def hilo(v):
        return split_bf16(jnp.where(noh, v[:, None], 0.0))

    g_hi, g_lo = hilo(g_ref[:])
    h_hi, h_lo = hilo(h_ref[:])
    A = jnp.concatenate([g_hi, g_lo, h_hi, h_lo], axis=1)  # [tile, 4n]

    # The int32 compare+select below is the measured best formulation
    # of the one-hot (round-2 pricing on v5e, B=256, N=1M): a bf16
    # arithmetic one-hot (relu(1 - |b - i|), exact for integers <= 256)
    # was 9% faster STANDALONE (17.6 vs 19.3 ms) but ~20% slower in the
    # fused train step (11.2-11.5 vs 14.1-14.2 trees/sec, alternating
    # A/B) — the 16-bit intermediates interact badly with the unrolled
    # multi-level program; direct bf16/int16 == compares crash the
    # Mosaic compiler outright. Tile 1024 beat 2048/4096.
    iota_b = lax.broadcasted_iota(jnp.int32, (tile, B), 1)
    ball = bins_ref[:]                                    # [tile, F]

    for f in range(F):  # static unroll: lane slices must be static
        oh = (ball[:, f:f + 1] == iota_b).astype(jnp.bfloat16)
        part = lax.dot_general(A, oh, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        out_ref[:, f * B:(f + 1) * B] += part


def pallas_histograms(bins, g, h, node_ids, n_nodes: int, F: int, B: int,
                      tile: int = _TILE, interpret: bool = False):
    """Per-(node, feature, bin) gradient/hessian sums on the MXU.

    bins: [N, F] int32 in [0, B); g, h: [N] f32; node_ids: [N] int32 —
    ids outside [0, n_nodes) contribute exactly nothing (the one-hot
    matches no column; the GBDT sibling-subtraction path relies on this
    to exclude right-child samples via a sentinel id). Returns
    (hist_g, hist_h): [n_nodes, F, B] f32. Rows with g == h == 0
    (shard padding) contribute exactly nothing.
    """
    N = bins.shape[0]
    if N == 0:
        z = jnp.zeros((n_nodes, F, B), jnp.float32)
        return z, z
    if N < tile:
        tile = -(-N // 8) * 8          # single step, sublane-aligned
    T = -(-N // tile)
    pad = T * tile - N
    if pad:  # zero g/h rows contribute exact-zero products
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        node_ids = jnp.pad(node_ids, (0, pad))
    C = 4 * n_nodes
    # under shard_map with check_vma, the out_shape must carry the
    # union of the inputs' varying-across-mesh-axes sets
    vma = frozenset().union(*(
        getattr(jax.typeof(x), "vma", None) or frozenset()
        for x in (bins, g, h, node_ids)))
    if vma:
        out_shape = jax.ShapeDtypeStruct((C, F * B), jnp.float32, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct((C, F * B), jnp.float32)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, tile=tile, F=F, B=B,
                          n_nodes=n_nodes),
        grid=(T,),
        in_specs=[
            pl.BlockSpec((tile, F), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((C, F * B), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_shape,
        interpret=interpret,
    )(bins, g, h, node_ids)
    out = out.reshape(2, 2, n_nodes, F, B)      # [g/h, hi/lo, n, F, B]
    return out[0, 0] + out[0, 1], out[1, 0] + out[1, 1]
