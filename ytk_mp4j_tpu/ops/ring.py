"""Hand-scheduled ring collectives over a mesh axis (``lax.ppermute``).

The reference implements its collectives BY HAND over sockets —
recursive halving/doubling rounds with explicit partner exchanges
(SURVEY.md section 3b). This module is the TPU-native expression of
that same idea one level below ``psum``: the classic bandwidth-optimal
ring algorithms written as explicit ``ppermute`` steps over the ICI
ring, inside ``shard_map``.

Why it exists alongside ``ops/collectives.py`` (which just emits
``lax.psum`` etc.):

- it PROVES the transport layer the way the reference's check programs
  prove the socket rounds — each ring step is an observable ICI
  neighbor exchange, differentially tested against the one-op XLA path;
- per-step chunking is under user control, which is what you need to
  overlap a collective with compute (XLA's fused psum is opaque);
- it is the scaffold for custom collective schedules (e.g. a
  bidirectional ring or a hierarchical inter/intra pipeline) that XLA
  will not emit on its own.

Algorithms (n = axis size, chunk c = my shard split into n pieces):

- ``ring_reduce_scatter``: n-1 steps; at step s each member sends the
  partially-reduced chunk ``(rank - s)`` to its right neighbor and
  merges the incoming chunk ``(rank - s - 1)``. After n-1 steps member
  r holds chunk ``(r + 1) % n`` fully reduced.
- ``ring_allgather``: n-1 steps of forwarding the newest chunk around
  the ring until every member holds all chunks.
- ``ring_allreduce`` = reduce-scatter + allgather (Rabenseifner's
  bandwidth bound: 2 (n-1)/n of the buffer over the wire, the same
  total the reference's halving/doubling pays over sockets).

All functions run per-shard inside ``shard_map`` over a 1-D mesh axis;
leading-dimension length must be divisible by n (pad outside).
"""

from __future__ import annotations

from jax import lax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators


def _ring_perm(n: int):
    """rank -> rank+1 (mod n): the 'send right' permutation."""
    return [(i, (i + 1) % n) for i in range(n)]


def _chunks(x, n: int):
    if x.shape[0] % n:
        raise Mp4jError(
            f"ring collectives need leading dim divisible by the axis "
            f"size; got {x.shape[0]} over {n} members (pad outside)")
    return x.reshape((n, x.shape[0] // n) + x.shape[1:])


def ring_reduce_scatter(x, operator: Operator = Operators.SUM,
                        axis_name="mp4j"):
    """Member r ends with chunk ``(r + 1) % n`` of the element-wise
    reduction, as a ``[len/n, ...]`` array (tiled layout)."""
    n = lax.axis_size(axis_name)
    ch = _chunks(x, n)
    if n == 1:
        return ch[0]
    r = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    # acc starts as my chunk (r); each step: send acc right, receive
    # the left neighbor's acc, merge my local copy of the chunk the
    # received acc represents
    acc = jnp.take(ch, r % n, axis=0)
    for s in range(n - 1):
        acc = lax.ppermute(acc, axis_name, perm)
        idx = (r - s - 1) % n                      # traced, per-member
        local = jnp.take(ch, idx, axis=0)
        acc = operator.jnp_fn(acc, local)
    return acc


def ring_allgather(x, axis_name="mp4j"):
    """Every member ends with ``[n * len, ...]``: member q's shard at
    block q. ``x`` is this member's shard."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    r = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    cur = x
    # place my shard, then forward the newest chunk n-1 times; after
    # step s I hold the shard of member (r - s - 1)
    out = out.at[r].set(cur)
    for s in range(n - 1):
        cur = lax.ppermute(cur, axis_name, perm)
        src = (r - s - 1) % n
        out = out.at[src].set(cur)
    return out.reshape((n * x.shape[0],) + x.shape[1:])


def ring_allreduce(x, operator: Operator = Operators.SUM,
                   axis_name="mp4j"):
    """Bandwidth-optimal ring allreduce: reduce-scatter + allgather.

    Every member ends with the full element-wise reduction (same
    semantics as ``collectives.allreduce``, hand-scheduled as 2 (n-1)
    ppermute steps moving 2 (n-1)/n of the buffer over ICI)."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    mine = ring_reduce_scatter(x, operator, axis_name)   # chunk (r+1)%n
    gathered = ring_allgather(mine, axis_name)
    # ring_allgather lays member q's chunk at block q, but member q
    # holds reduced chunk (q+1)%n — roll one block to restore order
    return jnp.roll(gathered, shift=mine.shape[0], axis=0)
