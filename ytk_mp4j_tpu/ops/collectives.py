"""Functional TPU collectives — the per-shard layer.

These functions run INSIDE ``shard_map`` (or any context where a named
mesh axis is in scope) and lower directly to XLA ICI collectives. They are
the TPU-native replacement for the reference's recursive-halving /
recursive-doubling socket algorithms (SURVEY.md section 3b): where the
reference hand-schedules log2(n) socket rounds, we emit one XLA op and let
the compiler schedule ICI DMA.

Semantics of each collective match the reference's capability list
(SURVEY.md section 1): allreduce / reduce / broadcast / allgather /
gather / scatter / reduce_scatter, over a named axis. Operators with a
native XLA reduction (SUM / MAX / MIN) use ``lax.psum / pmax / pmin``;
PROD and user-defined operators tree-reduce a gathered axis (XLA fuses the
reduction; correctness for any associative+commutative ``jnp_fn``).

``axis_name`` may be a TUPLE of mesh axis names (e.g. ``("inter",
"intra")``) for hierarchical two-level collectives over an inter x intra
mesh — the device-side analogue of the reference's process x thread
nesting (SURVEY.md section 3d). Members are then ranked in row-major
(inter-major) order, matching the blocked global-rank layout of
``ThreadCommSlave``. XLA fuses multi-axis psum/pmax/pmin into a staged
ICI/DCN schedule.

All functions are shape-polymorphic and jit-safe: no data-dependent
control flow, static axis sizes.
"""

from __future__ import annotations

from jax import lax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators


def _axes(axis_name) -> tuple:
    return axis_name if isinstance(axis_name, tuple) else (axis_name,)


def _axis_size(axis_name) -> int:
    n = 1
    for a in _axes(axis_name):
        n *= lax.axis_size(a)
    return n


def flat_index(axis_name):
    """Row-major member index across one or more mesh axes."""
    axes = _axes(axis_name)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def _tree_reduce_gathered(x, operator: Operator, axis_name):
    """Generic-operator reduction: all_gather then pairwise tree-reduce.

    Used when no native XLA collective exists (PROD, user-defined). The
    gather is bandwidth n*|x| vs the optimal |x|*2(n-1)/n, acceptable for
    the rare generic-op path; SUM/MAX/MIN never take it.
    """
    g = lax.all_gather(x, axis_name, axis=0, tiled=False)  # [n, ...]
    if isinstance(axis_name, tuple) and g.ndim > x.ndim + 1:
        g = g.reshape((-1,) + x.shape)  # collapse per-axis stacking
    n = g.shape[0]
    parts = [g[i] for i in range(n)]
    # Balanced pairwise tree keeps float error O(log n), like the
    # reference's recursive halving combine order.
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(operator.jnp_fn(parts[i], parts[i + 1]))
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def allreduce(x, operator: Operator = Operators.SUM, axis_name="mp4j"):
    """Element-wise reduce across the axis; every member gets the result."""
    if operator.lax_collective == "psum":
        return lax.psum(x, axis_name)
    if operator.lax_collective == "pmax":
        return lax.pmax(x, axis_name)
    if operator.lax_collective == "pmin":
        return lax.pmin(x, axis_name)
    return _tree_reduce_gathered(x, operator, axis_name)


def reduce(x, operator: Operator = Operators.SUM, root: int = 0,
           axis_name="mp4j"):
    """Reduce across the axis; only ``root``'s output is meaningful.

    XLA has no rooted-reduce primitive over ICI; the allreduce is the
    bandwidth-optimal lowering and non-root results are simply unused (the
    compiler may DCE per-device work it can prove dead).
    """
    return allreduce(x, operator, axis_name)


def broadcast(x, root: int = 0, axis_name="mp4j"):
    """Every member receives ``root``'s ``x``. Numeric dtypes only."""
    idx = flat_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def allgather(x, axis_name="mp4j", tiled: bool = True):
    """Concatenate every member's ``x`` along dim 0 (``tiled=True``), or
    stack on a new leading axis (``tiled=False``)."""
    return lax.all_gather(x, axis_name, axis=0, tiled=tiled)


def gather(x, root: int = 0, axis_name="mp4j", tiled: bool = True):
    """Root obtains the concatenation; non-root outputs are unused."""
    return allgather(x, axis_name, tiled=tiled)


def scatter(x, root: int = 0, axis_name="mp4j"):
    """Each member receives its block of ``root``'s ``x``.

    ``x.shape[0]`` must be divisible by the axis size (pad at the host
    layer; see ``meta.padded_block``).
    """
    n = _axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise Mp4jError(
            f"scatter dim0 {x.shape[0]} not divisible by axis size {n}")
    full = broadcast(x, root, axis_name)
    block = x.shape[0] // n
    idx = flat_index(axis_name)
    return lax.dynamic_slice_in_dim(full, idx * block, block, axis=0)


def reduce_scatter(x, operator: Operator = Operators.SUM, axis_name="mp4j"):
    """Element-wise reduce then split: member i receives block i of the
    reduction. ``x.shape[0]`` must be divisible by the axis size."""
    n = _axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise Mp4jError(
            f"reduce_scatter dim0 {x.shape[0]} not divisible by axis size {n}")
    if operator.lax_collective == "psum" and not isinstance(axis_name, tuple):
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    full = allreduce(x, operator, axis_name)
    block = x.shape[0] // n
    idx = flat_index(axis_name)
    return lax.dynamic_slice_in_dim(full, idx * block, block, axis=0)


def barrier(axis_name="mp4j"):
    """A synchronization token: a trivial psum every member must join.

    Under XLA's execution model devices are implicitly synchronized by the
    collective schedule, so this exists for API parity with the
    reference's ``barrier()`` (SURVEY.md section 2) and as an ordering
    device in multi-step programs.
    """
    return lax.psum(jnp.ones((), jnp.int32), axis_name)
