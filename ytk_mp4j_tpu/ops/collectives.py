"""Functional TPU collectives — the per-shard layer.

These functions run INSIDE ``shard_map`` (or any context where a named
mesh axis is in scope) and lower directly to XLA ICI collectives. They are
the TPU-native replacement for the reference's recursive-halving /
recursive-doubling socket algorithms (SURVEY.md section 3b): where the
reference hand-schedules log2(n) socket rounds, we emit one XLA op and let
the compiler schedule ICI DMA.

Semantics of each collective match the reference's capability list
(SURVEY.md section 1): allreduce / reduce / broadcast / allgather /
gather / scatter / reduce_scatter, over a named axis. Operators with a
native XLA reduction (SUM / MAX / MIN) use ``lax.psum / pmax / pmin``;
PROD and user-defined operators tree-reduce a gathered axis (XLA fuses the
reduction; correctness for any associative+commutative ``jnp_fn``).

``axis_name`` may be a TUPLE of mesh axis names (e.g. ``("inter",
"intra")``) for hierarchical two-level collectives over an inter x intra
mesh — the device-side analogue of the reference's process x thread
nesting (SURVEY.md section 3d). Members are then ranked in row-major
(inter-major) order, matching the blocked global-rank layout of
``ThreadCommSlave``. XLA fuses multi-axis psum/pmax/pmin into a staged
ICI/DCN schedule.

All functions are shape-polymorphic and jit-safe: no data-dependent
control flow, static axis sizes.
"""

from __future__ import annotations

import functools
import os
import time

import jax
from jax import lax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators


# ----------------------------------------------------------------------
# native-reduce capability probe
#
# Not every backend compiler accepts every all-reduce computation: the
# axon remote compiler rejected non-SUM all-reduce HLO ("Supported
# lowering only of Sum all reduce") in round 1, then accepted it in
# round 2 — so support is probed at runtime, once per (platform, op),
# by AOT-compiling a tiny shard_map program on the default backend.
# On an unsupported backend MAX/MIN transparently fall back to the
# gathered tree reduction (same semantics, more bandwidth).
#
# Override with MP4J_NATIVE_REDUCE=1 (always native) / =0 (always
# fallback) or set_native_reduce(); unset/None means auto-probe.
# ----------------------------------------------------------------------
# both caches are process-wide by design (R7-baselined): the probe
# verdict is a property of the platform, reset via set_native_reduce
_PROBE_CACHE: dict[tuple[str, str], bool] = {}
# (platform, kind) -> monotonic time of the last transient probe verdict
_TRANSIENT_AT: dict[tuple[str, str], float] = {}
_TRANSIENT_TTL = 60.0
_FORCE_NATIVE: bool | None = None


def set_native_reduce(enabled: bool | None) -> None:
    """Force pmax/pmin emission on (True) / off (False); None = probe."""
    global _FORCE_NATIVE
    _FORCE_NATIVE = enabled


def _tracing() -> bool:
    """True when called under an ambient jax trace (inside jit/shard_map
    tracing), where the probe cannot compile its own program — nested
    shard_map under a manual mesh fails on the mesh context."""
    try:
        from jax._src import core as _core
        return not _core.trace_state_clean()
    except Exception:  # fall through to the next probe (R5-baselined)
        pass
    try:  # pragma: no cover - only if the internal API moves
        return not jax.core.trace_state_clean()
    except Exception:  # pragma: no cover
        return True  # can't tell: behave as if tracing (don't probe)


def prime_native_reduce_probe(devices=None) -> dict:
    """Run the pmax/pmin capability probe now (outside any trace) and
    return the {kind: supported} map. Driver layers call this before
    building shard_map programs so trace-time lookups hit the cache.
    ``devices``: probe THESE devices (e.g. the executing mesh's) instead
    of the default backend — a CPU mesh on a TPU-default machine must
    not inherit the TPU's verdict."""
    return {k: _native_reduce_ok(k, probe_now=True, devices=devices)
            for k in ("pmax", "pmin")}


def resolve_native_reduce(operator: Operator, devices=None) -> bool | None:
    """The effective native-reduce decision for ``operator`` on
    ``devices`` (default backend if None), resolved OUTSIDE tracing.

    None when the operator has no probed native collective (SUM always
    lowers natively; PROD/custom always tree-reduce) — the decision is
    irrelevant there. Driver layers key their jit caches on this value
    and pass it back via the ``native_reduce`` override so a later
    ``set_native_reduce`` / env flip rebuilds rather than replaying a
    stale executable."""
    kind = operator.lax_collective
    if kind not in ("pmax", "pmin"):
        return None
    return _native_reduce_ok(kind, probe_now=True, devices=devices)


def _override_verdict() -> bool | None:
    """The forced verdict (set_native_reduce / MP4J_NATIVE_REDUCE), or
    None when unforced. The single source of override classification —
    :func:`_native_reduce_ok` and :func:`native_reduce_definitive` must
    agree on it or a job-wide pin could be derived under different
    rules than the verdict itself."""
    if _FORCE_NATIVE is not None:
        return _FORCE_NATIVE
    env = os.environ.get("MP4J_NATIVE_REDUCE")
    if env in ("0", "1"):
        return env == "1"
    return None


def _resolve_devices(devices=None) -> list | None:
    """Materialized device list (accepts one-shot iterators), or None
    when no backend exists at all."""
    if devices is not None:
        return list(devices)
    try:
        return list(jax.devices())
    except Exception:  # pragma: no cover - no backend at all
        return None


def native_reduce_definitive(kind: str, devices=None) -> bool:
    """True when the current verdict for ``kind`` is PINNED — an env /
    :func:`set_native_reduce` override or a cached definitive probe —
    rather than a transient-failure optimistic default. Multi-host
    layers use this to decide whether a job-wide agreed verdict may be
    cached for the life of the comm: a transient verdict must stay
    re-examinable or a backend that genuinely rejects pmax/pmin would
    be locked onto the failing native path forever."""
    if _override_verdict() is not None:
        return True
    devs = _resolve_devices(devices)
    if devs is None:  # pragma: no cover - no backend at all
        return True
    return (devs[0].platform, kind) in _PROBE_CACHE


def _native_reduce_ok(kind: str, probe_now: bool = False,
                      devices=None) -> bool:
    forced = _override_verdict()
    if forced is not None:
        return forced
    devs = _resolve_devices(devices)
    if devs is None:  # pragma: no cover - no backend at all
        return True
    key = (devs[0].platform, kind)
    ok = _PROBE_CACHE.get(key)
    if ok is None:
        if not probe_now and _tracing():
            # Can't compile a probe mid-trace; emit the native op
            # (uncached — a later outside-trace call will probe). On a
            # rejecting backend the user sees the compiler's own error,
            # no worse than having no fallback at all.
            return True
        last = _TRANSIENT_AT.get(key)
        if last is not None and time.monotonic() - last < _TRANSIENT_TTL:
            return True  # recent transient verdict: don't re-probe yet
        ok = _probe(kind, devs)
        if ok is not None:
            _PROBE_CACHE[key] = ok
            _TRANSIENT_AT.pop(key, None)
        else:
            # transient infra failure: optimistic, but remember WHEN so
            # a rejection message that happens to contain a transient
            # token (broad markers, ADVICE round-2) cannot trigger a
            # fresh compile probe on every resolve call — re-probe at
            # most once per _TRANSIENT_TTL seconds
            _TRANSIENT_AT[key] = time.monotonic()
            return True
    return ok


# Exception-text classification. Transient infra failures (tunnel/RPC
# hiccups) must NOT poison the cache with False, and they can contain
# compiler-ish words ("RPC failed while lowering request"), so they are
# checked FIRST; only then do the rejection fragments decide. The first
# rejection marker is the axon round-1 message.
_TRANSIENT_MARKERS = ("unavailable", "deadline", "cancelled", "canceled",
                      "connection", "socket", "rpc", "tunnel", "timeout",
                      "transient")
_REJECTION_MARKERS = ("all reduce", "all-reduce", "allreduce", "lowering",
                      "unsupported", "unimplemented", "not supported",
                      "not implemented", "invalid_argument")


def _probe(kind: str, devs) -> bool | None:
    """True = compiles; False = definitive rejection; None = transient
    failure (do not cache)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    fn = {"pmax": lax.pmax, "pmin": lax.pmin}[kind]
    n = min(2, len(devs))
    mesh = Mesh(np.array(devs[:n]), ("_mp4j_probe",))
    body = functools.partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=P("_mp4j_probe"), out_specs=P("_mp4j_probe"),
    )(lambda v: fn(v, "_mp4j_probe"))
    try:
        jax.jit(body).lower(
            jax.ShapeDtypeStruct((n, 8), jnp.float32)).compile()
        return True
    except Exception as e:
        msg = str(e).lower()
        if any(m in msg for m in _TRANSIENT_MARKERS):
            return None
        if any(m in msg for m in _REJECTION_MARKERS):
            return False
        return None


def _axes(axis_name) -> tuple:
    return axis_name if isinstance(axis_name, tuple) else (axis_name,)


def _axis_size(axis_name) -> int:
    n = 1
    for a in _axes(axis_name):
        n *= lax.axis_size(a)
    return n


def flat_index(axis_name):
    """Row-major member index across one or more mesh axes."""
    axes = _axes(axis_name)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def _tree_reduce_gathered(x, operator: Operator, axis_name):
    """Generic-operator reduction: all_gather then pairwise tree-reduce.

    Used when no native XLA collective exists (PROD, user-defined). The
    gather is bandwidth n*|x| vs the optimal |x|*2(n-1)/n, acceptable for
    the rare generic-op path; SUM/MAX/MIN never take it.
    """
    g = lax.all_gather(x, axis_name, axis=0, tiled=False)  # [n, ...]
    if isinstance(axis_name, tuple) and g.ndim > x.ndim + 1:
        g = g.reshape((-1,) + x.shape)  # collapse per-axis stacking
    n = g.shape[0]
    parts = [g[i] for i in range(n)]
    # Balanced pairwise tree keeps float error O(log n), like the
    # reference's recursive halving combine order.
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(operator.jnp_fn(parts[i], parts[i + 1]))
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def allreduce(x, operator: Operator = Operators.SUM, axis_name="mp4j",
              native_reduce: bool | None = None):
    """Element-wise reduce across the axis; every member gets the result.

    MAX/MIN emit ``lax.pmax/pmin`` only when the backend compiler
    accepts non-SUM all-reduce HLO (probed once per platform — see
    :func:`set_native_reduce`); otherwise they use the gathered tree
    reduction, like PROD and user-defined operators. ``native_reduce``
    overrides the probe — driver layers resolve it against the
    EXECUTING mesh's devices (:func:`resolve_native_reduce`) since the
    trace-time probe can only see the default backend."""
    if operator.lax_collective == "psum":
        return lax.psum(x, axis_name)

    def ok(kind):
        return (native_reduce if native_reduce is not None
                else _native_reduce_ok(kind))

    if operator.lax_collective == "pmax" and ok("pmax"):
        return lax.pmax(x, axis_name)
    if operator.lax_collective == "pmin" and ok("pmin"):
        return lax.pmin(x, axis_name)
    return _tree_reduce_gathered(x, operator, axis_name)


def reduce(x, operator: Operator = Operators.SUM, root: int = 0,
           axis_name="mp4j", native_reduce: bool | None = None):
    """Reduce across the axis; only ``root``'s output is meaningful.

    Lowering to a full allreduce is a DELIBERATE choice, not a
    shortcut. XLA has no rooted-reduce primitive over ICI, and the
    bandwidth arithmetic of the hand-built alternative does not pay:
    reduce-scatter + collect-blocks-to-root moves (n-1)/n + (n-1)/n of
    the buffer per member — exactly the allreduce's 2(n-1)/n
    Rabenseifner bound — with the collect phase concentrated onto
    root's links (a hot spot the allreduce avoids), and a ppermute
    binomial tree moves |x| * log n, strictly worse for n >= 4. The
    only true saving of a rooted reduce is non-root RECEIVE traffic,
    which XLA's allreduce already overlaps; the compiler may also DCE
    per-device work it can prove dead. The arithmetic is now backed by
    compiler artifacts: the v5e-8 cost analysis prices this lowering at
    8.39 MB bytes-accessed vs 53.6 MB (RS+collect) and 88.1 MB
    (binomial tree) for the hand-built rooted variants (checkaot
    ``rooted/*``, table in BASELINE.md). Execution-time validation
    still needs a multi-chip pod.
    """
    return allreduce(x, operator, axis_name, native_reduce)


def broadcast(x, root: int = 0, axis_name="mp4j"):
    """Every member receives ``root``'s ``x``. Numeric dtypes only."""
    idx = flat_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def allgather(x, axis_name="mp4j", tiled: bool = True):
    """Concatenate every member's ``x`` along dim 0 (``tiled=True``), or
    stack on a new leading axis (``tiled=False``)."""
    return lax.all_gather(x, axis_name, axis=0, tiled=tiled)


def gather(x, root: int = 0, axis_name="mp4j", tiled: bool = True):
    """Root obtains the concatenation; non-root outputs are unused.

    Like :func:`reduce`, the allgather lowering is the measured-cost
    choice: a rooted gather moves (n-1)/n of the result onto root's
    links (serialized many-to-one — ppermute can express it only as
    n-1 rounds), while the all_gather's ring pipelines the same bytes
    across ALL links concurrently; non-root outputs cost HBM, not
    wire. Artifact-backed at v5e-8: 104.9 MB bytes-accessed vs
    365.0 MB for the sequential rooted build (checkaot ``rooted/*``,
    BASELINE.md).
    """
    return allgather(x, axis_name, tiled=tiled)


def scatter(x, root: int = 0, axis_name="mp4j"):
    """Each member receives its block of ``root``'s ``x``.

    ``x.shape[0]`` must be divisible by the axis size (pad at the host
    layer; see ``meta.padded_block``).

    Broadcast-then-slice is the measured-cost choice, same class as
    :func:`reduce`/:func:`gather`: the v5e-8 compiler prices it at
    17.8 MB bytes-accessed vs 27.9 MB for a true rooted scatter built
    from n-1 ppermutes of blocks (checkaot ``rooted/*``, table in
    BASELINE.md) — XLA pipelines the psum ring but must serialize the
    one-to-many ppermute chain.
    """
    n = _axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise Mp4jError(
            f"scatter dim0 {x.shape[0]} not divisible by axis size {n}")
    full = broadcast(x, root, axis_name)
    block = x.shape[0] // n
    idx = flat_index(axis_name)
    return lax.dynamic_slice_in_dim(full, idx * block, block, axis=0)


def reduce_scatter(x, operator: Operator = Operators.SUM, axis_name="mp4j",
                   native_reduce: bool | None = None):
    """Element-wise reduce then split: member i receives block i of the
    reduction (i = :func:`flat_index`, row-major over tuple axes).
    ``x.shape[0]`` must be divisible by the axis size.

    SUM on a TUPLE axis (hierarchical inter x intra mesh) deliberately
    stays allreduce + local slice: XLA's tuple-axis psum is ALREADY a
    staged hierarchical all-reduce, and its fused lowering beats both
    hand-staged psum_scatter cascades on the v5e:2x4 compiler's cost
    model — 9.45 MB bytes-accessed vs 13.7 MB (outer-axis-first, no
    permute) and 51.4 MB (inner-first + block permutation, the
    DCN-shrinking schedule the wire arithmetic favors). Measured and
    rejected round 3 (checkaot ``hier_rs/*``, BASELINE.md); revisit if
    pod execution shows DCN-bound behavior the cost model misses."""
    n = _axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise Mp4jError(
            f"reduce_scatter dim0 {x.shape[0]} not divisible by axis size {n}")
    if operator.lax_collective == "psum" and not isinstance(axis_name, tuple):
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    full = allreduce(x, operator, axis_name, native_reduce)
    block = x.shape[0] // n
    idx = flat_index(axis_name)
    return lax.dynamic_slice_in_dim(full, idx * block, block, axis=0)


def barrier(axis_name="mp4j"):
    """A synchronization token: a trivial psum every member must join.

    Under XLA's execution model devices are implicitly synchronized by the
    collective schedule, so this exists for API parity with the
    reference's ``barrier()`` (SURVEY.md section 2) and as an ordering
    device in multi-step programs.
    """
    return lax.psum(jnp.ones((), jnp.int32), axis_name)
