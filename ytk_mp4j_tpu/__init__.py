"""ytk-mp4j-tpu: a TPU-native collective-communication framework.

A ground-up rebuild of the capabilities of ytk-mp4j (a pure-Java, MPI-like
message-passing library for distributed ML: gather / scatter / allgather /
reduce-scatter / broadcast / reduce / allreduce over dense arrays and sparse
``Map<K, V>`` operands, with pluggable reduction operators and a two-level
process x thread hierarchy — see SURVEY.md).

This rebuild is TPU-first:

- The hot path lowers collectives to XLA ICI collectives
  (``jax.lax.psum / psum_scatter / all_gather / ppermute``) under
  ``shard_map`` over a ``jax.sharding.Mesh`` (``comm.tpu_comm``).
- The reference's Kryo-over-TCP recursive-halving design is retained as a
  CPU reference implementation for differential testing
  (``comm.process_comm`` + ``comm.master``; build-plan phase 3), with the
  element-wise merge hot loop in native C++ (``csrc/mp4j_native.cpp``).
- Sparse map collectives pack to dense index/value buffers and ride the
  same ICI collectives (``ops.sparse``; build-plan phase 5).

Reference provenance: /root/reference was empty at survey time (SURVEY.md
paragraph 0); the API surface below is built from the capability list in
SURVEY.md section 2 and BASELINE.json, with naming chosen idiomatically.
"""

from ytk_mp4j_tpu.utils import compat as _compat

_compat.install()   # backfill jax.shard_map on jax < 0.6

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.utils import trace
from ytk_mp4j_tpu.utils.trace import trace_collectives

__version__ = "0.1.0"

__all__ = [
    "Mp4jError",
    "Operator",
    "Operators",
    "Operand",
    "Operands",
    "meta",
    "trace",
    "trace_collectives",
]
