"""Distributed correctness check program — multi-host (DCN) level.

Third check family: one ``main()`` per PROCESS joins a
``jax.distributed`` job (the TPU-native rendezvous replacing the
reference's master, SURVEY.md section 3a), then checks

1. the host-level :class:`DistributedComm` slave API (dense + map
   collectives against the numpy oracle), and
2. the perf path: a jitted ``shard_map`` psum over a GLOBAL mesh built
   from every process's devices — host-local data placed with
   ``jax.make_array_from_process_local_data``, the cross-host allreduce
   staged by XLA over ICI/DCN.

Launch (2 processes x 2 CPU devices each, loopback coordinator):

    for i in 0 1; do
        python -m ytk_mp4j_tpu.check.checkdist \
            --coordinator localhost:9876 --num-processes 2 \
            --process-id $i --local-devices 2 &
    done
"""

from __future__ import annotations

import argparse
import sys
import traceback

import numpy as np


def check(comm, length: int = 97) -> int:
    from ytk_mp4j_tpu import meta
    from ytk_mp4j_tpu.check._oracle import expected_reduce, rank_data
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    n, r = comm.slave_num, comm.rank
    fails = 0

    def expect(name, ok):
        nonlocal fails
        if not ok:
            fails += 1
            comm.error(f"{name} MISMATCH")

    for operand in (Operands.DOUBLE, Operands.FLOAT, Operands.INT):
        exact = operand.dtype.kind != "f"
        alls = [rank_data(q, length, operand, 3000) for q in range(n)]
        ranges = meta.partition_range(0, length, n)
        for op_name in ("SUM", "MAX", "MIN", "PROD"):
            op = Operators.by_name(op_name)
            want = expected_reduce(alls, op_name)
            arr = alls[r].copy()
            comm.allreduce_array(arr, operand, op)
            ok = (np.array_equal(arr, want) if exact
                  else np.allclose(arr, want, rtol=1e-5, atol=1e-6))
            expect(f"allreduce/{operand.name}/{op_name}", ok)
        # rooted + segment family
        want = expected_reduce(alls, "SUM")
        arr = alls[r].copy()
        comm.reduce_array(arr, operand, Operators.SUM, root=0)
        if r == 0:
            expect(f"reduce/{operand.name}",
                   np.allclose(arr, want, rtol=1e-5))
        arr = alls[r].copy()
        comm.broadcast_array(arr, operand, root=n - 1)
        expect(f"broadcast/{operand.name}", np.array_equal(arr, alls[n - 1]))
        arr = alls[r].copy()
        comm.reduce_scatter_array(arr, operand, Operators.SUM)
        s, e = ranges[r]
        expect(f"reduce_scatter/{operand.name}",
               np.allclose(arr[s:e], want[s:e], rtol=1e-5))
        arr = alls[r].copy()
        comm.allgather_array(arr, operand)
        want_g = np.concatenate(
            [alls[q][s:e] for q, (s, e) in enumerate(ranges)])
        expect(f"allgather/{operand.name}", np.array_equal(arr, want_g))
        arr = alls[r].copy()
        comm.scatter_array(arr, operand, root=0)
        s, e = ranges[r]
        expect(f"scatter/{operand.name}",
               np.array_equal(arr[s:e], alls[0][s:e]))
        comm.barrier()

    # map collectives over the pickled-object path
    maps = [{f"k{(q + j) % (n + 1)}": float(q * 10 + j) for j in range(3)}
            for q in range(n)]
    want_merged: dict = {}
    for m in maps:
        for k, v in m.items():
            want_merged[k] = want_merged.get(k, 0.0) + v
    d = dict(maps[r])
    comm.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
    expect("allreduce_map", d == want_merged)
    d = {f"r{r}": float(r)}
    comm.allgather_map(d, Operands.DOUBLE)
    expect("allgather_map", d == {f"r{q}": float(q) for q in range(n)})
    d = dict(maps[r])
    comm.reduce_scatter_map(d, Operands.DOUBLE, Operators.SUM)
    expect("reduce_scatter_map",
           d == {k: v for k, v in want_merged.items()
                 if meta.key_partition(k, n) == r})
    # int-keyed maps with a DRIFTING vocabulary: the device plane's
    # synchronized codecs must keep codes identical across processes
    # while only novel keys ride the pickled exchange
    for step in range(3):
        imaps = [{int(q * 5 + j + 3 * step): float(q * 10 + j)
                  for j in range(4)} for q in range(n)]
        want: dict = {}
        for m in imaps:
            for k, v in m.items():
                want[k] = want.get(k, 0.0) + v
        d = dict(imaps[r])
        comm.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
        expect(f"allreduce_map_int/{step}", d == want)
        d = dict(imaps[r])
        comm.reduce_scatter_map(d, Operands.DOUBLE, Operators.SUM)
        expect(f"reduce_scatter_map_int/{step}",
               d == {k: v for k, v in want.items()
                     if meta.key_partition(k, n) == r})
    # rooted reduce on the map device plane: only root's dict merges
    d = dict(maps[r])
    comm.reduce_map(d, Operands.DOUBLE, Operators.SUM, root=n - 1)
    expect("reduce_map", d == (want_merged if r == n - 1 else maps[r]))
    # MAX on the map device plane (segment reducers, not all-reduce HLO)
    d = dict(maps[r])
    want_max: dict = {}
    for m in maps:
        for k, v in m.items():
            want_max[k] = max(want_max.get(k, -np.inf), v)
    comm.allreduce_map(d, Operands.DOUBLE, Operators.MAX)
    expect("allreduce_map_max", d == want_max)
    # vocabulary reset is collective: every rank resets at the same
    # point, then the next call resynchronizes from live keys
    comm.reset_map_vocabularies()
    d = dict(maps[r])
    comm.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
    expect("allreduce_map_after_reset", d == want_merged)
    # a HOST-ONLY custom operator (python truthiness — untraceable)
    # must route numeric maps onto the pickled plane, not crash in jit
    from ytk_mp4j_tpu.operators import Operator
    absmax = Operator.custom(
        "ABSMAX_HOST", lambda a, b: a if abs(a) > abs(b) else b, 0.0)
    d = {k: (1.0 + v) * (-1.0 if r % 2 else 1.0)
         for k, v in maps[r].items()}
    plus = [{k: (1.0 + v) * (-1.0 if q % 2 else 1.0)
             for k, v in maps[q].items()} for q in range(n)]
    want_abs: dict = {}
    for m in plus:
        for k, v in m.items():
            want_abs[k] = (v if k not in want_abs
                           or abs(v) > abs(want_abs[k]) else want_abs[k])
    comm.allreduce_map(d, Operands.DOUBLE, absmax)
    expect("allreduce_map_custom_host", d == want_abs)
    return fails


def check_global_mesh(comm) -> int:
    """The perf path: jitted psum over a global (all-process) mesh."""
    import jax
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ytk_mp4j_tpu.comm.distributed import global_mesh, hier_global_mesh
    from ytk_mp4j_tpu.operators import Operators
    from ytk_mp4j_tpu.ops import collectives as coll

    fails = 0
    for mesh, axes in ((global_mesh(), "mp4j"),
                       (hier_global_mesh(), ("inter", "intra"))):
        D = mesh.size
        L = jax.local_device_count()
        spec = P(axes if isinstance(axes, str) else axes)
        # host-local rows -> one global [D, 8] array sharded over ranks
        local = np.stack([
            np.full(8, comm.rank * L + j, np.float32) for j in range(L)])
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), local, (D, 8))

        @partial(jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
        def f(x):
            return coll.allreduce(x, Operators.SUM, axes)

        out = jax.jit(f)(garr)
        # row q is constant q; psum over ranks puts sum(range(D)) in
        # every slot
        want = float(sum(range(D)))
        got = np.asarray(
            [s.data for s in out.addressable_shards][0]).reshape(-1)[0]
        if not np.isclose(got, want):
            comm.error(f"global-mesh psum MISMATCH: {got} != {want}")
            fails += 1
    return fails


def check_gbdt_global_mesh(comm) -> int:
    """Consumer end-to-end at DCN scale: distributed GBDT training over
    the global (all-process) mesh must match a single-device reference
    computed locally on each process from the same seeded data."""
    import jax

    from ytk_mp4j_tpu.comm.distributed import global_mesh
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
    from ytk_mp4j_tpu.parallel import make_mesh

    fails = 0
    rng = np.random.default_rng(1234)           # same data everywhere
    N, F, B = 512, 4, 16
    bins = rng.integers(0, B, (N, F)).astype(np.int32)
    y = (np.sin(bins[:, 1]) + 0.1 * rng.standard_normal(N)).astype(
        np.float32)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=3, learning_rate=0.3,
                     n_trees=2)

    dist = GBDTTrainer(cfg, mesh=global_mesh())
    # eval_set exercises the multi-process per-round evaluation path
    # (trees from the global mesh consumed by a local jit)
    trees_d, preds_d = dist.train(bins, y, eval_set=(bins[:64], y[:64]))
    if len(dist.eval_history_) != cfg.n_trees or not all(
            np.isfinite(m) for m in dist.eval_history_):
        comm.error("gbdt eval history MISMATCH")
        fails += 1

    local = GBDTTrainer(
        cfg, mesh=make_mesh(1, devices=jax.local_devices()[:1]))
    trees_s, preds_s = local.train(bins, y)
    # order-insensitive comparison: the distributed psum and the
    # single-device scan reduce histograms in different float orders
    # (~5e-6 rel), so a near-tied split gain may legitimately flip
    # argmax and move individual predictions by whole leaf deltas; the
    # training MSE is robust to that (both trees are near-optimal)
    # while still catching real collective bugs (wrong sums -> wrong
    # splits everywhere -> MSE collapses toward var(y))
    mse_d = float(np.mean((preds_d[:N] - y) ** 2))
    mse_s = float(np.mean((preds_s[:N] - y) ** 2))
    var = float(np.var(y))
    if not (mse_d < 0.5 * var
            and abs(mse_d - mse_s) <= max(0.1 * mse_s, 1e-3)):
        comm.error(f"gbdt global-mesh MISMATCH: mse_d={mse_d:.5f} "
                   f"mse_s={mse_s:.5f} var={var:.5f}")
        fails += 1
    return fails


def check_ffm_global_mesh(comm) -> int:
    """The sparse-gradient consumer at DCN scale: FFM with the
    gathered-row sparse allreduce (check_vma=False collective over a
    multi-process mesh) must train to the same loss as a local dense
    run on identical seeded data."""
    import jax

    from ytk_mp4j_tpu.comm.distributed import global_mesh
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer
    from ytk_mp4j_tpu.parallel import make_mesh

    fails = 0
    rng = np.random.default_rng(77)             # same data everywhere
    N, K, nf, k, F = 256, 3, 3, 3, 500
    feats = rng.integers(0, F, (N, K)).astype(np.int32)
    fields = rng.integers(0, nf, (N, K)).astype(np.int32)
    vals = rng.random((N, K)).astype(np.float32)
    y = (rng.random(N) > 0.5).astype(np.float32)
    cfg = FMConfig(model="ffm", n_features=F, n_fields=nf, k=k,
                   max_nnz=K, learning_rate=0.2, l2=1e-4,
                   init_scale=0.1)

    sparse = FMTrainer(cfg, mesh=global_mesh(), sparse_grads=True)
    _, losses_d = sparse.fit(feats, fields, vals, y, n_steps=6, seed=5)
    dense = FMTrainer(
        cfg, mesh=make_mesh(1, devices=jax.local_devices()[:1]),
        sparse_grads=False)
    _, losses_s = dense.fit(feats, fields, vals, y, n_steps=6, seed=5)
    # NaN-proof form (like check_gbdt_global_mesh): any non-finite loss
    # on EITHER side, or a divergence, must count as failure —
    # `abs(x - nan) > tol` is False and would otherwise pass silently
    ok = (all(np.isfinite(m) for m in losses_d)
          and np.isfinite(losses_s[-1])
          and abs(losses_d[-1] - losses_s[-1]) <= 1e-3)
    if not ok:
        comm.error(f"ffm global-mesh MISMATCH: sparse {losses_d}"
                   f" vs dense-local {losses_s[-1]}")
        fails += 1
    return fails


def check_ffm_round4_global_mesh(comm) -> int:
    """Round-4 FFM surfaces at DCN scale: the mesh-SHARDED embedding
    table and the streaming fit must both train to the replicated
    full-batch losses over the global (all-process) mesh."""
    from ytk_mp4j_tpu.comm.distributed import global_mesh
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer

    fails = 0
    rng = np.random.default_rng(42)             # same data everywhere
    N, K, nf, k, F = 192, 3, 3, 3, 300
    feats = rng.integers(0, F, (N, K)).astype(np.int32)
    fields = rng.integers(0, nf, (N, K)).astype(np.int32)
    vals = rng.random((N, K)).astype(np.float32)
    y = (rng.random(N) > 0.5).astype(np.float32)
    cfg = FMConfig(model="ffm", n_features=F, n_fields=nf, k=k,
                   max_nnz=K, learning_rate=0.2, init_scale=0.1)

    rep = FMTrainer(cfg, mesh=global_mesh(), sparse_grads=True)
    _, l_rep = rep.fit(feats, fields, vals, y, n_steps=3, seed=11)
    sh = FMTrainer(cfg, mesh=global_mesh(), sparse_grads=True,
                   table_sharding="sharded")
    p_sh, l_sh = sh.fit(feats, fields, vals, y, n_steps=3, seed=11)
    if not (all(np.isfinite(m) for m in l_sh)
            and np.allclose(l_sh, l_rep, rtol=1e-4, atol=1e-6)):
        comm.error(f"sharded-table global-mesh MISMATCH: {l_sh} "
                   f"vs {l_rep}")
        fails += 1
    # sharded SERVE over the multi-process mesh (a collective: every
    # process calls predict together; the output fetch is a
    # process_allgather) vs a local dense scorer on the gathered table
    import jax

    from ytk_mp4j_tpu.parallel import make_mesh

    got = sh.predict(p_sh, feats, fields, vals)
    local = FMTrainer(cfg, mesh=make_mesh(
        1, devices=jax.local_devices()[:1]))
    want = local.predict(
        (sh._to_host(p_sh[0]), sh._to_host(p_sh[1]),
         sh.full_table(p_sh)), feats, fields, vals)
    if not np.allclose(got, want, rtol=1e-4, atol=1e-5):
        comm.error("sharded predict global-mesh MISMATCH")
        fails += 1

    # reuse rep: same cfg/mesh/slots -> same compiled step; fit_stream
    # with params=None re-inits from the seed, no state carryover
    _, l_stream = rep.fit_stream(
        ((feats, fields, vals, y) for _ in range(3)), seed=11)
    if not np.allclose(l_stream, l_rep, rtol=1e-5, atol=1e-7):
        comm.error(f"fit_stream global-mesh MISMATCH: {l_stream} "
                   f"vs {l_rep}")
        fails += 1
    # configs[4] COMPOSED at DCN scale: streamed chunks into the
    # mesh-SHARDED table (reuses sh's compiled step; double-buffered
    # dispatch path)
    _, l_shs = sh.fit_stream(
        ((feats, fields, vals, y) for _ in range(3)), seed=11)
    if not np.allclose(l_shs, l_rep, rtol=1e-5, atol=1e-7):
        comm.error(f"sharded fit_stream global-mesh MISMATCH: {l_shs} "
                   f"vs {l_rep}")
        fails += 1
    return fails


def check_binning_dist(comm) -> int:
    """Distributed quantile binning at DCN scale: each process sketches
    its own shard, ONE allgather merges the sketches, and every rank
    must end with (a) identical edges and (b) edges within 2/Q of the
    exact quantile positions of the pooled data (the merge's documented
    tolerance, tests/test_binning.py)."""
    from ytk_mp4j_tpu.models.binning import QuantileBinner
    from ytk_mp4j_tpu.operands import Operands

    fails = 0
    rng = np.random.default_rng(99)             # same data everywhere
    N, F, B = 6_000, 3, 16
    X = np.stack([rng.standard_normal(N),
                  rng.lognormal(0.0, 1.0, N),
                  rng.uniform(-2, 9, N)], axis=1).astype(np.float32)
    shards = np.array_split(X, comm.slave_num)
    binner = QuantileBinner(B).fit_distributed(
        shards[comm.rank], comm, sample=None)

    flat = binner.edges.ravel().astype(np.float32)
    buf = np.zeros(comm.slave_num * flat.size, np.float32)
    buf[comm.rank * flat.size: (comm.rank + 1) * flat.size] = flat
    comm.allgather_array(buf, Operands.FLOAT)
    rows = buf.reshape(comm.slave_num, flat.size)
    if not all(np.array_equal(rows[0], r) for r in rows[1:]):
        comm.error("binning edges DIFFER across ranks")
        fails += 1

    qs = np.arange(1, B) / B
    err = 0.0
    for f in range(F):
        col = np.sort(X[:, f])
        pos = np.searchsorted(col, binner.edges[f], side="right") / N
        err = max(err, float(np.abs(pos - qs).max()))
    if err > 2.0 / B:
        comm.error(f"binning quantile error {err:.4f} > {2.0 / B:.4f}")
        fails += 1

    # distributed binning FROM INSIDE the trainer (round-5 consumer
    # path): every rank calls train_raw(comm=...) together; the binner
    # fits via fit_distributed on each rank's own rows and the edges +
    # predictions must agree across ranks
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer
    from ytk_mp4j_tpu.parallel import make_mesh
    import jax

    Xr = shards[comm.rank]
    yr = (Xr[:, 0] > 0).astype(np.float32)
    # WEIGHTED rows: the weights flow into the distributed sketch
    # (weighted CDF mass over the allgather) AND the boosting
    # gradients; rank-dependent data with job-identical edges is the
    # invariant under test
    wr = 1.0 + (np.arange(Xr.shape[0]) % 3).astype(np.float64)
    cfg = GBDTConfig(n_features=F, n_bins=B, depth=2, n_trees=2,
                     learning_rate=0.5)
    tr = GBDTTrainer(cfg, mesh=make_mesh(
        1, devices=jax.local_devices()[:1]))
    trees, _ = tr.train_raw(Xr, yr, seed=4, comm=comm,
                            sample_weight=wr)
    # per-rank data -> per-rank trees; the BINNER must still be
    # job-identical (the distributed sketch merge) and must equal a
    # standalone WEIGHTED fit_distributed with the same inputs (below
    # — weighted edges differ from the unweighted binner at the top)
    seg = tr.binner_.edges.ravel().astype(np.float32)
    buf2 = np.zeros(comm.slave_num * seg.size, np.float32)
    buf2[comm.rank * seg.size:(comm.rank + 1) * seg.size] = seg
    comm.allgather_array(buf2, Operands.FLOAT)
    rows2 = buf2.reshape(comm.slave_num, seg.size)
    if not all(np.array_equal(rows2[0], r) for r in rows2[1:]):
        comm.error("train_raw distributed binning DIFFERS across ranks")
        fails += 1
    standalone = QuantileBinner(B).fit_distributed(
        Xr, comm, sample=1_000_000, seed=4, sample_weight=wr)
    if not np.array_equal(tr.binner_.edges, standalone.edges):
        comm.error("train_raw binner != standalone weighted "
                   "fit_distributed")
        fails += 1
    if not np.isfinite(tr.predict_raw(X[:64], trees)).all():
        comm.error("train_raw predict_raw produced non-finite values")
        fails += 1
    return fails


def check_dense_plane_timing(comm, elems: int = 1 << 20) -> int:
    """A/B the dense data plane: device psum vs the host
    allgather+loop formulation on the same buffer. Correctness is
    asserted; the timing is logged (loopback CPU timings are noisy —
    the recorded numbers live in BASELINE.md)."""
    import time

    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    rng = np.random.default_rng(7 + comm.rank)
    base = rng.standard_normal(elems).astype(np.float32)
    reps = 3

    # warm both paths first: the device path jit-compiles on first use
    comm.allreduce_array(base.copy(), Operands.FLOAT, Operators.SUM)
    comm._reduce_rows(comm._allgather_rows(base.copy()), Operators.SUM)

    dev = None
    comm.barrier()
    t0 = time.perf_counter()
    for _ in range(reps):
        dev = base.copy()
        comm.allreduce_array(dev, Operands.FLOAT, Operators.SUM)
    t_dev = (time.perf_counter() - t0) / reps

    host = None
    comm.barrier()
    t0 = time.perf_counter()
    for _ in range(reps):
        rows = comm._allgather_rows(base.copy())
        host = comm._reduce_rows(rows, Operators.SUM)
    t_host = (time.perf_counter() - t0) / reps

    fails = 0
    if not np.allclose(dev, host, rtol=1e-5, atol=1e-5):
        comm.error("dense-plane device vs host MISMATCH")
        fails += 1
    comm.info(f"dense plane {elems} f32 x {comm.slave_num} ranks: "
              f"device {t_dev * 1e3:.1f} ms, host-allgather "
              f"{t_host * 1e3:.1f} ms ({t_host / max(t_dev, 1e-9):.2f}x)")
    return fails


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True, help="host:port")
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=2)
    ap.add_argument("--length", type=int, default=97)
    args = ap.parse_args(argv)

    # CPU multi-process job: each process contributes --local-devices
    # virtual devices (the "multi-node without a cluster" pattern,
    # SURVEY.md section 4). The device-count config is version-gated:
    # `jax_num_cpu_devices` only exists on newer jax; older versions
    # (this image ships one without it) take the XLA flag instead —
    # which must be in the environment BEFORE jax initializes any
    # backend, hence the env check ahead of the import.
    import os

    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count="
            f"{args.local_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", args.local_devices)
    except AttributeError:
        pass    # older jax: the XLA flag above already did the job
    # DOUBLE/LONG operands round-trip through the devices; without x64
    # they would be silently downcast (the backend raises instead)
    jax.config.update("jax_enable_x64", True)

    from ytk_mp4j_tpu.comm.distributed import init_distributed

    comm = init_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id)
    try:
        fails = check(comm, args.length)
        fails += check_global_mesh(comm)
        fails += check_gbdt_global_mesh(comm)
        fails += check_ffm_global_mesh(comm)
        fails += check_ffm_round4_global_mesh(comm)
        fails += check_binning_dist(comm)
        fails += check_dense_plane_timing(comm)
        comm.info(f"checkdist done: {fails} failures")
        comm.close(0 if fails == 0 else 1)
        # job-wide verdict: root-only checks fail on rank 0 alone, so
        # every process must report the aggregate, not its local count
        return comm.final_code
    except Exception:
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
