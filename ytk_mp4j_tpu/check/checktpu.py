"""Device-path correctness smoke — runs on the DEFAULT jax backend.

The device-path analogue of ``checkprocess``/``checkthread`` (the
reference's check-program strategy, SURVEY.md section 4): exercises
every collective x operator on BOTH device backends —

- ``TpuCommCluster`` (driver mode, host buffers in/out), and
- ``ops.collectives`` / ``ops.sparse`` inside a jitted ``shard_map``
  (the perf path),

against numpy oracles, on whatever devices the default backend exposes.
Run plainly on the axon tunnel this is the ONE-REAL-TPU-CHIP truth: it
proves the emitted all_reduce / all_gather / reduce_scatter /
collective_permute HLO compiles and executes on actual TPU hardware
(VERDICT round 1 item 1 — the axon compiler rejected non-SUM all-reduce
in round 1; ``ops.collectives`` now probes per platform and falls back
to the gathered tree reduction when that recurs).

    python -m ytk_mp4j_tpu.check.checktpu [--out artifact.json]

Exit code 0 iff every check passes; the artifact records platform,
device count, probe results, and per-family pass/fail counts.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster
from ytk_mp4j_tpu.check._oracle import expected_reduce, rank_data
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.ops import ring
from ytk_mp4j_tpu.ops import sparse as sparse_ops
from ytk_mp4j_tpu.parallel import make_mesh

SEED_BASE = 4200
OPS = ("SUM", "MAX", "MIN", "PROD")


class Tally:
    def __init__(self):
        self.passed = 0
        self.failures: list[str] = []

    def expect(self, name: str, got, want, exact: bool):
        ok = (np.array_equal(got, want) if exact
              else np.allclose(got, want, rtol=1e-4, atol=1e-5))
        if ok:
            self.passed += 1
        else:
            self.failures.append(name)
            print(f"FAIL {name}", file=sys.stderr)


def _operands():
    """Device-eligible operands for this backend (64-bit needs x64).
    SHORT/BYTE ride the device path too — int16/int8 collectives
    compile and execute on the real chip and AOT-compile for v5e-8
    (probed round 3), with numpy/Java wraparound semantics."""
    ops = [Operands.FLOAT, Operands.INT, Operands.SHORT, Operands.BYTE]
    if jax.config.jax_enable_x64:
        ops += [Operands.DOUBLE, Operands.LONG]
    return ops


def check_cluster(t: Tally, n: int, length: int = 192, devices=None):
    """Driver mode: all 7 dense collectives x operators + map family."""
    cluster = TpuCommCluster(mesh=make_mesh(n, devices=devices))
    for operand in _operands():
        exact = operand.dtype.kind != "f"
        alls = [rank_data(r, length, operand, SEED_BASE) for r in range(n)]
        for op_name in OPS:
            op = Operators.by_name(op_name)
            arrs = [a.copy() for a in alls]
            cluster.allreduce_array(arrs, operand, op)
            want = expected_reduce(alls, op_name)
            for r in range(n):
                t.expect(f"cluster/allreduce/{operand.name}/{op_name}",
                         arrs[r], want, exact)
            arrs = [a.copy() for a in alls]
            cluster.reduce_array(arrs, operand, op, root=n - 1)
            t.expect(f"cluster/reduce/{operand.name}/{op_name}",
                     arrs[n - 1], want, exact)
            arrs = [a.copy() for a in alls]
            cluster.reduce_scatter_array(arrs, operand, op)
            for r, (s, e) in enumerate(meta.partition_range(0, length, n)):
                t.expect(f"cluster/reduce_scatter/{operand.name}/{op_name}",
                         arrs[r][s:e], want[s:e], exact)
        root = 1 % n
        arrs = [a.copy() for a in alls]
        cluster.broadcast_array(arrs, operand, root=root)
        for r in range(n):
            t.expect(f"cluster/broadcast/{operand.name}", arrs[r],
                     alls[root], True)
        ranges = meta.partition_range(0, length, n)
        want_cat = np.concatenate(
            [alls[q][s:e] for q, (s, e) in enumerate(ranges)])
        arrs = [a.copy() for a in alls]
        cluster.allgather_array(arrs, operand)
        for r in range(n):
            t.expect(f"cluster/allgather/{operand.name}", arrs[r],
                     want_cat, True)
        arrs = [a.copy() for a in alls]
        cluster.gather_array(arrs, operand, root=0)
        t.expect(f"cluster/gather/{operand.name}", arrs[0], want_cat, True)
        arrs = [a.copy() for a in alls]
        cluster.scatter_array(arrs, operand, root=0)
        for r, (s, e) in enumerate(ranges):
            t.expect(f"cluster/scatter/{operand.name}", arrs[r][s:e],
                     alls[0][s:e], True)
    # sparse map family (values ride the device)
    for op_name in OPS:
        op = Operators.by_name(op_name)
        maps = [{f"k{j}": float(r + j + 1) for j in range(r + 1)}
                for r in range(n)]
        want: dict = {}
        for m in maps:
            for k, v in m.items():
                want[k] = op.np_fn(want[k], v) if k in want else v
        cluster.allreduce_map(maps, Operands.FLOAT, op)
        for m in maps:
            t.expect(f"cluster/allreduce_map/{op_name}",
                     np.array([m.get(k, np.nan) for k in sorted(want)]),
                     np.array([want[k] for k in sorted(want)]), False)
    cluster.barrier()


def check_functional(t: Tally, n: int, length: int = 64, devices=None):
    """The perf path: collectives inside one jitted shard_map program."""
    length = ((length + n - 1) // n) * n  # reduce_scatter/ring need n | L
    mesh = make_mesh(n, devices=devices)
    axis = mesh.axis_names[0]
    alls = [np.random.default_rng(SEED_BASE + r)
            .standard_normal(length).astype(np.float32) for r in range(n)]
    stacked = np.stack(alls)  # [n, L]
    custom = Operator.custom("ABSMAX",
                             lambda a, b: jnp.maximum(jnp.abs(a), jnp.abs(b)),
                             0.0)

    cases = {
        "allreduce_sum": (lambda x: coll.allreduce(x, Operators.SUM, axis),
                          lambda: expected_reduce(alls, "SUM")[None]
                          .repeat(n, 0)),
        "allreduce_max": (lambda x: coll.allreduce(x, Operators.MAX, axis),
                          lambda: expected_reduce(alls, "MAX")[None]
                          .repeat(n, 0)),
        "allreduce_min": (lambda x: coll.allreduce(x, Operators.MIN, axis),
                          lambda: expected_reduce(alls, "MIN")[None]
                          .repeat(n, 0)),
        "allreduce_prod": (lambda x: coll.allreduce(x, Operators.PROD, axis),
                           lambda: expected_reduce(alls, "PROD")[None]
                           .repeat(n, 0)),
        # singleton reduction applies the binary op n-1 = 0 times, so a
        # non-idempotent custom op returns the input unchanged at n=1
        # (same as the socket path's merge loop)
        "allreduce_custom": (lambda x: coll.allreduce(x, custom, axis),
                             lambda: (stacked if n == 1 else
                                      np.abs(stacked).max(0)[None]
                                      .repeat(n, 0))),
        "broadcast": (lambda x: coll.broadcast(x, 0, axis),
                      lambda: stacked[0][None].repeat(n, 0)),
        "reduce_scatter": (
            lambda x: coll.reduce_scatter(x[0], Operators.SUM, axis)[None],
            lambda: expected_reduce(alls, "SUM").reshape(n, -1)),
        "ring_allreduce": (
            lambda x: ring.ring_allreduce(x[0], Operators.SUM, axis)[None],
            lambda: expected_reduce(alls, "SUM")[None].repeat(n, 0)),
    }
    for name, (body, want) in cases.items():
        f = jax.jit(partial(
            jax.shard_map, mesh=mesh, check_vma=False,
            in_specs=P(axis), out_specs=P(axis))(body))
        got = np.asarray(f(stacked)).reshape(n, -1)
        t.expect(f"functional/{name}", got, want().reshape(n, -1), False)
    # allgather replicates: output spec P(None)
    f = jax.jit(partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=P(axis), out_specs=P(None, None))(
        lambda x: coll.allgather(x, axis, tiled=True)))
    t.expect("functional/allgather", np.asarray(f(stacked)), stacked, False)
    # sparse allreduce on device
    idx = np.stack([np.array([r, n + r], np.int32) for r in range(n)])
    val = np.stack([np.array([1.0, 2.0], np.float32) for r in range(n)])
    f = jax.jit(partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=(P(axis), P(axis)), out_specs=(P(None), P(None)))(
        lambda i, v: sparse_ops.sparse_allreduce(
            i[0], v[0], 2 * n, Operators.SUM, axis)))
    oi, ov = f(idx, val)
    got = {int(i): float(v) for i, v in zip(np.asarray(oi), np.asarray(ov))
           if i != sparse_ops.SENTINEL}
    want = {r: 1.0 for r in range(n)}
    want.update({n + r: 2.0 for r in range(n)})
    t.expect("functional/sparse_allreduce",
             np.array(sorted(got.items())), np.array(sorted(want.items())),
             False)
    # sparse reduce-scatter: each member keeps its block-owned share
    size = 2 * n
    f = jax.jit(partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis)))(
        lambda i, v: tuple(
            x[None] for x in sparse_ops.sparse_reduce_scatter(
                i[0], v[0], 2 * n, size, Operators.SUM, axis))))
    oi, ov = f(idx, val)
    oi, ov = np.asarray(oi), np.asarray(ov)
    got_rs = {}
    for r in range(n):
        for i, v in zip(oi[r], ov[r]):
            if i != sparse_ops.SENTINEL:
                t.expect("functional/sparse_reduce_scatter/owner",
                         meta.owner_of(int(i), 0, size, n), r, True)
                got_rs[int(i)] = float(v)
    t.expect("functional/sparse_reduce_scatter",
             np.array(sorted(got_rs.items())),
             np.array(sorted(want.items())), False)
    # sparse allgather: disjoint-union pairs, sorted, duplicates kept
    f = jax.jit(partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=(P(axis), P(axis)), out_specs=(P(None), P(None)))(
        lambda i, v: sparse_ops.sparse_allgather(i[0], v[0], axis)))
    oi, ov = map(np.asarray, f(idx, val))
    live = oi != sparse_ops.SENTINEL
    t.expect("functional/sparse_allgather",
             np.array(sorted(zip(oi[live], ov[live]))),
             np.array(sorted((int(i), float(v))
                             for row_i, row_v in zip(idx, val)
                             for i, v in zip(row_i, row_v))), False)


def check_ring_kernels_hw(t: Tally, n: int, devices=None):
    """Execute the Pallas ring RDMA kernels — all three collectives,
    uni AND bidirectional — COMPILED (not interpreted) on the current
    backend. On the 1-chip tunnel this is the degenerate hardware
    smoke VERDICT round 4 asked for: zero ring steps run, but Mosaic
    codegen, VMEM slot allocation, DMA/REGULAR semaphore allocation
    and the collective_id entry barrier all execute on real hardware
    (``force_kernel=True`` bypasses the n==1 identity fast path);
    with n > 1 chips the same code proves full ring semantics."""
    from ytk_mp4j_tpu.ops import ring_kernel as rk

    mesh = make_mesh(n, devices=devices)
    axis = mesh.axis_names[0]
    c = rk.min_chunk_elems(np.float32)
    L = 2 * c * n
    alls = [np.random.default_rng(SEED_BASE + 77 + r)
            .standard_normal(L).astype(np.float32) for r in range(n)]
    stacked = np.stack(alls)
    want_sum = expected_reduce(alls, "SUM")
    shards = stacked[:, : L // n]        # per-member allgather input

    def smap(body):
        return jax.jit(partial(
            jax.shard_map, mesh=mesh, check_vma=False,
            in_specs=P(axis), out_specs=P(axis))(body))

    for bidir in (False, True):
        tag = "bidir" if bidir else "uni"
        got = np.asarray(smap(
            lambda x, b=bidir: rk.ring_allreduce_kernel(
                x[0], Operators.SUM, axis, bidirectional=b,
                force_kernel=True)[None])(stacked))
        t.expect(f"ring_kernel_hw/allreduce/{tag}", got,
                 want_sum[None].repeat(n, 0), False)
        got = np.asarray(smap(
            lambda x, b=bidir: rk.ring_reduce_scatter_kernel(
                x[0], Operators.SUM, axis, bidirectional=b,
                force_kernel=True)[None])(stacked))
        t.expect(f"ring_kernel_hw/reduce_scatter/{tag}",
                 got.reshape(-1), want_sum, False)
        got = np.asarray(smap(
            lambda x, b=bidir: rk.ring_allgather_kernel(
                x[0], axis, bidirectional=b,
                force_kernel=True)[None])(shards))
        t.expect(f"ring_kernel_hw/allgather/{tag}", got,
                 shards.reshape(-1)[None].repeat(n, 0), False)


def _run_battery(n: int, devices=None) -> dict:
    t = Tally()
    section: dict = {"n_devices_used": n}
    try:
        check_cluster(t, n, devices=devices)
        check_functional(t, n, devices=devices)
        section["error"] = None
    except Exception:
        traceback.print_exc()
        section["error"] = traceback.format_exc(limit=3)
    section["passed"] = t.passed
    section["failures"] = t.failures
    section["ok"] = section["error"] is None and not t.failures
    return section


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write JSON artifact here")
    ap.add_argument("--n", type=int, default=None,
                    help="ranks (default: all devices)")
    ap.add_argument("--cpu-mesh-n", type=int, default=8,
                    help="ranks for the CPU-mesh execution section "
                         "(0 disables)")
    args = ap.parse_args(argv)
    # must happen before the first device query initializes backends:
    # the second section executes the SAME battery on an n>=8 CPU mesh
    # so real-HLO truth and multi-member execution semantics sit side
    # by side in one artifact (VERDICT round-2 #7)
    if args.cpu_mesh_n:
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_mesh_n)
        except Exception:
            pass                     # backends already up: section skips
    devs = jax.devices()
    n = args.n or len(devs)
    result = {
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        "n_devices_used": n,
        "native_reduce_probe": coll.prime_native_reduce_probe(),
    }
    if n == 1:
        result["identity_caveat"] = (
            "every collective over a 1-member axis is an identity; this "
            "section proves the emitted HLO compiles and executes on the "
            "real device, NOT cross-member semantics — see the cpu_mesh "
            "section for executed n>1 semantics")
    result.update(_run_battery(n, devices=devs[:n]))

    if devs[0].platform == "tpu":
        # compiled Pallas ring kernels on the real chip (interpret mode
        # and AOT cover CPU meshes and pod topologies; this is the one
        # place Mosaic codegen + semaphore/DMA allocation EXECUTE on
        # hardware)
        hw = Tally()
        sec: dict = {"n_devices_used": n, "caveat": (
            "n=1 runs ZERO ring steps: this proves Mosaic codegen, "
            "VMEM/semaphore allocation and the collective_id entry "
            "barrier execute on the chip, NOT cross-chip DMA "
            "semantics — those are covered by the interpreted n=8 "
            "mesh and the 8/16/64-chip AOT artifacts" if n == 1
            else None)}
        try:
            check_ring_kernels_hw(hw, n, devices=devs[:n])
            sec["error"] = None
        except Exception:
            traceback.print_exc()
            sec["error"] = traceback.format_exc(limit=3)
        sec["passed"] = hw.passed
        sec["failures"] = hw.failures
        sec["ok"] = sec["error"] is None and not hw.failures
        result["ring_kernel_hw"] = sec
        result["ok"] = result["ok"] and sec["ok"]

    if args.cpu_mesh_n and (devs[0].platform == "cpu"
                            and n >= args.cpu_mesh_n):
        # the main section already executed this battery on a CPU mesh
        # of sufficient width — re-running it would double the runtime
        # for a duplicate result
        result["cpu_mesh"] = {"skipped": True,
                              "reason": "main section ran on cpu"}
    elif args.cpu_mesh_n:
        try:
            cpu_devs = jax.devices("cpu")
        except Exception:
            cpu_devs = []
        if len(cpu_devs) >= args.cpu_mesh_n:
            section = _run_battery(args.cpu_mesh_n,
                                   devices=cpu_devs[: args.cpu_mesh_n])
            section["platform"] = "cpu"
            result["cpu_mesh"] = section
        else:
            # environmental (backends initialized before the config
            # update could widen the CPU platform): record the skip,
            # do not fail checks that DID run
            result["cpu_mesh"] = {
                "skipped": True, "reason":
                    f"only {len(cpu_devs)} cpu devices available"}

    cm = result.get("cpu_mesh")
    result["ok"] = result["ok"] and (
        cm is None or cm.get("skipped", False) or cm["ok"])
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
