"""Distributed correctness check program — thread (hybrid) level.

The thread-family counterpart of ``checkprocess`` (the reference ships
separate checkprocess/checkthread program families, SURVEY.md section 4):
one ``main()`` per PROCESS spawns ``--threads`` ThreadCommSlave endpoints
(joining a master when ``--master`` is given — the hybrid process x
thread job of SURVEY.md section 3d), runs every dense and map collective
on seeded per-global-rank data concurrently from all threads, and
compares with locally-computed expected values. Exit code 0 iff all
checks pass in this process.

Launch (2 processes x 3 threads, loopback):

    python -m ytk_mp4j_tpu.comm.master --port 9999 --slaves 2 &
    for i in 0 1; do
        python -m ytk_mp4j_tpu.check.checkthread \
            --master localhost:9999 --threads 3 &
    done

Standalone (pure-thread job, no master):

    python -m ytk_mp4j_tpu.check.checkthread --threads 4
"""

from __future__ import annotations

import argparse
import sys
import threading
import traceback

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.check._oracle import expected_reduce, rank_data
from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

SEED_BASE = 2000


def rank_map(rank: int, n: int) -> dict:
    # overlapping keys across ranks so merges are exercised
    return {f"k{(rank + j) % (n + 2)}": float(rank * 10 + j)
            for j in range(3)}


def check(slave: ThreadCommSlave, length: int = 129) -> int:
    """Run the battery on one thread endpoint; returns failure count."""
    n, r = slave.slave_num, slave.rank
    fails = 0

    def expect(name, ok):
        nonlocal fails
        if not ok:
            fails += 1
            slave.error(f"{name} MISMATCH")

    def expect_arr(name, got, want, exact):
        expect(name, np.array_equal(got, want) if exact
               else np.allclose(got, want, rtol=1e-5, atol=1e-6))

    for operand in (Operands.DOUBLE, Operands.FLOAT, Operands.INT):
        exact = operand.dtype.kind != "f"
        alls = [rank_data(q, length, operand, SEED_BASE) for q in range(n)]
        ranges = meta.partition_range(0, length, n)
        for op_name in ("SUM", "MAX"):
            op = Operators.by_name(op_name)
            want = expected_reduce(alls, op_name)
            # allreduce
            arr = alls[r].copy()
            slave.allreduce_array(arr, operand, op)
            expect_arr(f"allreduce/{operand.name}/{op_name}", arr, want,
                       exact)
            # reduce into global rank 1 (crosses thread AND process
            # boundaries whenever they exist)
            root = 1 % n
            arr = alls[r].copy()
            slave.reduce_array(arr, operand, op, root=root)
            if r == root:
                expect_arr(f"reduce/{operand.name}/{op_name}", arr, want,
                           exact)
            # reduce_scatter: my global-rank segment
            arr = alls[r].copy()
            slave.reduce_scatter_array(arr, operand, op)
            s, e = ranges[r]
            expect_arr(f"reduce_scatter/{operand.name}/{op_name}",
                       arr[s:e], want[s:e], exact)
        # broadcast from the last global rank
        root = n - 1
        arr = alls[r].copy()
        slave.broadcast_array(arr, operand, root=root)
        expect_arr(f"broadcast/{operand.name}", arr, alls[root], True)
        # allgather of per-global-rank segments
        arr = alls[r].copy()
        slave.allgather_array(arr, operand)
        want = np.concatenate(
            [alls[q][s:e] for q, (s, e) in enumerate(ranges)])
        expect_arr(f"allgather/{operand.name}", arr, want, True)
        # gather to global rank 0
        arr = alls[r].copy()
        slave.gather_array(arr, operand, root=0)
        if r == 0:
            expect_arr(f"gather/{operand.name}", arr, want, True)
        # scatter from global rank 0
        arr = alls[r].copy()
        slave.scatter_array(arr, operand, root=0)
        s, e = ranges[r]
        expect_arr(f"scatter/{operand.name}", arr[s:e], alls[0][s:e], True)
        slave.barrier()

    # map collectives (the reference's sparse Map family, SURVEY.md 3c)
    maps = [rank_map(q, n) for q in range(n)]
    want_merged: dict = {}
    for m in maps:
        for k, v in m.items():
            want_merged[k] = want_merged.get(k, 0.0) + v
    d = dict(maps[r])
    slave.allreduce_map(d, Operands.DOUBLE, Operators.SUM)
    expect("allreduce_map", d == want_merged)

    d = dict(maps[r])
    slave.reduce_map(d, Operands.DOUBLE, Operators.SUM, root=0)
    if r == 0:
        expect("reduce_map", d == want_merged)

    d = dict(maps[0]) if r == 0 else {}
    slave.broadcast_map(d, Operands.DOUBLE, root=0)
    expect("broadcast_map", d == maps[0])

    # disjoint per-rank keys for gather/allgather
    d = {f"r{r}": float(r)}
    slave.allgather_map(d, Operands.DOUBLE)
    expect("allgather_map",
           d == {f"r{q}": float(q) for q in range(n)})

    d = dict(maps[r])
    slave.reduce_scatter_map(d, Operands.DOUBLE, Operators.SUM)
    expect("reduce_scatter_map",
           d == {k: v for k, v in want_merged.items()
                 if meta.key_partition(k, n) == r})

    # thread-only synchronization primitive
    slave.thread_barrier()
    slave.barrier()
    return fails


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master", default=None,
                    help="host:port (omit for a standalone thread group)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--length", type=int, default=129)
    args = ap.parse_args(argv)
    if args.master is not None:
        host, port = args.master.rsplit(":", 1)
        slaves = ThreadCommSlave.spawn_group(args.threads, host, int(port))
    else:
        slaves = ThreadCommSlave.spawn_group(args.threads)

    fails = [0] * args.threads
    errors: list[BaseException] = []

    def worker(t: int):
        try:
            fails[t] = check(slaves[t], args.length)
            slaves[t].info(f"check done: {fails[t]} failures")
            slaves[t].close(0 if fails[t] == 0 else 1)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)
            slaves[t].close(2)

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(args.threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(300)
    if errors:
        traceback.print_exception(errors[0])
        return 2
    if any(th.is_alive() for th in threads):
        print("checkthread: worker hung", file=sys.stderr)
        return 3
    return 0 if sum(fails) == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
