"""Distributed correctness check program — process (socket) level.

Mirrors the reference's ``check/`` strategy (SURVEY.md section 4): a
``main()`` run as N real slave processes against a master, executing
every dense collective on seeded data and comparing with locally-computed
expected values. Exit code 0 iff all checks pass on this rank.

Launch (one master + N slaves, loopback):

    python -m ytk_mp4j_tpu.comm.master --port 9999 --slaves 4 &
    for i in 0 1 2 3; do
        python -m ytk_mp4j_tpu.check.checkprocess --master localhost:9999 &
    done
"""

from __future__ import annotations

import argparse
import sys
import traceback

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.check._oracle import expected_reduce, rank_data
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.operands import Operands
from ytk_mp4j_tpu.operators import Operators

SEED_BASE = 1000


def all_rank_data(n, length, operand):
    return [rank_data(r, length, operand, SEED_BASE) for r in range(n)]


def check(slave: ProcessCommSlave, length: int = 257) -> int:
    """Run the battery; returns number of failures."""
    n, r = slave.slave_num, slave.rank
    fails = 0

    def expect(name, got, want, exact):
        nonlocal fails
        ok = (np.array_equal(got, want) if exact
              else np.allclose(got, want, rtol=1e-5, atol=1e-6))
        if not ok:
            fails += 1
            slave.error(f"{name} MISMATCH")

    for operand in (Operands.DOUBLE, Operands.FLOAT, Operands.INT,
                    Operands.LONG):
        exact = operand.dtype.kind != "f"
        for op_name in ("SUM", "PROD", "MAX", "MIN"):
            op = Operators.by_name(op_name)
            alls = all_rank_data(n, length, operand)
            # allreduce
            arr = alls[r].copy()
            slave.allreduce_array(arr, operand, op)
            expect(f"allreduce/{operand.name}/{op_name}", arr,
                   expected_reduce(alls, op_name), exact)
            # reduce (root 0)
            arr = alls[r].copy()
            slave.reduce_array(arr, operand, op, root=0)
            if r == 0:
                expect(f"reduce/{operand.name}/{op_name}", arr,
                       expected_reduce(alls, op_name), exact)
            # reduce_scatter
            arr = alls[r].copy()
            ranges = meta.partition_range(0, length, n)
            slave.reduce_scatter_array(arr, operand, op)
            s, e = ranges[r]
            expect(f"reduce_scatter/{operand.name}/{op_name}", arr[s:e],
                   expected_reduce(alls, op_name)[s:e], exact)
        # broadcast (root 1 if exists)
        root = 1 % n
        alls = all_rank_data(n, length, operand)
        arr = alls[r].copy()
        slave.broadcast_array(arr, operand, root=root)
        expect(f"broadcast/{operand.name}", arr, alls[root], True)
        # allgather
        ranges = meta.partition_range(0, length, n)
        arr = alls[r].copy()
        slave.allgather_array(arr, operand)
        want = np.concatenate([alls[q][s:e] for q, (s, e) in enumerate(ranges)])
        expect(f"allgather/{operand.name}", arr, want, True)
        # gather (root 0)
        arr = alls[r].copy()
        slave.gather_array(arr, operand, root=0)
        if r == 0:
            expect(f"gather/{operand.name}", arr, want, True)
        # scatter (root 0)
        arr = alls[r].copy()
        slave.scatter_array(arr, operand, root=0)
        s, e = ranges[r]
        expect(f"scatter/{operand.name}", arr[s:e], alls[0][s:e], True)
        slave.barrier()
    # sub-range allreduce
    operand = Operands.DOUBLE
    alls = all_rank_data(n, 64, operand)
    arr = alls[r].copy()
    slave.allreduce_array(arr, operand, Operators.SUM, from_=10, to=50)
    want = alls[r].copy()
    want[10:50] = expected_reduce(alls, "SUM")[10:50]
    expect("allreduce/subrange", arr, want, False)
    return fails


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master", required=True, help="host:port")
    ap.add_argument("--length", type=int, default=257)
    args = ap.parse_args(argv)
    host, port = args.master.rsplit(":", 1)
    slave = ProcessCommSlave(host, int(port))
    try:
        fails = check(slave, args.length)
        slave.info(f"check done: {fails} failures")
        slave.close(0 if fails == 0 else 1)
        return 0 if fails == 0 else 1
    except Exception:
        traceback.print_exc()
        slave.close(2)
        return 2


if __name__ == "__main__":
    sys.exit(main())
