"""Shared numpy-oracle helpers for the check programs (process and
thread families both validate against the same locally-computed expected
values — SURVEY.md section 4)."""

from __future__ import annotations

import numpy as np

NP_REF = {"SUM": np.add, "PROD": np.multiply, "MAX": np.maximum,
          "MIN": np.minimum}


def rank_data(rank: int, length: int, operand, seed_base: int) -> np.ndarray:
    """Deterministic per-rank input (every rank can regenerate every
    other rank's data to compute expectations locally)."""
    rng = np.random.default_rng(seed_base + rank)
    if operand.dtype.kind == "f":
        return rng.standard_normal(length).astype(operand.dtype)
    return rng.integers(1, 4, length).astype(operand.dtype)


def expected_reduce(arrs, op_name: str) -> np.ndarray:
    out = arrs[0].copy()
    for a in arrs[1:]:
        out = NP_REF[op_name](out, a)
    return out
