"""AOT multi-chip compile proof against a real TPU topology.

``dryrun_multichip`` (driver entry) proves SEMANTICS on a virtual CPU
mesh; this program proves the other half of the north-star claim
(SURVEY.md section 6: ">=10x ... on a TPU pod"): that XLA + Mosaic will
actually COMPILE every multi-chip program — the GBDT train step (with
the Pallas histogram kernel), the FFM sparse-gradient step, every dense
collective x operator, the sparse allreduce, the ppermute ring, and the
Pallas RDMA ring kernel — for a real multi-chip TPU topology, using the
JAX AOT topology API (``jax.experimental.topologies.get_topology_desc``
+ ``jit(...).lower(...).compile()``), no chips required.

    python -m ytk_mp4j_tpu.check.checkaot [--topology v5e:2x4] [--out f]

Exit code 0 iff every program compiles; the artifact records per-program
status plus compiler cost analysis (flops / bytes accessed) where
available.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.ops import ring
from ytk_mp4j_tpu.ops import ring_kernel
from ytk_mp4j_tpu.ops import sparse as sparse_ops

AXIS = "mp4j"


def _f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _i32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _compile(name: str, results: dict, jitted, *avals) -> None:
    """Lower + compile one program for the topology; record the outcome
    and the compiler's own cost analysis (proof the executable exists)."""
    try:
        compiled = jitted.lower(*avals).compile()
        cost = {}
        try:
            ca = compiled.cost_analysis() or {}
            cost = {k: ca[k] for k in ("flops", "bytes accessed")
                    if k in ca}
        except Exception:
            pass
        results[name] = {"ok": True, "cost": cost}
        print(f"ok   {name} {cost}")
    except Exception as e:
        results[name] = {"ok": False,
                         "error": traceback.format_exc(limit=3)}
        print(f"FAIL {name}: {str(e)[:300]}", file=sys.stderr)


def _hier_mesh(devices, n: int) -> Mesh:
    """The one inter x intra topology every hier program compiles for
    (n//2 x 2, row-major ranks) — shared so the hier_rs evidence and
    the gbdt hier train step measure the same topology."""
    return Mesh(np.asarray(devices[:n]).reshape(n // 2, 2),
                ("inter", "intra"))


def _shard_mapped(mesh, body, in_specs, out_specs):
    return jax.jit(partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=in_specs, out_specs=out_specs)(body))


def check_collectives(results: dict, mesh: Mesh, n: int, L: int = 4096):
    """Every dense collective x operator in one program per operator
    family, plus the rooted/topology-shaped ones."""
    custom = Operator.custom(
        "ABSMAX", lambda a, b: jnp.maximum(jnp.abs(a), jnp.abs(b)), 0.0)

    for op in (Operators.SUM, Operators.MAX, Operators.MIN,
               Operators.PROD, custom):
        def body(x, _op=op):
            v = x[0]                                   # per-shard [L]
            ar = coll.allreduce(v, _op, AXIS)
            rs = coll.reduce_scatter(v, _op, AXIS)
            rd = coll.reduce(v, _op, root=0, axis_name=AXIS)
            return ar[None], rs[None], rd[None]
        _compile(f"collectives/{op.name}", results,
                 _shard_mapped(mesh, body, P(AXIS), (P(AXIS),) * 3),
                 _f32(n, L))

    def rooted(x):
        v = x[0]
        bc = coll.broadcast(v, 0, AXIS)
        ag = coll.allgather(v, AXIS)
        ga = coll.gather(v, 0, AXIS)
        sc = coll.scatter(v, 0, AXIS)
        tok = coll.barrier(AXIS)
        return bc[None], ag, ga, sc[None], tok[None]
    _compile("collectives/rooted", results,
             _shard_mapped(mesh, rooted, P(AXIS),
                           (P(AXIS), P(None), P(None), P(AXIS), P(AXIS))),
             _f32(n, L))


def check_rings(results: dict, mesh: Mesh, n: int, L: int | None = None):
    """The hand-scheduled ppermute ring and the Pallas RDMA kernels
    (compiled path: entry barrier + credit backpressure included).
    ``L`` scales with the topology: the reduce-scatter kernel splits a
    shard into n chunks and each chunk must be a full Mosaic tile
    (min_chunk_elems) — a fixed 8192 under-fills at n = 16."""
    if L is None:
        L = max(8192, n * ring_kernel.min_chunk_elems(jnp.float32))
    _compile("ring/ppermute_allreduce", results,
             _shard_mapped(
                 mesh, lambda x: ring.ring_allreduce(
                     x[0], Operators.SUM, AXIS)[None],
                 P(AXIS), P(AXIS)),
             _f32(n, L))
    for op in (Operators.SUM, Operators.MAX):
        _compile(f"ring/rdma_allreduce_{op.name}", results,
                 _shard_mapped(
                     mesh, lambda x, _op=op:
                     ring_kernel.ring_allreduce_kernel(
                         x[0], _op, AXIS)[None],
                     P(AXIS), P(AXIS)),
                 _f32(n, L))
    _compile("ring/rdma_allreduce_bidir", results,
             _shard_mapped(
                 mesh, lambda x: ring_kernel.ring_allreduce_kernel(
                     x[0], Operators.SUM, AXIS, bidirectional=True)[None],
                 P(AXIS), P(AXIS)),
             _f32(n, L))
    # unpadded length: exercises the internal identity padding
    _compile("ring/rdma_allreduce_unaligned", results,
             _shard_mapped(
                 mesh, lambda x: ring_kernel.ring_allreduce_kernel(
                     x[0], Operators.SUM, AXIS)[None],
                 P(AXIS), P(AXIS)),
             _f32(n, L + 7))
    for bidir in (False, True):
        tag = "_bidir" if bidir else ""
        _compile(f"ring/rdma_reduce_scatter{tag}", results,
                 _shard_mapped(
                     mesh, lambda x, b=bidir:
                     ring_kernel.ring_reduce_scatter_kernel(
                         x[0], Operators.SUM, AXIS,
                         bidirectional=b)[None],
                     P(AXIS), P(AXIS)),
                 _f32(n, L))
        _compile(f"ring/rdma_allgather{tag}", results,
                 _shard_mapped(
                     mesh, lambda x, b=bidir:
                     ring_kernel.ring_allgather_kernel(
                         x[0], AXIS, bidirectional=b)[None],
                     P(AXIS), P(AXIS)),
                 _f32(n, L))


def _rooted_reduce_rs_collect(v, n: int, root: int = 0):
    """Hand-built rooted reduce: psum_scatter, then n-1 ppermutes each
    delivering one reduced block to root (the many-to-one collect the
    coll.reduce docstring prices at (n-1)/n concentrated on root's
    links). Only root's output is meaningful."""
    block = lax.psum_scatter(v, AXIS, scatter_dimension=0, tiled=True)
    B = v.shape[0] // n
    out = jnp.zeros_like(v)
    out = lax.dynamic_update_slice_in_dim(
        out, block, coll.flat_index(AXIS) * B, 0)
    for i in range(1, n):
        src = (root + i) % n
        recv = lax.ppermute(block, AXIS, [(src, root)])
        out = lax.dynamic_update_slice_in_dim(out, recv, src * B, 0)
    return out


def _rooted_reduce_binomial(v, n: int):
    """Hand-built rooted reduce: binomial combining tree to rank 0 —
    log2(n) ppermute rounds each moving the FULL buffer (|x| * log n
    wire, the docstring's strictly-worse case for n >= 4)."""
    acc = v
    k = 1
    while k < n:
        pairs = [(r, r - k) for r in range(k, n, 2 * k)]
        recv = lax.ppermute(acc, AXIS, pairs)  # non-addressed get zeros
        acc = acc + recv
        k *= 2
    return acc


def _rooted_gather_sequential(v, n: int, root: int = 0):
    """Hand-built rooted gather: n-1 ppermutes each delivering one
    member's buffer to root (many-to-one serialization)."""
    out = jnp.zeros((n,) + v.shape, v.dtype)
    out = lax.dynamic_update_slice(
        out, v[None], (coll.flat_index(AXIS),) + (0,) * v.ndim)
    for i in range(1, n):
        src = (root + i) % n
        recv = lax.ppermute(v, AXIS, [(src, root)])
        out = lax.dynamic_update_slice(
            out, recv[None], (src,) + (0,) * v.ndim)
    return out


def _rooted_scatter_sequential(x, n: int, root: int = 0):
    """Hand-built rooted scatter: root sends block i to rank i, one
    ppermute per destination ((n-1) * B wire vs the broadcast+slice
    lowering's full-buffer psum)."""
    B = x.shape[0] // n
    idx = coll.flat_index(AXIS)
    own = lax.dynamic_slice_in_dim(x, idx * B, B, axis=0)
    out = jnp.where(idx == root, own, jnp.zeros_like(own))
    for i in range(1, n):
        dst = (root + i) % n
        blk = lax.dynamic_slice_in_dim(x, dst * B, B, axis=0)
        recv = lax.ppermute(blk, AXIS, [(root, dst)])
        out = jnp.where(idx == dst, recv, out)
    return out


def check_rooted_lowerings(results: dict, mesh: Mesh, n: int,
                           L: int = 1 << 20):
    """VERDICT round-2 #5: turn the rooted-collective docstring
    arithmetic (ops/collectives.py reduce/gather/scatter) into compiler
    artifacts — the current allreduce/allgather/broadcast lowerings
    side by side with faithful hand-built rooted variants, so the cost
    analysis is on record next to the prose (table in BASELINE.md)."""
    progs = {
        "rooted/reduce_current_allreduce":
            lambda x: coll.reduce(x[0], Operators.SUM, 0, AXIS)[None],
        "rooted/reduce_rs_collect":
            lambda x: _rooted_reduce_rs_collect(x[0], n)[None],
        "rooted/reduce_binomial":
            lambda x: _rooted_reduce_binomial(x[0], n)[None],
        "rooted/gather_current_allgather":
            lambda x: coll.gather(x[0], 0, AXIS)[None],
        "rooted/gather_sequential":
            lambda x: _rooted_gather_sequential(x[0], n)[None],
        "rooted/scatter_current_bcast_slice":
            lambda x: coll.scatter(x[0], 0, AXIS)[None],
        "rooted/scatter_sequential":
            lambda x: _rooted_scatter_sequential(x[0], n)[None],
    }
    for name, body in progs.items():
        _compile(name, results,
                 _shard_mapped(mesh, body, P(AXIS), P(AXIS)), _f32(n, L))


def check_hier_reduce_scatter(results: dict, devices, n: int,
                              L: int = 1 << 20):
    """Round-3 measured decision: tuple-axis reduce_scatter stays
    allreduce+slice because XLA's tuple psum is already hierarchical.
    These three programs keep the evidence on record (BASELINE.md):
    the current lowering vs the two hand-staged psum_scatter cascades
    (outer-first needs no permute; inner-first shrinks the buffer
    before the DCN stage but pays a block permutation)."""
    if n % 2:
        return
    mesh = _hier_mesh(devices, n)
    axes = ("inter", "intra")

    def current(x):
        return coll.reduce_scatter(x[0], Operators.SUM, axes)[None]

    def outer_first(x):
        out = lax.psum_scatter(x[0], "inter", scatter_dimension=0,
                               tiled=True)
        return lax.psum_scatter(out, "intra", scatter_dimension=0,
                                tiled=True)[None]

    def inner_first(x):
        v = x[0]
        grid = v.reshape(n // 2, 2, -1)
        out = grid.transpose(1, 0, 2).reshape(-1)
        out = lax.psum_scatter(out, "intra", scatter_dimension=0,
                               tiled=True)
        return lax.psum_scatter(out, "inter", scatter_dimension=0,
                                tiled=True)[None]

    for name, body in (("hier_rs/current_allreduce_slice", current),
                       ("hier_rs/staged_outer_first", outer_first),
                       ("hier_rs/staged_inner_first_permuted", inner_first)):
        _compile(name, results,
                 _shard_mapped(mesh, body, P(axes), P(axes)), _f32(n, L))


def check_sparse(results: dict, mesh: Mesh, n: int, cap: int = 1024):
    def body(i, v):
        return sparse_ops.sparse_allreduce(
            i[0], v[0], cap * n, Operators.SUM, AXIS)
    _compile("sparse/allreduce", results,
             _shard_mapped(mesh, body, (P(AXIS), P(AXIS)),
                           (P(None), P(None))),
             _i32(n, cap), _f32(n, cap))

    def body_rs(i, v):
        oi, ov = sparse_ops.sparse_reduce_scatter(
            i[0], v[0], cap * n, cap * n, Operators.SUM, AXIS)
        return oi[None], ov[None]
    _compile("sparse/reduce_scatter", results,
             _shard_mapped(mesh, body_rs, (P(AXIS), P(AXIS)),
                           (P(AXIS), P(AXIS))),
             _i32(n, cap), _f32(n, cap))

    def body_ag(i, v):
        return sparse_ops.sparse_allgather(i[0], v[0], AXIS)
    _compile("sparse/allgather", results,
             _shard_mapped(mesh, body_ag, (P(AXIS), P(AXIS)),
                           (P(None), P(None))),
             _i32(n, cap), _f32(n, cap))


def check_gbdt(results: dict, devices, n: int, per: int = 8192):
    """The flagship consumer's full train step (Pallas histogram kernel
    + psum allreduce + routing + leaf update) at the bench shape, on a
    flat mesh and on the hierarchical inter x intra mesh."""
    from ytk_mp4j_tpu.models.gbdt import GBDTConfig, GBDTTrainer

    kd = jax.eval_shape(lambda: jax.random.key_data(jax.random.key(0)))
    meshes = {"flat": Mesh(np.asarray(devices[:n]), (AXIS,))}
    if n % 2 == 0:
        meshes["hier"] = _hier_mesh(devices, n)
    cfgs = {
        "": GBDTConfig(n_features=28, n_bins=256, depth=6),
        # the data-handling graph: learned missing direction +
        # categorical equality splits
        "_missing_cat": GBDTConfig(n_features=28, n_bins=256, depth=6,
                                   missing_bin=True,
                                   categorical_features=(3, 17)),
        # the multiclass consumer: one tree per class per round
        "_softmax": GBDTConfig(n_features=28, n_bins=256, depth=6,
                               loss="softmax", n_classes=3),
    }
    for label, mesh in meshes.items():
        for suffix, cfg in cfgs.items():
            if suffix and label != "flat":
                continue            # one topology proof is enough
            tr = GBDTTrainer(cfg, mesh=mesh)
            if cfg.loss == "softmax":
                y_aval = _i32(n, per)                      # class ids
                preds_aval = _f32(n, per, cfg.n_classes)   # margins
            else:
                y_aval = _f32(n, per)
                preds_aval = _f32(n, per)
            _compile(f"gbdt/train_step_{label}{suffix}", results,
                     tr._build_step(),
                     _i32(n, per, cfg.n_features), y_aval,
                     preds_aval, _f32(n, per),
                     jax.ShapeDtypeStruct(kd.shape, kd.dtype))


def check_ffm(results: dict, devices, n: int, per: int = 1024):
    """The FFM sparse-gradient step (BASELINE.md configs[4] shape):
    score + grads + device-native sparse allreduce + update."""
    from ytk_mp4j_tpu.models.fm import FMConfig, FMTrainer

    cfg = FMConfig(model="ffm", n_features=100_000, n_fields=8, k=8,
                   max_nnz=8, learning_rate=0.05)
    mesh = Mesh(np.asarray(devices[:n]), (AXIS,))
    tr = FMTrainer(cfg, mesh=mesh, sparse_grads=True)
    params_avals = jax.eval_shape(lambda: tr.init_params(0))
    batch_avals = (_i32(n, per, cfg.max_nnz), _i32(n, per, cfg.max_nnz),
                   _f32(n, per, cfg.max_nnz), _f32(n, per, cfg.max_nnz),
                   _f32(n, per), _f32(n, per))
    _compile("ffm/sparse_train_step", results,
             tr._build_step(per * cfg.max_nnz),
             params_avals, *batch_avals)
    # round-4 A/B: mesh-sharded table (owner-routed rows over
    # all_to_all + compacted per-shard scatter) vs the replicated path
    trs = FMTrainer(cfg, mesh=mesh, sparse_grads=True,
                    table_sharding="sharded")
    sharded_avals = (
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((cfg.n_features,), jnp.float32),
        _f32(trs.n_rows_padded, cfg.k))
    _compile("ffm/sparse_train_step_sharded", results,
             trs._build_step(per * cfg.max_nnz),
             sharded_avals, *batch_avals)
    # round-5: fit_stream's double-buffered dispatch compiles THIS SAME
    # program (the stream stages chunks into identical padded shapes),
    # so the sharded+stream composition is covered by the row above;
    # the sharded SERVE program (owner-routed row fetch, no full-table
    # replica anywhere) is the remaining sharded surface
    _compile("ffm/sharded_serve", results, trs._build_sharded_predict(),
             sharded_avals, *batch_avals[:4])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x4",
                    help="TPU topology name (PJRT C-API spelling)")
    ap.add_argument("--out", default=None, help="write JSON artifact here")
    args = ap.parse_args(argv)

    from jax.experimental import topologies
    topo = topologies.get_topology_desc(topology_name=args.topology,
                                        platform="tpu")
    devices = topo.devices
    n = len(devices)
    mesh = Mesh(np.asarray(devices), (AXIS,))
    print(f"topology {args.topology}: {n} x {devices[0].device_kind}")

    results: dict = {}
    check_collectives(results, mesh, n)
    check_rooted_lowerings(results, mesh, n)
    check_hier_reduce_scatter(results, devices, n)
    check_rings(results, mesh, n)
    check_sparse(results, mesh, n)
    check_gbdt(results, devices, n)
    check_ffm(results, devices, n)

    ok = all(r["ok"] for r in results.values())
    artifact = {
        "topology": args.topology,
        "n_devices": n,
        "device_kind": devices[0].device_kind,
        "programs": results,
        "ok": ok,
    }
    line = json.dumps(artifact)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
