"""Model families of the reference's flagship consumer (ytk-learn),
rebuilt TPU-first as end-to-end workloads for the collectives library:
GBDT (histogram allreduce), linear models (gradient allreduce), FM/FFM
(sparse embedding-gradient allreduce)."""

from ytk_mp4j_tpu.models import fm, gbdt, linear

__all__ = ["fm", "gbdt", "linear"]
