from ytk_mp4j_tpu.models import gbdt

__all__ = ["gbdt"]
