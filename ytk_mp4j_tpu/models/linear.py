"""TPU-native distributed linear models (linear & logistic regression).

ytk-mp4j's consumer ytk-learn ships a "linear" model family trained by
data-parallel gradient descent: each worker computes gradients on its
shard and the gradient vector is ALLREDUCED every step (the same pattern
as the GBDT histogram allreduce, SURVEY.md section 1 — gradient
aggregation is the library's reason to exist).

TPU-first rebuild: the whole optimization step — forward, loss, grad,
``lax.psum`` over the mesh axis, optimizer update — is ONE jitted
``shard_map`` program. The gradient allreduce that the reference performs
with Kryo-socket recursive halving (SURVEY.md section 3b) is a single XLA
ICI collective; parameters stay replicated, data stays sharded.

Losses: ``squared`` (regression), ``logistic`` (binary classification,
labels in {0, 1}), and ``softmax`` (ytk-learn's multiclass_linear
family: w becomes [F, C], labels are int class ids); L2 as a penalty
gradient added before the momentum update (coupled, classic
SGD-with-weight-penalty; the reported loss is the data term only), L1
via a proximal shrink after the step (so momentum still sees a smooth
objective).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models._base import (DataParallelTrainer, EarlyStopper,
                                       StepStatsExchanger,
                                       per_example_loss,
                                       stage_softmax_labels)

LOSSES = ("squared", "logistic", "softmax")


@dataclass(frozen=True)
class LinearConfig:
    n_features: int
    loss: str = "squared"
    n_classes: int = 2          # used by loss="softmax" only
    learning_rate: float = 0.1
    l1: float = 0.0
    l2: float = 0.0
    momentum: float = 0.0

    def __post_init__(self):
        if self.loss not in LOSSES:
            raise Mp4jError(f"loss must be one of {LOSSES}, got {self.loss!r}")
        if self.loss == "softmax" and self.n_classes < 2:
            raise Mp4jError("softmax needs n_classes >= 2")


def _mean_loss_grad(params, x, y, sample_w, cfg: LinearConfig, axis_name):
    """Global-mean gradient of the (unregularized) loss.

    The psum'd (sum_grad, sum_weight) pair turns per-shard sums into the
    exact global mean — weighting also neutralizes padding rows (weight
    0), so sharded and single-device runs match bitwise up to reduction
    order.

    Params arrive replicated (``P()``); they are cast device-varying
    with ``lax.pcast`` before differentiation so the gradient stays a
    PER-SHARD quantity and the cross-shard sum is the EXPLICIT ``psum``
    below. (Without this, shard_map's varying-axis autodiff inserts the
    psum itself — the transpose of replication — and an explicit psum on
    top would multiply gradients by the shard count.)
    """
    w, b = params
    if axis_name is not None:
        w = lax.pcast(w, axis_name, to="varying")
        b = lax.pcast(b, axis_name, to="varying")

    def shard_sums(w, b):
        z = x @ w + b
        return jnp.sum(per_example_loss(z, y, cfg.loss) * sample_w)

    sum_loss, grads = jax.value_and_grad(
        lambda p: shard_sums(*p))((w, b))
    cnt = jnp.sum(sample_w)
    if axis_name is not None:
        sum_loss = lax.psum(sum_loss, axis_name)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), grads)  # THE gradient allreduce
        cnt = lax.psum(cnt, axis_name)
    denom = jnp.maximum(cnt, 1.0)
    mean_grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
    return sum_loss / denom, mean_grads


def train_step_shard(params, vel, x, y, sample_w, cfg: LinearConfig,
                     axis_name=None):
    """One optimization step on this shard. Returns (params, vel, loss)."""
    loss, (gw, gb) = _mean_loss_grad(params, x, y, sample_w, cfg, axis_name)
    w, b = params
    gw = gw + cfg.l2 * w                      # L2 penalty (not on bias)
    vw, vb = vel
    vw = cfg.momentum * vw + gw
    vb = cfg.momentum * vb + gb
    w = w - cfg.learning_rate * vw
    b = b - cfg.learning_rate * vb
    if cfg.l1 > 0.0:
        # proximal shrink keeps the objective smooth for momentum
        shrink = cfg.learning_rate * cfg.l1
        w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - shrink, 0.0)
    return (w, b), (vw, vb), loss


def predict(params, x, cfg: LinearConfig):
    w, b = params
    z = x @ w + b
    if cfg.loss == "logistic":
        return jax.nn.sigmoid(z)
    if cfg.loss == "softmax":
        return jax.nn.softmax(z, axis=-1)
    return z


class LinearTrainer(DataParallelTrainer):
    """Data-parallel linear/logistic regression over a mesh.

    The per-step program is one jitted ``shard_map``: data sharded over
    the mesh axis (or axes, for a hierarchical inter x intra mesh),
    parameters and optimizer state replicated, gradients psum'd.
    """

    def __init__(self, cfg: LinearConfig, mesh=None, n_devices=None):
        super().__init__(mesh=mesh, n_devices=n_devices)
        self.cfg = cfg
        self._step = None
        self._eval_fn = None
        self.eval_history_: list[float] = []

    def init_params(self):
        if self.cfg.loss == "softmax":
            # w [F, C], b [C]: ytk-learn's multiclass_linear family
            return (jnp.zeros((self.cfg.n_features, self.cfg.n_classes),
                              jnp.float32),
                    jnp.zeros((self.cfg.n_classes,), jnp.float32))
        return (jnp.zeros((self.cfg.n_features,), jnp.float32),
                jnp.zeros((), jnp.float32))

    def _build_step(self):
        cfg = self.cfg
        axes = self.axes
        dspec = P(axes)

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(P(), P(), dspec, dspec, dspec),
                 out_specs=(P(), P(), P()))
        def step(params, vel, x, y, sw):
            return train_step_shard(params, vel, x[0], y[0], sw[0], cfg, axes)

        return jax.jit(step)

    def shard_data(self, x: np.ndarray, y: np.ndarray,
                   sample_weight=None):
        """Pad + reshape to [n_shards, N/shard, ...]; padding rows carry
        sample weight 0 so results match unsharded runs for any N.
        ``sample_weight`` ([N], optional — ytk-learn's instance
        weights) scales each example's loss/gradient (the step
        normalizes by the weight sum: integer weights == row
        duplication)."""
        x = np.asarray(x, np.float32)
        y = self._stage_labels(y)
        if x.ndim != 2 or x.shape[1] != self.cfg.n_features:
            raise Mp4jError(
                f"x must be [N, {self.cfg.n_features}], got {x.shape}")
        N = x.shape[0]
        (x, y), per, sw = self._pad_rows([x, y])
        sw[:N] *= self._stage_weights(sample_weight, N)
        return (self._put_sharded(x, per), self._put_sharded(y, per),
                self._put_sharded(sw, per))

    def fit(self, x: np.ndarray, y: np.ndarray, n_steps: int = 100,
            params=None, eval_set=None,
            early_stopping_rounds: int | None = None,
            sample_weight=None, comm=None):
        """Run ``n_steps`` full-batch steps; returns (params, losses).

        ``eval_set=(x_va, y_va)`` tracks held-out loss per step (history
        in ``self.eval_history_``); ``early_stopping_rounds=k`` stops
        after k non-improving steps and returns the best round's
        params; ``sample_weight`` weights examples (see
        :meth:`shard_data`).

        ``comm`` (an mp4j comm; every rank calls ``fit`` together)
        syncs each step's training loss across the job — the mean
        history lands in ``self.sync_loss_history_`` ([n_steps]).
        Under ``MP4J_OVERLAP=1`` step k's exchange is submitted
        nonblocking and overlaps step k+1's device compute, drained at
        the loop boundary (bit-identical results — submit order is the
        collective order either way; see
        ``models._base.StepStatsExchanger``).
        """
        if early_stopping_rounds is not None and eval_set is None:
            raise Mp4jError("early_stopping_rounds requires an eval_set")
        if self._step is None:
            self._step = self._build_step()
        dx, dy, dsw = self.shard_data(x, y, sample_weight=sample_weight)
        if params is None:
            params = self.init_params()
        # committed up front: an uncommitted first call would compile
        # the step twice (see DataParallelTrainer._place_replicated)
        params = self._place_replicated(params)
        vel = jax.tree_util.tree_map(jnp.zeros_like, params)
        va = None
        if eval_set is not None:
            x_va = np.asarray(eval_set[0], np.float32)
            y_va = self._stage_labels(eval_set[1])
            if x_va.ndim != 2 or x_va.shape[1] != self.cfg.n_features:
                raise Mp4jError(
                    f"eval x must be [N, {self.cfg.n_features}], "
                    f"got {x_va.shape}")
            if y_va.shape != (x_va.shape[0],):
                raise Mp4jError(
                    f"eval y must be [{x_va.shape[0]}], got {y_va.shape}")
            va = (jnp.asarray(x_va), jnp.asarray(y_va))
        stopper = EarlyStopper(early_stopping_rounds)
        self.eval_history_ = stopper.history
        exchanger = StepStatsExchanger(comm)
        losses = []
        for i in range(n_steps):
            params, vel, loss = self._step(params, vel, dx, dy, dsw)
            # Synchronize each step: on hosts with fewer cores than mesh
            # devices, letting hundreds of small multi-collective programs
            # queue up can starve XLA's CPU collective rendezvous (its
            # device threads block 40s then abort). One program in flight
            # at a time costs nothing here (steps are data-dependent
            # anyway) and keeps the thread demand bounded.
            loss = jax.block_until_ready(loss)
            # step k's host-stats exchange: blocking here, or (under
            # MP4J_OVERLAP=1) in flight while step k+1 runs the device
            exchanger.submit(np.array([float(loss)], np.float64))
            losses.append(loss)
            if va is not None and stopper.update(
                    self._eval_loss(params, va), i, state=(params, vel)):
                if stopper.best_state is not None:
                    params, vel = stopper.best_state
                    losses = losses[:stopper.best_round + 1]
                break
        exchanger.drain()
        hist = exchanger.mean_history()
        self.sync_loss_history_ = (hist[:, 0] if hist.size
                                   else np.zeros(0, np.float64))
        return params, np.asarray(jax.device_get(losses))

    def fit_stream(self, batches, params=None,
                   batch_rows: int | None = None,
                   max_in_flight: int = 2):
        """Chunked (out-of-core) training: one optimizer step per
        ``(x, y)`` chunk (or ``(x, y, w)`` with per-chunk instance
        weights) — ytk-learn's linear family trains from the
        same streamed libsvm text as FFM
        (``utils.libsvm.read_libsvm`` + ``utils.libsvm.dense_chunks``
        adapts it to the dense [N, F] this model consumes). Chunks pad
        to ``batch_rows`` (default: first chunk, rounded up to the
        shard count) with zero-weight rows so ONE jitted program
        serves the stream; momentum state threads across chunks; the
        pipeline double-buffers exactly like
        :meth:`FMTrainer.fit_stream` (``max_in_flight=0``
        serializes). Feeding the full dataset as a single chunk E
        times is numerically identical to ``fit(n_steps=E)`` (tested).
        Returns (params, per-chunk losses)."""
        if self._step is None:
            self._step = self._build_step()
        if params is None:
            params = self.init_params()
        params = self._place_replicated(params)
        state = [params, jax.tree_util.tree_map(jnp.zeros_like, params)]

        def dispatch(staged):
            # the throttle inside _stream_fit also bounds the queued
            # multi-collective programs — see the sync note in fit()
            state[0], state[1], loss = self._step(state[0], state[1],
                                                  *staged)
            return loss

        losses = self._stream_fit(batches, self._stage_stream_chunk,
                                  dispatch, batch_rows, max_in_flight)
        return state[0], losses

    def _stage_stream_chunk(self, chunk, batch_rows: int | None):
        """Host half of one stream step: validate, pad to
        ``batch_rows`` (resolving it from the first chunk), start the
        async device placement."""
        x, y = chunk[:2]
        weights = chunk[2] if len(chunk) > 2 else None
        x = np.asarray(x, np.float32)
        y = self._stage_labels(y)
        if x.ndim != 2 or x.shape[1] != self.cfg.n_features:
            raise Mp4jError(
                f"x must be [N, {self.cfg.n_features}], got {x.shape}")
        if batch_rows is None:
            batch_rows = -(-x.shape[0] // self.n_shards) * self.n_shards
        N = x.shape[0]
        (x, y), sw, per = self._pad_stream_rows([x, y], batch_rows)
        sw[:N] *= self._stage_weights(weights, N)
        staged = (self._put_sharded(x, per), self._put_sharded(y, per),
                  self._put_sharded(sw, per))
        return staged, batch_rows

    def _stage_labels(self, y) -> np.ndarray:
        """Labels must be a flat [N] vector — a column-vector y would
        broadcast through the loss to an [N, N] matrix and train
        silently on garbage. softmax labels are additionally int32
        class ids validated in range (stage_softmax_labels, shared
        with the GBDT softmax path)."""
        y = np.asarray(y)
        if y.ndim != 1:
            raise Mp4jError(f"y must be 1-D [N], got shape {y.shape}")
        if self.cfg.loss != "softmax":
            return y.astype(np.float32)
        return stage_softmax_labels(y, self.cfg.n_classes)

    def _eval_loss(self, params, va) -> float:
        if self._eval_fn is None:
            cfg = self.cfg

            @jax.jit
            def run(params, x, y):
                w, b = params
                return jnp.mean(per_example_loss(x @ w + b, y, cfg.loss))

            self._eval_fn = run
        # params may span non-addressable devices on multi-process
        # meshes; a plain local jit cannot consume those directly
        return float(self._eval_fn(self._local_values(params), *va))

    def predict(self, params, x: np.ndarray) -> np.ndarray:
        x = jnp.asarray(np.asarray(x, np.float32))
        return np.asarray(predict(params, x, self.cfg))


# ----------------------------------------------------------------------
# serve adapter (ISSUE 19): the pull-mode sharded entry point
# ----------------------------------------------------------------------
class LinearServable:
    """Row-pull serve adapter for a trained linear model.

    ``kind="pull"``: the serve dispatcher shards the weight table by
    ``row_id % size`` across the job's ranks and the frontend pulls
    only the rows a batch touches over the columnar map plane —
    mirroring the owner-routed row fetch of the FFM AOT
    ``sharded_serve`` program on the host substrate. A row here is
    one feature's weight(s): width 1, or ``n_classes`` for softmax.
    Scoring is per example (never across the batch), so batched and
    sequential serve predictions are bitwise identical by
    construction.
    """

    kind = "pull"
    family = "linear"

    def __init__(self, params, cfg: LinearConfig):
        w, b = params
        self.cfg = cfg
        w = np.asarray(jax.device_get(w), np.float32)
        self._w = w if w.ndim == 2 else w[:, None]     # [D, width]
        self._b = np.atleast_1d(
            np.asarray(jax.device_get(b), np.float32))
        self.n_rows = self._w.shape[0]
        self.row_width = self._w.shape[1]
        self.resp_width = (cfg.n_classes if cfg.loss == "softmax"
                          else 1)

    def row_ids(self, req) -> np.ndarray:
        """Unique table rows one request touches (active slots only —
        a zero-valued slot contributes nothing, so its row is never
        pulled)."""
        ids, _fields, vals = req
        return np.unique(np.asarray(ids, np.int64)[
            np.asarray(vals, np.float32) != 0])

    def rows(self, ids) -> np.ndarray:
        """Float64 row vectors for the pull plane (the wire operand of
        ``allreduce_map`` is DOUBLE)."""
        return self._w[np.asarray(ids, np.int64)].astype(np.float64)

    def predict_sharded(self, reqs, rowmap) -> list:
        """Score a batch from pulled rows; one float64 vector per
        request. A row missing from ``rowmap`` scores as zeros — the
        degraded-but-deliverable contract the dispatcher's status byte
        reports."""
        out = []
        zero = np.zeros(self.row_width, np.float32)
        for ids, _fields, vals in reqs:
            ids = np.asarray(ids, np.int64)
            vals = np.asarray(vals, np.float32)
            z = self._b.astype(np.float32).copy()
            if self.cfg.loss != "softmax":
                z = z[:1].copy()
            for a in range(ids.shape[0]):
                if vals[a] == 0:
                    continue
                row = rowmap.get(int(ids[a]))
                row = zero if row is None else row.astype(np.float32)
                z += row * vals[a]
            out.append(_link(z, self.cfg.loss))
        return out


def _link(z: np.ndarray, loss: str) -> np.ndarray:
    """The prediction link on a host margin vector (numpy mirror of
    :func:`predict`'s heads, overflow-safe)."""
    z = np.asarray(z, np.float32)
    if loss == "logistic":
        p = np.empty_like(z, np.float64)
        pos = z >= 0
        p[pos] = 1.0 / (1.0 + np.exp(-z[pos].astype(np.float64)))
        e = np.exp(z[~pos].astype(np.float64))
        p[~pos] = e / (1.0 + e)
        return p
    if loss == "softmax":
        s = z.astype(np.float64) - z.max()
        e = np.exp(s)
        return e / e.sum()
    return z.astype(np.float64)


def servable(params, cfg: LinearConfig) -> LinearServable:
    """The serve plane's per-family entry point (ISSUE 19)."""
    return LinearServable(params, cfg)
