"""TPU-native distributed factorization machines (FM and field-aware FFM).

ytk-mp4j's consumer ytk-learn ships FM and FFM model families whose
training loop allreduces EMBEDDING GRADIENTS every step — and because a
mini-batch touches only a sparse subset of the feature vocabulary, the
reference ships them as a sparse ``Map<String, Float[]>`` over the Kryo
socket path ("FFM gradient allreduce", BASELINE.json configs[4];
SURVEY.md section 3c).

TPU-first rebuild. Instances are padded to ``max_nnz`` static slots
(feature id / field id / value / mask), the whole step is one jitted
``shard_map`` program, and the gradient allreduce is:

- **dense mode** (default): the full embedding-table gradient rides one
  ``lax.psum`` — bandwidth ~|V| but maximally MXU/HBM friendly; right
  whenever the vocabulary fits comfortably on-chip.
- **sparse mode** (``sparse_grads=True``): per-slot gradient rows ride
  as static-shape ``(row_index, grad_row)`` buffers — ONE all_gather
  each, then a single identity-dropping scatter-add into the table,
  which merges duplicate rows natively (the device-native analogue of
  the reference's key-wise map merge; the map API's sort + segment
  pack would be pure overhead here — round-3 A/B in BASELINE.md,
  64.2 -> 38.1 ms/step). Bandwidth ~nnz instead of ~|V|: the TPU
  translation of the reference's sparse map path.

Model scores (order-2, sigmoid/logloss for classification):

- FM:  ``w0 + sum_i w_i x_i + sum_{a<b} <v_a, v_b> x_a x_b`` with the
  O(K k) sum-of-squares identity.
- FFM: ``v`` is per (feature, field): ``sum_{a<b} <v_{a, field_b},
  v_{b, field_a}> x_a x_b`` over K^2 slot pairs (K = max_nnz, static).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.models._base import (DataParallelTrainer,
                                       EarlyStopper, StepStatsExchanger,
                                       per_example_loss)
from ytk_mp4j_tpu.operators import Operators
from ytk_mp4j_tpu.ops import sparse as sparse_ops

MODELS = ("fm", "ffm")
LOSSES = ("logistic", "squared")


@dataclass(frozen=True)
class FMConfig:
    n_features: int                 # vocabulary size |V|
    n_fields: int = 1               # >1 + model="ffm" => field-aware
    k: int = 8                      # latent dimension
    max_nnz: int = 16               # static non-zero slots per instance
    model: str = "fm"
    loss: str = "logistic"
    learning_rate: float = 0.1
    l2: float = 0.0                 # on embeddings + linear weights
    init_scale: float = 0.01

    def __post_init__(self):
        if self.model not in MODELS:
            raise Mp4jError(f"model must be one of {MODELS}")
        if self.loss not in LOSSES:
            raise Mp4jError(f"loss must be one of {LOSSES}")
        if self.model == "ffm" and self.n_fields < 2:
            raise Mp4jError("ffm needs n_fields >= 2")


def _gather_slots(V, rows):
    """The one embedding gather: per-slot rows of the (flat) table.

    rows come from :func:`_slot_rows` — [N, K] (fm) or [N, K, K]
    (ffm); result appends the latent dim k."""
    return V[rows]


def _score_from_slots(w0, w, E, feats, xv, cfg: FMConfig):
    """Model score given the already-gathered embedding rows ``E``.

    Split out from :func:`_score` so the sparse train step can
    differentiate with respect to E DIRECTLY (per-slot gradient rows)
    instead of the full table — the backward of a table gather is a
    dense scatter-add over |V| rows on the serial scatter unit."""
    linear = jnp.sum(w[feats] * xv, axis=1)
    if cfg.model == "fm":
        # 0.5 * ((sum_a v_a x_a)^2 - sum_a (v_a x_a)^2), summed over k
        Ex = E * xv[..., None]                         # [N, K, k]
        s = jnp.sum(Ex, axis=1)                        # [N, k]
        inter = 0.5 * jnp.sum(s * s - jnp.sum(Ex * Ex, axis=1), axis=1)
    else:
        # FFM: E[a, b] = v_{feat_a, field_b}; z += <E[a,b], E[b,a]> x_a x_b
        pair = jnp.einsum("nabk,nbak->nab", E, E)
        pair = pair * (xv[:, :, None] * xv[:, None, :])
        K = feats.shape[1]
        upper = jnp.triu(jnp.ones((K, K), pair.dtype), 1)
        inter = jnp.sum(pair * upper, axis=(1, 2))
    return w0 + linear + inter


def _score(params, feats, fields, vals, mask, cfg: FMConfig):
    """Model score for a batch of padded sparse instances.

    feats/fields: [N, K] int32; vals/mask: [N, K] f32.
    """
    w0, w, V = params
    xv = vals * mask                                   # zero padded slots
    E = _gather_slots(V, _slot_rows(feats, fields, cfg))
    return _score_from_slots(w0, w, E, feats, xv, cfg)


def _slot_rows(feats, fields, cfg: FMConfig):
    """Embedding-table row index touched by each gradient slot.

    FM touches row ``feat`` per slot ([N, K]); FFM touches row
    ``feat * n_fields + field_b`` per slot PAIR ([N, K, K]) — matching
    the [N, K(, K), k] slot-gradient layout of ``_score``'s gathers.
    """
    if cfg.model == "fm":
        return feats
    return feats[:, :, None] * cfg.n_fields + fields[:, None, :]


def _pcast_params(params, axis_name):
    """Cast params device-varying so grads stay per-shard and the
    cross-shard reduction is the explicit collective chosen by the
    caller (dense psum or sparse allreduce) — see models/linear.py."""
    if axis_name is None:
        return params
    return jax.tree_util.tree_map(
        lambda p: lax.pcast(p, axis_name, to="varying"), params)


def _weighted_mean_grads(p, score_fn, y, sw, cfg: FMConfig, axis_name):
    """Global-mean loss + grads of the sample-weighted shard loss —
    the one prologue shared by the dense and sparse steps. ``p`` is
    the differentiated pytree (full params, or (w0, w, E) with the
    gathered embedding rows on the sparse path); ``score_fn(p)`` the
    margin."""
    def shard_sum(q):
        return jnp.sum(per_example_loss(score_fn(q), y, cfg.loss) * sw)

    sum_loss, grads = jax.value_and_grad(shard_sum)(p)
    cnt = jnp.sum(sw)
    if axis_name is not None:
        sum_loss = lax.psum(sum_loss, axis_name)
        cnt = lax.psum(cnt, axis_name)
    denom = jnp.maximum(cnt, 1.0)
    return sum_loss / denom, grads, denom


def _mean_loss_grad(params, batch, cfg: FMConfig, axis_name):
    feats, fields, vals, mask, y, sw = batch
    params = _pcast_params(params, axis_name)
    return _weighted_mean_grads(
        params, lambda p: _score(p, feats, fields, vals, mask, cfg),
        y, sw, cfg, axis_name)


def train_step_dense(params, batch, cfg: FMConfig, axis_name=None):
    """One step; the embedding-gradient allreduce is a dense psum."""
    loss, (g0, gw, gV), denom = _mean_loss_grad(params, batch, cfg, axis_name)
    if axis_name is not None:
        g0 = lax.psum(g0, axis_name)
        gw = lax.psum(gw, axis_name)
        gV = lax.psum(gV, axis_name)       # THE dense gradient allreduce
    w0, w, V = params
    lr = cfg.learning_rate
    w0 = w0 - lr * (g0 / denom)
    w = w - lr * (gw / denom + cfg.l2 * w)
    V = V - lr * (gV / denom + cfg.l2 * V)
    return (w0, w, V), loss


def train_step_sparse(params, batch, cfg: FMConfig, capacity: int,
                      axis_name="mp4j"):
    """One step; embedding gradients ride the SPARSE path.

    Instead of psum'ing the dense [rows, k] gradient table, each shard
    ships its touched (row, grad_row) slots over ONE all_gather each
    and the merged update is a single identity-dropping scatter-add
    into V, which sums duplicate rows natively (bandwidth
    ~touched-slots, not ~|V|). ``capacity`` is the static slot bound
    the optional local dedupe packs into (it shrinks the all_gather
    payload when capacity < S; nothing is ever dropped by the
    scatter).

    The embedding table enters autodiff only through the GATHERED
    per-slot rows (``_score_from_slots``), so the backward yields the
    per-slot gradient rows [S, k] directly — differentiating through
    the gather would scatter-add a dense |V|-row gradient table on the
    serial scatter unit and immediately re-gather its touched rows
    (measured 1.8x the step time at |V|-rows = 8M single-chip).
    Duplicate local rows merge by sort + segmented reduction, and the
    update is one identity-dropping scatter into V.
    """
    feats, fields, vals, mask, y, sw = batch
    w0, w, V = _pcast_params(params, axis_name)
    rows = _slot_rows(feats, fields, cfg)       # [N, K] / [N, K, K]
    E = _gather_slots(V, rows)
    xv = vals * mask
    loss, (g0, gw, gE), denom = _weighted_mean_grads(
        (w0, w, E),
        lambda p: _score_from_slots(p[0], p[1], p[2], feats, xv, cfg),
        y, sw, cfg, axis_name)
    if axis_name is not None:
        g0 = lax.psum(g0, axis_name)
        gw = lax.psum(gw, axis_name)     # linear part stays dense (small)

    # Local duplicate-row merge (sort + segmented reduction) runs ONLY
    # when it shrinks the all_gather payload (capacity < S): the final
    # scatter-add merges duplicates natively, so with capacity >= S
    # the local sort would buy nothing (its round-2 incarnation
    # measured ~35 ms of pure overhead at S = 512k single-chip).
    S = rows.size
    k = V.shape[1]
    flat_rows = rows.reshape(-1)
    flat_g = gE.reshape(S, k)
    if capacity < S:
        si, sv = sparse_ops.sort_by_key(flat_rows, flat_g)
        li, lv = sparse_ops.segment_reduce_sorted(
            si, sv, capacity, Operators.SUM)
    else:
        li, lv = flat_rows.astype(jnp.int32), flat_g
    if axis_name is not None:
        # NOT sparse_allreduce: its post-gather sort + segment reduce
        # packs unique keys for the map API, but the table update below
        # is a scatter-add, which merges duplicate rows natively — the
        # pack would be pure overhead (measured ~17 ms at the 524288-
        # row union shape: sort ~2 ms + segment reduce ~15 ms; the
        # scatter costs the same either way, round-3 A/B in
        # BASELINE.md). Gather every shard's slots and scatter them all.
        oi = lax.all_gather(li, axis_name, axis=0, tiled=True)
        ov = lax.all_gather(lv, axis_name, axis=0, tiled=True)
    else:
        # no collective: the identity-dropping scatter-add below sums
        # duplicate rows natively, no dedupe needed
        oi, ov = li, lv
    lr = cfg.learning_rate
    w0 = w0 - lr * (g0 / denom)
    w = w - lr * (gw / denom + cfg.l2 * w)
    if cfg.l2:
        V = V * (1.0 - lr * cfg.l2)     # decay all rows, like the dense
    safe = jnp.where(oi == sparse_ops.SENTINEL, V.shape[0], oi)
    V = V.at[safe].add(-(lr / denom) * ov, mode="drop")
    return (w0, w, V), loss


def _fetch_rows_sharded(Vs, flat_rows, me, axis_name):
    """Owner-routed row fetch from a block-sharded table: every
    member's row-ids ride one (tiny, int32) all_gather, owners answer
    with their rows over one ``all_to_all``, and the per-owner
    contributions sum to the complete rows (each id is owned by exactly
    one member). Returns ([S, k] rows for THIS member's ids, gi [n, S]
    all requests, owner [n, S]) — the latter two are reused by the
    train step's backward routing."""
    B, _k = Vs.shape
    gi = lax.all_gather(flat_rows, axis_name, axis=0,
                        tiled=False)            # [n, S] all requests
    owner = gi // B
    local = jnp.where(owner == me, gi - me * B, 0)
    contrib = Vs[local]                         # [n, S, k] row gather
    contrib = jnp.where((owner == me)[..., None], contrib, 0.0)
    recv = lax.all_to_all(contrib, axis_name, split_axis=0,
                          concat_axis=0, tiled=False)   # [n, S, k]
    return jnp.sum(recv, axis=0), gi, owner


def train_step_sparse_sharded(params, batch, cfg: FMConfig, n: int,
                              axis_name="mp4j"):
    """One step with the embedding table SHARDED over the mesh: member
    m owns rows ``[m*B, (m+1)*B)`` of the (padded) table, B = rows/n.

    The replicated sparse step's serial floor is the per-chip
    scatter-add of ALL members' gradient rows (n*S descriptors into a
    full replica; BASELINE.md prices it at 69.2 of 74.6 costed GB,
    ~80 ns/row). Sharding changes both sides:

    - forward: slot row-ids ride one (tiny, int32) all_gather; each
      member gathers the requested rows IT OWNS from its shard (row
      gathers pipeline at ~4 ns/row) and one ``all_to_all`` delivers
      them — wire n*S*k, the same order as the replicated path's
      gradient all_gather;
    - backward: gradient rows route to their owners by ``all_to_all``
      (replacing the all_gather), then each member merges its received
      rows by sort + segmented reduction into at most
      ``C = min(n*S, B)`` slots — C is bounded by the SHARD SIZE, so
      no overflow is possible — and scatter-adds C descriptors into
      its [B, k] shard. Round-4 chip measurement: drop-mode scatters
      pay the serial unit per DESCRIPTOR, not per applied row (7/8
      sentinel rows save only 3%), so the compaction is what converts
      ownership into a real 1/n serial-floor cut; the set-scatter
      inside the segmented reduction is the cheaper scatter form
      (round-3: 15 vs 42 ms at 524288 rows).

    Table memory per chip is V/n rows — the piece that makes
    configs[4]'s Criteo-scale vocabulary fit a pod at all.
    """
    from ytk_mp4j_tpu.ops.collectives import flat_index

    feats, fields, vals, mask, y, sw = batch
    w0, w, Vs = params              # Vs: [B, k], this member's shard
    w0, w = (lax.pcast(w0, axis_name, to="varying"),
             lax.pcast(w, axis_name, to="varying"))
    B, k = Vs.shape
    me = flat_index(axis_name)
    rows = _slot_rows(feats, fields, cfg)       # [N, K] / [N, K, K]
    S = rows.size
    flat_rows = rows.reshape(-1).astype(jnp.int32)

    # ---- forward: owner-routed row fetch ----
    E_flat, gi, owner = _fetch_rows_sharded(Vs, flat_rows, me, axis_name)
    E = E_flat.reshape(rows.shape + (k,))

    xv = vals * mask
    loss, (g0, gw, gE), denom = _weighted_mean_grads(
        (w0, w, E),
        lambda p: _score_from_slots(p[0], p[1], p[2], feats, xv, cfg),
        y, sw, cfg, axis_name)
    g0 = lax.psum(g0, axis_name)
    gw = lax.psum(gw, axis_name)     # linear part stays dense (small)

    # ---- backward: owner-routed gradient rows ----
    dest = flat_rows // B                           # [S]
    onehot = dest[None, :] == jnp.arange(n)[:, None]
    send = gE.reshape(S, k)[None] * onehot[..., None]   # [n, S, k]
    recvg = lax.all_to_all(send, axis_name, split_axis=0,
                           concat_axis=0, tiled=False)  # [n, S, k]
    # received row j,s carries my local row id iff I own gi[j, s]
    loc_ids = jnp.where(owner == me, gi - me * B, sparse_ops.SENTINEL)
    si, sv = sparse_ops.sort_by_key(loc_ids.reshape(-1),
                                    recvg.reshape(-1, k))
    C = min(n * S, B)
    li, lv = sparse_ops.segment_reduce_sorted(si, sv, C, Operators.SUM)

    lr = cfg.learning_rate
    w0 = w0 - lr * (g0 / denom)
    w = w - lr * (gw / denom + cfg.l2 * w)
    if cfg.l2:
        Vs = Vs * (1.0 - lr * cfg.l2)
    safe = jnp.where(li == sparse_ops.SENTINEL, B, li)
    Vs = Vs.at[safe].add(-(lr / denom) * lv, mode="drop")
    return (w0, w, Vs), loss


def predict(params, feats, fields, vals, mask, cfg: FMConfig):
    z = _score(params, feats, fields, vals, mask, cfg)
    if cfg.loss == "logistic":
        return jax.nn.sigmoid(z)
    return z


class FMTrainer(DataParallelTrainer):
    """Data-parallel FM/FFM over a mesh.

    ``sparse_grads=True`` routes embedding gradients through the
    device-native sparse allreduce (the FFM workload of
    BASELINE.json configs[4]); default is the dense psum.
    """

    TABLE_SHARDINGS = ("replicated", "sharded")

    def __init__(self, cfg: FMConfig, mesh=None, n_devices=None,
                 sparse_grads: bool = False,
                 sparse_capacity: int | None = None,
                 table_sharding: str = "replicated"):
        super().__init__(mesh=mesh, n_devices=n_devices)
        self.cfg = cfg
        self.sparse_grads = sparse_grads
        self.sparse_capacity = sparse_capacity
        if table_sharding not in self.TABLE_SHARDINGS:
            raise Mp4jError(
                f"table_sharding must be one of {self.TABLE_SHARDINGS}")
        if table_sharding == "sharded" and not sparse_grads:
            raise Mp4jError(
                "table_sharding='sharded' rides the sparse-gradient "
                "path; pass sparse_grads=True")
        if sparse_capacity is not None and (
                table_sharding == "sharded" or not sparse_grads):
            # only the replicated sparse step consumes it; anywhere
            # else a tuned capacity would be silently dropped
            raise Mp4jError(
                "sparse_capacity applies to the replicated sparse path "
                "only (sparse_grads=True, table_sharding='replicated'); "
                "the sharded step sizes its buffers as "
                "C = min(n_shards * batch_slots, table_rows) and the "
                "dense step has no capacity at all")
        self.table_sharding = table_sharding
        self._step = None
        self._step_key = None
        self._eval_fn = None
        self._pred_fn = None      # sharded serve (jit retraces by shape)
        self.eval_history_: list[float] = []

    @property
    def n_rows(self) -> int:
        """Embedding-table rows: |V| for FM, |V| * n_fields for FFM."""
        if self.cfg.model == "fm":
            return self.cfg.n_features
        return self.cfg.n_features * self.cfg.n_fields

    @property
    def n_rows_padded(self) -> int:
        """Table rows padded to a multiple of the shard count (sharded
        mode stores B = n_rows_padded / n rows per member; the padding
        rows are never referenced — ids stay < n_rows)."""
        n = self.n_shards
        return -(-self.n_rows // n) * n

    def init_params(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        V = (self.cfg.init_scale
             * rng.standard_normal((self.n_rows, self.cfg.k))).astype(
                 np.float32)
        params = (jnp.zeros((), jnp.float32),
                  jnp.zeros((self.cfg.n_features,), jnp.float32),
                  jnp.asarray(V) if self.table_sharding != "sharded"
                  else V)
        return self._stage_table(params)   # no-op unless sharded

    def full_table(self, params) -> np.ndarray:
        """The complete [n_rows, k] embedding table on the host,
        whatever the sharding (the serve/save shape)."""
        return self._to_host(params[2])[: self.n_rows]

    def _place_params(self, params):
        """Commit params to their exact step shardings (replicated
        scalars/linear weights; replicated or block-sharded table) so
        the first step call compiles the same program signature as
        every later one — see ``DataParallelTrainer._place_replicated``
        for the duplicate-compile failure this prevents."""
        if self.table_sharding == "sharded":
            params = self._stage_table(params)
            return (*self._place_replicated(params[:2]), params[2])
        return self._place_replicated(params)

    def _stage_table(self, params):
        """Sharded mode: place a host/full-size table onto the mesh
        (padded to n_rows_padded, block-sharded). Already-staged params
        (from init_params or a previous step) pass through."""
        if self.table_sharding != "sharded":
            return params
        V = params[2]
        if (isinstance(V, jax.Array)
                and V.shape == (self.n_rows_padded, self.cfg.k)):
            return params
        V = np.asarray(V)[: self.n_rows]
        pad = self.n_rows_padded - self.n_rows
        if pad:
            V = np.pad(V, ((0, pad), (0, 0)))
        Vg = jax.make_array_from_callback(
            V.shape, self._row_sharding(), lambda idx: V[idx])
        return (jnp.asarray(params[0]), jnp.asarray(params[1]), Vg)

    def save_params(self, path: str, params) -> None:
        """Persist with the table in its portable [n_rows, k] shape
        (a sharded table is gathered + unpadded first, so the file is
        loadable at any shard count)."""
        if self.table_sharding == "sharded":
            params = (self._to_host(params[0]),
                      self._to_host(params[1]), self.full_table(params))
        super().save_params(path, params)

    def _build_step(self, per_shard_slots: int):
        cfg = self.cfg
        axes = self.axes
        dspec = P(axes)
        if self.table_sharding == "sharded":
            step_fn = partial(train_step_sparse_sharded, cfg=cfg,
                              n=self.n_shards, axis_name=axes)
            pspec = (P(), P(), dspec)   # table sharded over the mesh

            @partial(jax.shard_map, mesh=self.mesh, check_vma=False,
                     in_specs=(pspec,) + (dspec,) * 6,
                     out_specs=(pspec, P()))
            def step(params, feats, fields, vals, mask, y, sw):
                batch = (feats[0], fields[0], vals[0], mask[0], y[0],
                         sw[0])
                return step_fn(params, batch)

            return jax.jit(step)
        if self.sparse_grads:
            cap = self.sparse_capacity
            if cap is None:
                # global unique touched rows can't exceed total slots
                # this step, nor the table size
                bound = per_shard_slots * self.n_shards
                if cfg.model == "ffm":
                    bound *= cfg.max_nnz
                cap = min(self.n_rows, bound)
            step_fn = partial(train_step_sparse, cfg=cfg, capacity=cap,
                              axis_name=axes)
            # params are pcast to varying but returned under replicated
            # P() out_specs (every shard computes the identical update
            # from the all-gathered slots + psum'd scalars), which VMA
            # checking cannot prove — same waiver class as the sparse
            # path in comm.tpu_comm (correctness is covered by the
            # dense-vs-sparse differential test)
            check_vma = False
        else:
            step_fn = partial(train_step_dense, cfg=cfg, axis_name=axes)
            check_vma = True

        @partial(jax.shard_map, mesh=self.mesh, check_vma=check_vma,
                 in_specs=(P(),) + (dspec,) * 6, out_specs=(P(), P()))
        def step(params, feats, fields, vals, mask, y, sw):
            batch = (feats[0], fields[0], vals[0], mask[0], y[0], sw[0])
            return step_fn(params, batch)

        return jax.jit(step)

    def _check_instances(self, feats: np.ndarray, fields: np.ndarray):
        """Shared id-range validation for fit and predict inputs (JAX
        gathers clamp out-of-range indices silently, so bad ids must be
        rejected on the host)."""
        if feats.ndim != 2 or feats.shape[1] > self.cfg.max_nnz:
            raise Mp4jError(
                f"feats must be [N, K<={self.cfg.max_nnz}], got {feats.shape}")
        if (feats.min(initial=0) < 0
                or feats.max(initial=0) >= self.cfg.n_features):
            raise Mp4jError("feature id out of range")
        if self.cfg.model == "ffm" and (
                fields.min(initial=0) < 0
                or fields.max(initial=0) >= self.cfg.n_fields):
            raise Mp4jError("field id out of range")

    def shard_data(self, feats, fields, vals, y, sample_weight=None):
        """Pad + shard padded-sparse instances.

        feats/fields: [N, K] int (K <= max_nnz; padded slots = any id
        with value 0); vals: [N, K] float; y: [N]. ``sample_weight``
        ([N] f32, optional — ytk-learn's instance weights) scales each
        example's loss/gradient contribution (the step normalizes by
        the weight sum, so integer weights train exactly like row
        duplication) and composes with the padding zeros."""
        y = np.asarray(y, np.float32)
        feats, fields, vals, mask = self._stage_instances(feats, fields,
                                                          vals)
        N = feats.shape[0]
        (feats, fields, vals, mask, y), per, sw = self._pad_rows(
            [feats, fields, vals, mask, y])
        sw[:N] *= self._stage_weights(sample_weight, N)
        put = lambda a: self._put_sharded(a, per)  # noqa: E731
        return (put(feats), put(fields), put(vals), put(mask), put(y),
                put(sw))

    def fit(self, feats, fields, vals, y, n_steps: int = 100, params=None,
            seed: int = 0, eval_set=None,
            early_stopping_rounds: int | None = None,
            sample_weight=None, comm=None):
        """Full-batch training; returns (params, losses).

        ``eval_set=(feats_va, fields_va, vals_va, y_va)`` evaluates the
        held-out loss after every step (history in
        ``self.eval_history_``); ``early_stopping_rounds=k`` stops after
        k non-improving steps and returns the best round's params;
        ``sample_weight`` ([N]) weights each example's loss/gradient
        (integer weights == row duplication).

        ``comm`` (an mp4j comm; every rank calls ``fit`` together)
        syncs each step's training loss across the job into
        ``self.sync_loss_history_`` — under ``MP4J_OVERLAP=1`` the
        exchange is submitted nonblocking and overlaps the next step's
        device compute (bit-identical results; see
        ``models._base.StepStatsExchanger``).
        """
        if early_stopping_rounds is not None and eval_set is None:
            raise Mp4jError("early_stopping_rounds requires an eval_set")
        sharded = self.shard_data(feats, fields, vals, y,
                                  sample_weight=sample_weight)
        # the jitted step bakes in the sparse capacity, which depends on
        # the per-shard batch size — rebuild when that changes (a stale
        # smaller capacity would silently drop gradient rows)
        per_shard_slots = int(sharded[0].shape[1]) * self.cfg.max_nnz
        if self._step is None or self._step_key != per_shard_slots:
            self._step = self._build_step(per_shard_slots)
            self._step_key = per_shard_slots
        if params is None:
            params = self.init_params(seed)
        params = self._place_params(params)
        va = None
        if eval_set is not None:
            va = self._prep_eval(*eval_set)
        stopper = EarlyStopper(early_stopping_rounds)
        self.eval_history_ = stopper.history
        exchanger = StepStatsExchanger(comm)
        losses = []
        for i in range(n_steps):
            params, loss = self._step(params, *sharded)
            # bound in-flight programs; see models/linear.py fit()
            loss = jax.block_until_ready(loss)
            # step k's host-stats exchange: blocking, or (MP4J_OVERLAP=1)
            # in flight while step k+1 runs the device
            exchanger.submit(np.array([float(loss)], np.float64))
            losses.append(loss)
            if va is not None and stopper.update(
                    self._eval_loss(params, va), i, state=params):
                if stopper.best_state is not None:
                    params = stopper.best_state
                    losses = losses[:stopper.best_round + 1]
                break
        exchanger.drain()
        hist = exchanger.mean_history()
        self.sync_loss_history_ = (hist[:, 0] if hist.size
                                   else np.zeros(0, np.float64))
        return params, np.asarray(jax.device_get(losses))

    def fit_stream(self, batches, params=None, seed: int = 0,
                   batch_rows: int | None = None,
                   max_in_flight: int = 2):
        """Chunked (out-of-core) training for data that cannot be staged
        in memory — the Criteo-1TB shape of configs[4], where
        ytk-learn consumes streamed libsvm-format text. ``batches`` is
        any iterator/generator of ``(feats, fields, vals, y)``
        minibatches (``utils.libsvm.read_libsvm`` streams them from
        disk) — or 5-tuples with per-chunk instance weights appended;
        one optimizer step runs per chunk.

        Every chunk is padded to ``batch_rows`` total rows (default:
        the first chunk's size rounded up to the shard count) with
        zero-weight rows, so ONE jitted program serves the whole
        stream — drifting chunk sizes would otherwise recompile per
        distinct size. A chunk larger than ``batch_rows`` raises.
        Feeding the full dataset as a single chunk E times is
        numerically identical to ``fit(n_steps=E)`` (tested in
        tests/test_fm.py). Returns (params, per-chunk losses).

        The pipeline is DOUBLE-BUFFERED via the shared
        :meth:`DataParallelTrainer._stream_fit` loop: step k is
        dispatched asynchronously and chunk k+1 is parsed/padded/staged
        while the device runs it; losses are fetched once at the end.
        At most ``max_in_flight`` steps stay in flight, bounding device
        memory at ~max_in_flight staged batches. ``max_in_flight=0``
        reproduces the fully serialized round-4 behavior (the A/B
        baseline in bench.py; overlap measured 1.24-1.69x per trial on
        the streaming bench, BASELINE.md round 5)."""
        if params is None:
            params = self.init_params(seed)
        state = [self._place_params(params)]

        def dispatch(staged):
            sharded, per_shard_slots = staged
            # (re)build on padded-shape change: a stale smaller
            # capacity would silently drop gradient rows
            if self._step is None or self._step_key != per_shard_slots:
                self._step = self._build_step(per_shard_slots)
                self._step_key = per_shard_slots
            state[0], loss = self._step(state[0], *sharded)
            return loss

        losses = self._stream_fit(batches, self._stage_stream_chunk,
                                  dispatch, batch_rows, max_in_flight)
        return state[0], losses

    def _stage_stream_chunk(self, chunk, batch_rows: int | None):
        """Host half of one stream step: validate, pad to ``batch_rows``
        (resolving it from the first chunk), and start the async
        device placement. Returns ((sharded..., per_shard_slots),
        batch_rows)."""
        feats, fields, vals, y = chunk[:4]
        weights = chunk[4] if len(chunk) > 4 else None
        y = np.asarray(y, np.float32)
        feats, fields, vals, mask = self._stage_instances(
            feats, fields, vals)
        if batch_rows is None:
            batch_rows = (-(-feats.shape[0] // self.n_shards)
                          * self.n_shards)
        N = feats.shape[0]
        (feats, fields, vals, mask, y), sw, per = self._pad_stream_rows(
            [feats, fields, vals, mask, y], batch_rows)
        sw[:N] *= self._stage_weights(weights, N)
        sharded = tuple(self._put_sharded(a, per)
                        for a in (feats, fields, vals, mask, y, sw))
        return (sharded, per * self.cfg.max_nnz), batch_rows

    def _stage_instances(self, feats, fields, vals):
        """The one staging path for padded-sparse instances: validate id
        ranges, pad the slot axis to max_nnz, derive the nonzero mask
        (padded slots carry value 0). Shared by shard_data, predict and
        eval so the padding convention cannot drift between them."""
        feats = np.asarray(feats, np.int32)
        fields = np.asarray(fields, np.int32)
        vals = np.asarray(vals, np.float32)
        self._check_instances(feats, fields)
        padK = self.cfg.max_nnz - feats.shape[1]
        if padK:
            zK = ((0, 0), (0, padK))
            feats, fields, vals = (np.pad(feats, zK), np.pad(fields, zK),
                                   np.pad(vals, zK))
        mask = (vals != 0).astype(np.float32)
        return feats, fields, vals, mask

    def _prep_eval(self, feats, fields, vals, y):
        """Pad + stage a held-out batch once for per-step evaluation."""
        feats, fields, vals, mask = self._stage_instances(feats, fields,
                                                          vals)
        return (jnp.asarray(feats), jnp.asarray(fields),
                jnp.asarray(vals), jnp.asarray(mask),
                jnp.asarray(np.asarray(y, np.float32)))

    def _eval_loss(self, params, va) -> float:
        if self._eval_fn is None:
            cfg = self.cfg

            @jax.jit
            def run(params, feats, fields, vals, mask, y):
                z = _score(params, feats, fields, vals, mask, cfg)
                return jnp.mean(per_example_loss(z, y, cfg.loss))

            self._eval_fn = run
        # params may span non-addressable devices on multi-process
        # meshes; a plain local jit cannot consume those directly
        return float(self._eval_fn(self._local_values(params), *va))

    def _build_sharded_predict(self):
        """Serve-side shard_map program: owner-routed row fetch from
        the SHARDED table — the full [n_rows, k] replica is never
        materialized anywhere, which is the point of sharding a
        Criteo-scale vocabulary in the first place."""
        from ytk_mp4j_tpu.ops.collectives import flat_index

        cfg = self.cfg
        axes = self.axes
        dspec = P(axes)

        @partial(jax.shard_map, mesh=self.mesh, check_vma=False,
                 in_specs=((P(), P(), dspec),) + (dspec,) * 4,
                 out_specs=dspec)
        def run(params, feats, fields, vals, mask):
            w0, w, Vs = params
            f0, fl0 = feats[0], fields[0]
            rows = _slot_rows(f0, fl0, cfg)
            E_flat, _, _ = _fetch_rows_sharded(
                Vs, rows.reshape(-1).astype(jnp.int32),
                flat_index(axes), axes)
            E = E_flat.reshape(rows.shape + (Vs.shape[1],))
            z = _score_from_slots(w0, w, E, f0, vals[0] * mask[0], cfg)
            if cfg.loss == "logistic":
                z = jax.nn.sigmoid(z)
            return z[None]

        return jax.jit(run)

    def predict(self, params, feats, fields, vals):
        feats, fields, vals, mask = self._stage_instances(feats, fields,
                                                          vals)
        if self.table_sharding == "sharded":
            params = self._stage_table(params)
            N = feats.shape[0]
            (f, fl, v, m), per, _sw = self._pad_rows(
                [feats, fields, vals, mask])
            if self._pred_fn is None:
                self._pred_fn = self._build_sharded_predict()
            staged = [self._put_sharded(a, per) for a in (f, fl, v, m)]
            # _to_host, not np.asarray: on multi-process (global)
            # meshes the output spans non-addressable devices, so the
            # fetch is a collective process_allgather — every process
            # must call predict together there
            out = self._to_host(self._pred_fn(params, *staged))
            return out.reshape(-1)[:N]
        return np.asarray(predict(params, jnp.asarray(feats),
                                  jnp.asarray(fields), jnp.asarray(vals),
                                  jnp.asarray(mask), self.cfg))


# ----------------------------------------------------------------------
# serve adapter (ISSUE 19): the pull-mode sharded entry point
# ----------------------------------------------------------------------
class FMServable:
    """Row-pull serve adapter for a trained FM / FFM model — the host
    twin of :meth:`FMTrainer._build_sharded_predict` (the AOT
    ``ffm/sharded_serve`` program): the full table is never
    materialized on the frontend; a batch pulls exactly the rows it
    touches, owner-routed by ``row_id % size`` over the columnar map
    plane, and hot rows come out of the frontend cache instead.

    A pull ROW is one feature's whole serve payload: ``[w[f]]`` +
    its embedding row(s) — ``1 + k`` floats for FM, ``1 +
    n_fields * k`` for FFM (feature f's rows against every field,
    flattened). Scoring is per example in slot order, so batched and
    sequential serve predictions are bitwise identical by
    construction.
    """

    kind = "pull"

    def __init__(self, params, cfg: FMConfig):
        w0, w, V = params
        self.cfg = cfg
        self.family = cfg.model
        self._w0 = float(jax.device_get(w0))
        self._w = np.asarray(jax.device_get(w), np.float32)
        V = np.asarray(jax.device_get(V), np.float32)
        nf = cfg.n_fields if cfg.model == "ffm" else 1
        # [n_features, nf * k]: feature f's embedding payload
        self._E = np.ascontiguousarray(
            V[:cfg.n_features * nf].reshape(cfg.n_features,
                                            nf * cfg.k))
        self.n_rows = cfg.n_features
        self.row_width = 1 + nf * cfg.k
        self.resp_width = 1

    def row_ids(self, req) -> np.ndarray:
        """Unique features an instance's ACTIVE slots touch."""
        feats, _fields, vals = req
        return np.unique(np.asarray(feats, np.int64)[
            np.asarray(vals, np.float32) != 0])

    def rows(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        return np.concatenate(
            [self._w[ids, None], self._E[ids]],
            axis=1).astype(np.float64)

    def predict_sharded(self, reqs, rowmap) -> list:
        out = []
        k = self.cfg.k
        zero = np.zeros(self.row_width, np.float32)
        for feats, fields, vals in reqs:
            feats = np.asarray(feats, np.int64)
            fields = np.asarray(fields, np.int32)
            vals = np.asarray(vals, np.float32)
            act = np.flatnonzero(vals != 0)
            rows = [rowmap.get(int(feats[a]))
                    for a in act]
            rows = [zero if r is None else r.astype(np.float32)
                    for r in rows]
            z = np.float32(self._w0)
            for r, a in zip(rows, act):
                z += r[0] * vals[a]
            if self.cfg.model == "fm":
                # 0.5 * ((sum_a v_a x_a)^2 - sum_a (v_a x_a)^2) over k
                s = np.zeros(k, np.float32)
                ss = np.zeros(k, np.float32)
                for r, a in zip(rows, act):
                    ex = r[1:] * vals[a]
                    s += ex
                    ss += ex * ex
                z += np.float32(0.5) * np.sum(s * s - ss)
            else:
                # FFM: sum_{a<b} <E[f_a, fl_b], E[f_b, fl_a]> x_a x_b
                for i in range(len(act)):
                    for j in range(i + 1, len(act)):
                        a, b = act[i], act[j]
                        ra = rows[i][1 + fields[b] * k:
                                     1 + (fields[b] + 1) * k]
                        rb = rows[j][1 + fields[a] * k:
                                     1 + (fields[a] + 1) * k]
                        z += np.dot(ra, rb) * vals[a] * vals[b]
            out.append(_serve_link(z, self.cfg.loss))
        return out


def _serve_link(z, loss: str) -> np.ndarray:
    """Overflow-safe host link on a scalar margin."""
    z = float(z)
    if loss == "logistic":
        if z >= 0:
            p = 1.0 / (1.0 + np.exp(-z))
        else:
            e = np.exp(z)
            p = e / (1.0 + e)
        return np.asarray([p], np.float64)
    return np.asarray([z], np.float64)


def servable(params, cfg: FMConfig) -> FMServable:
    """The serve plane's per-family entry point (ISSUE 19) — covers
    both ``model="fm"`` and ``model="ffm"``."""
    return FMServable(params, cfg)
