"""Shared data-parallel trainer plumbing for the model families.

Every ytk-learn-style consumer here (GBDT, linear, FM/FFM) shards its
samples over the mesh the same way: flat or hierarchical mesh axes, rows
padded up to a multiple of the shard count, padding rows neutralized by a
zero sample weight so distributed results match single-device runs for
any N (SURVEY.md section 4's differential-testing requirement).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ytk_mp4j_tpu.parallel.mesh import make_mesh


def per_example_loss(z, y, loss: str):
    """Per-example data loss shared by the linear and FM/FFM families.

    ``logistic``: softplus-form logloss on {0, 1} labels, written as
    ``max(z, 0) - z y + log1p(exp(-|z|))`` for overflow-free evaluation
    at large |z|. ``squared``: 0.5 (z - y)^2.
    """
    if loss == "logistic":
        return jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return 0.5 * (z - y) ** 2


class DataParallelTrainer:
    """Mesh bookkeeping + sample sharding shared by the trainers."""

    def __init__(self, mesh=None, n_devices=None):
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.axes = (self.mesh.axis_names[0]
                     if len(self.mesh.axis_names) == 1
                     else tuple(self.mesh.axis_names))

    @property
    def n_shards(self) -> int:
        return self.mesh.size

    def _row_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axes))

    def _pad_rows(self, arrays: list[np.ndarray]):
        """Pad dim 0 of each array to a multiple of ``n_shards``; returns
        (padded arrays, per-shard rows, sample-weight vector with zeros on
        the padding rows)."""
        N = arrays[0].shape[0]
        n = self.n_shards
        per = -(-N // n)
        pad = per * n - N
        sw = np.ones(N, np.float32)
        if pad:
            arrays = [
                np.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
                for a in arrays
            ]
            sw = np.pad(sw, (0, pad))
        return arrays, per, sw

    def _put_sharded(self, a: np.ndarray, per: int):
        """Reshape [n*per, ...] -> [n, per, ...] and place on the mesh.

        ``make_array_from_callback`` (each process materializes only its
        addressable shards) makes this work unchanged on MULTI-PROCESS
        meshes (jax.distributed), where a plain device_put cannot target
        non-addressable devices; the callback path is identical to
        device_put on single-process meshes."""
        a = a.reshape((self.n_shards, per) + a.shape[1:])
        return jax.make_array_from_callback(
            a.shape, self._row_sharding(), lambda idx: a[idx])

    @staticmethod
    def _to_host(x) -> np.ndarray:
        """Fetch a (possibly cross-process-sharded) device array to a
        host numpy array on EVERY process."""
        if x.is_fully_addressable:
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
