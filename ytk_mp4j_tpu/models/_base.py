"""Shared data-parallel trainer plumbing for the model families.

Every ytk-learn-style consumer here (GBDT, linear, FM/FFM) shards its
samples over the mesh the same way: flat or hierarchical mesh axes, rows
padded up to a multiple of the shard count, padding rows neutralized by a
zero sample weight so distributed results match single-device runs for
any N (SURVEY.md section 4's differential-testing requirement).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ytk_mp4j_tpu.parallel.mesh import make_mesh


def per_example_loss(z, y, loss: str):
    """Per-example data loss shared by the linear and FM/FFM families.

    ``logistic``: softplus-form logloss on {0, 1} labels, written as
    ``max(z, 0) - z y + log1p(exp(-|z|))`` for overflow-free evaluation
    at large |z|. ``squared``: 0.5 (z - y)^2. ``softmax``: cross
    entropy over ``z`` [N, C] with integer labels — the true-class
    logit is selected by a one-hot dot, not a per-row gather (the
    serial gather unit; same choice as the GBDT routing).
    """
    if loss == "logistic":
        return jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if loss == "softmax":
        lse = jax.nn.logsumexp(z, axis=-1)
        zy = jnp.sum(
            z * jax.nn.one_hot(y, z.shape[-1], dtype=z.dtype), axis=-1)
        return lse - zy
    return 0.5 * (z - y) ** 2


def stage_softmax_labels(y, n_classes: int) -> "np.ndarray":
    """Validate + cast integer class labels, shared by every softmax
    trainer (linear, GBDT): out-of-range ids would one-hot to silent
    garbage, so they must be an error."""
    import numpy as np

    from ytk_mp4j_tpu.exceptions import Mp4jError

    y = np.asarray(y, np.int32)
    if y.size and (y.min() < 0 or y.max() >= n_classes):
        raise Mp4jError(
            f"softmax labels must lie in [0, {n_classes}), got range "
            f"[{y.min()}, {y.max()}]")
    return y


def save_npz(path: str, cfg, arrays: dict) -> None:
    """Model-persistence writer shared by every trainer: the config
    dataclass (repr of asdict, decoded by literal_eval) plus named
    arrays. Writes through a file object so the exact user path is
    honored (np.savez(path) silently appends ".npz"); only process 0
    writes on multi-process jobs."""
    from dataclasses import asdict

    if jax.process_index() != 0:
        return
    with open(path, "wb") as f:
        np.savez(f, config=np.array(repr(asdict(cfg))), **arrays)


def load_npz(path: str, config_cls):
    """Counterpart of :func:`save_npz`: returns (config instance,
    {name: array}) with pickle disabled."""
    import ast

    with np.load(path, allow_pickle=False) as z:
        cfg = config_cls(**ast.literal_eval(str(z["config"])))
        arrays = {k: z[k] for k in z.files if k != "config"}
    return cfg, arrays


class StepStatsExchanger:
    """Per-step host-collective statistics exchange for the epoch
    loops (ISSUE 17 trainer integration).

    When a trainer is handed an mp4j ``comm`` every step's scalar
    statistics (training loss, eval metric) are summed across the
    comm's ranks so each rank's history reflects the whole job —
    ytk-learn's aggregated progress/metric reporting. Two modes,
    selected by ``MP4J_OVERLAP`` (``utils.tuning.overlap_enabled``):

    - blocking (default): ``submit``/``submit_map`` run
      ``allreduce_array``/``allreduce_map`` inline — step k's exchange
      completes before step k+1's compute dispatches (today's loops
      bit-for-bit).
    - overlap (``MP4J_OVERLAP=1``): they post ``iallreduce``/
      ``iallreduce_map`` and return immediately; the comm's
      progression thread drives the wire while the device runs the
      NEXT step, and ``drain()`` at the epoch boundary blocks on
      ``wait_all()``.

    The exchanged stats are OBSERVATIONAL (synced histories), never
    control flow — early stopping keeps reading the local metric — so
    deferring the wait is legal, and on == off is bit-exact by
    construction: identical collectives in identical submit order on
    every rank, only the wait point moves. Values a ``submit`` call
    returned are defined only after the next ``drain()``.
    """

    def __init__(self, comm, overlap: bool | None = None):
        from ytk_mp4j_tpu.utils import tuning

        self.comm = comm
        self.overlap = (tuning.overlap_enabled()
                        if overlap is None else bool(overlap))
        self._arrays: list[np.ndarray] = []
        self._maps: list[dict] = []

    @property
    def active(self) -> bool:
        return self.comm is not None and self.comm.slave_num > 1

    def submit(self, stats: np.ndarray) -> np.ndarray:
        """Sum ``stats`` (float64 [K]) over the comm's ranks, in
        place; the array's values are defined after ``drain()``."""
        stats = np.ascontiguousarray(stats, np.float64)
        if self.active:
            from ytk_mp4j_tpu.operands import Operands

            if self.overlap:
                self.comm.iallreduce(stats, Operands.DOUBLE)
            else:
                self.comm.allreduce_array(stats, Operands.DOUBLE)
        self._arrays.append(stats)
        return stats

    def submit_map(self, d: dict) -> dict:
        """Map-plane twin of :meth:`submit` (GBDT's per-round named
        metrics ride ``iallreduce_map`` so tiny rounds coalesce)."""
        if self.active:
            from ytk_mp4j_tpu.operands import Operands

            if self.overlap:
                self.comm.iallreduce_map(d, Operands.DOUBLE)
            else:
                self.comm.allreduce_map(d, Operands.DOUBLE)
        self._maps.append(d)
        return d

    def drain(self) -> None:
        """The step/epoch-boundary drain: every submitted exchange is
        complete (and its values defined) after this returns."""
        if self.active and self.overlap:
            self.comm.wait_all()

    def mean_history(self) -> np.ndarray:
        """[n_steps, K] job-wide MEAN of every array submitted so far
        (sum / rank count). Call after :meth:`drain`."""
        if not self._arrays:
            return np.zeros((0, 0), np.float64)
        n = self.comm.slave_num if self.active else 1
        return np.stack(self._arrays) / float(n)

    def mean_map_history(self) -> list[dict]:
        """Per-round job-wide mean of every map submitted so far."""
        n = float(self.comm.slave_num if self.active else 1)
        return [{k: v / n for k, v in d.items()} for d in self._maps]


class EarlyStopper:
    """The shared early-stopping state machine (GBDT/linear/FM fits).

    ``update(metric, round_idx, state)`` records one round; ``state``
    is an arbitrary rollback payload kept only for the best round and
    only when stopping is enabled (a snapshot can pin large device
    buffers). Returns True when ``rounds`` consecutive non-improving
    rounds have passed. NaN metrics never count as improvements, so a
    NaN-only history leaves ``best_round == -1`` (callers keep
    everything in that case rather than truncating to empty).
    """

    _MIN_DELTA = 1e-12

    def __init__(self, rounds: int | None):
        self.rounds = rounds
        self.best_metric = np.inf
        self.best_round = -1
        self.best_state = None
        self.history: list[float] = []

    def update(self, metric: float, round_idx: int, state=None) -> bool:
        self.history.append(metric)
        if metric < self.best_metric - self._MIN_DELTA:
            self.best_metric, self.best_round = metric, round_idx
            if self.rounds is not None:
                self.best_state = state
            return False
        return (self.rounds is not None
                and round_idx - self.best_round >= self.rounds)


class DataParallelTrainer:
    """Mesh bookkeeping + sample sharding shared by the trainers."""

    def __init__(self, mesh=None, n_devices=None):
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.axes = (self.mesh.axis_names[0]
                     if len(self.mesh.axis_names) == 1
                     else tuple(self.mesh.axis_names))

    @property
    def n_shards(self) -> int:
        return self.mesh.size

    def _row_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axes))

    def _place_replicated(self, tree):
        """Commit a parameter pytree to the mesh, replicated, BEFORE the
        first step call. A jitted step fed uncommitted host arrays
        compiles once for them and AGAIN for its own committed outputs
        on the next call — a duplicate compile of the identical program
        (measured ~8 s for the FFM sparse step at the bench shape).
        device_put is a no-op when the placement already matches."""
        sh = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda p: jax.device_put(p, sh), tree)

    def _pad_rows(self, arrays: list[np.ndarray]):
        """Pad dim 0 of each array to a multiple of ``n_shards``; returns
        (padded arrays, per-shard rows, sample-weight vector with zeros on
        the padding rows)."""
        N = arrays[0].shape[0]
        n = self.n_shards
        per = -(-N // n)
        pad = per * n - N
        sw = np.ones(N, np.float32)
        if pad:
            arrays = [
                np.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
                for a in arrays
            ]
            sw = np.pad(sw, (0, pad))
        return arrays, per, sw

    def _stream_fit(self, batches, stage_chunk, dispatch,
                    batch_rows: int | None, max_in_flight: int):
        """The shared double-buffered streaming loop (FM and linear
        fit_stream): dispatch step k asynchronously, then parse/stage
        chunk k+1 while the device runs it, with at most
        ``max_in_flight`` steps outstanding (the throttle blocks on the
        (k - max_in_flight)-th loss; 0 serializes). Losses are fetched
        once at the end — a per-chunk fetch costs one full host
        round-trip each on remote-tunnel topologies, and both
        jnp.stack-then-fetch and copy_to_host_async prefixes measured
        SLOWER than the plain device_get (BASELINE.md round 5).

        ``stage_chunk(chunk, batch_rows) -> (staged, batch_rows)``
        does the host half (validate/pad/placement; resolves
        batch_rows from the first chunk); ``dispatch(staged) -> loss``
        runs the device half, carrying trainer state in its closure.
        Returns the per-chunk loss array."""
        if batch_rows is not None:
            # the padded batch splits evenly over the mesh
            batch_rows = -(-batch_rows // self.n_shards) * self.n_shards
        pending: list = []
        staged = None
        for chunk in batches:
            if staged is not None:  # overlap: device runs step k-1
                pending.append(dispatch(staged))
                if len(pending) > max_in_flight:
                    # bounds device memory AND queued programs (jax has
                    # no "wait for queue depth" primitive)
                    jax.block_until_ready(pending[-1 - max_in_flight])
            staged, batch_rows = stage_chunk(chunk, batch_rows)
        if staged is not None:
            pending.append(dispatch(staged))
        if not pending:
            return np.zeros(0, np.float32)
        return np.asarray(jax.device_get(pending))

    def _pad_stream_rows(self, arrays, batch_rows: int):
        """Pad dim 0 of each chunk array up to ``batch_rows`` (raising
        when the chunk is larger) and build the zero-on-padding sample
        weights; returns (padded arrays, sw, per-shard rows)."""
        from ytk_mp4j_tpu.exceptions import Mp4jError

        N = arrays[0].shape[0]
        if N > batch_rows:
            raise Mp4jError(
                f"chunk of {N} rows exceeds batch_rows={batch_rows}; "
                "raise batch_rows or shrink the reader's chunk size")
        pad = batch_rows - N
        sw = np.ones(N, np.float32)
        if pad:
            arrays = [np.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
                      for a in arrays]
            sw = np.pad(sw, (0, pad))
        return arrays, sw, batch_rows // self.n_shards

    @staticmethod
    def _stage_weights(sample_weight, N: int):
        """Validate optional [N] instance weights (ytk-learn's
        per-example weighting); returns 1.0 when absent so callers can
        multiply into the padding sample-weight vector unconditionally.
        The checks mirror binning._check_weights — NaN/negative weights
        would corrupt the weighted-mean steps SILENTLY (NaN losses, or
        sign-flipped gradients), and an all-zero vector trains nothing
        while reporting loss 0. Individual zeros are fine (a zero
        weight excludes the row, like padding)."""
        if sample_weight is None:
            return np.float32(1.0)
        from ytk_mp4j_tpu.exceptions import Mp4jError

        sw = np.asarray(sample_weight, np.float32)
        if sw.shape != (N,):
            raise Mp4jError(
                f"sample_weight must be [N={N}], got {sw.shape}")
        if not np.isfinite(sw).all() or (sw < 0).any():
            raise Mp4jError(
                "sample_weight must be finite and non-negative")
        if N and not (sw > 0).any():
            raise Mp4jError(
                "sample_weight sums to zero: nothing to train on")
        return sw

    def _put_sharded(self, a: np.ndarray, per: int):
        """Reshape [n*per, ...] -> [n, per, ...] and place on the mesh.

        ``make_array_from_callback`` (each process materializes only its
        addressable shards) makes this work unchanged on MULTI-PROCESS
        meshes (jax.distributed), where a plain device_put cannot
        target non-addressable devices for ROW-SHARDED placements like
        this one (fully-REPLICATED placements of host inputs are fine —
        see ``_place_replicated``); the callback path is identical to
        device_put on single-process meshes."""
        a = a.reshape((self.n_shards, per) + a.shape[1:])
        return jax.make_array_from_callback(
            a.shape, self._row_sharding(), lambda idx: a[idx])

    def save_params(self, path: str, params) -> None:
        """Persist a flat tuple of parameter arrays + the trainer config
        as a portable .npz (the train-then-serve flow; the GBDT trainer
        has its own tree-structured save_model)."""
        # _to_host is COLLECTIVE on multi-process meshes (params may
        # span non-addressable devices): every process must reach it
        # before the process-0 write gate inside save_npz
        arrays = {f"p_{i}": self._to_host(p)
                  for i, p in enumerate(params)}
        save_npz(path, self.cfg, arrays)

    @staticmethod
    def load_params(path: str, config_cls):
        """Load (config, params tuple) saved by :meth:`save_params`;
        ``config_cls`` is the trainer's config dataclass."""
        cfg, arrays = load_npz(path, config_cls)
        return cfg, tuple(arrays[f"p_{i}"] for i in range(len(arrays)))

    @classmethod
    def _local_values(cls, tree):
        """Make every array in a pytree usable in a plain (local) jit:
        arrays spanning non-addressable devices (multi-process meshes)
        are fetched via the collective ``_to_host``; everything else
        passes through untouched. Used by the per-step eval paths."""
        return jax.tree_util.tree_map(
            lambda p: (cls._to_host(p)
                       if not getattr(p, "is_fully_addressable", True)
                       else p), tree)

    @staticmethod
    def _to_host(x) -> np.ndarray:
        """Fetch a (possibly cross-process-sharded) device array to a
        host numpy array on EVERY process. Host numpy inputs (e.g.
        params straight from :meth:`load_params`) pass through."""
        if isinstance(x, np.ndarray) or not hasattr(
                x, "is_fully_addressable"):
            return np.asarray(x)
        if x.is_fully_addressable:
            return np.asarray(x)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
