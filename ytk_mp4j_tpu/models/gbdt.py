"""TPU-native distributed GBDT — the north-star workload.

ytk-mp4j's flagship consumer is ytk-learn's distributed GBDT, whose inner
loop is a per-tree-level (node x feature x bin) gradient/hessian
HISTOGRAM ALLREDUCE across data-parallel workers (BASELINE.json:
"ytk-learn GBDT histogram allreduce — Higgs 11Mx28, 256 bins"). This
module is that consumer rebuilt TPU-first so the collectives library can
be measured end-to-end:

- samples are sharded over the mesh (pure data parallelism, the only
  parallelism the reference stack has — SURVEY.md section 2);
- each device builds local histograms with a single XLA segment-sum over
  ``node*F*B + f*B + bin`` flat ids (static shapes, no Python loops over
  samples);
- ``lax.psum`` over the mesh axis IS the histogram allreduce that the
  reference performs with Kryo-socket recursive halving;
- split finding (regularized gain over bin-cumulative G/H), node
  routing, and leaf updates are all jit-compiled; the per-level loop is
  unrolled (depth is static).

Everything runs inside ONE jitted ``shard_map`` training step per tree —
the histogram allreduce never leaves the device.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ytk_mp4j_tpu.models._base import (DataParallelTrainer, EarlyStopper,
                                       StepStatsExchanger,
                                       per_example_loss,
                                       stage_softmax_labels)
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.ops.hist_kernel import split_bf16


@dataclass(frozen=True)
class GBDTConfig:
    n_features: int = 28
    n_bins: int = 256           # byte-binned, like ytk-learn's 256-bin hists
    depth: int = 6
    # "squared": regression (g = pred - y, h = 1); "logistic": binary
    # classification on {0,1} labels with second-order (Newton) leaf
    # values, the reference consumer's Higgs objective; "softmax":
    # multiclass on integer labels — one tree per class per round
    # against the diagonal softmax gradient/hessian
    loss: str = "squared"
    n_classes: int = 2          # used by loss="softmax" only
    # stochastic boosting (ytk-learn's sample_rate / feature_sample_rate):
    # per tree, each sample is kept with prob ``subsample`` (dropped
    # samples get weight 0; kept ones are scaled 1/subsample so
    # gradient sums stay unbiased) and each feature is kept with prob
    # ``colsample`` (masked features never win a split)
    subsample: float = 1.0
    colsample: float = 1.0
    # split regularization (ytk-learn's min-gain / min-child thresholds):
    # a node whose best gain < min_split_gain stops splitting (routes all
    # samples left, equivalent to keeping the node a leaf); candidate
    # splits whose left or right hessian sum < min_child_hessian are
    # disqualified
    min_split_gain: float = 0.0
    min_child_hessian: float = 0.0
    learning_rate: float = 0.1
    reg_lambda: float = 1.0
    n_trees: int = 10
    # "pallas": fused one-hot MXU matmul in VMEM (default; ~25% over
    # "matmul", see ops/hist_kernel.py); "matmul": XLA one-hot MXU
    # matmul (~5x the scatter strategies on v5e — see the performance
    # note below; also the fallback when the pallas constraints don't
    # hold); "pair": feature-pair joint scatter histograms (exact in
    # f32, the differential oracle); "flat": one scatter per feature
    hist_mode: str = "pallas"
    # Missing-value handling (ytk-learn routes missing by a learned
    # per-split default direction): when True, bin 0 is the RESERVED
    # missing bucket across all features (QuantileBinner(...,
    # missing_bucket=True) emits this convention) and every split
    # evaluates both "missing goes left" and "missing goes right",
    # keeping the better gain; the chosen direction is stored per node
    # and replayed at predict time.
    missing_bin: bool = False
    # Categorical features (ytk-learn's one-hot split type): listed
    # feature indices split by EQUALITY — "bin == b goes right, rest
    # left" — instead of the ordered "bin <= b" rule. Bin B-1 cannot be
    # a split category (it doubles as the node-freeze sentinel); bin
    # categorical values into [0, B-2] (and into [1, B-2] under
    # missing_bin, where 0 is the missing bucket).
    categorical_features: tuple = ()

    def __post_init__(self):
        # Mp4jError for ALL input validation, matching train() and the
        # linear/FM config classes (the library-wide exception type)
        if self.hist_mode not in ("pallas", "matmul", "pair", "flat"):
            raise Mp4jError(
                f"hist_mode must be 'pallas', 'matmul', 'pair' or "
                f"'flat', got {self.hist_mode!r}")
        if self.loss not in ("squared", "logistic", "softmax"):
            raise Mp4jError(
                f"loss must be 'squared', 'logistic' or 'softmax', "
                f"got {self.loss!r}")
        if self.loss == "softmax" and self.n_classes < 2:
            raise Mp4jError(
                f"softmax needs n_classes >= 2, got {self.n_classes}")
        if not (0.0 < self.subsample <= 1.0
                and 0.0 < self.colsample <= 1.0):
            raise Mp4jError(
                f"subsample/colsample must be in (0, 1], got "
                f"{self.subsample}/{self.colsample}")
        cats = []
        for f in self.categorical_features:
            if isinstance(f, bool) or not isinstance(f, (int, np.integer)):
                raise Mp4jError(
                    f"categorical_features must be int feature indices, "
                    f"got {f!r}")
            if not 0 <= f < self.n_features:
                raise Mp4jError(
                    f"categorical_features must be indices in [0, "
                    f"{self.n_features}), got {f}")
            cats.append(int(f))
        object.__setattr__(self, "categorical_features", tuple(cats))

    def _cat_mask(self) -> np.ndarray | None:
        """Static [F] bool mask of equality-split features (None when
        there are none — keeps the all-numeric compiled graph
        unchanged)."""
        if not self.categorical_features:
            return None
        m = np.zeros(self.n_features, bool)
        m[list(self.categorical_features)] = True
        return m


# ----------------------------------------------------------------------
# histogram building (the hot op)
#
# TPU performance note (measured on v5e, N=1M x F=28 x B=256): a scatter
# (segment_sum) histogram is bound by the chip's serial scatter unit at
# ~13 ns per (sample, feature) contribution, independent of bucket
# count. Widening scatter rows ([M,2]/[M,4]/[M,8] updates) is 4x SLOWER
# (XLA emulates row scatters element-wise); pre-sorting indices does not
# help; complex64 / 64-bit packed scatters are emulated 10-20x slower;
# v5e has no SparseCore. Within the scatter family the one lever is
# element count: feature-PAIR joint (B x B) histograms halve elements
# (mode "pair", exact in f32, ~1.3x).
#
# The way OFF the serial unit is the MXU: hist[q,n,(f,b)] =
# A^T @ OH with A[i,(q,n)] = q_i * [node_i == n] (bf16, hi/lo-split for
# near-f32 accuracy) and OH[i,(f,b)] = [bins[i,f] == b] (bf16 one-hot,
# exact), tiled with lax.scan so OH never materializes beyond one tile.
# The one-hot "wastes" B x the FLOPs but rides the otherwise-idle
# systolic array: measured 51-66 ms/level vs 220-368 ms for the best
# scatter (4-6x), rel err ~5e-6. The hi/lo split MUST be computed by
# mantissa bit-masking: written as a - f32(bf16(a)), XLA's algebraic
# simplifier folds the convert pair and the low part silently becomes
# zero (measured: identical error to plain bf16).
#
# The per-level full-N scan is the measured optimum, not an oversight
# (round-2 pricing on v5e at N=1M, see BASELINE.md): active-sample
# compaction (scan only the ~N/2 left-child rows below the root) costs
# argsort 25 ms + row/vector gathers 62/46 ms per level on the serial
# unit against ~21 ms of histogram saved; leaf-wise growth needs the
# same gathers; int8 one-hot/accumulation and narrower A operands are
# within noise of bf16 because the one-hot GENERATION (a VPU compare
# per (sample, feature, bin)) — not the matmul — is the floor.
# ----------------------------------------------------------------------
_MATMUL_TILE = 1024  # contraction tile; OH tile = tile*F*B*2 bytes in VMEM


def build_histograms(bins, g, h, node_ids, n_nodes: int, cfg: GBDTConfig,
                     interpret: bool | None = None):
    """Per-(node, feature, bin) gradient/hessian sums.

    bins: [N, F] int32 (values in [0, B)); g, h: [N] f32;
    node_ids: [N] int32 — CONTRACT for every strategy: ids outside
    [0, n_nodes) contribute nothing (the one-hot strategies match no
    column; the scatter strategies rely on JAX's drop-out-of-bounds
    scatter semantics). The sibling-subtraction path in _build_tree
    passes a sentinel id for right-child samples and depends on this.
    Returns (hist_g, hist_h): [n_nodes, F, B] f32.

    Strategy "pallas" (default): the fused VMEM one-hot MXU kernel
    (ops/hist_kernel.py); falls back to "matmul" when the kernel's
    lane-alignment constraints don't hold on a compiled backend.
    ``interpret`` selects the kernel's interpret mode (None: interpret
    unless running on TPU — the CPU test suite and the driver's virtual
    CPU meshes take the interpreted path). Strategy "matmul": XLA
    one-hot MXU matmul per tile (see the performance note). Strategy
    "pair" (when F is even and the joint table fits): one scatter of
    N*F/2 elements into per-feature-PAIR joint (B x B) histograms, then
    marginalize. Strategy "flat": one scatter of N*F elements (the
    fallback, and the shape the socket baseline mirrors).
    """
    F, B = cfg.n_features, cfg.n_bins
    if cfg.hist_mode == "pallas":
        from ytk_mp4j_tpu.ops.hist_kernel import (pallas_hist_supported,
                                                  pallas_histograms)
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        # the pallas HLO interpreter is not vma-aware, so interpreting
        # inside shard_map trips check_vma; the matmul strategy is the
        # semantically identical stand-in there (CPU test meshes)
        under_shard_map = bool(getattr(jax.typeof(g), "vma", None))
        if interpret and not under_shard_map:
            return pallas_histograms(bins, g, h, node_ids, n_nodes, F, B,
                                     interpret=True)
        if not interpret and pallas_hist_supported(B, F, n_nodes):
            return pallas_histograms(bins, g, h, node_ids, n_nodes, F, B)
        return _build_histograms_matmul(bins, g, h, node_ids, n_nodes, cfg)
    if cfg.hist_mode == "matmul":
        return _build_histograms_matmul(bins, g, h, node_ids, n_nodes, cfg)
    joint_mb = n_nodes * (F // 2) * B * B * 4 * 2 / 1e6
    if cfg.hist_mode == "pair" and F % 2 == 0 and joint_mb <= 1024:
        return _build_histograms_pair(bins, g, h, node_ids, n_nodes, cfg)
    return _build_histograms_flat(bins, g, h, node_ids, n_nodes, cfg)


def _build_histograms_matmul(bins, g, h, node_ids, n_nodes, cfg):
    F, B = cfg.n_features, cfg.n_bins
    N = bins.shape[0]
    tile = min(_MATMUL_TILE, N) if N else 1   # N == 0: scan over 0 tiles
    T = -(-N // tile)
    pad = T * tile - N
    if pad:  # zero g/h rows contribute exact-zero products
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        g = jnp.pad(g, (0, pad))
        h = jnp.pad(h, (0, pad))
        node_ids = jnp.pad(node_ids, (0, pad))
    iota_b = jnp.arange(B, dtype=bins.dtype)
    iota_n = jnp.arange(n_nodes, dtype=node_ids.dtype)

    def tile_fn(acc, xs):
        bt, gt, ht, nt = xs
        oh = (bt[:, :, None] == iota_b).astype(jnp.bfloat16)
        oh = oh.reshape(tile, F * B)                  # exact 0/1
        noh = nt[:, None] == iota_n

        def amat(v):
            hi, lo = split_bf16(jnp.where(noh, v[:, None], 0.0))
            return jnp.concatenate([hi, lo], 1)       # [tile, 2*n_nodes]

        A = jnp.concatenate([amat(gt), amat(ht)], 1)  # [tile, 4*n_nodes]
        part = lax.dot_general(A, oh, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        return acc + part, None

    xs = (bins.reshape(T, tile, F), g.reshape(T, tile),
          h.reshape(T, tile), node_ids.reshape(T, tile))
    # the data dependence on g marks the carry as device-varying so the
    # scan carry types line up when this runs per-shard inside
    # shard_map; isfinite keeps the marker an exact 0 even when g[0] is
    # inf/NaN (a bare `g[0] * 0` would poison every bin)
    marker = jnp.isfinite(g[0] if N else jnp.float32(0)).astype(jnp.float32) * 0
    acc0 = jnp.zeros((4 * n_nodes, F * B), jnp.float32) + marker
    out, _ = lax.scan(tile_fn, acc0, xs)
    out = out.reshape(2, 2, n_nodes, F, B)            # [q, hi/lo, n, F, B]
    return out[0, 0] + out[0, 1], out[1, 0] + out[1, 1]


def _build_histograms_flat(bins, g, h, node_ids, n_nodes, cfg):
    F, B = cfg.n_features, cfg.n_bins
    flat_ids = (node_ids[:, None] * (F * B)
                + jnp.arange(F, dtype=jnp.int32)[None, :] * B
                + bins)                                   # [N, F]
    seg = flat_ids.reshape(-1)
    gs = jnp.broadcast_to(g[:, None], bins.shape).reshape(-1)
    hs = jnp.broadcast_to(h[:, None], bins.shape).reshape(-1)
    hist_g = jax.ops.segment_sum(gs, seg, num_segments=n_nodes * F * B)
    hist_h = jax.ops.segment_sum(hs, seg, num_segments=n_nodes * F * B)
    return (hist_g.reshape(n_nodes, F, B), hist_h.reshape(n_nodes, F, B))


def _build_histograms_pair(bins, g, h, node_ids, n_nodes, cfg):
    """Joint (feature-pair, B x B) histograms + marginalization: halves
    the scatter elements (the serial-unit bound above), exactly."""
    F, B = cfg.n_features, cfg.n_bins
    P = F // 2
    b1 = bins[:, 0::2]                                    # [N, P]
    b2 = bins[:, 1::2]
    flat = (node_ids[:, None] * (P * B * B)
            + jnp.arange(P, dtype=jnp.int32)[None, :] * (B * B)
            + b1 * B + b2).reshape(-1)
    gs = jnp.broadcast_to(g[:, None], b1.shape).reshape(-1)
    hs = jnp.broadcast_to(h[:, None], b1.shape).reshape(-1)
    HG = jax.ops.segment_sum(gs, flat, num_segments=n_nodes * P * B * B)
    HH = jax.ops.segment_sum(hs, flat, num_segments=n_nodes * P * B * B)
    HG = HG.reshape(n_nodes, P, B, B)
    HH = HH.reshape(n_nodes, P, B, B)
    # marginalize the joint table: even features sum out b2, odd sum b1
    hg = jnp.stack([HG.sum(-1), HG.sum(-2)], 2).reshape(n_nodes, F, B)
    hh = jnp.stack([HH.sum(-1), HH.sum(-2)], 2).reshape(n_nodes, F, B)
    return hg, hh


# ----------------------------------------------------------------------
# gather-free routing primitives
#
# TPU performance note (measured on v5e, N=1M): per-sample gathers run
# on the chip's serial scatter/gather unit — jnp.take_along_axis over
# [N, F] costs ~24 ms and even a 64-entry table lookup ~9 ms, while the
# equivalent one-hot select (compare + multiply + row-sum on the VPU)
# costs ~7 ms and is EXACT (one term of the sum is nonzero). The leaf
# G/H segment-sum (~12 ms on the scatter unit) becomes a hi/lo-split
# bf16 one-hot matmul on the MXU like the histograms.
# ----------------------------------------------------------------------
def _onehot_select(table, idx, n: int):
    """``table[idx]`` per sample without the serial gather unit.

    table: [n] (any dtype); idx: [N] int32 in [0, n).
    Exact: the one-hot picks a single term per row. Masked with
    ``where`` — NOT ``table * noh`` — so a non-finite table entry
    (e.g. a NaN leaf value from an empty leaf at reg_lambda=0) reaches
    only the rows that select it, exactly like the gather it replaces.
    """
    noh = idx[:, None] == jnp.arange(n, dtype=idx.dtype)
    return jnp.where(noh, table[None, :], 0).sum(1)


def _onehot_row_select(mat, col_idx):
    """``mat[i, col_idx[i]]`` per row without the serial gather unit."""
    F = mat.shape[1]
    noh = col_idx[:, None] == jnp.arange(F, dtype=col_idx.dtype)
    return jnp.where(noh, mat, 0).sum(1)


def _onehot_segment_sum2(val_a, val_b, seg_ids, n_segments: int):
    """Per-segment sums of two value vectors in ONE MXU pass (hi/lo
    bf16 split, ~2^-17 relative like the histogram path) instead of the
    serial scatter unit; the [N, n_segments] one-hot operand is
    streamed once for both."""
    noh = (seg_ids[:, None]
           == jnp.arange(n_segments, dtype=seg_ids.dtype)
           ).astype(jnp.bfloat16)
    a_hi, a_lo = split_bf16(val_a)
    b_hi, b_lo = split_bf16(val_b)
    A = jnp.stack([a_hi, a_lo, b_hi, b_lo], 1)      # [N, 4] bf16
    out = lax.dot_general(A, noh, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return out[0] + out[1], out[2] + out[3]         # [n_segments] f32 x2


def _route_samples(bins, node_ids, feat, bin_, n_nodes: int, dir_=None,
                   cat_mask=None, missing_bin: bool = False,
                   n_bins: int | None = None):
    """One level of sample routing: ``node_ids*2 + go_right`` via the
    exact one-hot selects, where ``go_right`` is ``bins[i, feat[n]] >
    bin_[n]`` for numeric features, ``== bin_[n]`` for categorical ones
    (never at the freeze sentinel B-1), and the node's learned default
    direction ``dir_`` for the missing bucket (bin 0) under
    ``missing_bin``. The all-numeric default compiles to exactly the
    round-1 graph. (A fused Pallas version was measured 2x SLOWER —
    13.3 vs 7.6 ms standalone at N=1M — a kernel block of [tile, F]
    pins F=28 on the 128-wide lane dimension at 22% occupancy, while
    XLA is free to lay the N axis across lanes and to fuse the selects
    into neighboring passes.)"""
    nf = _onehot_select(feat, node_ids, n_nodes)
    nb = _onehot_select(bin_, node_ids, n_nodes)
    v = _onehot_row_select(bins, nf)
    go_right = v > nb
    if missing_bin:
        nd = _onehot_select(dir_, node_ids, n_nodes)
        go_right = jnp.where(v == 0, nd > 0, go_right)
    if cat_mask is not None:
        # is this sample's node split on a categorical feature?
        node_cat = jnp.asarray(cat_mask)[feat]        # [n_nodes] bool
        sc = _onehot_select(node_cat.astype(jnp.int32), node_ids,
                            n_nodes) > 0
        go_right = jnp.where(sc, (v == nb) & (nb != n_bins - 1),
                             go_right)
    return node_ids * 2 + go_right.astype(jnp.int32)


def best_splits(hist_g, hist_h, reg_lambda: float, feat_mask=None,
                min_child_hessian: float = 0.0, cat_mask=None,
                missing_bin: bool = False):
    """Regularized best split per node.

    hist_*: [n_nodes, F, B]. Returns (feat [n_nodes], bin [n_nodes],
    gain [n_nodes], dir [n_nodes]) — numeric features split "bin <= b
    goes left"; features flagged in ``cat_mask`` ([F] bool, optional)
    split "bin == b goes right". ``dir`` is the learned default
    direction for the missing bucket (1 = right; all zeros unless
    ``missing_bin``): with ``missing_bin`` every numeric candidate is
    scored with bin 0's G/H on the left AND on the right, and the
    better variant wins — ytk-learn's sparsity-aware split. ``feat_mask``
    ([F] bool, optional) disqualifies masked-out features (column
    sampling): their gain is -inf so they can never win; candidates
    whose left or right hessian sum < ``min_child_hessian`` are
    likewise disqualified.
    """
    cg = jnp.cumsum(hist_g, axis=-1)        # G_left for split at bin b
    ch = jnp.cumsum(hist_h, axis=-1)
    Gt = cg[..., -1:]
    Ht = ch[..., -1:]
    lam = reg_lambda
    mch = min_child_hessian

    def score(G, H):
        return (G * G) / (H + lam)

    def variant_gain(GL, HL):
        """Gain of a (left, right) partition given the left sums.

        A 0/0 score (empty child at reg_lambda == 0) is NaN; it must be
        disqualified HERE, per variant — NaN would propagate through the
        jnp.maximum combining missing-left/right variants (killing a
        valid sibling variant) and would win jnp.argmax (freezing a node
        with good splits elsewhere). The numpy oracle's ``gain > best``
        ignores NaN the same way; an all-degenerate node still freezes
        via gain = -inf."""
        g = score(GL, HL) + score(Gt - GL, Ht - HL) - score(Gt, Ht)
        if mch > 0.0:
            ok = (HL >= mch) & (Ht - HL >= mch)
            g = jnp.where(ok, g, -jnp.inf)
        return jnp.where(jnp.isnan(g), -jnp.inf, g)

    gain = variant_gain(cg, ch)             # missing (bin 0) left
    direction = jnp.zeros(gain.shape, bool)
    if missing_bin:
        # move bin 0 (the reserved missing bucket) to the right child
        gain_r = variant_gain(cg - hist_g[..., :1], ch - hist_h[..., :1])
        # at b=0 the right-variant's left child is empty BY CONSTRUCTION
        # (bin 0 moved right leaves nothing <= 0): never a split, and at
        # reg_lambda=0 its 0/0 NaN would otherwise win argmax in EVERY
        # node and freeze the whole tree
        gain_r = gain_r.at[..., 0].set(-jnp.inf)
        direction = gain_r > gain
        gain = jnp.maximum(gain, gain_r)
    if cat_mask is not None:
        # equality split: category b alone goes right
        cat_gain = variant_gain(Gt - hist_g, Ht - hist_h)
        cat = jnp.asarray(cat_mask)[None, :, None]
        gain = jnp.where(cat, cat_gain, gain)
        direction = jnp.where(cat, False, direction)
    # splitting at the last bin sends everything left (numeric) /
    # doubles as the freeze sentinel (categorical) — never a candidate
    gain = gain.at[..., -1].set(-jnp.inf)
    if feat_mask is not None:
        gain = jnp.where(feat_mask[None, :, None], gain, -jnp.inf)
    flat = gain.reshape(gain.shape[0], -1)
    best = jnp.argmax(flat, axis=-1)
    B = hist_g.shape[-1]
    dir_flat = direction.reshape(direction.shape[0], -1)
    best_dir = jnp.take_along_axis(dir_flat, best[:, None], axis=-1)[:, 0]
    return ((best // B).astype(jnp.int32), (best % B).astype(jnp.int32),
            jnp.take_along_axis(flat, best[:, None], axis=-1)[:, 0],
            best_dir.astype(jnp.int32))


# ----------------------------------------------------------------------
# one boosting round (tree build) — per-shard body
# ----------------------------------------------------------------------
def _build_tree(bins, g, h, cfg: GBDTConfig, axis_name, interpret,
                feat_mask=None):
    """Grow one tree from per-sample gradients/hessians; the per-level
    histogram psum over ``axis_name`` is THE distributed allreduce.
    Returns (delta [N] — the learning-rate-scaled leaf value each sample
    receives — and the tree)."""
    N = bins.shape[0]
    node_ids = jnp.zeros((N,), dtype=jnp.int32)
    n_internal = 2 ** cfg.depth - 1
    tree_feat = jnp.zeros((n_internal,), dtype=jnp.int32)
    tree_bin = jnp.zeros((n_internal,), dtype=jnp.int32)
    tree_dir = jnp.zeros((n_internal,), dtype=jnp.int32)
    cat_mask = cfg._cat_mask()

    def reduced_histograms(ids, n):
        """Local histogram build + the distributed allreduce (psum)."""
        a, b = build_histograms(bins, g, h, ids, n, cfg,
                                interpret=interpret)
        if axis_name is not None:
            a = lax.psum(a, axis_name)      # THE histogram allreduce
            b = lax.psum(b, axis_name)
        return a, b

    level_start = 0
    prev_hg = prev_hh = None
    for d in range(cfg.depth):          # depth static -> unrolled
        n_nodes = 2 ** d
        if d == 0:
            hg, hh = reduced_histograms(node_ids, n_nodes)
        else:
            # histogram-subtraction trick (the classic GBDT sibling
            # identity hist(parent) = hist(left) + hist(right)): build
            # only the LEFT children — samples in right nodes map to an
            # out-of-range sentinel id and contribute nothing — then
            # derive the right siblings from the previous level's
            # (already psum'd) parent histograms. Halves both the MXU
            # work and the allreduce bytes at every level below the
            # root. Precision caveat: the derived right child inherits
            # error RELATIVE TO ITS PARENT's magnitude (~5e-6 in the
            # bf16 hist modes), so a tiny right child's histogram is
            # noisier than a directly-built one; the hessian clamp
            # below keeps that noise from producing negative hessian
            # sums (which could cross H + reg_lambda through zero in
            # best_splits and crown a garbage split).
            n_half = n_nodes // 2
            left_ids = jnp.where(node_ids % 2 == 0, node_ids // 2,
                                 n_half)
            hl_g, hl_h = reduced_histograms(left_ids, n_half)
            hg = jnp.stack([hl_g, prev_hg - hl_g],
                           axis=1).reshape(n_nodes, *hl_g.shape[1:])
            hh = jnp.stack([hl_h, jnp.maximum(prev_hh - hl_h, 0.0)],
                           axis=1).reshape(n_nodes, *hl_h.shape[1:])
        prev_hg, prev_hh = hg, hh
        feat, bin_, gain, dir_ = best_splits(
            hg, hh, cfg.reg_lambda, feat_mask, cfg.min_child_hessian,
            cat_mask, cfg.missing_bin)
        # freeze any node whose best gain does not clear the threshold:
        # bin B-1 routes every sample left (v > B-1 is never true for
        # numeric, and categorical routing never goes right at B-1),
        # keeping the node whole. The ~(gain > thr) form also freezes
        # gain == 0 (empty/pure nodes would otherwise record a phantom
        # feat-0 "split", poisoning feature_importance), gain == -inf
        # (no admissible candidate, e.g. min_child_hessian disqualified
        # everything), and NaN gains (0/0 at reg_lambda == 0).
        freeze = ~(gain > cfg.min_split_gain)
        bin_ = jnp.where(freeze, cfg.n_bins - 1, bin_)
        dir_ = jnp.where(freeze, 0, dir_)   # frozen: missing stays left
        tree_feat = lax.dynamic_update_slice(tree_feat, feat, (level_start,))
        tree_bin = lax.dynamic_update_slice(tree_bin, bin_, (level_start,))
        tree_dir = lax.dynamic_update_slice(tree_dir, dir_, (level_start,))
        # route samples: go right if bin value > split bin (gather-free,
        # see the routing performance note above)
        node_ids = _route_samples(bins, node_ids, feat, bin_, n_nodes,
                                  dir_, cat_mask, cfg.missing_bin,
                                  cfg.n_bins)
        level_start += n_nodes

    # leaf values from (all-reduced) leaf G/H
    n_leaves = 2 ** cfg.depth
    leaf_g, leaf_h = _onehot_segment_sum2(g, h, node_ids, n_leaves)
    if axis_name is not None:
        leaf_g = lax.psum(leaf_g, axis_name)
        leaf_h = lax.psum(leaf_h, axis_name)
    leaf_val = -leaf_g / (leaf_h + cfg.reg_lambda)
    delta = cfg.learning_rate * _onehot_select(leaf_val, node_ids,
                                               n_leaves)
    return delta, (tree_feat, tree_bin, tree_dir, leaf_val)


def _sampling_masks(rng_key, cfg: GBDTConfig, N: int, axis_name):
    """Per-tree stochastic-boosting masks (None when inactive).

    Returns (sample_scale [N] f32 | None, feat_mask [F] bool | None).
    The feature mask is derived from the key alone, so it is identical
    on every shard; the sample mask folds in the shard index so shards
    draw independent keeps. Kept samples are scaled 1/subsample to keep
    gradient sums unbiased; at least one feature always survives."""
    sample_scale = None
    feat_mask = None
    if rng_key is None:
        return sample_scale, feat_mask
    if cfg.colsample < 1.0:
        keep = jax.random.bernoulli(jax.random.fold_in(rng_key, 1),
                                    cfg.colsample, (cfg.n_features,))
        # all-dropped draw: rescue a UNIFORMLY RANDOM feature (a fixed
        # index would bias the ensemble toward it at small colsample)
        rescue = jax.random.randint(jax.random.fold_in(rng_key, 3), (),
                                    0, cfg.n_features)
        fallback = (jnp.arange(cfg.n_features) == rescue) & ~keep.any()
        feat_mask = keep | fallback
    if cfg.subsample < 1.0:
        k = jax.random.fold_in(rng_key, 2)
        if axis_name is not None:
            k = jax.random.fold_in(k, lax.axis_index(axis_name))
        keep = jax.random.bernoulli(k, cfg.subsample, (N,))
        sample_scale = keep.astype(jnp.float32) / cfg.subsample
    return sample_scale, feat_mask


def train_tree_shard(bins, y, preds, cfg: GBDTConfig, axis_name=None,
                     weights=None, interpret=None, rng_key=None):
    """One boosting round on this shard's samples. Returns
    (new_preds, tree).

    ``weights`` ([N] f32, default all-ones) scales each sample's
    gradient/hessian contribution — the driver uses weight 0 to neutralize
    shard-padding rows so padded and unpadded runs are bit-equivalent.
    ``rng_key`` drives per-tree stochastic boosting when
    cfg.subsample/colsample < 1 (no key -> deterministic full-data
    trees regardless of the rates).

    Scalar objectives ("squared", "logistic"): preds/y are [N]; one tree
    is grown; tree = (feats [nodes], bins [nodes], leaf values
    [2^depth]) in level-order heap layout. "softmax" (multiclass, the
    ytk-learn classification objective): preds are margins [N, C], y is
    integer class labels [N]; one tree is grown PER CLASS against the
    diagonal softmax g/h (g_c = p_c - 1[y=c], h_c = p_c (1 - p_c));
    tree = a C-tuple of per-class trees.
    """
    sample_scale, feat_mask = _sampling_masks(rng_key, cfg,
                                              bins.shape[0], axis_name)
    if sample_scale is not None:
        weights = (sample_scale if weights is None
                   else weights * sample_scale)

    if cfg.loss == "softmax":
        C = cfg.n_classes
        p = jax.nn.softmax(preds, axis=1)          # [N, C]
        trees = []
        deltas = []
        for c in range(C):                         # C static -> unrolled
            onehot_y = (y.astype(jnp.int32) == c).astype(jnp.float32)
            g = p[:, c] - onehot_y
            h = p[:, c] * (1.0 - p[:, c])
            if weights is not None:
                g = g * weights
                h = h * weights
            delta, tree = _build_tree(bins, g, h, cfg, axis_name,
                                      interpret, feat_mask)
            deltas.append(delta)
            trees.append(tree)
        return preds + jnp.stack(deltas, axis=1), tuple(trees)

    # gradient/hessian of the scalar objective at the current margin
    if cfg.loss == "logistic":
        p = jax.nn.sigmoid(preds)
        g = p - y
        h = p * (1.0 - p)
    else:  # squared error: g = pred - y, h = 1
        g = preds - y
        h = jnp.ones_like(preds)
    if weights is not None:
        g = g * weights
        h = h * weights
    delta, tree = _build_tree(bins, g, h, cfg, axis_name, interpret,
                              feat_mask)
    return preds + delta, tree


def predict_tree(bins, tree, cfg: GBDTConfig):
    """Route samples through one tree (level-order heap layout)."""
    tree_feat, tree_bin, tree_dir, leaf_val = tree
    cat_mask = cfg._cat_mask()
    N = bins.shape[0]
    node = jnp.zeros((N,), dtype=jnp.int32)   # level-local node index
    level_start = 0
    for d in range(cfg.depth):
        n_nodes = 2 ** d
        level_feat = lax.dynamic_slice(tree_feat, (level_start,),
                                       (n_nodes,))
        level_bin = lax.dynamic_slice(tree_bin, (level_start,), (n_nodes,))
        level_dir = lax.dynamic_slice(tree_dir, (level_start,), (n_nodes,))
        node = _route_samples(bins, node, level_feat, level_bin, n_nodes,
                              level_dir, cat_mask, cfg.missing_bin,
                              cfg.n_bins)
        level_start += n_nodes
    return _onehot_select(leaf_val, node, 2 ** cfg.depth)


# ----------------------------------------------------------------------
# driver: full training under shard_map over a mesh
# ----------------------------------------------------------------------
class GBDTTrainer(DataParallelTrainer):
    """Data-parallel GBDT over a mesh (1-D or hierarchical)."""

    def __init__(self, cfg: GBDTConfig, mesh=None, n_devices=None):
        super().__init__(mesh=mesh, n_devices=n_devices)
        self.cfg = cfg
        self._step = None
        self._predict = None
        self._margin_step = None
        self._stacked_trees = None
        self.eval_history_: list[float] = []
        self.binner_ = None    # fitted by train_raw; rides save_model

    def _build_step(self):
        cfg = self.cfg
        axes = self.axes
        spec = P(axes)
        # the pallas kernel compiles only on TPU meshes; interpret it on
        # the virtual CPU meshes the tests and the driver dry-run use
        interpret = self.mesh.devices.flat[0].platform != "tpu"

        sampling = cfg.subsample < 1.0 or cfg.colsample < 1.0

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(spec, spec, spec, spec, P()),
                 out_specs=(spec, P(None)))
        def step(bins, y, preds, weights, key_data):
            rng_key = (jax.random.wrap_key_data(key_data)
                       if sampling else None)
            new_preds, tree = train_tree_shard(
                bins[0], y[0], preds[0], cfg, axes, weights=weights[0],
                interpret=interpret, rng_key=rng_key)
            return new_preds[None], tree

        return jax.jit(step)

    def shard_data(self, bins: np.ndarray, y: np.ndarray,
                   sample_weight: np.ndarray | None = None):
        """Pad + reshape host data to [n_shards, N/shard, ...] and place
        on the mesh. Padding rows get sample weight 0 so they contribute
        nothing to histograms or leaves (distributed results stay
        equivalent to single-device for any N — EXCEPT under
        cfg.subsample < 1, where each shard deliberately draws an
        independent keep mask, so distributed and single-device runs
        are different but equally valid stochastic realizations).
        ``sample_weight`` ([N] f32, optional — ytk-learn's instance
        weights) scales each sample's gradient/hessian contribution and
        composes with the padding zeros."""
        self._check_bins_width(bins)
        N = bins.shape[0]
        (bins, y), per, w = self._pad_rows([bins, y])
        w[:N] *= self._stage_weights(sample_weight, N)
        if self.cfg.loss == "softmax":
            preds = np.zeros((y.shape[0], self.cfg.n_classes), np.float32)
        else:
            preds = np.zeros_like(y, np.float32)
        return (self._put_sharded(bins, per), self._put_sharded(y, per),
                self._put_sharded(preds, per),
                self._put_sharded(w, per))

    def train(self, bins: np.ndarray, y: np.ndarray,
              n_trees: int | None = None, seed: int = 0,
              sample_weight: np.ndarray | None = None,
              eval_set=None, early_stopping_rounds: int | None = None,
              comm=None):
        """Full boosting run; returns (trees, final margins [padded] —
        [N] for scalar objectives, [N, n_classes] for softmax).
        ``seed`` drives the per-tree stochastic-boosting masks when
        cfg.subsample/colsample < 1 (same seed -> same trees);
        ``sample_weight`` scales per-instance g/h contributions.

        ``eval_set=(bins_va, y_va)`` evaluates the objective's metric on
        held-out data after every round (margins updated incrementally,
        one tree per round — not a full re-predict); with
        ``early_stopping_rounds=k`` training stops after k rounds
        without improvement and the returned ensemble is truncated to
        the best round. The per-round metric history is available as
        ``self.eval_history_`` afterwards.

        ``comm`` (an mp4j comm; every rank calls ``train`` together)
        syncs each round's statistics across the job on the map plane
        (round count + eval metric when an ``eval_set`` is given) —
        the per-round job-wide means land in
        ``self.sync_round_history_``. Under ``MP4J_OVERLAP=1`` round
        k's exchange rides ``iallreduce_map`` and overlaps round
        k+1's device compute, drained at the boosting-loop boundary
        (bit-identical trees — the exchanged stats are observational;
        see ``models._base.StepStatsExchanger``).
        """
        if self._step is None:
            self._step = self._build_step()
        if self.cfg.loss == "softmax":
            y = stage_softmax_labels(y, self.cfg.n_classes)
        else:
            y = np.asarray(y, np.float32)
        dbins, dy, dpreds, dw = self.shard_data(
            np.asarray(bins, np.int32), y, sample_weight=sample_weight)

        if early_stopping_rounds is not None and eval_set is None:
            raise Mp4jError("early_stopping_rounds requires an eval_set")
        va = None
        if eval_set is not None:
            va_host = np.asarray(eval_set[0], np.int32)
            self._check_bins_width(va_host, "eval_set bins")
            va_bins = jnp.asarray(va_host)
            va_y = np.asarray(eval_set[1])
            va_margins = None
            va = (va_bins, va_y)
        stopper = EarlyStopper(early_stopping_rounds)
        self.eval_history_ = stopper.history

        base_key = jax.random.key(seed)
        exchanger = StepStatsExchanger(comm)
        trees = []
        for i in range(n_trees if n_trees is not None
                       else self.cfg.n_trees):
            kd = jax.random.key_data(jax.random.fold_in(base_key, i))
            dpreds, tree = self._step(dbins, dy, dpreds, dw, kd)
            trees.append(tree)
            metric = None
            if va is not None:
                va_margins = self._update_margins(va[0], tree, va_margins)
                metric = self._eval_metric(np.asarray(va_margins), va[1])
            # round k's job-wide stats ride the map plane: blocking, or
            # (MP4J_OVERLAP=1) in flight while round k+1 grows its tree
            stats = {"trees": np.float64(1.0)}
            if metric is not None:
                stats["metric"] = np.float64(metric)
            exchanger.submit_map(stats)
            if metric is not None:
                # state: the margin snapshot matching the kept ensemble
                if stopper.update(metric, i, state=dpreds):
                    if stopper.best_state is not None:
                        trees = trees[:stopper.best_round + 1]
                        dpreds = stopper.best_state
                    break
        exchanger.drain()
        self.sync_round_history_ = exchanger.mean_map_history()
        preds = self._to_host(dpreds)
        if self.cfg.loss == "softmax":
            return trees, preds.reshape(-1, self.cfg.n_classes)
        return trees, preds.reshape(-1)

    def train_raw(self, X, y, n_trees: int | None = None, seed: int = 0,
                  sample_weight: np.ndarray | None = None,
                  eval_set=None, early_stopping_rounds: int | None = None,
                  binner=None, comm=None,
                  bin_sample: int | None = 1_000_000):
        """The ytk-learn consumer entry point: RAW continuous features
        [N, F] -> internal quantile binning -> boosted training, in one
        call (the reference consumer bins internally; SURVEY.md
        section 1 flagship consumer + section 3b).

        A :class:`~ytk_mp4j_tpu.models.binning.QuantileBinner` with
        ``n_bins=cfg.n_bins`` and ``missing_bucket=cfg.missing_bin`` is
        fitted on X — via ``fit_distributed`` over ``comm`` when one is
        given (an mp4j comm with ``slave_num > 1``: every rank calls
        ``train_raw`` together, each sketches its OWN X and one
        allgather merges, so raw features never leave their rank) —
        then X is transformed and :meth:`train` runs. NaN feature
        values flow to the missing bucket (pair with
        ``cfg.missing_bin=True`` for learned default directions).

        The fitted binner is kept as ``self.binner_`` and persisted by
        :meth:`save_model`; ``eval_set=(X_va, y_va)`` takes RAW
        features, transformed with the same binner. Pass a pre-fitted
        ``binner`` to reuse edges (its edges are used as-is).
        ``sample_weight`` both weights the quantile sketch (a heavily
        weighted region earns finer bins, ytk-learn's weighted
        training) and scales the boosting gradients. Returns
        ``(trees, margins)`` like :meth:`train`; serve raw features
        with :meth:`predict_raw`."""
        from ytk_mp4j_tpu.models.binning import QuantileBinner

        X = np.asarray(X, np.float32)
        if binner is None:
            binner = QuantileBinner(n_bins=self.cfg.n_bins,
                                    missing_bucket=self.cfg.missing_bin)
        # a finer binner would emit bin ids >= cfg.n_bins, which the
        # histogram one-hot silently drops from every gradient sum —
        # the same silent-misrouting class _check_bins_width guards;
        # coarser is legal (load_model's rule). missing-bucket
        # conventions must agree or NaN routing silently changes.
        if binner.n_bins > self.cfg.n_bins:
            raise Mp4jError(
                f"binner.n_bins={binner.n_bins} exceeds "
                f"cfg.n_bins={self.cfg.n_bins}: out-of-range bin ids "
                "would silently vanish from the histograms (a coarser "
                "binner is fine)")
        if bool(binner.missing_bucket) != bool(self.cfg.missing_bin):
            raise Mp4jError(
                f"binner.missing_bucket={binner.missing_bucket} but "
                f"cfg.missing_bin={self.cfg.missing_bin}: the reserved "
                "bin-0 conventions must match or NaN routing silently "
                "changes")
        if binner.edges is None:
            if comm is not None and comm.slave_num > 1:
                binner.fit_distributed(X, comm, sample=bin_sample,
                                       seed=seed,
                                       sample_weight=sample_weight)
            else:
                binner.fit(X, sample=bin_sample, seed=seed,
                           sample_weight=sample_weight)
        self.binner_ = binner
        if eval_set is not None:
            eval_set = (binner.transform(eval_set[0]), eval_set[1])
        return self.train(
            binner.transform(X), y, n_trees=n_trees, seed=seed,
            sample_weight=sample_weight, eval_set=eval_set,
            early_stopping_rounds=early_stopping_rounds, comm=comm)

    def predict_raw(self, X, trees, proba: bool = False):
        """Serve RAW continuous features through the binner fitted by
        :meth:`train_raw` (or installed on ``self.binner_`` by
        :meth:`load_model`'s caller)."""
        if self.binner_ is None:
            raise Mp4jError(
                "no fitted binner on this trainer: train with "
                "train_raw, or set trainer.binner_ (load_model returns "
                "the persisted binner)")
        return self.predict(self.binner_.transform(X), trees,
                            proba=proba)

    def _check_bins_width(self, bins, what: str = "bins") -> None:
        """A bin matrix narrower/wider than cfg.n_features would make
        one-hot feature routing silently select value 0 for
        out-of-range split features (routing every sample left), so
        wrong widths must be an error, not plausible-looking margins."""
        if bins.ndim != 2 or bins.shape[1] != self.cfg.n_features:
            raise Mp4jError(
                f"{what} must be [N, n_features={self.cfg.n_features}], "
                f"got {bins.shape}")

    def _update_margins(self, bins, tree, margins):
        """Incrementally add one round's tree output to held-out
        margins (jitted once per trainer)."""
        cfg = self.cfg
        if self._margin_step is None:
            softmax = cfg.loss == "softmax"

            @jax.jit
            def add(bins, tree, margins):
                if softmax:
                    delta = jnp.stack(
                        [predict_tree(bins, t, cfg) for t in tree],
                        axis=1)
                else:
                    delta = predict_tree(bins, tree, cfg)
                return margins + cfg.learning_rate * delta

            self._margin_step = add
        if margins is None:
            shape = ((bins.shape[0], cfg.n_classes)
                     if cfg.loss == "softmax" else (bins.shape[0],))
            margins = jnp.zeros(shape, jnp.float32)
        # trees from the shard_map step may span non-addressable devices
        # on multi-process meshes; fetch them for this local jit
        return self._margin_step(bins, self._local_values(tree), margins)

    def _eval_metric(self, margins: np.ndarray, y: np.ndarray) -> float:
        """The objective's validation metric (lower is better):
        squared -> mse, logistic -> logloss, softmax -> logloss."""
        if self.cfg.loss == "squared":
            return float(np.mean((margins - y) ** 2))
        if self.cfg.loss == "logistic":
            return float(np.mean(np.asarray(
                per_example_loss(margins, y, "logistic"))))
        z = margins - margins.max(axis=1, keepdims=True)
        logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
        return float(-np.mean(logp[np.arange(len(y)), y.astype(int)]))

    def predict(self, bins: np.ndarray, trees,
                proba: bool = False) -> np.ndarray:
        """Ensemble prediction: sum of learning-rate-scaled tree outputs
        over any binned matrix (one jit; ``lax.scan`` over the stacked
        ensemble, so program size is one tree regardless of T).
        Returns raw margins ([N], or [N, n_classes] for softmax);
        ``proba=True`` applies the sigmoid (logistic) or softmax. The
        jitted runner is cached on the trainer — repeated predict()
        calls retrace only when (bins shape, tree count) changes."""
        if self._predict is None:
            cfg = self.cfg
            softmax = cfg.loss == "softmax"

            @jax.jit
            def run(bins, stacked):
                # lax.scan over the stacked ensemble: program size is
                # one tree regardless of T (the unrolled loop compiled
                # O(T) programs — a compile-time cliff at ytk-learn-
                # scale ensembles; round-3 measurement in BASELINE.md)
                def body(out, tree):
                    if softmax:
                        delta = jnp.stack(
                            [predict_tree(bins,
                                          tuple(a[c] for a in tree), cfg)
                             for c in range(cfg.n_classes)], axis=1)
                    else:
                        delta = predict_tree(bins, tree, cfg)
                    return out + cfg.learning_rate * delta, None

                shape = ((bins.shape[0], cfg.n_classes) if softmax
                         else (bins.shape[0],))
                out, _ = lax.scan(body, jnp.zeros(shape, jnp.float32),
                                  stacked)
                return out

            self._predict = run
        bins = np.asarray(bins, np.int32)
        self._check_bins_width(bins)
        out = np.asarray(self._predict(jnp.asarray(bins),
                                       self._stack_trees(trees)))
        if not proba:
            return out
        if self.cfg.loss == "softmax":
            z = out - out.max(axis=1, keepdims=True)   # overflow-free
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        # two-branch sigmoid: exp only ever sees non-positive
        # arguments, so large |margin| cannot overflow
        p = np.empty_like(out)
        pos = out >= 0
        p[pos] = 1.0 / (1.0 + np.exp(-out[pos]))
        e = np.exp(out[~pos])
        p[~pos] = e / (1.0 + e)
        return p

    def _stack_trees(self, trees):
        """Stack the per-round tree tuples into [T(, n_classes), ...]
        component arrays so predict can ``lax.scan`` over the ensemble
        (trees are fixed-shape tuples — SURVEY.md section 2 GBDT row).
        Host-side fetch doubles as the non-addressable-device hop for
        multi-process meshes. The stacked tuple is cached by tree
        identity (holding the list keeps ids stable), so repeated
        predict() on the same ensemble pays the O(T) fetch once."""
        trees = list(trees)
        cached = self._stacked_trees
        if (cached is not None and len(cached[0]) == len(trees)
                and all(a is b for a, b in zip(cached[0], trees))):
            return cached[1]
        if not trees:
            # length-0 scan: margins stay at the zero init, matching the
            # pre-scan contract for an untrained/zero-round ensemble
            C = 2 ** self.cfg.depth
            lead = ((0, self.cfg.n_classes)
                    if self.cfg.loss == "softmax" else (0,))
            return (jnp.zeros(lead + (C - 1,), jnp.int32),
                    jnp.zeros(lead + (C - 1,), jnp.int32),
                    jnp.zeros(lead + (C - 1,), jnp.int32),
                    jnp.zeros(lead + (C,), jnp.float32))
        if self.cfg.loss == "softmax":
            stacked = tuple(
                jnp.asarray(np.stack(
                    [[np.asarray(cls[j]) for cls in rnd] for rnd in trees]))
                for j in range(4))
        else:
            stacked = tuple(
                jnp.asarray(np.stack([np.asarray(t[j]) for t in trees]))
                for j in range(4))
        self._stacked_trees = (trees, stacked)
        return stacked

    def feature_importance(self, trees) -> np.ndarray:
        """Split-count feature importance over the ensemble (ytk-learn's
        model-report style): how many internal nodes split on each
        feature, normalized to sum to 1. Frozen nodes (split bin B-1
        routes everything left — no real split) are excluded."""
        counts = np.zeros(self.cfg.n_features, np.int64)
        for round_trees in trees:
            per_class = (round_trees if self.cfg.loss == "softmax"
                         else (round_trees,))
            for tf, tb, _td, _lv in per_class:
                real = np.asarray(tb) != self.cfg.n_bins - 1
                np.add.at(counts, np.asarray(tf)[real], 1)
        total = counts.sum()
        return (counts / total if total else
                np.zeros(self.cfg.n_features)).astype(np.float64)

    def save_model(self, path: str, trees, binner=None) -> None:
        """Persist the ensemble (and the fitted binner's edges — the
        one from :meth:`train_raw` by default) as a portable .npz —
        the reference consumer's train-then-serve flow."""
        from ytk_mp4j_tpu.models._base import save_npz

        if binner is None:
            binner = self.binner_
        arrays = {"n_trees": np.int64(len(trees))}
        for i, round_trees in enumerate(trees):
            per_class = (round_trees if self.cfg.loss == "softmax"
                         else (round_trees,))
            for c, (tf, tb, td, lv) in enumerate(per_class):
                arrays[f"feat_{i}_{c}"] = np.asarray(tf)
                arrays[f"bin_{i}_{c}"] = np.asarray(tb)
                arrays[f"dir_{i}_{c}"] = np.asarray(td)
                arrays[f"leaf_{i}_{c}"] = np.asarray(lv)
        if binner is not None and binner.edges is not None:
            arrays["bin_edges"] = binner.edges
            arrays["bin_missing"] = np.bool_(binner.missing_bucket)
        save_npz(path, self.cfg, arrays)

    @staticmethod
    def load_model(path: str):
        """Load a saved ensemble; returns (cfg, trees, binner|None)."""
        from ytk_mp4j_tpu.models._base import load_npz
        from ytk_mp4j_tpu.models.binning import QuantileBinner

        cfg, z = load_npz(path, GBDTConfig)
        C = cfg.n_classes if cfg.loss == "softmax" else 1

        def tree(i, c):
            tf = z[f"feat_{i}_{c}"]
            # models saved before default-direction support have no dir
            # arrays; all-left (0) IS their training-time behavior
            td = z.get(f"dir_{i}_{c}")
            if td is None:
                td = np.zeros_like(tf)
            return (tf, z[f"bin_{i}_{c}"], td, z[f"leaf_{i}_{c}"])

        if cfg.loss == "softmax":
            trees = [tuple(tree(i, c) for c in range(C))
                     for i in range(int(z["n_trees"]))]
        else:
            trees = [tree(i, 0) for i in range(int(z["n_trees"]))]
        binner = None
        if "bin_edges" in z:
            # binning granularity may differ from cfg.n_bins (a
            # coarser binner feeding a finer histogram is legal);
            # derive it from the saved edges + missing-bucket flag
            edges = z["bin_edges"]
            mb = bool(z.get("bin_missing", False))
            binner = QuantileBinner(edges.shape[1] + (2 if mb else 1),
                                    missing_bucket=mb)
            binner.edges = edges
        return cfg, trees, binner


# ----------------------------------------------------------------------
# serve adapter (ISSUE 19): the reduce-mode sharded entry point
# ----------------------------------------------------------------------
class GBDTServable:
    """Tree-shard serve adapter for a trained ensemble.

    ``kind="reduce"``: unlike the embedding families there is no row
    to pull — every example visits every tree — so the serve
    dispatcher shards the ENSEMBLE (round ``t`` lives on rank
    ``t % size``), each rank routes the batch through its own trees,
    and the per-rank partial margins meet in one fixed-shape
    ``allreduce``. The host router mirrors ``_route_samples`` /
    :func:`predict_tree` exactly (ordered splits, categorical
    equality splits, the learned missing-bucket direction, the B-1
    freeze sentinel), and margins accumulate per example in float64
    in fixed tree order — so partial sums are independent of batch
    composition and batched == sequential stays bitwise true through
    the deterministic reduce.
    """

    kind = "reduce"
    family = "gbdt"

    def __init__(self, trees, cfg: GBDTConfig):
        self.cfg = cfg
        self.n_rounds = len(trees)
        self.req_width = cfg.n_features
        self.resp_width = (cfg.n_classes if cfg.loss == "softmax"
                          else 1)
        # [T, C, ...] host component arrays (C=1 unless softmax)
        def _host(rnd):
            per_class = rnd if cfg.loss == "softmax" else (rnd,)
            return [tuple(np.asarray(jax.device_get(a))
                          for a in cls) for cls in per_class]
        self._trees = [_host(rnd) for rnd in trees]
        self._cat_mask = cfg._cat_mask()

    def _route(self, bins: np.ndarray, tree) -> np.ndarray:
        """[N] leaf values — numpy mirror of :func:`predict_tree`."""
        cfg = self.cfg
        tree_feat, tree_bin, tree_dir, leaf_val = tree
        N = bins.shape[0]
        node = np.zeros(N, np.int64)
        rows = np.arange(N)
        start = 0
        for d in range(cfg.depth):
            n_nodes = 2 ** d
            f = np.asarray(tree_feat[start:start + n_nodes])[node]
            b = np.asarray(tree_bin[start:start + n_nodes])[node]
            v = bins[rows, f]
            go_right = v > b
            if cfg.missing_bin:
                dd = np.asarray(
                    tree_dir[start:start + n_nodes])[node]
                go_right = np.where(v == 0, dd > 0, go_right)
            if self._cat_mask is not None:
                sc = self._cat_mask[f]
                go_right = np.where(
                    sc, (v == b) & (b != cfg.n_bins - 1), go_right)
            node = node * 2 + go_right.astype(np.int64)
            start += n_nodes
        return np.asarray(leaf_val)[node]

    def partial_margins(self, bins: np.ndarray, rank: int,
                        size: int) -> np.ndarray:
        """[N, resp_width] float64 margins over THIS rank's tree shard
        (rounds ``t % size == rank``); summing the partials of all
        ranks reproduces :meth:`GBDTTrainer.predict`'s raw margins up
        to the f32->f64 accumulation swap."""
        bins = np.asarray(bins, np.int64)
        out = np.zeros((bins.shape[0], self.resp_width), np.float64)
        lr = np.float64(self.cfg.learning_rate)
        for t in range(rank, self.n_rounds, size):
            for c, cls in enumerate(self._trees[t]):
                out[:, c] += lr * self._route(bins, cls).astype(
                    np.float64)
        return out

    def link(self, margins: np.ndarray) -> list:
        """Frontend head: margins [N, resp_width] -> one float64
        prediction vector per example (proba via the same two-branch
        sigmoid / max-shifted softmax as :meth:`GBDTTrainer.predict`;
        squared stays the raw margin)."""
        out = []
        for m in margins:
            if self.cfg.loss == "logistic":
                z = float(m[0])
                if z >= 0:
                    p = 1.0 / (1.0 + np.exp(-z))
                else:
                    e = np.exp(z)
                    p = e / (1.0 + e)
                out.append(np.asarray([p], np.float64))
            elif self.cfg.loss == "softmax":
                z = m - m.max()
                e = np.exp(z)
                out.append(e / e.sum())
            else:
                out.append(np.asarray([float(m[0])], np.float64))
        return out


def servable(trees, cfg: GBDTConfig) -> GBDTServable:
    """The serve plane's per-family entry point (ISSUE 19)."""
    return GBDTServable(trees, cfg)
