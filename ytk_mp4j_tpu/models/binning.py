"""Quantile feature binning — continuous features -> GBDT bin ids.

The reference's GBDT consumer (ytk-learn) bins continuous features into
<=256 quantile buckets before histogram building; this is that front
end rebuilt TPU-first. Bin edges are fit from (a sample of) the data on
the host (one pass of np.quantile per feature); the transform runs on
device as a one-hot-free comparison count — ``bin(x) = #edges <= x`` —
which is N*F*B VPU lane-ops, the same shape as one histogram level, and
avoids the serial gather unit a searchsorted would use.

Distributed fitting (``fit_distributed``): each rank sketches its own
shard — per-feature quantile edges plus finite-value counts — and the
fixed-size sketches ride ONE ``allgather_array`` on any SPMD backend
(``ProcessCommSlave`` / ``ThreadCommSlave`` / ``DistributedComm``);
every rank then merges the pooled sketches identically, so all ranks
end with the same edges without ever centralizing raw features. The
merge treats each rank's sketch ``[min, q_1/Q, ..., q_(Q-1)/Q, max]``
as a piecewise-linear CDF through per-point (value, cdf) pairs,
count-weight-averages the per-rank CDFs (left and right limits, so
tied-value jumps survive pooling), and inverts the pooled CDF at the
target quantiles — exact when one rank holds a feature's
distinct-valued data, O(1/Q) in quantile space across ranks, and
TIE-ROBUST: repeated values carry their true empirical mass through
the merge via the sketch's cdf row (round 4; tested against the
single-host fit, including 90%-mass-in-5-values, in
``tests/test_binning.py``).
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError


class FeatureSketch(NamedTuple):
    """One rank's distributed-fit contribution (see ``local_sketch``).

    values: [F, Q+1] quantile points ``[min, q_{1/Q}, ..., max]``.
    counts: [F] merge weights (full-shard non-NaN counts).
    finite: [F] 1.0 where the sketched rows hold any finite value.
    cdf:    [F, Q+1] the CDF ordinate of each value point. Equals the
            grid ``[0, 1/Q, ..., 1]`` for distinct-valued data; runs of
            TIED value points carry the shard's TRUE empirical CDF jump
            (left limit at the run start, right limit at the run end) so
            repeated values keep their mass through the merge — the
            weighted-quantile-sketch fix (VERDICT round 3 item 4).
    """

    values: np.ndarray
    counts: np.ndarray
    finite: np.ndarray
    cdf: np.ndarray


def _check_weights(sample_weight, n_rows: int) -> np.ndarray:
    """Validate instance weights for the weighted sketch paths:
    [N] finite non-negative, not identically zero."""
    sw = np.asarray(sample_weight, np.float64)
    if sw.shape != (n_rows,):
        raise Mp4jError(
            f"sample_weight must be [N={n_rows}], got {sw.shape}")
    if not np.isfinite(sw).all() or (sw < 0).any():
        raise Mp4jError(
            "sample_weight must be finite and non-negative")
    if n_rows and not (sw > 0).any():
        raise Mp4jError("sample_weight sums to zero: no weighted mass "
                        "to fit quantiles from")
    return sw


def _sorted_weighted_col(col, w):
    """One feature column -> (sorted values, cumulative weights) with
    NaN and zero-weight rows dropped. Returns (None, None) when no
    weighted data remains."""
    m = ~np.isnan(col) & (w > 0)
    v, wv = col[m], w[m]
    if v.size == 0:
        return None, None
    o = np.argsort(v, kind="stable")
    return v[o], np.cumsum(wv[o])


def _wq_inverted_cdf(v_sorted, cw, qs):
    """Weighted quantiles, inverted-CDF convention: the smallest value
    whose weighted CDF reaches q — ``np.quantile(...,
    method="inverted_cdf", weights=...)`` and the classic GBDT weighted
    quantile sketch both define quantiles this way, and it is exact
    under ties (integer weights == row duplication, property-tested)."""
    pos = np.searchsorted(cw, np.asarray(qs) * cw[-1], side="left")
    return v_sorted[np.minimum(pos, v_sorted.size - 1)]


def _cdf_limits(xp, fp, x):
    """Left and right limits of the piecewise-linear CDF through
    ``(xp, fp)`` — duplicate ``xp`` entries form vertical jumps —
    evaluated at sorted points ``x``. Outside ``[xp[0], xp[-1]]`` the
    CDF is 0 / 1 (the conventions of the pre-round-4 ``np.interp``
    evaluation, which this generalizes: with strictly increasing ``xp``
    both limits reduce to ``np.interp(x, xp, fp, left=0, right=1)``)."""
    E = xp.size
    iL = np.searchsorted(xp, x, side="left")
    iR = np.searchsorted(xp, x, side="right")
    present = iR > iL
    lo = np.clip(iR - 1, 0, E - 1)
    hi = np.clip(iR, 0, E - 1)
    x0, x1, y0, y1 = xp[lo], xp[hi], fp[lo], fp[hi]
    with np.errstate(invalid="ignore"):   # inf - inf at sentinel runs
        denom = x1 - x0
        ok = denom > 0
        t = np.where(ok, (x - x0) / np.where(ok, denom, 1.0), 0.0)
        # a segment anchored at -inf spans infinitely far left: every
        # finite x sits at its right end (inf/inf -> NaN otherwise)
        t = np.where(np.isnan(t), np.where(np.isneginf(x0), 1.0, 0.0), t)
    interp = y0 + t * (y1 - y0)
    interp = np.where(iR == 0, 0.0, np.where(iR == E, 1.0, interp))
    left = np.where(present, fp[np.clip(iL, 0, E - 1)], interp)
    right = np.where(present, fp[np.clip(iR - 1, 0, E - 1)], interp)
    return left, right


class QuantileBinner:
    """Per-feature quantile binning into ``n_bins`` buckets.

    fit: edges[f, j] = the (j+1)/Q quantile of feature f over Q-1
    internal edges, where Q = n_bins normally and Q = n_bins - 1 under
    ``missing_bucket`` (one bucket is reserved, see below).
    transform: bin = number of edges <= x — in [0, n_bins) normally,
    shifted to [1, n_bins) under ``missing_bucket``.

    ``missing_bucket=True`` RESERVES bin 0 for missing values: finite
    values bin into [1, B) over B-2 internal edges and NaN maps to
    exactly bin 0 — the convention ``GBDTConfig(missing_bin=True)``
    expects for learned-default-direction routing. (The default mode
    also sends NaN to bin 0, but shares it with the lowest quantile.)
    """

    def __init__(self, n_bins: int = 256, missing_bucket: bool = False):
        lo = 3 if missing_bucket else 2   # the bucket consumes one bin;
        if not lo <= n_bins <= 65536:     # 2 would leave zero edges
            raise Mp4jError(
                f"n_bins must be in [{lo}, 65536]"
                f"{' with missing_bucket' if missing_bucket else ''}, "
                f"got {n_bins}")
        self.n_bins = n_bins
        self.missing_bucket = missing_bucket
        # [F, B-1] f32 ([F, B-2] under missing_bucket)
        self.edges: np.ndarray | None = None

    def fit(self, X, sample: int | None = 1_000_000, seed: int = 0,
            sample_weight=None):
        """Fit per-feature quantile edges from (a row sample of) X.

        Missing values (NaN) are ignored when computing quantiles; at
        transform time they land in bin 0 (the missing bucket — every
        ``x >= edge`` comparison is False). A feature with no finite
        values at all cannot be binned and raises.

        ``sample_weight`` ([N] >= 0, optional — ytk-learn's instance
        weights): edges become WEIGHTED quantiles (inverted-CDF
        convention, matching ``np.quantile(method="inverted_cdf",
        weights=...)``; integer weights bin exactly like row
        duplication). ``None`` keeps the round-4 unweighted path
        bit-for-bit (numpy's default linear interpolation)."""
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise Mp4jError(f"X must be [N, F], got {X.shape}")
        sw = (None if sample_weight is None
              else _check_weights(sample_weight, X.shape[0]))
        if sample is not None and X.shape[0] > sample:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], sample, replace=False)
            X = X[idx]
            if sw is not None:
                sw = sw[idx]   # uniform row sample keeps weights unbiased
        # a feature must have at least one finite value (of positive
        # weight, when weighted); inf sentinels are fine (they produce
        # inf edges, which compare like any other value at transform
        # time and land inf samples in the top bins)
        evid = (np.isfinite(X) if sw is None
                else np.isfinite(X) & (sw[:, None] > 0))
        bad = ~evid.any(axis=0)
        if bad.any():
            raise Mp4jError(
                f"features {np.flatnonzero(bad).tolist()} have no "
                "finite values to fit quantile edges from"
                + ("" if sw is None else " (zero-weight rows carry no "
                   "evidence)"))
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        qs = np.arange(1, nb) / nb
        if sw is not None:
            edges = np.empty((X.shape[1], nb - 1), np.float32)
            for f in range(X.shape[1]):
                v, cw = _sorted_weighted_col(X[:, f], sw)
                edges[f] = _wq_inverted_cdf(v, cw, qs)
            # inverted_cdf picks actual data values — no inf-inf
            # interpolation, so no NaN repair is needed
            self.edges = edges
            return self
        with warnings.catch_warnings():
            # inf sentinels make nanquantile warn on inf-inf interpolation
            warnings.simplefilter("ignore", RuntimeWarning)
            edges = np.nanquantile(X, qs, axis=0).T.astype(np.float32)
        # quantiles straddling inf sentinels interpolate to NaN; an
        # edge of +inf keeps the edge vector ordered and is matched
        # only by x = +inf (x >= inf), which belongs in the top bins
        self.edges = np.where(np.isnan(edges), np.float32(np.inf), edges)
        return self

    def local_sketch(self, X_shard, sample: int | None = 1_000_000,
                     seed: int = 0, sample_weight=None) -> FeatureSketch:
        """Per-rank half of the distributed fit: a :class:`FeatureSketch`
        with this shard's quantile points ``[min, q_{1/Q}, ...,
        q_{(Q-1)/Q}, max]`` ([F, Q+1]), merge-weight counts [F] (f32 —
        exact to 2**24 rows; beyond that the merge WEIGHT is
        approximate, which is harmless), finite-value evidence [F]
        (see ``merge_sketches``), and the per-point CDF ordinates
        [F, Q+1] — the grid for distinct data, true empirical jumps at
        tied points (see :class:`FeatureSketch`). A feature with no
        data on this shard yields NaN sketch rows and count 0 — legal
        locally, resolved at merge (another rank may hold its data).

        ``sample_weight`` ([N] >= 0, optional): quantile points become
        weighted quantiles (see :meth:`fit`), merge counts become
        per-feature WEIGHT totals (the [R, F] counts stack already IS
        the merge's weight vector, so weighted shards pool correctly
        with no wire-format change), and the CDF ordinates carry the
        weighted empirical limits at every point — ties and skewed
        weights ride the merge at their true mass."""
        X = np.asarray(X_shard, np.float32)
        if X.ndim != 2:
            raise Mp4jError(f"X must be [N, F], got {X.shape}")
        sw = (None if sample_weight is None
              else _check_weights(sample_weight, X.shape[0]))
        # merge weight = the FULL shard's data count / weight total
        # (NaN = missing is excluded; inf sentinels are data, exactly
        # as in fit) — it must be taken before sampling, or a 10M-row
        # shard sampled to 1M would weigh the same as a true 1M-row
        # shard in the merge
        if sw is None:
            counts = (~np.isnan(X)).sum(axis=0).astype(np.float32)
        else:
            counts = ((~np.isnan(X)) * sw[:, None]).sum(
                axis=0).astype(np.float32)
        if sample is not None and X.shape[0] > sample:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], sample, replace=False)
            X = X[idx]
            if sw is not None:
                sw = sw[idx]
        if sw is not None:
            return self._weighted_sketch(X, sw, counts)
        # evidence comes from the rows actually sketched, mirroring
        # fit()'s sample-then-check order: if sampling dropped every
        # data row of a feature, the sketch row is all-NaN and must
        # carry no weight either, or it would feed NaN into the merge
        finite = np.isfinite(X).any(axis=0).astype(np.float32)
        counts = np.where((~np.isnan(X)).any(axis=0), counts,
                          np.float32(0.0))
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        qs = np.arange(1, nb) / nb
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            inner = np.nanquantile(X, qs, axis=0).T
            lo = np.nanmin(X, axis=0)
            hi = np.nanmax(X, axis=0)
        # same inf rule as fit(): quantiles straddling inf sentinels
        # interpolate to NaN; +inf keeps the sketch monotone (hi
        # includes the inf itself, so [.., inf, .., inf] stays ordered)
        inner = np.where(np.isnan(inner), np.inf, inner)
        sketch = np.concatenate(
            [lo[:, None], inner, hi[:, None]], axis=1).astype(np.float32)
        # CDF ordinates: grid everywhere, EXCEPT runs of tied sketch
        # values, which are widened to the shard's true empirical jump
        # — [frac < v, frac <= v] — so a value holding (say) 40% of the
        # mass carries 40% through the merge instead of the <= 1/Q the
        # grid can express. Distinct-valued data keeps the exact grid,
        # preserving the merge's single-rank exactness.
        E = sketch.shape[1]
        grid = (np.arange(E) / nb).astype(np.float32)
        cdfs = np.tile(grid, (X.shape[1], 1))
        for f in range(X.shape[1]):
            row = sketch[f]
            if np.isnan(row).any() or not (row[1:] == row[:-1]).any():
                continue
            col = X[:, f]
            col = np.sort(col[~np.isnan(col)])
            M = col.size
            j = 0
            while j < E:
                k = j
                while k + 1 < E and row[k + 1] == row[j]:
                    k += 1
                if k > j:
                    left = np.searchsorted(col, row[j], side="left") / M
                    right = np.searchsorted(col, row[j],
                                            side="right") / M
                    a = min(grid[j], left)
                    b = max(grid[k], right)
                    cdfs[f, j:k + 1] = np.linspace(a, b, k - j + 1)
                j = k + 1
            cdfs[f] = np.maximum.accumulate(np.clip(cdfs[f], 0.0, 1.0))
        # a shard whose feature is all-NaN contributes a NaN sketch row
        # with count 0 — merge_sketches skips it by the count
        return FeatureSketch(sketch, counts, finite, cdfs)

    def _weighted_sketch(self, X, sw, counts) -> FeatureSketch:
        """Weighted :meth:`local_sketch` body: per-feature weighted
        quantile points + weighted empirical CDF ordinates. For
        distinct-valued data the ordinates land exactly on the grid
        (each inverted-CDF point v_q satisfies F_left < q <= F_right),
        so the merge's single-rank inversion reproduces the weighted
        fit at every grid quantile; tied runs are widened to their true
        weighted jump, like the unweighted path."""
        F = X.shape[1]
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        E = nb + 1
        qs = np.arange(1, nb) / nb
        grid = np.arange(E) / nb
        sketch = np.full((F, E), np.nan, np.float32)
        cdfs = np.tile(grid.astype(np.float32), (F, 1))
        finite = np.zeros(F, np.float32)
        counts = counts.astype(np.float32).copy()
        for f in range(F):
            v, cw = _sorted_weighted_col(X[:, f], sw)
            if v is None:
                # sampling (or zero weights) left no data: the sketch
                # row must carry no merge weight, like the unweighted
                # sample-then-check order
                counts[f] = 0.0
                continue
            finite[f] = float(np.isfinite(v).any())
            inner = _wq_inverted_cdf(v, cw, qs)
            row = np.concatenate([[v[0]], inner,
                                  [v[-1]]]).astype(np.float32)
            sketch[f] = row
            W = cw[-1]
            cw0 = np.concatenate([[0.0], cw])
            left = cw0[np.searchsorted(v, row, side="left")] / W
            right = cw0[np.searchsorted(v, row, side="right")] / W
            out = np.empty(E)
            j = 0
            while j < E:
                k = j
                while k + 1 < E and row[k + 1] == row[j]:
                    k += 1
                if k > j:
                    a = min(grid[j], left[j])
                    b = max(grid[k], right[j])
                    out[j:k + 1] = np.linspace(a, b, k - j + 1)
                else:
                    out[j] = np.clip(grid[j], left[j], right[j])
                j = k + 1
            cdfs[f] = np.maximum.accumulate(np.clip(out, 0.0, 1.0))
        return FeatureSketch(sketch, counts, finite, cdfs)

    def merge_sketches(self, sketch_stack, counts_stack,
                       finite_stack=None, cdf_stack=None):
        """Merge per-rank sketches into fitted edges (identical on
        every caller). Each rank's sketch is a piecewise-linear CDF
        through its (value, cdf) points — the grid [0, 1/Q, ..., 1]
        when ``cdf_stack`` is omitted, the tie-aware ordinates of
        :class:`FeatureSketch` when given. The pooled CDF is the
        count-weighted average of the per-rank CDFs, evaluated (left
        AND right limits, so tied-value jumps survive pooling) at the
        union of all sketch values and inverted at the target
        quantiles. Guarantees: exact when one rank holds all of a
        feature's distinct-valued data; O(1/Q) in quantile space across
        ranks for continuous data; and — with ``cdf_stack`` — a value
        carrying mass >= 2/Q on some shard appears as a tied run whose
        TRUE mass rides the merge, so heavy ties no longer collapse to
        grid resolution (a target quantile landing strictly inside a
        pooled jump inverts to exactly that tied value, as
        ``np.nanquantile`` on the pooled data does; property-tested
        under 90%-mass-in-5-values in tests/test_binning.py). Edges
        stay monotone and inside [min, max].
        [R, F, Q+1] sketches + [R, F] counts (+ [R, F, Q+1] cdf) ->
        self fitted.

        ``finite_stack`` ([R, F], optional): per-rank does-this-feature-
        have-any-FINITE-value evidence. ``fit()`` refuses a feature with
        no finite values (all-NaN or all-±inf); when the stack is given
        (``fit_distributed`` ships it alongside the sketches) the merge
        raises under the same condition instead of silently emitting
        all-inf edges (ADVICE round 3). It is deliberately separate from
        the merge WEIGHT: an inf-only shard still carries its inf mass
        into the pooled CDF — exactly as its rows would in a single-host
        ``fit`` — it just cannot by itself testify that the feature is
        binnable."""
        sketch_stack = np.asarray(sketch_stack, np.float32)
        counts_stack = np.asarray(counts_stack, np.float32)
        R, F, E = sketch_stack.shape
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        if E != nb + 1:
            raise Mp4jError(
                f"sketch has {E} points per feature; this binner needs "
                f"{nb + 1} (n_bins mismatch?)")
        no_data = (counts_stack <= 0).all(axis=0)
        if no_data.any():
            raise Mp4jError(
                f"features {np.flatnonzero(no_data).tolist()} have no "
                "non-missing values on any rank")
        if finite_stack is not None:
            no_finite = (np.asarray(finite_stack, np.float32)
                         <= 0).all(axis=0)
            if no_finite.any():
                raise Mp4jError(
                    f"features {np.flatnonzero(no_finite).tolist()} "
                    "have no finite values on any rank (all NaN/inf); "
                    "fit() refuses these too")
        grid = np.arange(E) / nb                     # [0, 1/Q, ..., 1]
        if cdf_stack is None:
            cdf_stack = np.broadcast_to(grid, sketch_stack.shape)
        else:
            cdf_stack = np.asarray(cdf_stack)
            if cdf_stack.shape != sketch_stack.shape:
                raise Mp4jError(
                    f"cdf stack shape {cdf_stack.shape} != sketch "
                    f"shape {sketch_stack.shape}")
            # ordinates ride the wire as float32; snap grid knots back
            # to their exact float64 values so the distinct-data
            # inversion stays bit-exact against fit() (f32(0.9) =
            # 0.90000004 would otherwise shift every inversion knot)
            g32 = grid.astype(np.float32)
            cdf_stack = np.where(
                cdf_stack.astype(np.float32) == g32,
                grid, cdf_stack.astype(np.float64))
        qs = grid[1:-1]
        merged = np.empty((F, nb - 1), np.float32)
        for f in range(F):
            live = counts_stack[:, f] > 0
            w = counts_stack[live, f]
            w = w / w.sum()
            # pooled CDF limits at every distinct sketch value: the
            # count-weighted average of the per-rank CDFs' left/right
            # limits (jumps at tied points survive pooling)
            pts = np.unique(sketch_stack[live, f])
            pl = np.zeros(pts.shape)
            pr = np.zeros(pts.shape)
            for r_w, r_sk, r_cdf in zip(w, sketch_stack[live, f],
                                        cdf_stack[live, f]):
                lt, rt = _cdf_limits(r_sk, r_cdf, pts)
                pl += r_w * lt
                pr += r_w * rt
            # inversion polyline: (left, v), (right, v) per value —
            # vertical jump segments invert to exactly v
            inv_x = np.empty(2 * pts.size)
            inv_x[0::2] = pl
            inv_x[1::2] = pr
            merged[f] = np.interp(qs, inv_x, np.repeat(pts, 2))
        self.edges = np.where(np.isnan(merged), np.float32(np.inf),
                              merged)
        return self

    def fit_distributed(self, X_shard, comm,
                        sample: int | None = 1_000_000, seed: int = 0,
                        sample_weight=None):
        """SPMD distributed fit: every rank calls this with ITS OWN
        shard and an mp4j comm exposing ``rank`` / ``slave_num`` /
        ``allgather_array`` (socket, thread, and jax.distributed
        backends all do). One fixed-size allgather moves the sketches;
        raw features never leave their rank. All ranks return fitted
        with identical edges.

        Each rank's wire segment leads with a (n_bins, missing_bucket,
        F) header, validated after the allgather: a binner-config or
        feature-count mismatch across ranks would otherwise garble the
        merge silently (or shear the flat buffer into misaligned
        segments).

        ``sample_weight`` weighs THIS RANK's rows (see
        :meth:`local_sketch`); the merge pools weighted and unweighted
        shards through the same counts vector."""
        from ytk_mp4j_tpu.operands import Operands

        edges, counts, finite, cdfs = self.local_sketch(
            X_shard, sample, seed, sample_weight=sample_weight)
        F, E = edges.shape
        n, r = comm.slave_num, comm.rank
        hdr = np.asarray(
            [self.n_bins, int(self.missing_bucket), F], np.float32)
        H = len(hdr)
        seg = H + 2 * F * E + 2 * F
        # segment length is itself config-dependent (F, E); a mismatch
        # would shear the main allgather into misaligned blocks before
        # any header could be read, so sizes are exchanged first
        sizes = np.zeros(n, np.float32)
        sizes[r] = seg
        comm.allgather_array(sizes, Operands.FLOAT)
        if not (sizes == seg).all():
            raise Mp4jError(
                f"fit_distributed sketch-size mismatch across ranks: "
                f"{sizes.astype(int).tolist()} (n_bins / missing_bucket "
                f"/ feature-count differ)")
        buf = np.zeros(n * seg, np.float32)
        s = r * seg
        o0, o1 = H, H + F * E               # values
        o2 = o1 + F * E                      # cdf ordinates
        o3, o4 = o2 + F, o2 + 2 * F          # counts | finite
        buf[s: s + H] = hdr
        buf[s + o0: s + o1] = edges.ravel()
        buf[s + o1: s + o2] = cdfs.ravel()
        buf[s + o2: s + o3] = counts
        buf[s + o3: s + o4] = finite
        comm.allgather_array(buf, Operands.FLOAT)
        rows = buf.reshape(n, seg)
        for p in range(n):
            if not np.array_equal(rows[p, :H], hdr):
                raise Mp4jError(
                    f"fit_distributed config mismatch: rank {p} sent "
                    f"(n_bins, missing_bucket, F) = "
                    f"{rows[p, :H].astype(int).tolist()}, this rank has "
                    f"{hdr.astype(int).tolist()}")
        return self.merge_sketches(
            rows[:, o0:o1].reshape(n, F, E),
            rows[:, o2:o3],
            rows[:, o3:o4],
            cdf_stack=rows[:, o1:o2].reshape(n, F, E))

    def transform(self, X) -> np.ndarray:
        """Continuous [N, F] -> int32 bin ids in [0, n_bins).

        NaN inputs land in bin 0 (the missing bucket; see fit) — this
        deliberately diverges from ``np.searchsorted``, which sorts NaN
        after every edge. Under ``missing_bucket`` finite values land
        in [1, n_bins) and bin 0 is EXACTLY the NaN set."""
        if self.edges is None:
            raise Mp4jError("binner is not fitted")
        X = np.asarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.edges.shape[0]:
            raise Mp4jError(
                f"X must be [N, {self.edges.shape[0]}], got {X.shape}")
        # The compare-count broadcasts to an [rows, F, B-1] intermediate
        # before the reduction; if the backend fails to fuse it (seen on
        # CPU), a Higgs-scale transform would transiently need ~7 GB.
        # Chunk rows so the worst-case intermediate stays ~256 MB.
        fb = self.edges.shape[0] * max(1, self.edges.shape[1])
        chunk = max(1, (64 << 20) // fb)
        edges_d = jnp.asarray(self.edges)
        run = partial(_transform_device, shift=self.missing_bucket)
        if X.shape[0] <= chunk:
            return np.asarray(run(jnp.asarray(X), edges_d))
        out = np.empty(X.shape, np.int32)
        for s in range(0, X.shape[0], chunk):
            e = min(s + chunk, X.shape[0])
            out[s:e] = np.asarray(run(jnp.asarray(X[s:e]), edges_d))
        return out

    def fit_transform(self, X, **kw) -> np.ndarray:
        return self.fit(X, **kw).transform(X)


@partial(jax.jit, static_argnames=("shift",))
def _transform_device(X, edges, shift: bool = False):
    # bin = #edges <= x; comparison count instead of searchsorted keeps
    # the op off the serial gather unit (see module docstring). With
    # ``shift`` (the reserved missing bucket), finite values move up to
    # [1, B) and NaN — for which every comparison is False — stays the
    # SOLE occupant of bin 0.
    b = (X[:, :, None] >= edges[None, :, :]).sum(-1, dtype=jnp.int32)
    if shift:
        b = jnp.where(jnp.isnan(X), 0, b + 1)
    return b
