"""Quantile feature binning — continuous features -> GBDT bin ids.

The reference's GBDT consumer (ytk-learn) bins continuous features into
<=256 quantile buckets before histogram building; this is that front
end rebuilt TPU-first. Bin edges are fit from (a sample of) the data on
the host (one pass of np.quantile per feature); the transform runs on
device as a one-hot-free comparison count — ``bin(x) = #edges <= x`` —
which is N*F*B VPU lane-ops, the same shape as one histogram level, and
avoids the serial gather unit a searchsorted would use.

Distributed fitting (``fit_distributed``): each rank sketches its own
shard — per-feature quantile edges plus finite-value counts — and the
fixed-size sketches ride ONE ``allgather_array`` on any SPMD backend
(``ProcessCommSlave`` / ``ThreadCommSlave`` / ``DistributedComm``);
every rank then merges the pooled sketches identically, so all ranks
end with the same edges without ever centralizing raw features. The
merge treats each rank's sketch ``[min, q_1/Q, ..., q_(Q-1)/Q, max]``
as a piecewise-linear CDF, count-weight-averages the per-rank CDFs,
and inverts the pooled CDF at the target quantiles — exact when one
rank holds a feature's distinct-valued data, O(1/Q) in quantile space
across ranks (tested against the single-host fit in
``tests/test_binning.py``).
"""

from __future__ import annotations

import warnings
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError


class QuantileBinner:
    """Per-feature quantile binning into ``n_bins`` buckets.

    fit: edges[f, j] = the (j+1)/Q quantile of feature f over Q-1
    internal edges, where Q = n_bins normally and Q = n_bins - 1 under
    ``missing_bucket`` (one bucket is reserved, see below).
    transform: bin = number of edges <= x — in [0, n_bins) normally,
    shifted to [1, n_bins) under ``missing_bucket``.

    ``missing_bucket=True`` RESERVES bin 0 for missing values: finite
    values bin into [1, B) over B-2 internal edges and NaN maps to
    exactly bin 0 — the convention ``GBDTConfig(missing_bin=True)``
    expects for learned-default-direction routing. (The default mode
    also sends NaN to bin 0, but shares it with the lowest quantile.)
    """

    def __init__(self, n_bins: int = 256, missing_bucket: bool = False):
        lo = 3 if missing_bucket else 2   # the bucket consumes one bin;
        if not lo <= n_bins <= 65536:     # 2 would leave zero edges
            raise Mp4jError(
                f"n_bins must be in [{lo}, 65536]"
                f"{' with missing_bucket' if missing_bucket else ''}, "
                f"got {n_bins}")
        self.n_bins = n_bins
        self.missing_bucket = missing_bucket
        # [F, B-1] f32 ([F, B-2] under missing_bucket)
        self.edges: np.ndarray | None = None

    def fit(self, X, sample: int | None = 1_000_000, seed: int = 0):
        """Fit per-feature quantile edges from (a row sample of) X.

        Missing values (NaN) are ignored when computing quantiles; at
        transform time they land in bin 0 (the missing bucket — every
        ``x >= edge`` comparison is False). A feature with no finite
        values at all cannot be binned and raises.
        """
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise Mp4jError(f"X must be [N, F], got {X.shape}")
        if sample is not None and X.shape[0] > sample:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], sample, replace=False)
            X = X[idx]
        # a feature must have at least one finite value; inf sentinels
        # are fine (they produce inf edges, which compare like any other
        # value at transform time and land inf samples in the top bins)
        bad = ~np.isfinite(X).any(axis=0)
        if bad.any():
            raise Mp4jError(
                f"features {np.flatnonzero(bad).tolist()} have no "
                "finite values to fit quantile edges from")
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        qs = np.arange(1, nb) / nb
        with warnings.catch_warnings():
            # inf sentinels make nanquantile warn on inf-inf interpolation
            warnings.simplefilter("ignore", RuntimeWarning)
            edges = np.nanquantile(X, qs, axis=0).T.astype(np.float32)
        # quantiles straddling inf sentinels interpolate to NaN; an
        # edge of +inf keeps the edge vector ordered and is matched
        # only by x = +inf (x >= inf), which belongs in the top bins
        self.edges = np.where(np.isnan(edges), np.float32(np.inf), edges)
        return self

    def local_sketch(self, X_shard, sample: int | None = 1_000_000,
                     seed: int = 0):
        """Per-rank half of the distributed fit: this shard's quantile
        sketch ``[min, q_{1/Q}, ..., q_{(Q-1)/Q}, max]`` ([F, Q+1] —
        the known CDF grid [0, 1/Q, ..., 1] makes the sketch a
        piecewise-linear CDF) plus per-feature finite-value counts [F]
        (f32 — exact to 2**24 rows; beyond that the merge WEIGHT is
        approximate, which is harmless). A feature with no finite
        values on THIS shard yields NaN rows and count 0 — legal
        locally, resolved at merge (another rank may hold its data)."""
        X = np.asarray(X_shard, np.float32)
        if X.ndim != 2:
            raise Mp4jError(f"X must be [N, F], got {X.shape}")
        # merge weight = the FULL shard's data count (NaN = missing is
        # excluded; inf sentinels are data, exactly as in fit) — it must
        # be taken before sampling, or a 10M-row shard sampled to 1M
        # would weigh the same as a true 1M-row shard in the merge
        counts = (~np.isnan(X)).sum(axis=0).astype(np.float32)
        if sample is not None and X.shape[0] > sample:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], sample, replace=False)
            X = X[idx]
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        qs = np.arange(1, nb) / nb
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            inner = np.nanquantile(X, qs, axis=0).T
            lo = np.nanmin(X, axis=0)
            hi = np.nanmax(X, axis=0)
        # same inf rule as fit(): quantiles straddling inf sentinels
        # interpolate to NaN; +inf keeps the sketch monotone (hi
        # includes the inf itself, so [.., inf, .., inf] stays ordered)
        inner = np.where(np.isnan(inner), np.inf, inner)
        sketch = np.concatenate(
            [lo[:, None], inner, hi[:, None]], axis=1).astype(np.float32)
        # a shard whose feature is all-NaN contributes a NaN sketch row
        # with count 0 — merge_sketches skips it by the count
        return sketch, counts

    def merge_sketches(self, sketch_stack, counts_stack):
        """Merge per-rank sketches into fitted edges (identical on
        every caller). Each rank's sketch is a piecewise-linear CDF
        (grid [0, 1/Q, ..., 1] over its Q+1 points); the pooled CDF is
        their count-weighted average, evaluated at the union of all
        sketch points and inverted at the target quantiles. Exact when
        one rank holds all of a feature's DISTINCT-VALUED data;
        O(1/Q)-in-quantile-space across ranks. Heavily tied data
        collapses sketch points into CDF jumps whose inversion can
        differ from nanquantile's order-statistic interpolation — like
        any quantile-of-quantiles sketch — but edges stay monotone and
        inside [min, max] (tested in tests/test_binning.py).
        [R, F, Q+1] sketches + [R, F] counts -> self fitted."""
        sketch_stack = np.asarray(sketch_stack, np.float32)
        counts_stack = np.asarray(counts_stack, np.float32)
        R, F, E = sketch_stack.shape
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        if E != nb + 1:
            raise Mp4jError(
                f"sketch has {E} points per feature; this binner needs "
                f"{nb + 1} (n_bins mismatch?)")
        no_data = (counts_stack <= 0).all(axis=0)
        if no_data.any():
            raise Mp4jError(
                f"features {np.flatnonzero(no_data).tolist()} have no "
                "non-missing values on any rank")
        grid = np.arange(E) / nb                     # [0, 1/Q, ..., 1]
        qs = grid[1:-1]
        merged = np.empty((F, nb - 1), np.float32)
        for f in range(F):
            live = counts_stack[:, f] > 0
            w = counts_stack[live, f]
            w = w / w.sum()
            pts = np.sort(sketch_stack[live, f].ravel())
            # pooled CDF at every sketch point: count-weighted average
            # of the per-rank piecewise-linear CDFs (0 left, 1 right)
            cdf = np.zeros(pts.shape)
            for r_w, r_sk in zip(w, sketch_stack[live, f]):
                cdf += r_w * np.interp(pts, r_sk, grid, left=0.0,
                                       right=1.0)
            merged[f] = np.interp(qs, cdf, pts)
        self.edges = np.where(np.isnan(merged), np.float32(np.inf),
                              merged)
        return self

    def fit_distributed(self, X_shard, comm,
                        sample: int | None = 1_000_000, seed: int = 0):
        """SPMD distributed fit: every rank calls this with ITS OWN
        shard and an mp4j comm exposing ``rank`` / ``slave_num`` /
        ``allgather_array`` (socket, thread, and jax.distributed
        backends all do). One fixed-size allgather moves the sketches;
        raw features never leave their rank. All ranks return fitted
        with identical edges.

        Each rank's wire segment leads with a (n_bins, missing_bucket,
        F) header, validated after the allgather: a binner-config or
        feature-count mismatch across ranks would otherwise garble the
        merge silently (or shear the flat buffer into misaligned
        segments)."""
        from ytk_mp4j_tpu.operands import Operands

        edges, counts = self.local_sketch(X_shard, sample, seed)
        F, E = edges.shape
        n, r = comm.slave_num, comm.rank
        hdr = np.asarray(
            [self.n_bins, int(self.missing_bucket), F], np.float32)
        H = len(hdr)
        seg = H + F * E + F
        # segment length is itself config-dependent (F, E); a mismatch
        # would shear the main allgather into misaligned blocks before
        # any header could be read, so sizes are exchanged first
        sizes = np.zeros(n, np.float32)
        sizes[r] = seg
        comm.allgather_array(sizes, Operands.FLOAT)
        if not (sizes == seg).all():
            raise Mp4jError(
                f"fit_distributed sketch-size mismatch across ranks: "
                f"{sizes.astype(int).tolist()} (n_bins / missing_bucket "
                f"/ feature-count differ)")
        buf = np.zeros(n * seg, np.float32)
        s = r * seg
        buf[s: s + H] = hdr
        buf[s + H: s + H + F * E] = edges.ravel()
        buf[s + H + F * E: s + seg] = counts
        comm.allgather_array(buf, Operands.FLOAT)
        rows = buf.reshape(n, seg)
        for p in range(n):
            if not np.array_equal(rows[p, :H], hdr):
                raise Mp4jError(
                    f"fit_distributed config mismatch: rank {p} sent "
                    f"(n_bins, missing_bucket, F) = "
                    f"{rows[p, :H].astype(int).tolist()}, this rank has "
                    f"{hdr.astype(int).tolist()}")
        return self.merge_sketches(
            rows[:, H: H + F * E].reshape(n, F, E),
            rows[:, H + F * E:])

    def transform(self, X) -> np.ndarray:
        """Continuous [N, F] -> int32 bin ids in [0, n_bins).

        NaN inputs land in bin 0 (the missing bucket; see fit) — this
        deliberately diverges from ``np.searchsorted``, which sorts NaN
        after every edge. Under ``missing_bucket`` finite values land
        in [1, n_bins) and bin 0 is EXACTLY the NaN set."""
        if self.edges is None:
            raise Mp4jError("binner is not fitted")
        X = np.asarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.edges.shape[0]:
            raise Mp4jError(
                f"X must be [N, {self.edges.shape[0]}], got {X.shape}")
        # The compare-count broadcasts to an [rows, F, B-1] intermediate
        # before the reduction; if the backend fails to fuse it (seen on
        # CPU), a Higgs-scale transform would transiently need ~7 GB.
        # Chunk rows so the worst-case intermediate stays ~256 MB.
        fb = self.edges.shape[0] * max(1, self.edges.shape[1])
        chunk = max(1, (64 << 20) // fb)
        edges_d = jnp.asarray(self.edges)
        run = partial(_transform_device, shift=self.missing_bucket)
        if X.shape[0] <= chunk:
            return np.asarray(run(jnp.asarray(X), edges_d))
        out = np.empty(X.shape, np.int32)
        for s in range(0, X.shape[0], chunk):
            e = min(s + chunk, X.shape[0])
            out[s:e] = np.asarray(run(jnp.asarray(X[s:e]), edges_d))
        return out

    def fit_transform(self, X, **kw) -> np.ndarray:
        return self.fit(X, **kw).transform(X)


@partial(jax.jit, static_argnames=("shift",))
def _transform_device(X, edges, shift: bool = False):
    # bin = #edges <= x; comparison count instead of searchsorted keeps
    # the op off the serial gather unit (see module docstring). With
    # ``shift`` (the reserved missing bucket), finite values move up to
    # [1, B) and NaN — for which every comparison is False — stays the
    # SOLE occupant of bin 0.
    b = (X[:, :, None] >= edges[None, :, :]).sum(-1, dtype=jnp.int32)
    if shift:
        b = jnp.where(jnp.isnan(X), 0, b + 1)
    return b
