"""Quantile feature binning — continuous features -> GBDT bin ids.

The reference's GBDT consumer (ytk-learn) bins continuous features into
<=256 quantile buckets before histogram building; this is that front
end rebuilt TPU-first. Bin edges are fit from (a sample of) the data on
the host (one pass of np.quantile per feature); the transform runs on
device as a one-hot-free comparison count — ``bin(x) = #edges <= x`` —
which is N*F*B VPU lane-ops, the same shape as one histogram level, and
avoids the serial gather unit a searchsorted would use.

Distributed fitting: each rank can fit edges on its shard and
``allreduce`` the per-feature quantile sketches by simple averaging
(quantile-of-quantiles approximation), or fit on rank 0 and broadcast —
`QuantileBinner.fit` takes the whole matrix and is cheap enough for the
ytk-learn-scale datasets (one numpy quantile pass).
"""

from __future__ import annotations

import warnings
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ytk_mp4j_tpu.exceptions import Mp4jError


class QuantileBinner:
    """Per-feature quantile binning into ``n_bins`` buckets.

    fit: edges[f, j] = the (j+1)/Q quantile of feature f over Q-1
    internal edges, where Q = n_bins normally and Q = n_bins - 1 under
    ``missing_bucket`` (one bucket is reserved, see below).
    transform: bin = number of edges <= x — in [0, n_bins) normally,
    shifted to [1, n_bins) under ``missing_bucket``.

    ``missing_bucket=True`` RESERVES bin 0 for missing values: finite
    values bin into [1, B) over B-2 internal edges and NaN maps to
    exactly bin 0 — the convention ``GBDTConfig(missing_bin=True)``
    expects for learned-default-direction routing. (The default mode
    also sends NaN to bin 0, but shares it with the lowest quantile.)
    """

    def __init__(self, n_bins: int = 256, missing_bucket: bool = False):
        lo = 3 if missing_bucket else 2   # the bucket consumes one bin;
        if not lo <= n_bins <= 65536:     # 2 would leave zero edges
            raise Mp4jError(
                f"n_bins must be in [{lo}, 65536]"
                f"{' with missing_bucket' if missing_bucket else ''}, "
                f"got {n_bins}")
        self.n_bins = n_bins
        self.missing_bucket = missing_bucket
        # [F, B-1] f32 ([F, B-2] under missing_bucket)
        self.edges: np.ndarray | None = None

    def fit(self, X, sample: int | None = 1_000_000, seed: int = 0):
        """Fit per-feature quantile edges from (a row sample of) X.

        Missing values (NaN) are ignored when computing quantiles; at
        transform time they land in bin 0 (the missing bucket — every
        ``x >= edge`` comparison is False). A feature with no finite
        values at all cannot be binned and raises.
        """
        X = np.asarray(X, np.float32)
        if X.ndim != 2:
            raise Mp4jError(f"X must be [N, F], got {X.shape}")
        if sample is not None and X.shape[0] > sample:
            idx = np.random.default_rng(seed).choice(
                X.shape[0], sample, replace=False)
            X = X[idx]
        # a feature must have at least one finite value; inf sentinels
        # are fine (they produce inf edges, which compare like any other
        # value at transform time and land inf samples in the top bins)
        bad = ~np.isfinite(X).any(axis=0)
        if bad.any():
            raise Mp4jError(
                f"features {np.flatnonzero(bad).tolist()} have no "
                "finite values to fit quantile edges from")
        nb = self.n_bins - 1 if self.missing_bucket else self.n_bins
        qs = np.arange(1, nb) / nb
        with warnings.catch_warnings():
            # inf sentinels make nanquantile warn on inf-inf interpolation
            warnings.simplefilter("ignore", RuntimeWarning)
            edges = np.nanquantile(X, qs, axis=0).T.astype(np.float32)
        # quantiles straddling inf sentinels interpolate to NaN; an
        # edge of +inf keeps the edge vector ordered and is matched
        # only by x = +inf (x >= inf), which belongs in the top bins
        self.edges = np.where(np.isnan(edges), np.float32(np.inf), edges)
        return self

    def transform(self, X) -> np.ndarray:
        """Continuous [N, F] -> int32 bin ids in [0, n_bins).

        NaN inputs land in bin 0 (the missing bucket; see fit) — this
        deliberately diverges from ``np.searchsorted``, which sorts NaN
        after every edge. Under ``missing_bucket`` finite values land
        in [1, n_bins) and bin 0 is EXACTLY the NaN set."""
        if self.edges is None:
            raise Mp4jError("binner is not fitted")
        X = np.asarray(X, np.float32)
        if X.ndim != 2 or X.shape[1] != self.edges.shape[0]:
            raise Mp4jError(
                f"X must be [N, {self.edges.shape[0]}], got {X.shape}")
        # The compare-count broadcasts to an [rows, F, B-1] intermediate
        # before the reduction; if the backend fails to fuse it (seen on
        # CPU), a Higgs-scale transform would transiently need ~7 GB.
        # Chunk rows so the worst-case intermediate stays ~256 MB.
        fb = self.edges.shape[0] * max(1, self.edges.shape[1])
        chunk = max(1, (64 << 20) // fb)
        edges_d = jnp.asarray(self.edges)
        run = partial(_transform_device, shift=self.missing_bucket)
        if X.shape[0] <= chunk:
            return np.asarray(run(jnp.asarray(X), edges_d))
        out = np.empty(X.shape, np.int32)
        for s in range(0, X.shape[0], chunk):
            e = min(s + chunk, X.shape[0])
            out[s:e] = np.asarray(run(jnp.asarray(X[s:e]), edges_d))
        return out

    def fit_transform(self, X, **kw) -> np.ndarray:
        return self.fit(X, **kw).transform(X)


@partial(jax.jit, static_argnames=("shift",))
def _transform_device(X, edges, shift: bool = False):
    # bin = #edges <= x; comparison count instead of searchsorted keeps
    # the op off the serial gather unit (see module docstring). With
    # ``shift`` (the reserved missing bucket), finite values move up to
    # [1, B) and NaN — for which every comparison is False — stays the
    # SOLE occupant of bin 0.
    b = (X[:, :, None] >= edges[None, :, :]).sum(-1, dtype=jnp.int32)
    if shift:
        b = jnp.where(jnp.isnan(X), 0, b + 1)
    return b
