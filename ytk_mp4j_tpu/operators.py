"""Reduction operator system.

The reference exposes per-element-type operator constants
``Operators.Double.SUM`` etc. plus user-defined operator interfaces
(``IDoubleOperator`` ...) — SURVEY.md section 2, confirmed op set
``{SUM, MAX, MIN, PROD}`` from BASELINE.json. Operators must be
commutative + associative binary reductions.

TPU-first redesign: an :class:`Operator` is dtype-generic (element type
lives on the :class:`~ytk_mp4j_tpu.operands.Operand`, not the operator).
Each operator carries

- ``np_fn``   — a numpy ufunc-style binary used by the CPU socket path's
  merge hot loop (the native C++ kernel covers the builtin four; numpy is
  the fallback and the path for user-defined operators),
- ``jnp_fn``  — a jax binary used when the TPU path must tree-reduce a
  gathered axis (PROD and user-defined ops have no native ICI collective),
- ``lax_collective`` — name of the bandwidth-optimal XLA primitive when
  one exists (``psum`` / ``pmax`` / ``pmin``), else ``None``,
- ``identity(dtype)`` — the identity element, needed for padding so that
  padded lanes never perturb results.

User-defined operators: ``Operator.custom(name, fn, identity)`` with a
single polymorphic binary ``fn`` working on both numpy and jax arrays
(jnp and np share the ufunc surface, so one callable usually serves both).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError

# native_code ids must match csrc/mp4j_native.cpp OpCode.
_NATIVE_SUM, _NATIVE_PROD, _NATIVE_MAX, _NATIVE_MIN = 0, 1, 2, 3


@dataclass(frozen=True)
class Operator:
    name: str
    np_fn: Callable[[Any, Any], Any]
    jnp_fn: Callable[[Any, Any], Any]
    lax_collective: str | None
    _identity: Callable[[np.dtype], Any]
    native_code: int | None = None

    def identity(self, dtype) -> Any:
        """Identity element as a 0-d numpy scalar of ``dtype``."""
        return np.asarray(self._identity(np.dtype(dtype)), dtype=dtype)[()]

    def __call__(self, a, b):
        return self.np_fn(a, b)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Operator({self.name})"

    @staticmethod
    def custom(
        name: str,
        fn: Callable[[Any, Any], Any],
        identity: Any,
        jnp_fn: Callable[[Any, Any], Any] | None = None,
    ) -> "Operator":
        """A user-defined commutative/associative reduction.

        ``fn`` must accept two arrays (numpy in the socket path, traced jax
        arrays in the TPU path unless a separate ``jnp_fn`` is given) and
        return their element-wise reduction. ``identity`` is the value such
        that ``fn(identity, x) == x``; it is used for static-shape padding.
        """
        return Operator(
            name=name,
            np_fn=fn,
            jnp_fn=jnp_fn if jnp_fn is not None else fn,
            lax_collective=None,
            _identity=lambda dt, _i=identity: _i,
            native_code=None,
        )


def _sum_identity(dt: np.dtype):
    return 0


def _prod_identity(dt: np.dtype):
    return 1


def _max_identity(dt: np.dtype):
    if dt.kind == "f":
        return -np.inf
    if dt.kind == "V":
        # ml_dtypes low-precision floats. Use the dtype's representable
        # minimum, not -inf: fp8 variants (e4m3fn) have no inf, where
        # casting -inf would poison the identity with NaN
        import ml_dtypes

        return ml_dtypes.finfo(dt).min
    return np.iinfo(dt).min


def _min_identity(dt: np.dtype):
    if dt.kind == "f":
        return np.inf
    if dt.kind == "V":
        import ml_dtypes

        return ml_dtypes.finfo(dt).max
    return np.iinfo(dt).max


def _make_builtins():
    import jax.numpy as jnp  # deferred so numpy-only users avoid jax import

    sum_ = Operator("SUM", np.add, jnp.add, "psum", _sum_identity, _NATIVE_SUM)
    prod = Operator(
        "PROD", np.multiply, jnp.multiply, None, _prod_identity, _NATIVE_PROD
    )
    max_ = Operator("MAX", np.maximum, jnp.maximum, "pmax", _max_identity, _NATIVE_MAX)
    min_ = Operator("MIN", np.minimum, jnp.minimum, "pmin", _min_identity, _NATIVE_MIN)
    return sum_, prod, max_, min_


class Operators:
    """Namespace of builtin operators, mirroring the reference's
    ``Operators`` constants container (SURVEY.md section 2 [U])."""

    SUM: Operator
    PROD: Operator
    MAX: Operator
    MIN: Operator

    _ALL: dict[str, Operator] = {}

    @classmethod
    def by_name(cls, name: str) -> Operator:
        try:
            return cls._ALL[name.upper()]
        except KeyError:
            raise Mp4jError(f"unknown operator {name!r}") from None


Operators.SUM, Operators.PROD, Operators.MAX, Operators.MIN = _make_builtins()
Operators._ALL = {
    "SUM": Operators.SUM,
    "PROD": Operators.PROD,
    "MAX": Operators.MAX,
    "MIN": Operators.MIN,
}
