"""mp4j-lint — a collective-protocol static analyzer for the comm stack.

Mismatched collective schedules across ranks (a rank-dependent branch
that skips an allreduce, a dtype disagreement between the two ends of a
halving step, a blocking socket with no timeout) produce silent
deadlocks that no single-process unit test catches. This package is an
AST-based rule engine over the repo's own idioms — the Python analogue
of the protocol checkers production MPI/NCCL stacks ship.

Pieces:

- :mod:`~ytk_mp4j_tpu.analysis.engine` — visitor framework and the
  two-pass driver (per-file rules + whole-program
  :class:`~ytk_mp4j_tpu.analysis.engine.ProgramRule` instances);
- :mod:`~ytk_mp4j_tpu.analysis.callgraph` — package index +
  conservative call graph (ISSUE 14);
- :mod:`~ytk_mp4j_tpu.analysis.locks` — lock discovery, held-set
  propagation and the job-wide lock-order graph the R19-R21
  concurrency rules (and ``mp4j-lint graph``) ride;
- :mod:`~ytk_mp4j_tpu.analysis.rules` — one module per rule (R1..R21);
- :mod:`~ytk_mp4j_tpu.analysis.report` — findings with file:line and
  severity;
- :mod:`~ytk_mp4j_tpu.analysis.baseline` — the committed suppression
  file ``baseline.toml`` (stale entries are ``B001`` findings in the
  tier-1 gate's ``--strict`` mode);
- :mod:`~ytk_mp4j_tpu.analysis.cli` — the ``mp4j-lint`` entry point
  (also ``python -m ytk_mp4j_tpu.analysis``).
"""

from ytk_mp4j_tpu.analysis.engine import Engine, LintResult
from ytk_mp4j_tpu.analysis.report import Finding, Severity

__all__ = ["Engine", "LintResult", "Finding", "Severity", "lint_paths"]


def lint_paths(paths, baseline_path=None):
    """Lint ``paths`` with all rules and the committed baseline (or
    ``baseline_path``); returns a :class:`LintResult`."""
    import os

    from ytk_mp4j_tpu.analysis import baseline as baseline_mod
    from ytk_mp4j_tpu.analysis.cli import DEFAULT_BASELINE

    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE
    bl = (baseline_mod.load(baseline_path)
          if os.path.exists(baseline_path) else None)
    return Engine(baseline=bl).lint_paths(paths)
