"""Committed suppression file (``baseline.toml``) for mp4j-lint.

A baseline entry accepts a finding permanently, with a recorded reason:

.. code-block:: toml

    [[suppression]]
    rule = "R2"
    file = "ytk_mp4j_tpu/comm/process_comm.py"
    context = "ProcessCommSlave.barrier"
    reason = "barrier waits on peers indefinitely by design (fail-stop)"

Matching is by rule id, file suffix (so absolute and relative
invocations both match), and — when present — the finding's enclosing
``Class.func`` scope (``context``) and a message substring
(``contains``). Keying on scope instead of line number keeps the
baseline stable under unrelated edits.

The repo targets Python 3.10 (no ``tomllib``), so this module parses
the small TOML subset it emits: ``[[suppression]]`` tables with string
values. Anything fancier is a format error.
"""

from __future__ import annotations

import dataclasses
import re

from ytk_mp4j_tpu.analysis.report import Finding
from ytk_mp4j_tpu.exceptions import Mp4jError

_TABLE_RE = re.compile(r"^\[\[suppression\]\]\s*$")
_KV_RE = re.compile(r'^(\w+)\s*=\s*"((?:[^"\\]|\\.)*)"\s*$')


@dataclasses.dataclass
class Entry:
    rule: str
    file: str
    context: str = ""       # "" matches any scope
    contains: str = ""      # "" matches any message
    reason: str = ""
    line: int = 0           # the entry's own [[suppression]] line

    def match(self, f: Finding) -> bool:
        if f.rule != self.rule:
            return False
        if not (f.path == self.file or f.path.endswith("/" + self.file)):
            return False
        if self.context and f.context != self.context:
            return False
        return not self.contains or self.contains in f.message


class Baseline:
    def __init__(self, entries: list[Entry] | None = None):
        self.entries = entries or []
        self.used: set[int] = set()       # indices matched at least once

    def match(self, f: Finding) -> bool:
        for i, e in enumerate(self.entries):
            if e.match(f):
                self.used.add(i)
                return True
        return False

    def unused(self) -> list[Entry]:
        return [e for i, e in enumerate(self.entries) if i not in self.used]


def parse(text: str) -> Baseline:
    entries: list[Entry] = []
    current: dict[str, str] | None = None
    current_line = 0

    def flush():
        nonlocal current
        if current is not None:
            if "rule" not in current or "file" not in current:
                raise Mp4jError(
                    "baseline entry missing required 'rule'/'file' keys")
            entries.append(Entry(
                rule=current["rule"], file=current["file"],
                context=current.get("context", ""),
                contains=current.get("contains", ""),
                reason=current.get("reason", ""),
                line=current_line))
            current = None

    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if _TABLE_RE.match(line):
            flush()
            current = {}
            current_line = lineno
            continue
        m = _KV_RE.match(line)
        if m and current is not None:
            current[m.group(1)] = m.group(2).replace('\\"', '"')
            continue
        raise Mp4jError(
            f"baseline.toml line {lineno}: unsupported syntax {line!r} "
            "(only [[suppression]] tables with string values)")
    flush()
    return Baseline(entries)


def load(path: str) -> Baseline:
    with open(path, encoding="utf-8") as fh:
        return parse(fh.read())


def _portable_path(path: str) -> str:
    """Entry paths must survive re-invocation from other directories:
    strip ``./`` noise and anchor absolute paths at the package root
    when one is present (suffix matching does the rest)."""
    import posixpath

    p = posixpath.normpath(path)
    if posixpath.isabs(p) and "/ytk_mp4j_tpu/" in p:
        p = "ytk_mp4j_tpu/" + p.rsplit("/ytk_mp4j_tpu/", 1)[1]
    return p


def _quote(s: str) -> str:
    return '"' + s.replace('"', '\\"') + '"'


def render_entries(entries: list[Entry],
                   header: str | None = None) -> str:
    """Baseline text re-serializing ``entries`` verbatim (reasons and
    ``contains`` keys preserved) — ``--prune-baseline`` rewrites the
    committed file through this so dropping stale entries never
    degrades the kept ones."""
    lines = [header if header is not None else
             "# mp4j-lint baseline — accepted findings with reasons.",
             ""]
    for e in entries:
        lines += ["[[suppression]]",
                  f"rule = {_quote(e.rule)}",
                  f"file = {_quote(e.file)}"]
        if e.context:
            lines.append(f"context = {_quote(e.context)}")
        if e.contains:
            lines.append(f"contains = {_quote(e.contains)}")
        lines += [f"reason = {_quote(e.reason)}", ""]
    return "\n".join(lines)


def render(findings, reason: str = "accepted by baseline") -> str:
    """Baseline text accepting every given finding (for --write-baseline)."""
    lines = ["# mp4j-lint baseline — accepted findings with reasons.",
             "# Regenerate with: mp4j-lint --no-baseline --write-baseline"
             " <path> (then add reasons)", ""]
    seen = set()
    for f in findings:
        key = (f.rule, _portable_path(f.path), f.context)
        if key in seen:
            continue
        seen.add(key)
        lines += [
            "[[suppression]]",
            f'rule = "{f.rule}"',
            f'file = "{_portable_path(f.path)}"',
            f'context = "{f.context}"',
            f'reason = "{reason}"',
            "",
        ]
    return "\n".join(lines)
