"""AST rule engine for mp4j-lint.

The engine parses each target file once, annotates the tree (parent
links, a ``Class.func`` scope for every node), collects inline
``# mp4j-lint: disable=Rn`` directives from the source, and runs every
registered :class:`Rule` over the tree. Rules are ``ast.NodeVisitor``
subclasses with scope tracking built in — a rule implements ``visit_*``
methods and calls :meth:`Rule.report` to emit findings.

Suppression comes in two layers:

- inline: ``# mp4j-lint: disable=R3`` (comma-separated ids, optional
  free-text reason in parentheses) on the finding's line, or on a
  comment-only line immediately above it;
- baseline: entries in ``baseline.toml`` matched by (rule, file suffix,
  scope) — see :mod:`ytk_mp4j_tpu.analysis.baseline`.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import os
import re

from ytk_mp4j_tpu.analysis.report import Finding, Severity

_DIRECTIVE_RE = re.compile(
    r"#\s*mp4j-lint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:\(|$)")


def attr_chain(node: ast.AST) -> list[str] | None:
    """``self._g.slots`` -> ``["self", "_g", "slots"]``; None when the
    expression is not a pure dotted name (calls, subscripts, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(call: ast.Call) -> str | None:
    """Terminal name of the called object: ``a.b.c(...)`` -> ``"c"``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def receiver_chain(call: ast.Call) -> list[str] | None:
    """Dotted receiver of a method call: ``self.sock.recv(...)`` ->
    ``["self", "sock"]``; None for plain functions or computed bases."""
    if isinstance(call.func, ast.Attribute):
        return attr_chain(call.func.value)
    return None


def parse_inline_suppressions(source: str) -> dict[int, set[str]]:
    """Map of 1-based line number -> suppressed rule ids on that line.

    A directive on a comment-only line applies to the next line as well
    (so long reasons can sit above the statement they annotate)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):    # standalone comment line
            out.setdefault(i + 1, set()).update(rules)
    return out


@dataclasses.dataclass
class LintContext:
    """Everything a rule needs about one file."""

    path: str                       # posix-normalized display path
    tree: ast.Module
    source: str
    suppressions: dict[int, set[str]]

    def is_inline_suppressed(self, rule_id: str, line: int) -> bool:
        on = self.suppressions.get(line, ())
        return rule_id in on or "*" in on

    def in_dirs(self, *parts: str) -> bool:
        """True when the file lives under any of the given package
        directories (e.g. ``ctx.in_dirs("comm", "transport")``)."""
        segs = self.path.split("/")
        return any(p in segs for p in parts)


class Rule(ast.NodeVisitor):
    """Base class for lint rules.

    Subclasses set ``rule_id`` / ``severity`` / ``title`` /
    ``description`` and implement ``visit_*`` methods. The base visitor
    maintains ``self.scope`` (list of enclosing class/function names) —
    rules that override ``visit_FunctionDef`` / ``visit_ClassDef`` must
    call ``self.generic_visit_scoped(node)`` instead of
    ``generic_visit`` to keep it accurate.
    """

    rule_id: str = "R?"
    severity: Severity = Severity.WARNING
    title: str = ""
    description: str = ""
    # a minimal self-contained snippet the rule FIRES on, shown by
    # `mp4j-lint --explain RN` (and executed there, so the catalogue
    # stays honest); example_path places it for dir-scoped rules
    example: str = ""
    example_path: str = "ytk_mp4j_tpu/comm/example.py"

    def run(self, ctx: LintContext) -> list[Finding]:
        self.ctx = ctx
        self.findings: list[Finding] = []
        self.scope: list[str] = []
        self.visit(ctx.tree)
        return self.findings

    # -- scope bookkeeping ---------------------------------------------
    def generic_visit_scoped(self, node: ast.AST) -> None:
        self.scope.append(getattr(node, "name", "<anon>"))
        try:
            self.generic_visit(node)
        finally:
            self.scope.pop()

    def visit_FunctionDef(self, node):          # noqa: N802
        self.generic_visit_scoped(node)

    def visit_AsyncFunctionDef(self, node):     # noqa: N802
        self.generic_visit_scoped(node)

    def visit_ClassDef(self, node):             # noqa: N802
        self.generic_visit_scoped(node)

    def qualname(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"

    # -- emission -------------------------------------------------------
    def report(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=self.rule_id,
            severity=self.severity,
            path=self.ctx.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            context=self.qualname(),
        ))


class ProgramRule:
    """Base class for WHOLE-PROGRAM rules (ISSUE 14).

    Per-file rules are blind to cross-function interleavings — a lock
    acquired here and a blocking call three frames deeper in another
    module. A ProgramRule runs ONCE over the whole indexed path set:
    ``run_program(program)`` receives a :class:`Program` exposing the
    package index (``program.index``) and the lock model
    (``program.locks``) and returns findings pinned to real
    file:line sites, so inline and baseline suppression apply
    unchanged."""

    rule_id: str = "R?"
    severity: Severity = Severity.ERROR
    title: str = ""
    description: str = ""
    example: str = ""
    example_path: str = "ytk_mp4j_tpu/comm/example.py"

    def run_program(self, program: "Program") -> list[Finding]:
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str,
                context: str = "<module>", col: int = 1) -> Finding:
        return Finding(rule=self.rule_id, severity=self.severity,
                       path=path, line=line, col=col, message=message,
                       context=context)


class Program:
    """The parsed path set seen whole; index, lock model, race model
    and resource model are built lazily and shared by every
    ProgramRule of one engine run — and, via :meth:`shared`, across
    SAME-PROCESS runs over identical sources (the tier-1 strict gate
    and the rule tests used to re-parse the package per run)."""

    # content-keyed cache of whole programs; tiny LRU — the tier-1
    # gate plus a handful of snippet programs is the working set
    _cache: "collections.OrderedDict[tuple, Program]" = \
        collections.OrderedDict()
    _cache_max = 4

    def __init__(self, contexts: list[LintContext]):
        self.contexts = contexts
        self._index = None
        self._locks = None
        self._races = None
        self._resources = None

    @classmethod
    def shared(cls, contexts: list[LintContext]) -> "Program":
        """The cached Program for this exact (path, source) set.
        Safe because Programs are read-only after construction and
        contexts are invalidated upstream when file content changes."""
        key = tuple((c.path, hash(c.source)) for c in contexts)
        prog = cls._cache.get(key)
        if prog is None:
            prog = cls(contexts)
            cls._cache[key] = prog
            while len(cls._cache) > cls._cache_max:
                cls._cache.popitem(last=False)
        else:
            cls._cache.move_to_end(key)
        return prog

    @property
    def index(self):
        if self._index is None:
            from ytk_mp4j_tpu.analysis.callgraph import ProgramIndex
            self._index = ProgramIndex(self.contexts)
        return self._index

    @property
    def locks(self):
        if self._locks is None:
            from ytk_mp4j_tpu.analysis.locks import LockModel
            self._locks = LockModel(self.index)
        return self._locks

    @property
    def races(self):
        if self._races is None:
            from ytk_mp4j_tpu.analysis.races import RaceModel
            self._races = RaceModel(self.index, self.locks)
        return self._races

    @property
    def resources(self):
        if self._resources is None:
            from ytk_mp4j_tpu.analysis.resources import ResourceModel
            self._resources = ResourceModel(self.index)
        return self._resources


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]          # unsuppressed
    suppressed: list[Finding]        # matched an inline or baseline entry

    @property
    def ok(self) -> bool:
        return not self.findings


class Engine:
    """Run a set of rules over files, applying suppressions.

    Two-pass since ISSUE 14: per-file rules run file by file as
    always; :class:`ProgramRule` instances run once over a
    :class:`Program` built from every parsed file of the invocation —
    so ``mp4j-lint path.py`` still works (the program is that one
    file) while the tier-1 gate over the package runs the
    interprocedural rules whole-program.

    ``strict_baseline=True`` additionally reports every baseline entry
    that matched NO finding as a ``B001`` error pinned at the entry's
    own line — the baseline must stay honest as code moves. Strict
    mode only makes sense when linting the full path set the baseline
    was written against (the tier-1 gate); single-file invocations
    leave it off."""

    # path -> ((mtime_ns, size), LintContext): parsing + suppression
    # scanning is the dominant per-run cost and file content is stable
    # within a test session — contexts are reused until the file's
    # stat signature moves (ISSUE 16)
    _context_cache: dict[str, tuple[tuple, "LintContext"]] = {}

    @classmethod
    def clear_caches(cls) -> None:
        """Drop the parsed-context and Program caches (benchmarks
        measuring a cold run, tests mutating files in place)."""
        cls._context_cache.clear()
        Program._cache.clear()

    def __init__(self, rules=None, baseline=None,
                 strict_baseline: bool = False,
                 baseline_path: str | None = None):
        if rules is None:
            from ytk_mp4j_tpu.analysis.rules import get_rules
            rules = get_rules()
        self.rules = [r for r in rules if not isinstance(r, ProgramRule)]
        self.program_rules = [r for r in rules
                              if isinstance(r, ProgramRule)]
        self.baseline = baseline     # analysis.baseline.Baseline or None
        self.strict_baseline = strict_baseline
        self.baseline_path = baseline_path
        self.last_linted_paths: list[str] = []

    # -- file discovery -------------------------------------------------
    @staticmethod
    def collect_files(paths) -> list[str]:
        out = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(
                        d for d in dirnames if d != "__pycache__")
                    out.extend(os.path.join(dirpath, f)
                               for f in sorted(filenames)
                               if f.endswith(".py"))
            else:
                out.append(p)
        return out

    # -- entry points ---------------------------------------------------
    def load_contexts(self, paths):
        """Collect and parse ``paths`` into lint contexts. Returns
        ``(contexts, error_findings)`` — unreadable/unparsable files
        become E001 findings instead of vanishing. The shared loader
        for :meth:`lint_paths`, the ``graph`` subcommand and the
        tier-1 cycle-free gate."""
        contexts: list[LintContext] = []
        errors: list[Finding] = []
        for path in self.collect_files(paths):
            ctx, errs = self._load(path)
            if ctx is None:
                errors.extend(errs)
            else:
                contexts.append(ctx)
        return contexts, errors

    def lint_paths(self, paths) -> LintResult:
        contexts, findings = self.load_contexts(paths)
        # stashed for callers needing post-run staleness (CLI prune)
        self.last_linted_paths = [ctx.path for ctx in contexts]
        suppressed: list[Finding] = []
        for ctx in contexts:
            r = self._run_file_rules(ctx)
            findings.extend(r.findings)
            suppressed.extend(r.suppressed)
        r = self._run_program_rules(contexts)
        findings.extend(r.findings)
        suppressed.extend(r.suppressed)
        findings.extend(self._stale_baseline_findings(
            self.last_linted_paths))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return LintResult(findings, suppressed)

    def lint_file(self, path: str) -> LintResult:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            return LintResult([Finding(
                "E001", Severity.ERROR, path.replace(os.sep, "/"),
                0, 1, f"cannot read file: {e}")], [])
        return self.lint_source(source, path)

    def lint_source(self, source: str, path: str = "<string>") -> LintResult:
        ctx, errs = self._parse(source, path)
        if ctx is None:
            return LintResult(errs, [])
        r = self._run_file_rules(ctx)
        rp = self._run_program_rules([ctx])
        return LintResult(r.findings + rp.findings,
                          r.suppressed + rp.suppressed)

    # -- internals ------------------------------------------------------
    def _load(self, path: str):
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_size)
            cached = Engine._context_cache.get(path)
            if cached is not None and cached[0] == sig:
                return cached[1], []
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            return None, [Finding(
                "E001", Severity.ERROR, path.replace(os.sep, "/"),
                0, 1, f"cannot read file: {e}")]
        ctx, errs = self._parse(source, path)
        if ctx is not None:
            Engine._context_cache[path] = (sig, ctx)
        return ctx, errs

    def _parse(self, source: str, path: str):
        display = path.replace(os.sep, "/")
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return None, [Finding(
                "E001", Severity.ERROR, display,
                e.lineno or 0, (e.offset or 0) or 1,
                f"syntax error: {e.msg}")]
        return LintContext(
            path=display,
            tree=tree,
            source=source,
            suppressions=parse_inline_suppressions(source),
        ), []

    def _apply_suppressions(self, raw, ctx_by_path) -> LintResult:
        keep: list[Finding] = []
        dropped: list[Finding] = []
        for f in raw:
            ctx = ctx_by_path.get(f.path)
            if ctx is not None \
                    and ctx.is_inline_suppressed(f.rule, f.line):
                dropped.append(f)
            elif self.baseline is not None and self.baseline.match(f):
                dropped.append(f)
            else:
                keep.append(f)
        return LintResult(keep, dropped)

    def _run_file_rules(self, ctx: LintContext) -> LintResult:
        raw: list[Finding] = []
        for rule in self.rules:
            raw.extend(rule.run(ctx))
        return self._apply_suppressions(raw, {ctx.path: ctx})

    def _run_program_rules(self, contexts) -> LintResult:
        if not self.program_rules or not contexts:
            return LintResult([], [])
        program = Program.shared(contexts)
        raw: list[Finding] = []
        for rule in self.program_rules:
            raw.extend(rule.run_program(program))
        return self._apply_suppressions(
            raw, {ctx.path: ctx for ctx in contexts})

    def stale_entries(self, linted_paths) -> list:
        """Baseline entries provably stale for THIS run: unused, AND
        their rule actually ran, AND the linted path set plausibly
        covered their file — a ``--select R18`` or single-file
        invocation cannot prove anything stale about entries it never
        looked at (code-review finding: prune/strict on a partial run
        used to condemn every live entry outside the run's scope).

        Coverage is per entry: its exact file was linted, or some
        linted path lives under the entry's top-level package segment
        (so whole-package and tmp-tree runs see deleted-file entries
        as stale, while ``mp4j-lint one_file.py`` only judges that
        file's entries). A SUBTREE run (`mp4j-lint ytk_mp4j_tpu/obs`)
        still treats package-mate entries as in scope — run
        strict/prune from the package root."""
        if self.baseline is None:
            return []
        rule_ids = {r.rule_id for r in self.rules} \
            | {r.rule_id for r in self.program_rules}
        out = []
        for e in self.baseline.unused():
            if e.rule not in rule_ids:
                continue
            top = e.file.split("/")[0]
            covered = any(
                p == e.file or p.endswith("/" + e.file)
                or p.startswith(top + "/") or ("/" + top + "/") in p
                for p in linted_paths)
            if covered:
                out.append(e)
        return out

    def _stale_baseline_findings(self, linted_paths) -> list[Finding]:
        """Strict mode: an unused baseline entry is itself a finding —
        the accepted surface must shrink with the code, or a revived
        hazard at a moved site sails through on a stale excuse."""
        if not self.strict_baseline or self.baseline is None:
            return []
        path = (self.baseline_path or "baseline.toml").replace(
            os.sep, "/")
        return [Finding(
            "B001", Severity.ERROR, path, e.line, 1,
            f"stale baseline entry ({e.rule} {e.file}"
            + (f" {e.context}" if e.context else "")
            + ") no longer matches any finding — remove it (mp4j-lint "
            "--prune-baseline) or re-justify it against a live site",
            context="<baseline>")
            for e in self.stale_entries(linted_paths)]
