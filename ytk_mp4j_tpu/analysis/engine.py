"""AST rule engine for mp4j-lint.

The engine parses each target file once, annotates the tree (parent
links, a ``Class.func`` scope for every node), collects inline
``# mp4j-lint: disable=Rn`` directives from the source, and runs every
registered :class:`Rule` over the tree. Rules are ``ast.NodeVisitor``
subclasses with scope tracking built in — a rule implements ``visit_*``
methods and calls :meth:`Rule.report` to emit findings.

Suppression comes in two layers:

- inline: ``# mp4j-lint: disable=R3`` (comma-separated ids, optional
  free-text reason in parentheses) on the finding's line, or on a
  comment-only line immediately above it;
- baseline: entries in ``baseline.toml`` matched by (rule, file suffix,
  scope) — see :mod:`ytk_mp4j_tpu.analysis.baseline`.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from ytk_mp4j_tpu.analysis.report import Finding, Severity

_DIRECTIVE_RE = re.compile(
    r"#\s*mp4j-lint:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:\(|$)")


def attr_chain(node: ast.AST) -> list[str] | None:
    """``self._g.slots`` -> ``["self", "_g", "slots"]``; None when the
    expression is not a pure dotted name (calls, subscripts, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(call: ast.Call) -> str | None:
    """Terminal name of the called object: ``a.b.c(...)`` -> ``"c"``."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def receiver_chain(call: ast.Call) -> list[str] | None:
    """Dotted receiver of a method call: ``self.sock.recv(...)`` ->
    ``["self", "sock"]``; None for plain functions or computed bases."""
    if isinstance(call.func, ast.Attribute):
        return attr_chain(call.func.value)
    return None


def parse_inline_suppressions(source: str) -> dict[int, set[str]]:
    """Map of 1-based line number -> suppressed rule ids on that line.

    A directive on a comment-only line applies to the next line as well
    (so long reasons can sit above the statement they annotate)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):    # standalone comment line
            out.setdefault(i + 1, set()).update(rules)
    return out


@dataclasses.dataclass
class LintContext:
    """Everything a rule needs about one file."""

    path: str                       # posix-normalized display path
    tree: ast.Module
    source: str
    suppressions: dict[int, set[str]]

    def is_inline_suppressed(self, rule_id: str, line: int) -> bool:
        on = self.suppressions.get(line, ())
        return rule_id in on or "*" in on

    def in_dirs(self, *parts: str) -> bool:
        """True when the file lives under any of the given package
        directories (e.g. ``ctx.in_dirs("comm", "transport")``)."""
        segs = self.path.split("/")
        return any(p in segs for p in parts)


class Rule(ast.NodeVisitor):
    """Base class for lint rules.

    Subclasses set ``rule_id`` / ``severity`` / ``title`` /
    ``description`` and implement ``visit_*`` methods. The base visitor
    maintains ``self.scope`` (list of enclosing class/function names) —
    rules that override ``visit_FunctionDef`` / ``visit_ClassDef`` must
    call ``self.generic_visit_scoped(node)`` instead of
    ``generic_visit`` to keep it accurate.
    """

    rule_id: str = "R?"
    severity: Severity = Severity.WARNING
    title: str = ""
    description: str = ""

    def run(self, ctx: LintContext) -> list[Finding]:
        self.ctx = ctx
        self.findings: list[Finding] = []
        self.scope: list[str] = []
        self.visit(ctx.tree)
        return self.findings

    # -- scope bookkeeping ---------------------------------------------
    def generic_visit_scoped(self, node: ast.AST) -> None:
        self.scope.append(getattr(node, "name", "<anon>"))
        try:
            self.generic_visit(node)
        finally:
            self.scope.pop()

    def visit_FunctionDef(self, node):          # noqa: N802
        self.generic_visit_scoped(node)

    def visit_AsyncFunctionDef(self, node):     # noqa: N802
        self.generic_visit_scoped(node)

    def visit_ClassDef(self, node):             # noqa: N802
        self.generic_visit_scoped(node)

    def qualname(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"

    # -- emission -------------------------------------------------------
    def report(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=self.rule_id,
            severity=self.severity,
            path=self.ctx.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            context=self.qualname(),
        ))


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]          # unsuppressed
    suppressed: list[Finding]        # matched an inline or baseline entry

    @property
    def ok(self) -> bool:
        return not self.findings


class Engine:
    """Run a set of rules over files, applying suppressions."""

    def __init__(self, rules=None, baseline=None):
        if rules is None:
            from ytk_mp4j_tpu.analysis.rules import get_rules
            rules = get_rules()
        self.rules = list(rules)
        self.baseline = baseline     # analysis.baseline.Baseline or None

    # -- file discovery -------------------------------------------------
    @staticmethod
    def collect_files(paths) -> list[str]:
        out = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(
                        d for d in dirnames if d != "__pycache__")
                    out.extend(os.path.join(dirpath, f)
                               for f in sorted(filenames)
                               if f.endswith(".py"))
            else:
                out.append(p)
        return out

    # -- entry points ---------------------------------------------------
    def lint_paths(self, paths) -> LintResult:
        findings: list[Finding] = []
        suppressed: list[Finding] = []
        for path in self.collect_files(paths):
            r = self.lint_file(path)
            findings.extend(r.findings)
            suppressed.extend(r.suppressed)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return LintResult(findings, suppressed)

    def lint_file(self, path: str) -> LintResult:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            return LintResult([Finding(
                "E001", Severity.ERROR, path.replace(os.sep, "/"),
                0, 1, f"cannot read file: {e}")], [])
        return self.lint_source(source, path)

    def lint_source(self, source: str, path: str = "<string>") -> LintResult:
        display = path.replace(os.sep, "/")
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return LintResult([Finding(
                "E001", Severity.ERROR, display,
                e.lineno or 0, (e.offset or 0) or 1,
                f"syntax error: {e.msg}")], [])
        ctx = LintContext(
            path=display,
            tree=tree,
            source=source,
            suppressions=parse_inline_suppressions(source),
        )
        keep: list[Finding] = []
        dropped: list[Finding] = []
        for rule in self.rules:
            for f in rule.run(ctx):
                if ctx.is_inline_suppressed(f.rule, f.line):
                    dropped.append(f)
                elif self.baseline is not None and self.baseline.match(f):
                    dropped.append(f)
                else:
                    keep.append(f)
        return LintResult(keep, dropped)
