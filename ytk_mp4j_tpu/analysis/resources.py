"""Acquire/release path analysis over the call graph (ISSUE 16).

Every fd-reuse window and orphaned-thread incident in CHANGES.md is
the same shape: a resource acquired, an exception edge between the
acquire and the release, and nothing on that edge that closes it.
This module models the package's acquire vocabulary and checks the
edges:

- **R24 (resource leaked on exception path)** — sockets
  (``socket.socket``/``create_connection``/``accept``), files
  (``open``/``os.fdopen``), shm segments (``os.memfd_create``,
  ``mmap.mmap``), transport channels (constructors of ``transport/``
  classes with a ``close``), and lock ``acquire()`` outside ``with``.
  A tracked resource is SAFE inside a ``with``, once a ``try`` whose
  ``finally`` (or handler) releases it encloses the risky region, or
  once ownership transfers — returned/yielded, stored into an
  attribute or container (the registered-drain pattern:
  ``_drain_dead_channels`` owns what ``self._channels`` holds), or
  passed to another call. Any OTHER statement that can raise while
  the resource is live and unprotected is a leaked exception edge,
  charged at the acquire site.
- **R25 (thread started without join/daemon/stop registration)** —
  a started ``Thread``/``Timer`` must be daemonized, joined, or
  stored somewhere the program provably joins/cancels (an attribute
  or list some function calls ``.join()``/``.cancel()`` on, directly
  or via a drain loop). A fire-and-forget non-daemon thread outlives
  shutdown and deadlocks interpreter exit.

Per-function path reasoning, whole-program release registry: the
join/daemon registry is built over every module first, so storing a
thread in ``self._threads`` is fine exactly when someone, anywhere,
drains that list.
"""

from __future__ import annotations

import ast
import dataclasses

from ytk_mp4j_tpu.analysis.engine import attr_chain

# acquire chains -> kind
_OPENERS: dict[tuple[str, ...], str] = {
    ("open",): "file",
    ("io", "open"): "file",
    ("os", "fdopen"): "file",
    ("gzip", "open"): "file",
    ("socket", "socket"): "socket",
    ("socket", "create_connection"): "socket",
    ("mmap", "mmap"): "shm segment",
    ("os", "memfd_create"): "memfd",
}

# verbs that fully release a tracked resource
_RELEASES = {"close", "shutdown", "detach", "release", "join", "stop",
             "cancel", "terminate", "kill", "unlink"}

# thread lifecycle registrations R25 accepts
_THREAD_STOPS = {"join", "cancel", "stop"}


@dataclasses.dataclass
class Leak:
    """One R24 finding candidate."""

    kind: str
    name: str                    # variable / dotted lock chain
    path: str
    func: str                    # display of the owning function
    lineno: int                  # acquire site (the fix site)
    risk_lineno: int             # first unprotected raising statement
    risk_desc: str


@dataclasses.dataclass
class ThreadLeak:
    """One R25 finding candidate."""

    path: str
    func: str
    lineno: int                  # constructor site
    detail: str


@dataclasses.dataclass
class _Res:
    kind: str
    lineno: int
    reported: bool = False


class ResourceModel:
    """Whole-program acquire/release verdicts for R24/R25."""

    def __init__(self, index):
        self.index = index
        self.leaks: list[Leak] = []
        self.thread_leaks: list[ThreadLeak] = []
        self.joined_attrs, self.daemon_attrs = self._thread_registry()
        for fi in sorted(index.functions.values(),
                         key=lambda f: f.key):
            _FnWalker(self, fi).walk()
            self._scan_threads(fi)

    # -- the whole-program thread registry ------------------------------
    def _thread_registry(self) -> tuple[set[str], set[str]]:
        """Attrs provably joined/cancelled or daemonized SOMEWHERE:
        ``self.X.join()``, ``for t in self.Y: t.join()`` (loop-drain),
        ``self.X.daemon = True``."""
        joined: set[str] = set()
        daemon: set[str] = set()
        for fi in self.index.functions.values():
            loop_srcs: dict[str, str] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.For, ast.AsyncFor)) \
                        and isinstance(node.target, ast.Name):
                    ch = attr_chain(node.iter)
                    if ch and len(ch) >= 2:
                        loop_srcs[node.target.id] = ch[-1]
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _THREAD_STOPS:
                    ch = attr_chain(node.func.value)
                    if not ch:
                        continue
                    if len(ch) >= 2:
                        joined.add(ch[-1])
                    elif ch[0] in loop_srcs:
                        joined.add(loop_srcs[ch[0]])
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    ch = attr_chain(node.targets[0])
                    if ch and ch[-1] == "daemon" and len(ch) >= 3 \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        daemon.add(ch[-2])
        return joined, daemon

    # -- R25: thread lifecycle ------------------------------------------
    def _is_thread_ctor(self, call: ast.Call, fi) -> bool:
        if self.index.type_of_expr(call, fi.module) \
                == "threading.Thread":
            return True
        chain = attr_chain(call.func) or []
        if chain and chain[-1] == "Timer":
            return (chain == ["threading", "Timer"]
                    or fi.module.from_names.get(
                        "Timer", ("", ""))[1] == "Timer")
        return False

    @staticmethod
    def _ctor_daemonized(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon" \
                    and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return True
        return False

    def _scan_threads(self, fi) -> None:
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call) \
                    and self._is_thread_ctor(node.value, fi):
                if self._ctor_daemonized(node.value):
                    continue
                tgt = node.targets[0]
                ch = attr_chain(tgt)
                if isinstance(tgt, ast.Name):
                    self._judge_local_thread(fi, node, tgt.id)
                elif ch and len(ch) >= 2:
                    self._judge_attr_thread(fi, node, ch[-1])
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start" \
                    and isinstance(node.func.value, ast.Call) \
                    and self._is_thread_ctor(node.func.value, fi) \
                    and not self._ctor_daemonized(node.func.value):
                self.thread_leaks.append(ThreadLeak(
                    fi.path, fi.display, node.func.value.lineno,
                    "started inline without binding: it can never be "
                    "joined"))

    def _judge_local_thread(self, fi, assign, name: str) -> None:
        started = joined = escaped = False
        stored_attr = None
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                ch = attr_chain(node.func) or []
                if ch[:1] == [name] and len(ch) == 2:
                    if ch[1] == "start":
                        started = True
                    elif ch[1] in _THREAD_STOPS:
                        joined = True
                    continue
                if any(isinstance(a, ast.Name) and a.id == name
                       for a in node.args):
                    # passed along (a register call / a list append
                    # with a drained attr): judged via the receiver
                    # attr when there is one, else ownership transfer
                    recv = attr_chain(node.func) or []
                    if len(recv) == 3 and recv[-1] == "append":
                        stored_attr = recv[-2]
                    else:
                        escaped = True
                if any(isinstance(kw.value, ast.Name)
                       and kw.value.id == name
                       for kw in node.keywords):
                    escaped = True
            elif isinstance(node, ast.Assign):
                ch = attr_chain(node.targets[0]) \
                    if len(node.targets) == 1 else None
                if isinstance(node.value, ast.Name) \
                        and node.value.id == name and ch:
                    if len(ch) >= 2:
                        stored_attr = ch[-1]
                    else:
                        escaped = True
                if ch and ch[:1] == [name] and ch[-1] == "daemon" \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    joined = True        # daemonized before start
            elif isinstance(node, (ast.Return, ast.Yield)) \
                    and isinstance(getattr(node, "value", None),
                                   ast.Name) \
                    and node.value.id == name:
                escaped = True
        if not started and stored_attr is None and not escaped:
            return                       # never started: not R25's job
        if joined or escaped:
            return
        if stored_attr is not None:
            if stored_attr in self.joined_attrs \
                    or stored_attr in self.daemon_attrs:
                return
            self.thread_leaks.append(ThreadLeak(
                fi.path, fi.display, assign.lineno,
                f"stored in '{stored_attr}' but no function joins, "
                f"cancels or daemonizes that attribute"))
            return
        self.thread_leaks.append(ThreadLeak(
            fi.path, fi.display, assign.lineno,
            f"'{name}' is started but never joined, daemonized or "
            f"registered for stop"))

    def _judge_attr_thread(self, fi, assign, attr: str) -> None:
        if attr in self.joined_attrs or attr in self.daemon_attrs:
            return
        self.thread_leaks.append(ThreadLeak(
            fi.path, fi.display, assign.lineno,
            f"stored in '{attr}' but no function joins, cancels or "
            f"daemonizes that attribute"))


class _FnWalker:
    """One function's R24 path check: a recursive statement walk with
    the live-resource table and the enclosing-``try`` protection set."""

    def __init__(self, model: ResourceModel, fi):
        self.model = model
        self.index = model.index
        self.fi = fi
        self.live: dict[str, _Res] = {}
        # one prescan fills both: names captured by nested
        # defs/lambdas (their lifetime leaves this function's paths —
        # never tracked) and the lock-acquire chains this function
        # also releases (paired acquire/release methods are a
        # different, reviewed discipline)
        self.closure_names, self.releasable_chains = \
            self._prescan(fi.node)

    def walk(self) -> None:
        self._stmts(self.fi.node.body, frozenset())
        for name, r in sorted(self.live.items()):
            if not r.reported:
                self.model.leaks.append(Leak(
                    r.kind, name, self.fi.path, self.fi.display,
                    r.lineno, r.lineno,
                    "never released or handed off on any path"))

    @staticmethod
    def _prescan(fnode) -> tuple[set[str], set[tuple[str, ...]]]:
        closure: set[str] = set()
        chains: set[tuple[str, ...]] = set()
        for node in ast.walk(fnode):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fnode:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        closure.add(sub.id)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                ch = attr_chain(node.func.value)
                if ch:
                    chains.add(tuple(ch))
        return closure, chains

    # -- classification --------------------------------------------------
    def _acquire_kind(self, expr) -> str | None:
        if not isinstance(expr, ast.Call):
            return None
        chain = attr_chain(expr.func)
        if chain:
            kind = _OPENERS.get(tuple(chain))
            if kind:
                return kind
            if chain[-1] == "accept":
                return "socket"
        t = self.index.type_of_expr(expr, self.fi.module)
        ci = self.index.class_of_key(t) if t and ":" in (t or "") \
            else None
        if ci is not None and ci.module.ctx.in_dirs("transport") \
                and self.index.lookup_method(ci, "close") is not None:
            return "channel"
        return None

    # -- the walk --------------------------------------------------------
    def _stmts(self, body, protected: frozenset) -> None:
        for stmt in body:
            self._stmt(stmt, protected)

    def _stmt(self, node, protected: frozenset) -> None:
        if isinstance(node, ast.Try):
            # names whose release/handoff sits on the exception edges
            # of THIS try are protected inside its body
            guarded: set[str] = set()
            for blk in [node.finalbody] + [h.body for h in
                                           node.handlers]:
                for s in blk:
                    rel, esc, _ = self._stmt_facts(s)
                    guarded |= rel | esc
            # a catch-all handler that does not re-raise ABSORBS the
            # body's exception edges: control falls through to the
            # statements after the try, where a conditional release
            # (`except Exception: ok = False` ... `if not ok:
            # ch.close()`) settles the resource — the end-of-function
            # sweep still reports it if no path ever releases
            if self._absorbs(node):
                guarded.add("*")
            inner = protected | frozenset(guarded)
            self._stmts(node.body, inner)
            for h in node.handlers:
                self._stmts(h.body, protected)
            self._stmts(node.orelse, inner)
            self._stmts(node.finalbody, protected)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # `with open(p) as fh:` — scoped by construction; other
            # context managers (locks) are not risky edges themselves
            withheld: set[str] = set()
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name) \
                        and self._acquire_kind(item.context_expr):
                    withheld.add(item.optional_vars.id)
            self._stmts(node.body, protected | frozenset(withheld))
            return
        if isinstance(node, (ast.If, ast.While)):
            self._simple(node.test, node, protected)
            self._stmts(node.body, protected)
            self._stmts(node.orelse, protected)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._simple(node.iter, node, protected)
            self._stmts(node.body, protected)
            self._stmts(node.orelse, protected)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        self._simple(node, node, protected)

    def _simple(self, scan_node, stmt, protected: frozenset) -> None:
        """One non-compound statement (or a compound head expression):
        releases and escapes first, then the riskiness check, then new
        acquisitions become live."""
        rel, esc, raisy = self._stmt_facts(scan_node)
        acq = self._acquisitions(stmt if scan_node is stmt else None)
        if raisy:
            self._risk(stmt, protected | frozenset(rel) | frozenset(esc),
                       self._describe(scan_node))
        for n in rel | esc:
            self.live.pop(n, None)
        self.live.update(acq)

    def _risk(self, stmt, safe_names: frozenset, desc: str) -> None:
        if "*" in safe_names:   # inside an absorbing try (see _absorbs)
            return
        for name, r in self.live.items():
            if r.reported or name in safe_names:
                continue
            r.reported = True
            self.model.leaks.append(Leak(
                r.kind, name, self.fi.path, self.fi.display,
                r.lineno, stmt.lineno, desc))

    @staticmethod
    def _absorbs(node: ast.Try) -> bool:
        """True when every exception edge out of this try's body lands
        in a catch-all handler that does not re-raise — control is
        guaranteed to continue after the try, so the body's raises are
        not leak edges (the fall-through path owns the release)."""
        catch_all = False
        for h in node.handlers:
            if h.type is None:
                catch_all = True
            else:
                names = (h.type.elts if isinstance(h.type, ast.Tuple)
                         else [h.type])
                catch_all = catch_all or any(
                    isinstance(n, ast.Name)
                    and n.id in ("Exception", "BaseException")
                    for n in names)
            for s in h.body:
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Raise):
                        return False
        return catch_all

    # -- statement facts -------------------------------------------------
    def _acquisitions(self, stmt) -> dict[str, _Res]:
        out: dict[str, _Res] = {}
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            if isinstance(stmt, ast.Expr):
                self._lock_acquire_stmt(stmt.value)
            return out
        kind = self._acquire_kind(stmt.value)
        if kind is None:
            return out
        tgt = stmt.targets[0]
        if isinstance(tgt, ast.Name):
            names = [tgt.id]
        elif isinstance(tgt, ast.Tuple) and tgt.elts \
                and isinstance(tgt.elts[0], ast.Name):
            # `conn, addr = lsock.accept()` — the fd is element 0
            names = [tgt.elts[0].id]
        else:
            return out
        for n in names:
            if n not in self.closure_names:
                out[n] = _Res(kind, stmt.lineno)
        return out

    def _lock_acquire_stmt(self, expr) -> None:
        """``self._lock.acquire()`` outside ``with``: tracked by its
        dotted chain, only when this function also releases it."""
        if not isinstance(expr, ast.Call) \
                or not isinstance(expr.func, ast.Attribute) \
                or expr.func.attr != "acquire":
            return
        ch = attr_chain(expr.func.value)
        if not ch or tuple(ch) not in self.releasable_chains:
            return
        self.live.setdefault(".".join(ch),
                             _Res("lock", expr.lineno))

    def _stmt_facts(self, node) -> tuple[set, set, bool]:
        """ONE walk over a statement, three facts: released names,
        escaped names (ownership transfers: returned/yielded, stored
        into an attribute/container/alias, or passed as a call
        argument), and whether the statement has a raise edge (an
        explicit raise/assert, or any call that is not purely its own
        acquire/release bookkeeping). The type-resolving
        ``_acquire_kind`` probe runs last and only until the first
        risky call settles the verdict — it is the expensive check."""
        rel: set[str] = set()
        esc: set[str] = set()
        raisy = isinstance(node, (ast.Raise, ast.Assert))
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                v = sub.value
                if v is not None:
                    esc |= {n.id for n in ast.walk(v)
                            if isinstance(n, ast.Name)}
            elif isinstance(sub, ast.Assign):
                for n in ast.walk(sub.value):
                    if isinstance(n, ast.Name) and n.id in self.live:
                        esc.add(n.id)
            elif isinstance(sub, ast.Call):
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name) \
                                and n.id in self.live:
                            esc.add(n.id)
                bookkeeping = False
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _RELEASES:
                    ch = attr_chain(sub.func.value)
                    if ch and len(ch) == 1:
                        rel.add(ch[0])
                    elif ch:
                        rel.add(".".join(ch))     # lock chains
                    bookkeeping = True
                elif isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "acquire":
                    bookkeeping = True
                chain = attr_chain(sub.func) or []
                if chain == ["os", "close"]:
                    if sub.args and isinstance(sub.args[0], ast.Name):
                        rel.add(sub.args[0].id)
                    bookkeeping = True
                if not raisy and not bookkeeping \
                        and not self._acquire_kind(sub):
                    raisy = True
        return rel, esc, raisy

    @staticmethod
    def _describe(node) -> str:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                ch = attr_chain(sub.func)
                if ch:
                    return f"call to {'.'.join(ch)} at line " \
                           f"{sub.lineno}"
                return f"call at line {sub.lineno}"
        if isinstance(node, ast.Raise):
            return f"raise at line {node.lineno}"
        return f"statement at line {getattr(node, 'lineno', 0)}"
