"""``python -m ytk_mp4j_tpu.analysis`` — the mp4j-lint CLI."""

import sys

from ytk_mp4j_tpu.analysis.cli import main

sys.exit(main())
