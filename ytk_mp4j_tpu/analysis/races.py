"""Eraser/RacerD-style lockset race analysis (ISSUE 16).

The lock model answers "what is held HERE"; this module answers the
question every hand-written hardening pass in CHANGES.md was chasing:
*which lock protects which shared field, and is it always the same
one?* The classic lockset discipline, adapted to the package:

1. **Thread roots** — the entry points concurrency actually flows
   from: every ``threading.Thread(target=...)`` / ``Timer(...)``
   callback, every callable registered as a ``*_hook``/``*_cb``, and
   one merged ``main`` root for the public API surface (the collective
   path). Constructors are NOT roots: ``__init__``-time writes happen
   before the object is published, the classic happens-before edge.
2. **Reachability with lock contexts** — for each root, a monotone
   fixpoint over the call graph computes the set of held-lock contexts
   each function can be entered under (``{} ∪ caller-held`` per call
   edge; reuses :mod:`callgraph` resolution and the per-call held sets
   of :mod:`locks`).
3. **Site records** — every :class:`~ytk_mp4j_tpu.analysis.locks.
   AccessEvent` of a reachable function becomes ``(root, site, write,
   lockset)`` records, one per entry context, with the local held set
   unioned in. Field identity is canonicalized to the DEEPEST base
   class that assigns the attribute, so a base-class field written
   through two subclasses is one field.
4. **The verdict** — a field is *shared* when records from >= 2
   distinct roots exist and at least one is a write; it is *racy*
   when some write's lockset has empty intersection with some other
   root's access lockset. The report carries both witness sites, the
   roots that reach them, and the candidate lock (the lock most often
   held across the field's accesses — the one the fix should use).

Missed call edges and unresolvable receivers drop records, never
invent them: like the rest of the analysis stack, a finding here is a
witnessed interleaving, not a guess.
"""

from __future__ import annotations

import ast
import dataclasses

from ytk_mp4j_tpu.analysis.engine import attr_chain
from ytk_mp4j_tpu.analysis.locks import _is_hookish

# entry contexts per function per root are capped; past the cap the
# set collapses to its intersection (= the locks GUARANTEED held),
# which can only make a field look less protected — the sound
# direction for a race detector
_MAX_CONTEXTS = 16


@dataclasses.dataclass(frozen=True)
class SiteRecord:
    """One access to a shared field from one root, with its lockset."""

    root: str                    # "main" | "thread:Cls.meth" | "hook:..."
    path: str
    lineno: int
    func: str                    # display of the accessing function
    write: bool
    lockset: frozenset[str]      # LockDecl keys held at the site


@dataclasses.dataclass
class FieldReport:
    """The lockset verdict for one shared mutable field."""

    owner: str                   # canonical ClassInfo key
    attr: str
    records: list[SiteRecord]
    roots: tuple[str, ...]
    racy: bool
    # (write site, conflicting other-root site) when racy
    witness: tuple[SiteRecord, SiteRecord] | None
    candidate: str | None        # lock key the fix should take

    @property
    def display(self) -> str:
        cls = self.owner.rsplit(":", 1)[-1]
        return f"{cls}.{self.attr}"


class RaceModel:
    """Thread roots + per-root lock contexts + shared-field records."""

    def __init__(self, index, locks):
        self.index = index
        self.locks = locks
        # root id -> entry function keys
        self.roots: dict[str, set[str]] = {}
        # class key -> attrs its OWN methods assign to ``self`` —
        # filled by _discover_roots in the same walk that finds roots
        self._declared: dict[str, set[str]] = {}
        self._discover_roots()
        # (owner, attr) -> [SiteRecord]
        self.fields: dict[tuple[str, str], list[SiteRecord]] = {}
        for root, entries in self.roots.items():
            self._collect(root, entries)
        self._reports: list[FieldReport] | None = None

    # -- field identity -------------------------------------------------
    def canonical_owner(self, owner_key: str, attr: str) -> str:
        """The deepest base class that assigns ``attr`` — merges a
        base-class field accessed through several subclasses."""
        ci = self.index.classes.get(owner_key)
        if ci is None:
            return owner_key
        cand = owner_key
        for c in self.index.mro(ci):      # nearest first
            if attr in self._declared.get(c.key, ()):
                cand = c.key
        return cand

    # -- thread-root discovery ------------------------------------------
    def _discover_roots(self) -> None:
        thread_entries: set[str] = set()
        for fkey, s in self.locks.summaries.items():
            fi = s.func
            # the same walk also records which attrs this method
            # assigns to ``self`` (canonical_owner's evidence) — one
            # pass over every function node, not two
            decl = None if fi.cls is None else self._declared.setdefault(
                f"{fi.module.name}:{fi.cls}", set())
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    self._root_from_call(node, fi, thread_entries)
                elif isinstance(node, ast.Assign):
                    if len(node.targets) == 1:
                        ch = attr_chain(node.targets[0])
                        if ch and _is_hookish(ch[-1]):
                            for t in self._func_ref(node.value, fi):
                                self._add_root(f"hook:{t.display}", t,
                                               thread_entries)
                    if decl is not None:
                        for t in node.targets:
                            ch = attr_chain(t)
                            if ch and len(ch) == 2 and ch[0] == "self":
                                decl.add(ch[1])
                elif decl is not None and isinstance(
                        node, (ast.AnnAssign, ast.AugAssign)):
                    ch = attr_chain(node.target)
                    if ch and len(ch) == 2 and ch[0] == "self":
                        decl.add(ch[1])
        # the merged "main" root: the public API surface — public
        # functions NO internal code calls. A public method with an
        # internal caller (the master invoking HealthEngine.fold
        # under its lock) is plumbing: its concurrency contexts are
        # the CALLERS' paths, and inventing an extra bare-entry
        # context would report every such site as lock-free. Thread
        # targets are excluded too (target=self.run only runs there).
        called: set[str] = set()
        for fkey, s in self.locks.summaries.items():
            for call in s.calls:
                called.update(c for c in call.callees if c != fkey)
        main: set[str] = set()
        for fi in self.index.functions.values():
            if fi.name.startswith("_"):
                continue
            if fi.key in thread_entries or fi.key in called:
                continue
            main.add(fi.key)
        if main:
            self.roots["main"] = main

    def _root_from_call(self, call: ast.Call, fi, thread_entries):
        chain = attr_chain(call.func) or []
        name = chain[-1] if chain else None
        # type resolution is the expensive step: only a call carrying
        # a ``target=`` keyword can mint a thread root, so every other
        # call skips it (a Thread ctor without target= contributes no
        # root either way)
        if any(kw.arg == "target" for kw in call.keywords):
            t = self.index.type_of_expr(call, fi.module)
            if t == "threading.Thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        for tgt in self._func_ref(kw.value, fi):
                            self._add_root(f"thread:{tgt.display}", tgt,
                                           thread_entries)
                return
        if name == "Timer" and (
                chain == ["threading", "Timer"]
                or (len(chain) == 1 and fi.module.from_names.get(
                    "Timer", ("", ""))[1] == "Timer")):
            cb = call.args[1] if len(call.args) >= 2 else None
            for kw in call.keywords:
                if kw.arg == "function":
                    cb = kw.value
            if cb is not None:
                for tgt in self._func_ref(cb, fi):
                    self._add_root(f"thread:{tgt.display}", tgt,
                                   thread_entries)
            return
        # callable registered through a hookish keyword: runs on
        # whatever thread fires the hook — its own root
        for kw in call.keywords:
            if kw.arg and _is_hookish(kw.arg):
                for tgt in self._func_ref(kw.value, fi):
                    self._add_root(f"hook:{tgt.display}", tgt,
                                   thread_entries)

    def _add_root(self, root_id: str, fi, thread_entries: set) -> None:
        self.roots.setdefault(root_id, set()).add(fi.key)
        thread_entries.add(fi.key)

    def _func_ref(self, expr, fi) -> list:
        """Resolve a function/bound-method REFERENCE expression."""
        ch = attr_chain(expr)
        if not ch:
            return []
        if len(ch) == 1:
            t = fi.module.functions.get(ch[0])
            return [t] if t is not None else []
        owner = self.index._owner_class(ch[:-1], fi, {})
        if owner is not None:
            t = self.index.lookup_method(owner, ch[-1])
            return [t] if t is not None else []
        return []

    # -- reachability with lock contexts --------------------------------
    def _reach_contexts(self, entries) -> dict[str, set[frozenset]]:
        ctxs: dict[str, set[frozenset]] = {}
        work: list[str] = []
        for e in entries:
            if e in self.locks.summaries:
                ctxs[e] = {frozenset()}
                work.append(e)
        while work:
            f = work.pop()
            for call in self.locks.summaries[f].calls:
                h = frozenset(call.held)
                for ckey in call.callees:
                    if ckey == f or ckey not in self.locks.summaries:
                        continue
                    cur = ctxs.setdefault(ckey, set())
                    new = {c | h for c in ctxs[f]} - cur
                    if not new:
                        continue
                    cur |= new
                    if len(cur) > _MAX_CONTEXTS:
                        inter = frozenset.intersection(*cur)
                        cur.clear()
                        cur.add(inter)
                    work.append(ckey)
        return ctxs

    def _collect(self, root: str, entries) -> None:
        for fkey, cset in self._reach_contexts(entries).items():
            s = self.locks.summaries[fkey]
            fi = s.func
            for a in s.accesses:
                owner = self.canonical_owner(a.owner, a.attr)
                recs = self.fields.setdefault((owner, a.attr), [])
                for c in cset:
                    recs.append(SiteRecord(
                        root, fi.path, a.lineno, fi.display, a.write,
                        c | frozenset(a.held)))

    # -- verdicts -------------------------------------------------------
    def field_reports(self) -> list[FieldReport]:
        if self._reports is not None:
            return self._reports
        out: list[FieldReport] = []
        for (owner, attr), recs in sorted(self.fields.items()):
            recs = self._dedup(recs)
            roots = tuple(sorted({r.root for r in recs}))
            writes = [r for r in recs if r.write]
            racy = False
            witness = None
            if len(roots) >= 2 and writes:
                racy, witness = self._find_race(recs, writes)
            out.append(FieldReport(
                owner=owner, attr=attr, records=recs, roots=roots,
                racy=racy, witness=witness,
                candidate=self._candidate(recs)))
        self._reports = out
        return out

    @staticmethod
    def _dedup(recs: list[SiteRecord]) -> list[SiteRecord]:
        """One record per (root, site, lockset); a write at a site
        subsumes the read the walker also recorded there."""
        write_sites = {(r.root, r.path, r.lineno, r.lockset)
                       for r in recs if r.write}
        seen: set = set()
        out: list[SiteRecord] = []
        for r in sorted(recs, key=lambda r: (r.path, r.lineno,
                                             not r.write, r.root,
                                             sorted(r.lockset))):
            if not r.write and (r.root, r.path, r.lineno,
                                r.lockset) in write_sites:
                continue
            key = (r.root, r.path, r.lineno, r.write, r.lockset)
            if key in seen:
                continue
            seen.add(key)
            out.append(r)
        return out

    @staticmethod
    def _find_race(recs, writes):
        """First (write, other-root access) pair with disjoint
        locksets; pairs where a lock was held SOMEWHERE are preferred
        so the witness names the broken discipline, not just two bare
        sites. Pairs at the SAME site are skipped: "one function,
        reachable from two roots, racing with itself at one line" is
        the entry-enumeration artifact (serve() run inline vs on a
        thread), not two distinct accesses — a genuinely racy field
        always has a second site to witness with."""
        best = None
        for w in writes:
            for o in recs:
                if o.root == w.root:
                    continue
                if (o.path, o.lineno) == (w.path, w.lineno):
                    continue
                if w.lockset & o.lockset:
                    continue
                pair = (w, o)
                if w.lockset or o.lockset:
                    return True, pair
                if best is None:
                    best = pair
        return (True, best) if best is not None else (False, None)

    @staticmethod
    def _candidate(recs) -> str | None:
        counts: dict[str, int] = {}
        for r in recs:
            for lk in r.lockset:
                counts[lk] = counts.get(lk, 0) + 1
        if not counts:
            return None
        return max(sorted(counts), key=lambda k: counts[k])

    # -- views ----------------------------------------------------------
    def shared_fields(self) -> list[FieldReport]:
        """Fields reachable from >= 2 roots with a write involved —
        the concurrency contract surface ``mp4j-lint races`` prints."""
        return [fr for fr in self.field_reports()
                if len(fr.roots) >= 2 and any(r.write
                                              for r in fr.records)]

    def to_text(self) -> str:
        shared = self.shared_fields()
        racy = [fr for fr in shared if fr.racy]
        lines = [f"{len(self.roots)} thread roots, {len(shared)} "
                 f"shared mutable fields, {len(racy)} with "
                 f"inconsistent locksets"]
        for fr in shared:
            locks = self._lock_coverage(fr)
            cov = ", ".join(
                f"{self.locks.locks[k].display}:{n}/{len(fr.records)}"
                for k, n in locks) or "none"
            verdict = "RACE" if fr.racy else "ok"
            lines.append(f"  {fr.display}  roots=[{', '.join(fr.roots)}]"
                         f"  locks held: {cov}  {verdict}")
            if fr.racy and fr.witness:
                w, o = fr.witness
                lines.append(
                    f"    write {w.path}:{w.lineno} ({w.func}, "
                    f"{w.root}) holds "
                    f"[{self._names(w.lockset)}] vs "
                    f"{'write' if o.write else 'read'} "
                    f"{o.path}:{o.lineno} ({o.func}, {o.root}) holds "
                    f"[{self._names(o.lockset)}]")
        return "\n".join(lines)

    def _lock_coverage(self, fr: FieldReport):
        counts: dict[str, int] = {}
        for r in fr.records:
            for lk in r.lockset:
                counts[lk] = counts.get(lk, 0) + 1
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def _names(self, lockset) -> str:
        return ", ".join(sorted(self.locks.locks[k].display
                                for k in lockset))

    def to_dot(self) -> str:
        """The shared-field -> lockset graph as GraphViz DOT: field
        boxes (red when racy), lock ovals, an edge per (field, lock)
        labeled with how many of the field's access sites hold it."""
        lines = ["digraph mp4j_shared_fields {",
                 "  rankdir=LR;",
                 '  node [fontname="monospace"];']
        shared = self.shared_fields()
        used_locks: set[str] = set()
        for fr in shared:
            color = ', color=red' if fr.racy else ''
            lines.append(
                f'  "{fr.owner}.{fr.attr}" [shape=box, '
                f'label="{fr.display}\\nroots: '
                f'{", ".join(fr.roots)}"{color}];')
            for lk, n in self._lock_coverage(fr):
                used_locks.add(lk)
                style = ("solid" if n == len(fr.records) else "dashed")
                lines.append(
                    f'  "{fr.owner}.{fr.attr}" -> "{lk}" '
                    f'[label="{n}/{len(fr.records)}", style={style}];')
        for lk in sorted(used_locks):
            d = self.locks.locks[lk]
            lines.append(f'  "{lk}" [shape=oval, '
                         f'label="{d.display}\\n{d.kind}"];')
        lines.append("}")
        return "\n".join(lines)
