"""R15 — roster-derived topology cached in a long-lived attribute.

Elastic membership (ISSUE 10) made the roster MUTABLE mid-job: a
replacement swaps a dead rank's roster entry, and a shrink renumbers
every survivor — ``self._rank``, ``self._n``, the host groups and the
leader sets all change at an ``abort_go``. The one safe pattern is the
roster-versioned accessor: ``ProcessCommSlave._set_roster`` derives
every topology quantity in one place, and everything else READS those
attributes at use time. Code that derives-and-caches its own copy
(``self._fanout = self._n - 1`` in ``__init__``, a member list squirreled
away at construction) keeps answering with the OLD topology after a
membership change — the silent-wrong-schedule class that deadlocks or
mispairs exchanges instead of failing loudly.

Heuristic: inside a class in ``comm/``, an assignment whose TARGET is a
``self.…`` attribute and whose VALUE reads a topology source through
``self`` — ``self._n`` / ``self._rank`` / ``self._roster`` /
``self.slave_num`` / ``self.rank`` / ``self._host_groups`` /
``self._members`` / ``self._leader`` / ``self._leaders`` — or calls
``_derive_host_groups``. Local variables (read-at-use-time) and plain
reads are never flagged; only the caching assignment is. Sanctioned
sites — the accessor itself, the identity mirrors it drives, and the
fixed-roster backends (thread/device groups cannot shrink or be
replaced mid-job) — are accepted in baseline.toml or carry inline
suppressions.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, attr_chain, call_name
from ytk_mp4j_tpu.analysis.report import Severity

# the roster-derived quantities _set_roster owns (reading one of these
# into a long-lived attribute is caching topology)
_SOURCES = frozenset({
    "_n", "_rank", "_roster", "slave_num", "rank",
    "_host_groups", "_members", "_leader", "_leaders",
})

# deriving helpers whose result IS topology
_DERIVERS = frozenset({"_derive_host_groups"})


def _reads_topology(expr: ast.AST) -> str | None:
    """The first topology source ``expr`` reads through ``self`` (or a
    deriving call), else None. F-string subtrees are pruned: a rank
    interpolated into a thread NAME or log label is cosmetic identity,
    not a schedule-bearing cache (the R11 operand-pruning precedent)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.JoinedStr):
            continue            # cosmetic: f"...{self._rank}..."
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if (chain and len(chain) == 2 and chain[0] == "self"
                    and chain[1] in _SOURCES):
                return chain[1]
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in _DERIVERS:
                return name + "()"
        stack.extend(ast.iter_child_nodes(node))
    return None


def _self_attr_target(target: ast.AST) -> str | None:
    """Dotted name of a ``self.…`` assignment target, else None."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            got = _self_attr_target(el)
            if got is not None:
                return got
        return None
    if not isinstance(target, ast.Attribute):
        return None
    chain = attr_chain(target)
    if chain and chain[0] == "self" and len(chain) >= 2:
        return ".".join(chain)
    return None


class R15TopologyCache(Rule):
    rule_id = "R15"
    severity = Severity.ERROR
    title = "roster-derived topology cached in a long-lived attribute"
    description = ("an attribute assignment derives its value from "
                   "rank/slave_num/roster topology; elastic membership "
                   "(replace/shrink) mutates those mid-job, so the "
                   "cache silently answers with the OLD topology — "
                   "read through the roster-versioned accessor "
                   "(_set_roster's attributes) at use time instead")
    example = """\
class Slave:
    def __init__(self, roster):
        self._n = len(roster)
        self._right = (self._rank + 1) % self._n    # stale after shrink
"""

    def _in_scope(self) -> bool:
        # class bodies only: a module-level constant cannot cache a
        # live object's topology, and free functions receive theirs
        # as arguments (read-at-call-time, which is the point)
        return self.ctx.in_dirs("comm") and len(self.scope) >= 2

    def visit_Assign(self, node):               # noqa: N802
        self._check(node, node.targets, node.value)

    def visit_AnnAssign(self, node):            # noqa: N802
        if node.value is not None:
            self._check(node, [node.target], node.value)

    def visit_AugAssign(self, node):            # noqa: N802
        self._check(node, [node.target], node.value)

    def _check(self, node, targets, value) -> None:
        if not self._in_scope():
            return
        src = _reads_topology(value)
        if src is None:
            return
        for tgt in targets:
            name = _self_attr_target(tgt)
            if name is None:
                continue
            if name.split(".", 1)[1] in _SOURCES:
                # writing a source itself is (re)derivation, not
                # caching — only the sanctioned sites do it, and they
                # are baselined as such; skipping here keeps the rule
                # about CONSUMERS
                continue
            self.report(node, (
                f"'{name}' caches topology derived from "
                f"'{src}': a replace/shrink membership change "
                "mutates rank/slave_num/roster mid-job and this "
                "attribute keeps the old answer — read the "
                "roster-versioned attributes (_set_roster) at use "
                "time instead"))
            return
