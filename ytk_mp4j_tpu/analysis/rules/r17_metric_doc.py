"""R17 — a metric family without a ``METRICS_DOC`` entry (doc drift).

The metrics plane's catalogue, :data:`ytk_mp4j_tpu.obs.metrics.
METRICS_DOC`, is the one table operators (and the README's metric
table) trust to enumerate every series the job can emit. A family
registered in code but absent from the table is invisible
observability: it scrapes fine, graphs fine, and nobody knows it
exists or what it means — exactly the drift this rule guards against
(ISSUE 12 satellite).

Two surfaces are checked:

- **registry registrations** anywhere in the package: a string-literal
  family name passed to ``<metrics>.inc(...)`` / ``set_gauge(...)`` /
  ``observe(...)`` (receiver heuristic: the terminal receiver name
  contains ``metric`` or is the conventional ``m``). An f-string
  family (``f"latency/{name}"``) is matched by its constant prefix
  against the table's ``<segment>`` wildcard keys.
- **Prometheus families** rendered in ``obs/metrics.py``: every
  ``# TYPE mp4j_*`` line (including f-string templates, matched by
  constant prefix) must name a documented family.

Fix: add the family's one-line entry to ``METRICS_DOC`` — or delete
the series.
"""

from __future__ import annotations

import ast
import re

from ytk_mp4j_tpu.analysis.engine import Rule, call_name, receiver_chain
from ytk_mp4j_tpu.analysis.report import Severity

_REGISTRY_METHODS = frozenset({"inc", "set_gauge", "observe"})
_TYPE_RE = re.compile(r"#\s*TYPE\s+(mp4j_[a-z0-9_]*)")


def _doc_keys() -> tuple:
    # resolved lazily so snippet tests exercise the REAL catalogue —
    # the rule's whole point is agreement with the shipped table
    from ytk_mp4j_tpu.obs.metrics import METRICS_DOC
    return tuple(METRICS_DOC)


def documented(name: str, keys=None, prefix: bool = False) -> bool:
    """Whether ``name`` matches the catalogue: exactly, via a
    ``<segment>`` wildcard key's constant prefix, or — for an
    f-string's leading constant (``prefix=True``) — as a prefix of
    any key."""
    keys = _doc_keys() if keys is None else keys
    if name in keys:
        return True
    for k in keys:
        if "<" in k and name.startswith(k.split("<", 1)[0]):
            return True
        if prefix and name and k.startswith(name):
            return True
    return False


class R17MetricDoc(Rule):
    rule_id = "R17"
    severity = Severity.ERROR
    title = "metric family missing from METRICS_DOC"
    description = ("a metric family is registered or rendered without "
                   "a matching obs.metrics.METRICS_DOC entry — an "
                   "undocumented series is invisible observability")
    example = """\
def book(self):
    self._metrics.inc("nope/undocumented_family", 1)
"""

    def visit_Call(self, node: ast.Call):         # noqa: N802
        name = call_name(node)
        if name in _REGISTRY_METHODS and node.args:
            recv = receiver_chain(node)
            term = recv[-1] if recv else ""
            if "metric" in term or term == "m":
                self._check_family_arg(node, node.args[0])
        self.generic_visit(node)

    def _check_family_arg(self, call: ast.Call, arg: ast.AST) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not documented(arg.value):
                self.report(call, (
                    f"metric family {arg.value!r} has no METRICS_DOC "
                    "entry — document it in obs/metrics.py (or use a "
                    "<segment> wildcard key) so the series is not "
                    "invisible observability"))
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            lead = (head.value if isinstance(head, ast.Constant)
                    and isinstance(head.value, str) else "")
            if not documented(lead, prefix=True):
                self.report(call, (
                    f"dynamic metric family with prefix {lead!r} "
                    "matches no METRICS_DOC key — add a "
                    "'<segment>'-style wildcard entry"))

    def visit_Constant(self, node: ast.Constant):  # noqa: N802
        # Prometheus `# TYPE` lines, only in the renderer module —
        # elsewhere a matching string is quoted documentation
        if self.ctx.path.endswith("obs/metrics.py") \
                and isinstance(node.value, str):
            for fam in _TYPE_RE.findall(node.value):
                # an f-string template's constant half ends mid-name
                # (`mp4j_rank_`): prefix-match those
                partial = node.value.rstrip().endswith(fam)
                if not documented(fam, prefix=partial):
                    self.report(node, (
                        f"Prometheus family {fam!r} is rendered but "
                        "has no METRICS_DOC entry — the endpoint "
                        "serves a series the catalogue denies exists"))
        self.generic_visit(node)
