"""R13 — raw-byte serialization of a possibly non-contiguous array.

The audit plane (ISSUE 8) compares digests of the "same" payload
across ranks; the columnar/framed planes serialize arrays by raw
buffer. Both are only correct when the bytes are a CANONICAL function
of the values: ``x.tobytes()`` / ``memoryview(x)`` on a strided view
walks (or refuses) the underlying buffer differently than on a
contiguous copy, and a non-native-endian array byte-serializes
differently than an equal native one. Two ranks holding equal VALUES
in different layouts then digest differently — a **false divergence**
that fires the audit alarm on a healthy job (or frames corrupt bytes
on the wire). ``np.ascontiguousarray`` + a dtype/byte-order pin before
the byte read is the discipline (see ``obs.audit.canon_array``).

Heuristic: in ``comm/``, ``obs/``, ``transport/`` a ``memoryview(x)``
call on a bare name, or any ``x.tobytes()`` call, fires unless the
name was PINNED in its scope — assigned from a contiguity-guaranteeing
constructor (``ascontiguousarray``, ``astype``, ``empty``, ``zeros``,
``ones``, ``frombuffer``, ``bytearray``, ``bytes``, ``copy``,
``mmap``, ``canon_array``), from an already-pinned name, or from a
subscript of one (slices of freshly constructed 1-D buffers).
``memoryview(f(...))`` with a call argument stays quiet — the callee
owns that contract (e.g. ``_raw_view``, whose own internal
``memoryview`` is the baselined sanctioned site: its callers pin).
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Finding, Severity

_PIN_FNS = frozenset({
    "ascontiguousarray", "astype", "empty", "zeros", "ones",
    "frombuffer", "bytearray", "bytes", "copy", "mmap", "canon_array",
})


class R13DigestContiguity(Rule):
    rule_id = "R13"
    severity = Severity.ERROR
    title = "raw-byte read of a possibly non-contiguous array"
    description = (".tobytes()/memoryview on an array that may be "
                   "non-contiguous or non-native-endian makes digests "
                   "and wire bytes a function of memory LAYOUT, not "
                   "values — a false-divergence hazard; pin with "
                   "np.ascontiguousarray (+ dtype/byte order) first")
    example = """\
def digest(arr):
    return crc32(arr.tobytes())     # strided/BE layout changes bytes
"""

    _MSG = ("{what} on {name!r} without a contiguity/dtype pin: a "
            "strided or non-native-endian array serializes different "
            "bytes for equal values (audit false divergence / corrupt "
            "frame); pass it through np.ascontiguousarray (or "
            "obs.audit.canon_array) first")

    def run(self, ctx):
        # collected during the walk, resolved afterwards (the pinning
        # assignment may appear after the use in source order)
        self._pinned: dict[str, set[str]] = {}
        self._uses: list[tuple[str, str, str, ast.AST]] = []
        return super().run(ctx)

    def visit_Module(self, node):               # noqa: N802
        if not self.ctx.in_dirs("comm", "obs", "transport"):
            return
        self.generic_visit(node)
        for what, name, qual, call in self._uses:
            if name and name in self._pinned.get(qual, ()):
                continue
            self.findings.append(Finding(
                rule=self.rule_id, severity=self.severity,
                path=self.ctx.path,
                line=getattr(call, "lineno", 0),
                col=getattr(call, "col_offset", 0) + 1,
                message=self._MSG.format(what=what,
                                         name=name or "<expr>"),
                context=qual))

    def visit_Assign(self, node):               # noqa: N802
        pin = self._pins(node.value)
        if pin:
            names = self._pinned.setdefault(self.qualname(), set())
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        self.generic_visit(node)

    def _pins(self, value: ast.AST) -> bool:
        """Whether an assignment RHS guarantees a canonical buffer."""
        if isinstance(value, ast.Call):
            return call_name(value) in _PIN_FNS
        if isinstance(value, ast.Name):
            return value.id in self._pinned.get(self.qualname(), ())
        if isinstance(value, ast.Subscript):
            base = value.value
            return (isinstance(base, ast.Name)
                    and base.id in self._pinned.get(self.qualname(), ()))
        return False

    def visit_Call(self, node):                 # noqa: N802
        qual = self.qualname()
        f = node.func
        if (isinstance(f, ast.Name) and f.id == "memoryview"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)):
            self._uses.append(("memoryview()", node.args[0].id, qual,
                               node))
        elif isinstance(f, ast.Attribute) and f.attr == "tobytes":
            name = f.value.id if isinstance(f.value, ast.Name) else ""
            self._uses.append((".tobytes()", name, qual, node))
        self.generic_visit(node)
