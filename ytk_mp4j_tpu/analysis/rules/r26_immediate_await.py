"""R26 — an in-loop ``i*`` submit awaited with no compute between.

The whole point of the nonblocking API (ISSUE 11) — and of the trainer
overlap loops built on it (ISSUE 17) — is that the exchange runs WHILE
the caller computes something independent. A loop body that submits a
nonblocking collective and immediately awaits it::

    for g in grads:
        f = comm.iallreduce(g)
        f.wait()

pays the submission machinery (future allocation, queue handoff,
progression-thread wakeup) and buys zero overlap — it is strictly
slower than the blocking twin, and usually indicates the author MEANT
to overlap and lost the compute statement in a refactor. The fix is
one of: move the next step's independent compute between submit and
await, batch several submits before one ``wait_all()`` drain (the
engine pipelines them), or call the blocking collective.

Heuristic (loop-body statement order, one loop at a time): an
assignment ``f = comm.i*(...)`` among a loop's DIRECT statements opens
a "clean" future; a later ``f.wait()`` / ``f.result()`` — or a
``comm.wait_all()`` — reached while the future is still clean fires
the rule. ANY other statement (including compound statements, whose
bodies are not inspected) counts as compute and marks every open
future dirty — conservative in the non-firing direction, so the rule
only speaks when the iteration provably interleaves nothing. Nested
loops are checked on their own visit.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import (
    Rule, call_name, receiver_chain)
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules.r16_unawaited_future import I_METHODS

_AWAITS = frozenset({"wait", "result"})


class R26ImmediateAwait(Rule):
    rule_id = "R26"
    severity = Severity.WARNING
    title = "in-loop i* submit awaited with no intervening compute"
    description = (
        "a nonblocking collective submitted inside a loop is awaited "
        "in the same iteration with no compute statement in between: "
        "the overlap is defeated — interleave independent compute, "
        "batch submits before one wait_all(), or use the blocking "
        "twin")
    example = """\
def epoch(comm, grads):
    for g in grads:
        f = comm.iallreduce(g)
        f.wait()
"""

    def visit_For(self, node):              # noqa: N802
        self._check_loop(node)
        self.generic_visit(node)

    visit_AsyncFor = visit_For              # noqa: N815

    def visit_While(self, node):            # noqa: N802
        self._check_loop(node)
        self.generic_visit(node)

    @staticmethod
    def _submit_of(stmt: ast.stmt):
        """``f = comm.i*(...)`` -> (name, call, receiver) else None."""
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                or not isinstance(stmt.targets[0], ast.Name):
            return None
        call = stmt.value
        if not isinstance(call, ast.Call) \
                or call_name(call) not in I_METHODS:
            return None
        recv = receiver_chain(call)
        return (stmt.targets[0].id, call,
                tuple(recv) if recv else None)

    @staticmethod
    def _await_of(stmt: ast.stmt):
        """``f.wait()`` / ``r = f.result()`` -> ("future", f, call);
        ``comm.wait_all()`` -> ("all", receiver, call); else None."""
        call = None
        if isinstance(stmt, ast.Expr):
            call = stmt.value
        elif isinstance(stmt, ast.Assign):
            call = stmt.value
        if not isinstance(call, ast.Call):
            return None
        name = call_name(call)
        if name in _AWAITS and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name):
            return "future", call.func.value.id, call
        if name == "wait_all":
            recv = receiver_chain(call)
            return "all", tuple(recv) if recv else None, call
        return None

    def _check_loop(self, loop: ast.AST) -> None:
        # clean: futures submitted this iteration with NO compute
        # statement since — name -> (submit line, receiver)
        clean: dict[str, tuple[int, tuple | None]] = {}
        for stmt in loop.body:
            sub = self._submit_of(stmt)
            if sub is not None:
                name, _call, recv = sub
                clean[name] = (stmt.lineno, recv)
                continue
            aw = self._await_of(stmt)
            if aw is None:
                # compute: every open submit earned its overlap
                clean.clear()
                continue
            kind, key, call = aw
            if kind == "future":
                hit = clean.pop(key, None)
                if hit is not None:
                    self.report(call, (
                        f"future '{key}' (line {hit[0]}) is awaited "
                        f"with no compute since its submit — the "
                        f"overlap is defeated; interleave compute or "
                        f"use the blocking twin"))
                # an await of a dirty future blocks but computes
                # nothing: other clean futures stay clean
            else:
                drained = [(f, ln) for f, (ln, recv) in clean.items()
                           if key is None or recv is None
                           or recv == key]
                for f, _ln in drained:
                    clean.pop(f)
                if len(drained) == 1:
                    # a LONE submit drained immediately is pointless;
                    # several batched submits pipeline against each
                    # other (the engine's k-fold amortization) and
                    # pass
                    f, ln = drained[0]
                    self.report(call, (
                        f"future '{f}' (line {ln}) is drained by "
                        f"wait_all() with no compute since its "
                        f"submit — the overlap is defeated; "
                        f"interleave compute or use the blocking "
                        f"twin"))
