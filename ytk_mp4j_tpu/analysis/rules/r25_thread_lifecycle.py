"""R25 — thread started without join/daemon/stop registration
(ISSUE 16).

A non-daemon thread that nobody joins outlives ``close()``: it pins
the interpreter at exit, keeps sockets and segments alive past
teardown, and — in this package — keeps COLLECTING on a plane the
master already declared dead. Every sanctioned thread in the comm
stack is either daemonized at construction, joined at shutdown, or
parked in a registry some drain loop joins; this rule makes that the
checked invariant.

Accepted lifecycles: ``daemon=True`` (or ``t.daemon = True`` before
start), a ``join()``/``cancel()`` in the constructing function, or
storage in an attribute/list that ANY function in the program joins,
cancels or daemonizes (the whole-program registry). Handing the
thread to another call transfers the obligation and is accepted.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity

_DIRS = ("comm", "resilience", "obs", "transport", "analysis")


class R25ThreadLifecycle(ProgramRule):
    rule_id = "R25"
    severity = Severity.ERROR
    title = "thread started without join/daemon/stop registration"
    description = ("a Thread/Timer is started with no shutdown "
                   "story: not daemonized, never joined/cancelled, "
                   "and not stored anywhere the program drains — it "
                   "outlives close() and pins interpreter exit")
    example = """\
import threading

class Pump:
    def start(self):
        self._t = threading.Thread(target=self._drain)
        self._t.start()

    def _drain(self):
        pass
"""

    def run_program(self, program):
        model = program.resources
        out = []
        seen = set()
        for tl in model.thread_leaks:
            segs = tl.path.split("/")
            if not any(p in segs for p in _DIRS):
                continue
            key = (tl.path, tl.lineno)
            if key in seen:
                continue
            seen.add(key)
            out.append(self.finding(
                tl.path, tl.lineno,
                f"thread has no shutdown story: {tl.detail} — "
                f"daemonize it at construction, join it at close, or "
                f"register it with a joined/cancelled registry",
                context=tl.func))
        return out
