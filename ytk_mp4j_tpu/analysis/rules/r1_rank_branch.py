"""R1 — collective call under a rank-dependent branch.

The classic MPI deadlock shape: a branch conditioned on the caller's
rank (``rank`` / ``thread_rank`` / ``proc_rank`` / the algorithms'
``vr`` / ``_tr``) where the two arms do not issue the same collective
schedule. Ranks taking different arms then disagree about which
collective comes next and the job hangs with no error.

Balanced branches (both arms issue the same multiset of collectives)
are fine — e.g. ``if rank == 0: broadcast(...) else: broadcast(...)``
with different operands. Point-to-point sends/receives inside rank
branches are NOT flagged: that is the normal shape of the binomial /
halving algorithms themselves.

Known-good idioms that structurally match (a leader thread joining the
process barrier between two thread barriers) carry inline
``# mp4j-lint: disable=R1`` suppressions documenting why they are safe.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules.common import (
    collective_calls, expr_mentions_rank)


class R1RankConditionalCollective(Rule):
    rule_id = "R1"
    severity = Severity.ERROR
    title = "rank-conditional collective"
    description = ("collective/barrier call inside a branch conditioned "
                   "on rank, without a matching call on the other arm")
    example = """\
def step(comm, grads):
    comm.allreduce_array(grads)
    if comm.rank == 0:
        comm.barrier()          # ranks != 0 never arrive
"""

    def visit_If(self, node: ast.If):           # noqa: N802
        if expr_mentions_rank(node.test):
            body_calls = collective_calls(node.body)
            orelse_calls = collective_calls(node.orelse)
            if body_calls != orelse_calls:
                only = body_calls - orelse_calls or orelse_calls - body_calls
                names = ", ".join(sorted(only))
                self.report(node, (
                    f"collective schedule differs across a rank-dependent "
                    f"branch ({names} on one arm only): ranks taking "
                    f"different arms will deadlock"))
        self.generic_visit(node)
