"""R5 — bare ``except:`` and swallowed broad exceptions in comm paths.

Two shapes:

- ``except:`` with no type, anywhere: catches ``SystemExit`` /
  ``KeyboardInterrupt`` and hides protocol violations — in a collective
  this converts a crash (diagnosable) into a rank silently falling out
  of the schedule (deadlock for everyone else).
- ``except Exception: pass`` (broad type, body only pass/continue) in
  the comm hot paths (``comm/``, ``transport/``, ``ops/``): a transport
  or reduction error vanishes and the ranks drift apart. Narrow types
  (``except OSError: pass``) are accepted — swallowing a *specific*
  failure is a documented decision, swallowing everything is not.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, attr_chain
from ytk_mp4j_tpu.analysis.report import Severity

_BROAD = {"Exception", "BaseException"}
_HOT_DIRS = ("comm", "transport", "ops")


def _is_noop_body(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue        # docstring / ellipsis
        return False
    return True


class R5SwallowedException(Rule):
    rule_id = "R5"
    severity = Severity.ERROR
    title = "swallowed exception in comm path"
    description = ("bare except (anywhere) or broad except with a no-op "
                   "body in comm/transport/ops hot paths")
    example = """\
def relay():
    try:
        forward()
    except:                     # bare: catches KeyboardInterrupt too
        raise RuntimeError("relay failed")
"""

    def visit_ExceptHandler(self, node: ast.ExceptHandler):  # noqa: N802
        if node.type is None:
            self.report(node, (
                "bare 'except:' catches everything including "
                "KeyboardInterrupt — name the failure being handled"))
        elif self.ctx.in_dirs(*_HOT_DIRS) and _is_noop_body(node.body):
            types = (node.type.elts if isinstance(node.type, ast.Tuple)
                     else [node.type])
            names = [chain[-1] for t in types if (chain := attr_chain(t))]
            if any(n in _BROAD for n in names):
                self.report(node, (
                    f"'except {'/'.join(names)}: pass' in a comm hot path "
                    f"swallows transport/reduction failures — ranks drift "
                    f"out of the collective schedule silently"))
        self.generic_visit(node)
