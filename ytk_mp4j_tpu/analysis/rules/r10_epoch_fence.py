"""R10 — peer-channel I/O that bypasses the epoch-fence wrapper.

ISSUE 5's recovery engine fences the peer data plane: every channel
acquisition on a collective path must go through the slave's
``_fenced(peer)`` wrapper, which raises while an abort round is in
flight (or when the running attempt is a zombie pinned to a stale
epoch) instead of letting the caller dial into — or keep writing to —
a torn-down epoch. A send/recv on a channel obtained straight from
``_channel(...)`` (or built bare from ``Channel(...)``/``connect(...)``)
skips that check: in the recovery window it can consume frames that
belong to the retry stream, the exact corruption the fence exists to
prevent.

Heuristic: inside a ``*CommSlave`` class in ``comm/``, flag a
channel-I/O method call (``send_array``/``recv_array``/
``recv_array_into``/``send_map_columns``/``recv_map_columns``/
``send_raw``/``recv_raw_into``/``send_obj``/``recv``) whose receiver
is ``self._channel(...)`` directly, or a local name bound from
``self._channel(...)`` / ``Channel(...)`` / ``connect(...)`` in the
same function. Receivers from ``self._fenced(...)`` — and the master
control channel, which has no epoch — are not flagged. The sanctioned
sites are the two peer-handshake exchanges (they *establish* a
channel's epoch, so the fence cannot apply yet): accepted in
baseline.toml.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity

# channel I/O surface (transport.channel.Channel)
_PEER_IO = frozenset({
    "send_array", "recv_array", "recv_array_into", "send_map_columns",
    "recv_map_columns", "send_raw", "recv_raw_into", "send_obj", "recv",
})

# expressions that produce an UNFENCED channel
_RAW_PRODUCERS = frozenset({"_channel", "Channel", "TcpChannel",
                            "ShmChannel", "connect"})


def _producer(expr: ast.AST) -> str | None:
    """``self._channel(...)`` / ``Channel(...)`` / ``connect(...)`` ->
    the producer name; None otherwise."""
    if isinstance(expr, ast.Call):
        name = call_name(expr)
        if name in _RAW_PRODUCERS:
            return name
    return None


def _raw_bound_names(fn: ast.AST) -> dict[str, str]:
    """Local names assigned from a raw channel producer in ``fn``
    (one level of data flow, like R9's dict tracking)."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            prod = _producer(node.value)
            if prod is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = prod
    return out


class R10EpochFenceBypass(Rule):
    rule_id = "R10"
    severity = Severity.ERROR
    title = "peer-channel I/O bypasses the epoch fence"
    description = ("a send_*/recv_* call runs on a channel obtained "
                   "outside the slave's _fenced() wrapper; during a "
                   "recovery round it can write into (or steal frames "
                   "from) the retry's stream — acquire peer channels "
                   "via _fenced(peer) on every data path")
    example = """\
class ProcessCommSlave:
    def _send(self, peer, data):
        ch = self._channel(peer)        # not _fenced(peer)
        ch.send_array(data)
"""

    def visit_ClassDef(self, node):             # noqa: N802
        if self.ctx.in_dirs("comm") and "CommSlave" in node.name:
            self.scope.append(node.name)
            try:
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.scope.append(item.name)
                        try:
                            self._scan(item)
                        finally:
                            self.scope.pop()
            finally:
                self.scope.pop()
            return
        self.generic_visit_scoped(node)

    def _scan(self, fn: ast.AST) -> None:
        bound = _raw_bound_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _PEER_IO:
                continue
            recv = node.func.value
            prod = _producer(recv)
            if prod is None and isinstance(recv, ast.Name):
                prod = bound.get(recv.id)
            if prod is not None:
                self.report(node, (
                    f"{node.func.attr}() on a channel from {prod}() "
                    "bypasses the epoch fence; acquire the channel "
                    "via self._fenced(peer) so an in-flight abort "
                    "round (or a zombie attempt) cannot touch the "
                    "new epoch's stream"))
