"""R14 — telemetry-artifact write without the torn-write discipline.

Postmortem bundles, audit dumps, trace exports and sink manifests are
read by OTHER processes, possibly while the writer is dying: a plain
``open(path, "w")`` + ``json.dump`` torn by a crash leaves a
syntactically truncated file at the REAL path, and a reader (the
``mp4j-scope`` report, the bench-diff gate) either crashes on it or —
worse — silently trusts a half-written document. The discipline is
tmp-file + ``os.replace``: the visible path only ever holds a
complete artifact (see ``obs.postmortem._dump``). Append-only streams
are the one exception — the durable sink's crc-framed segments
(``obs/sink.py``) tolerate a torn tail BY DESIGN and must append in
place; such sites carry a baseline entry arguing exactly that.

Heuristic: in ``obs/`` (where every telemetry/postmortem/sink writer
lives), an ``open(..., mode)`` call whose mode string writes (``w``/
``a``/``x``/``+``) fires unless the ENCLOSING function also calls
``os.replace`` (the tmp+rename discipline — the lint is scope-local,
like R13's pin tracking). Reads (``r``/``rb``/default mode) never
fire.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, attr_chain, call_name
from ytk_mp4j_tpu.analysis.report import Severity

_WRITE_CHARS = set("wax+")


class R14TornWrite(Rule):
    rule_id = "R14"
    severity = Severity.ERROR
    title = "telemetry artifact written without tmp+os.replace"
    description = ("a write-mode open() in obs/ whose scope never "
                   "calls os.replace can tear mid-crash and leave a "
                   "truncated artifact at the real path; write to a "
                   ".tmp sibling and os.replace it (append-only "
                   "crc-framed streams are baselined exceptions)")
    example = """\
import json

def dump(path, obj):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)      # a crash mid-write leaves a torn file
"""
    example_path = "ytk_mp4j_tpu/obs/example.py"

    _MSG = ("open(..., {mode!r}) without os.replace in scope: a crash "
            "mid-write leaves a torn file at the visible path that "
            "readers may trust as complete; write a tmp sibling and "
            "os.replace it (or baseline the site if the format is "
            "append-only and torn-tail tolerant)")

    def run(self, ctx):
        self._opens: list[tuple[str, str, ast.Call]] = []
        self._replacing: set[str] = set()
        return super().run(ctx)

    def visit_Module(self, node):               # noqa: N802
        if not self.ctx.in_dirs("obs"):
            return
        self.generic_visit(node)
        for mode, qual, call in self._opens:
            if qual in self._replacing:
                continue
            self.findings.append(self._finding(call, mode, qual))

    def _finding(self, call, mode, qual):
        from ytk_mp4j_tpu.analysis.report import Finding
        return Finding(
            rule=self.rule_id, severity=self.severity,
            path=self.ctx.path,
            line=getattr(call, "lineno", 0),
            col=getattr(call, "col_offset", 0) + 1,
            message=self._MSG.format(mode=mode),
            context=qual)

    def visit_Call(self, node):                 # noqa: N802
        qual = self.qualname()
        name = call_name(node)
        if name == "replace":
            chain = attr_chain(node.func)
            if chain and chain[0] == "os":
                self._replacing.add(qual)
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = self._mode(node)
            if mode is not None and _WRITE_CHARS & set(mode):
                self._opens.append((mode, qual, node))
        self.generic_visit(node)

    @staticmethod
    def _mode(node: ast.Call) -> str | None:
        """The literal mode string of an open() call (positional or
        keyword); None for default/read-only or a computed mode (a
        computed mode is someone else's contract)."""
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        else:
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
        if mode is None:
            return None
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None
