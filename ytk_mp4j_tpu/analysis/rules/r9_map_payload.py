"""R9 — pickled dict payload on a collective map path.

ISSUE 4's columnar data plane ships numeric-operand map collectives as
(codes:int32, values) column pairs through the persistent key codec
(``comm.keycodec``): one vectorized encode per call, framed-array wire
frames, sorted-union merges. A pickled whole-dict send on a map path
re-introduces the per-call Kryo-analogue cost the codec exists to
amortize — and, worse, a rank that pickles while its exchange partner
expects column frames corrupts the wire protocol (the map-plane
equivalent of R4's operand mismatch). The ONE sanctioned pickle site is
the negotiated fallback helper (``_send_map_obj``: object values,
object operators, un-codec-able key mixes), accepted in baseline.toml.

Heuristic: inside a function in ``comm/`` whose name contains ``map``,
a ``_send`` / ``send_obj`` / ``_sendrecv`` call whose payload argument
is dict-shaped — a dict display, a ``dict(...)`` call, a name bound to
one in the same function, a conventional map identifier (``d``,
``acc``, ``m``, ``merged``, ``recv``, ``share``/``shares``, ``union``),
or a subscript of one (``shares[peer]``). Negotiation headers (tuples)
and column frames (``send_map_columns`` / ``send_array``) are not
flagged.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity

# callee -> index of the payload argument
_SEND_CALLS = {"_send": 1, "send_obj": 0, "_sendrecv": 2}

# the repo's conventional map-payload identifiers (R1-style vocabulary)
_MAP_NAMES = frozenset(
    {"d", "acc", "m", "merged", "recv", "share", "shares", "union"})


def _dict_bound_names(fn: ast.AST) -> set[str]:
    """Names assigned from a dict display or ``dict(...)`` call
    anywhere in ``fn`` (one level of data flow — enough for the
    ``acc = dict(d)`` shape the map tree uses)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_dict_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _is_dict_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Dict) or isinstance(expr, ast.DictComp):
        return True
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id == "dict")


def _is_dictish(expr: ast.AST, bound: set[str]) -> bool:
    if _is_dict_expr(expr):
        return True
    if isinstance(expr, ast.Name):
        return expr.id in _MAP_NAMES or expr.id in bound
    if isinstance(expr, ast.Subscript):
        return _is_dictish(expr.value, bound)
    return False


class R9PickledMapPayload(Rule):
    rule_id = "R9"
    severity = Severity.ERROR
    title = "pickled map payload on a collective map path"
    description = ("a map collective sends a pickled dict instead of "
                   "routing through the columnar (codes, values) "
                   "encoder; outside the negotiated fallback this "
                   "re-pays the per-call serialization the key codec "
                   "amortizes and can desync the wire plane")
    example = """\
def reduce_map(self, d, operand, operator, root):
    acc = dict(d)
    self._send(0, acc, compress=operand.compress)   # pickled dict
"""

    def visit_FunctionDef(self, node):          # noqa: N802
        if self.ctx.in_dirs("comm") and "map" in node.name.lower():
            self.scope.append(node.name)
            try:
                self._scan(node)
            finally:
                self.scope.pop()
            return  # _scan covered the whole subtree (incl. nested defs)
        self.generic_visit_scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

    def _scan(self, fn: ast.AST) -> None:
        bound = _dict_bound_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            idx = _SEND_CALLS.get(call_name(node))
            if idx is None or len(node.args) <= idx:
                continue
            if _is_dictish(node.args[idx], bound):
                self.report(node, (
                    "pickled dict payload on a map collective path: "
                    "numeric-operand maps must travel as (codes, "
                    "values) columns through the key codec "
                    "(send_map_columns); only the negotiated fallback "
                    "site may pickle whole maps"))
