"""R20 — blocking call while holding a lock (ISSUE 14).

The class behind the heartbeat/ctl stalls: a thread that blocks on a
peer (socket recv/send, ``Event.wait``/``Condition.wait`` on a
DIFFERENT object, a thread ``join``, a subprocess, a collective
``wait()``) while holding a lock turns one slow peer into a stalled
PLANE — every other thread needing that lock now waits on the peer
too, and if the peer needs one of those threads to make progress the
job deadlocks. Per-function AST cannot see it: the lock is taken in
one function and the blocking call sits three frames deeper.

The lock model supplies both halves: per-call-site held-lock sets and
each callee's transitively reachable blocking operations (with one
witness chain). R20 charges the frame WHERE THE LOCK IS HELD — the
fix site — naming the lock, the operation, and the chain.

Exemptions by construction: a ``wait()``/``wait_for()`` on the held
condition itself RELEASES it for the duration (the house barrier
pattern) and is only charged against OTHER held locks. Deliberate
serialize-sends-under-a-dedicated-lock sites (``_master_send``) carry
baseline entries arguing the bound.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity

_DIRS = ("comm", "resilience", "obs", "transport", "analysis")


class R20BlockingUnderLock(ProgramRule):
    rule_id = "R20"
    severity = Severity.ERROR
    title = "blocking call under a held lock"
    description = ("socket/channel I/O, waits on another object, "
                   "thread joins, subprocesses or collective wait() "
                   "reached while a lock is held (interprocedurally): "
                   "one slow peer stalls every thread that needs the "
                   "lock — move the blocking call outside the held "
                   "region")
    example = """\
import threading

class Slave:
    def __init__(self, chan):
        self._lock = threading.Lock()
        self._chan = chan

    def flush(self, obj):
        with self._lock:
            self._ship(obj)         # blocks a peer away, lock held

    def _ship(self, obj):
        self._chan.send_obj(obj)    # the blocking frame
"""

    def run_program(self, program):
        model = program.locks
        out = []
        seen = set()
        for fkey, s in sorted(model.summaries.items()):
            fi = s.func
            if not fi.module.ctx.in_dirs(*_DIRS):
                continue
            for b in s.blockers:
                for held in b.held:
                    self._charge(model, out, seen, fi, held,
                                 b.lineno, b.what, (fi.display,),
                                 b.recv_lock)
            for call in s.calls:
                if not call.held:
                    continue
                for ckey in call.callees:
                    blk = model.trans_blockers.get(ckey)
                    if not blk:
                        continue
                    for (terminal, recv_lock), ent in sorted(
                            blk.items(), key=lambda kv: kv[0][0]):
                        what = ent[2] if ent[0] == "direct" else ent[3]
                        tail, _ = model._chase(
                            model.trans_blockers, ckey,
                            (terminal, recv_lock))
                        for held in call.held:
                            self._charge(
                                model, out, seen, fi, held,
                                call.lineno, what,
                                (fi.display,) + tail, recv_lock)
        return out

    def _charge(self, model, out, seen, fi, held_lock, lineno, what,
                chain, recv_lock):
        if recv_lock is not None and recv_lock == held_lock:
            return      # wait on the held condition releases it
        key = (fi.key, held_lock, what, lineno)
        if key in seen:
            return
        seen.add(key)
        lock = model.locks[held_lock]
        via = (" via " + " -> ".join(chain) if len(chain) > 1 else "")
        out.append(self.finding(
            fi.path, lineno,
            f"blocking {what} reached while holding "
            f"{lock.display}{via}: one slow peer stalls every thread "
            f"that needs the lock — move the blocking call outside "
            f"the held region (mint under the lock, dispatch from an "
            f"outbox outside it)",
            context=fi.display))
