"""R24 — resource leaked on an exception path (ISSUE 16).

The fd-reuse hardening pass, machine-checked: a socket, file, shm
segment, transport channel or bare ``acquire()`` whose release sits
AFTER a statement that can raise — with no ``try/finally``, no
``with``, and no ownership transfer between the acquire and that
edge — leaks exactly when the peer misbehaves, which is exactly when
the job can least afford a dangling fd or a stuck lock. The resource
model walks every function's paths and charges the ACQUIRE site (the
fix site), naming the first unprotected raising statement.

Ownership transfer ends this function's liability: returning the
resource, storing it in an attribute/registry (the
``_drain_dead_channels`` pattern owns what ``self._channels`` holds),
or passing it to another call. Straight-line code that never releases
at all is the degenerate case and is also charged.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity

_DIRS = ("comm", "resilience", "obs", "transport", "analysis")


class R24ResourceLeak(ProgramRule):
    rule_id = "R24"
    severity = Severity.ERROR
    title = "resource leaked on an exception path"
    description = ("a socket/file/segment/channel/lock acquired here "
                   "is still unreleased when a later statement can "
                   "raise, and no try/finally, with-block or "
                   "ownership transfer covers that edge — the "
                   "exception leaks the fd (or wedges the lock)")
    example = """\
import socket

def probe(host):
    s = socket.create_connection((host, 9999))
    s.sendall(b"ping")          # raises -> fd leaked
    reply = s.recv(16)
    s.close()
    return reply
"""
    example_path = "ytk_mp4j_tpu/comm/example.py"

    def run_program(self, program):
        model = program.resources
        out = []
        seen = set()
        for leak in model.leaks:
            segs = leak.path.split("/")
            if not any(p in segs for p in _DIRS):
                continue
            key = (leak.path, leak.lineno, leak.name)
            if key in seen:
                continue
            seen.add(key)
            if leak.kind == "lock":
                msg = (f"lock {leak.name} acquired outside 'with' is "
                       f"not released on the exception edge: "
                       f"{leak.risk_desc} can raise first — use "
                       f"'with', or release in a try/finally")
            else:
                msg = (f"{leak.kind} '{leak.name}' acquired here may "
                       f"leak: {leak.risk_desc} can raise before the "
                       f"release, and no try/finally, with-block or "
                       f"ownership transfer covers that edge — wrap "
                       f"the acquire in try/finally or hand the "
                       f"resource off first")
            out.append(self.finding(
                leak.path, leak.lineno, msg, context=leak.func))
        return out
