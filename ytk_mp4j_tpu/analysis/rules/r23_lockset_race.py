"""R23 — inconsistent lockset on a shared field (ISSUE 16).

The Eraser discipline: every shared mutable field is protected by SOME
fixed lock held at every access. The race model enumerates thread
roots (``Thread(target=)``, ``Timer`` callbacks, registered hooks, the
public collective surface), propagates held-lock contexts along the
call graph, and records every field access with its lockset. A field
reachable from two roots with a write whose lockset shares nothing
with another root's access is a data race the next adversarial
interleaving can realize — torn progress tuples and eviction-race
segment loss were exactly this class.

The finding charges the WRITE witness (the fix site), names both
sites with their roots and locksets, and names the candidate lock —
the one most of the field's accesses already hold. Deliberate
lock-free publication (the shm ring head/tail indices, the poison
flag) carries reasoned baseline entries instead of a lock.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity

_DIRS = ("comm", "resilience", "obs", "transport", "analysis")


def _in_dirs(path: str) -> bool:
    segs = path.split("/")
    return any(p in segs for p in _DIRS)


class R23LocksetRace(ProgramRule):
    rule_id = "R23"
    severity = Severity.ERROR
    title = "inconsistent lockset on a shared field"
    description = ("a field reachable from two thread roots is "
                   "written with a lockset sharing no lock with "
                   "another root's access: no lock orders the two "
                   "sites, so the next interleaving tears it — hold "
                   "the candidate lock at every access, or argue the "
                   "lock-free publication in a baseline entry")
    example = """\
import threading

class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "idle"
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        self.state = "running"      # no lock held

    def status(self):
        with self._lock:
            return self.state       # reader holds _lock
"""

    def run_program(self, program):
        model = program.races
        out = []
        for fr in model.field_reports():
            if not fr.racy or fr.witness is None:
                continue
            w, o = fr.witness
            if not (_in_dirs(w.path) or _in_dirs(o.path)):
                continue
            cand = (f"candidate lock "
                    f"{model.locks.locks[fr.candidate].display}: take "
                    f"it at every access"
                    if fr.candidate is not None else
                    "no lock is ever held here: give the field one")
            out.append(self.finding(
                w.path, w.lineno,
                f"shared field {fr.display} has inconsistent "
                f"locksets: write at {w.path}:{w.lineno} ({w.func}, "
                f"{w.root}) holds [{model._names(w.lockset)}] vs "
                f"{'write' if o.write else 'read'} at "
                f"{o.path}:{o.lineno} ({o.func}, {o.root}) holds "
                f"[{model._names(o.lockset)}] — no common lock; "
                f"{cand}, or argue the lock-free publication in a "
                f"baseline entry",
                context=w.func))
        return out
