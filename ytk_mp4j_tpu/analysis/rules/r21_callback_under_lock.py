"""R21 — callback/dispatch under the minting lock (ISSUE 14).

The PR 13 outbox discipline, machine-checked. Two shapes:

(a) **Hook under lock** — invoking a user-supplied callable
    (``*_hook`` / ``*_callback`` / ``*_cb``), directly or through a
    call chain, while any lock is held. A hook is arbitrary code: it
    can block, it can call back into the object that is holding the
    lock, and no review of THIS repo can bound it. Fire the hook
    after releasing (collect under the lock, dispatch from an outbox
    outside it — the autoscaler's ``_emit_locked``/``_flush_events``
    pair is the house pattern and the negative case).

(b) **Re-entrant dispatch** — a call chain started while holding a
    non-reentrant lock that RE-ACQUIRES that same lock (the
    controller holding its lock dispatching into the master, whose
    path calls ``controller.status()``, which takes the controller
    lock again: self-deadlock on a plain ``Lock``). R19 catches
    opposite-order PAIRS; this catches the same-lock loop. Edges
    between two instances of one ``(class, attr)`` site share a node,
    so a genuinely per-instance nesting needs a reasoned suppression
    stating the instance-order argument.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity

_DIRS = ("comm", "resilience", "obs", "transport", "analysis")


class R21CallbackUnderLock(ProgramRule):
    rule_id = "R21"
    severity = Severity.ERROR
    title = "callback/dispatch under the minting lock"
    description = ("a hook/callback invoked, or the held lock "
                   "re-acquired through a call chain, while the lock "
                   "is held: arbitrary user code under a lock can "
                   "block or re-enter — mint events under the lock, "
                   "dispatch from an outbox outside it")
    example = """\
import threading

class Controller:
    def __init__(self, on_alert):
        self._lock = threading.Lock()
        self._on_alert = on_alert

    def settle(self, ev):
        with self._lock:
            self._events = [ev]
            self._alert_hook(ev)        # user code under the lock

    def _alert_hook(self, ev):
        self._on_alert(ev)
"""

    def run_program(self, program):
        model = program.locks
        out = []
        seen = set()
        for fkey, s in sorted(model.summaries.items()):
            fi = s.func
            if not fi.module.ctx.in_dirs(*_DIRS):
                continue
            for h in s.hooks:
                if h.held:
                    self._charge_hook(model, out, seen, fi, h.name,
                                      h.held, h.lineno, (fi.display,))
            for call in s.calls:
                if not call.held:
                    continue
                for ckey in call.callees:
                    hooks = model.trans_hooks.get(ckey)
                    if hooks:
                        for name in sorted(hooks):
                            tail, _ = model._chase(
                                model.trans_hooks, ckey, name)
                            self._charge_hook(
                                model, out, seen, fi, name, call.held,
                                call.lineno, (fi.display,) + tail)
        # (b) same-lock re-entry through a call chain
        for lockkey, edge in model.reentries:
            key = ("reentry", lockkey, edge.path, edge.lineno)
            if key in seen:
                continue
            seen.add(key)
            decl = model.locks[lockkey]
            out.append(self.finding(
                edge.path, edge.lineno,
                f"call chain re-acquires non-reentrant "
                f"{decl.display} while already holding it "
                f"(via {' -> '.join(edge.chain)}): self-deadlock on "
                f"the first execution — dispatch after releasing, or "
                f"argue the per-instance order in a suppression",
                context=edge.chain[0] if edge.chain else "<module>"))
        return out

    def _charge_hook(self, model, out, seen, fi, name, held, lineno,
                     chain):
        key = (fi.key, name, lineno)
        if key in seen:
            return
        seen.add(key)
        locks = ", ".join(sorted(model.locks[h].display for h in held))
        via = (" via " + " -> ".join(chain) if len(chain) > 1 else "")
        out.append(self.finding(
            fi.path, lineno,
            f"hook/callback '{name}' invoked{via} while holding "
            f"[{locks}]: arbitrary user code under a lock can block "
            f"the plane or re-enter it — collect under the lock, "
            f"dispatch from an outbox outside it",
            context=fi.display))
