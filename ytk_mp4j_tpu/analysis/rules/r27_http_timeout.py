"""R27 — HTTP fetch without an explicit timeout in obs/ code.

The observability planes scrape live masters over HTTP
(``mp4j-scope live``, the fleet poller). ``urllib.request.urlopen``
and raw ``http.client`` connections default to NO timeout — the
socket blocks forever — so an unbounded fetch wedges the scrape loop
exactly when a master hangs, which is exactly when the operator needs
the view (ISSUE 18). Every fetch in ``obs/`` must carry an explicit
bound: ``timeout=`` (or the positional timeout slot), with the
staleness state machine — not the socket — deciding what a silent
master means.

Scoped to ``obs/``: the comm planes own their socket discipline under
R2, and analysis/test code fetching fixtures is not a scrape loop.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity

# urlopen(url, data=None, timeout=...) — timeout is the 3rd
# positional; HTTPConnection(host, port=..., timeout=...) likewise
_FETCHERS = {"urlopen": 3, "HTTPConnection": 3, "HTTPSConnection": 3}


class R27HttpNoTimeout(Rule):
    rule_id = "R27"
    severity = Severity.WARNING
    title = "HTTP fetch without explicit timeout"
    description = ("urllib.request.urlopen / http.client connection "
                   "in obs/ without timeout= — a hung master wedges "
                   "the scrape loop exactly when the view matters")
    example = """\
import urllib.request

def scrape(base):
    with urllib.request.urlopen(base + "/metrics.json") as resp:
        return resp.read()      # blocks forever on a hung master
"""
    example_path = "ytk_mp4j_tpu/obs/example.py"

    def visit_Call(self, node: ast.Call):       # noqa: N802
        name = call_name(node)
        slot = _FETCHERS.get(name)
        if slot is not None and self.ctx.in_dirs("obs"):
            has_kw = any(kw.arg == "timeout" for kw in node.keywords)
            # a **kwargs splat may carry the timeout — out of static
            # reach, give it the benefit of the doubt
            has_splat = any(kw.arg is None for kw in node.keywords)
            if not has_kw and not has_splat and len(node.args) < slot:
                self.report(node, (
                    f"{name}(...) with no explicit timeout: the "
                    f"socket default is block-forever, so a hung "
                    f"endpoint wedges this scrape thread exactly "
                    f"when the fleet/live view is needed most — "
                    f"pass timeout= and let the staleness state "
                    f"machine interpret silence"))
        self.generic_visit(node)
