"""R19 — lock-order cycle across the whole program (ISSUE 14).

Two code paths that acquire the same pair of locks in opposite orders
deadlock the first time their threads interleave — and in this package
the two acquisitions are usually in DIFFERENT functions, often
different modules (the master's telemetry fold vs the controller's
dispatch path), which is why eighteen per-file rules never saw the
class. The lock model builds the package-wide lock-order graph — an
edge ``A -> B`` for every witnessed "``B`` acquired while ``A`` held",
through ``with`` nesting and call chains alike — and R19 reports every
strongly connected component of size >= 2, with one witness chain per
direction.

The "master -> controller only" discipline (PR 13's module docstring)
stops being prose here: an autoscaler path that dispatched into the
master while holding the controller lock would close the cycle with
the master's ``status()`` path and fire this rule.

Same-lock re-entry through a call chain is R21's half of the job;
edges between two instances of one ``(class, attr)`` site share a
node, which is the conservative merge — an order violation between
any two instances violates the class's one discipline.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.engine import ProgramRule
from ytk_mp4j_tpu.analysis.report import Severity


class R19LockOrderCycle(ProgramRule):
    rule_id = "R19"
    severity = Severity.ERROR
    title = "lock-order cycle"
    description = ("two call paths acquire the same locks in opposite "
                   "orders (interprocedural): the first adversarial "
                   "interleaving deadlocks both threads — pick one "
                   "job-wide order per lock pair")
    example = """\
import threading

class Master:
    def __init__(self):
        self._lock = threading.Lock()
        self._ctl = Controller(self)

    def status(self):
        with self._lock:
            return self._ctl.snapshot()     # master -> controller

class Controller:
    def __init__(self, master):
        self._lock = threading.Lock()
        self._master = master

    def snapshot(self):
        with self._lock:
            return dict(vars(self))

    def dispatch(self, ev):
        with self._lock:
            self._master.status()           # controller -> master: cycle
"""

    def run_program(self, program):
        model = program.locks
        out = []
        for scc in model.cycles():
            members = set(scc)
            # witness edges inside the component, one per direction
            edges = [e for (s, d), e in sorted(model.edges.items())
                     if s in members and d in members]
            if not edges:
                continue
            names = ", ".join(model.locks[k].display for k in scc)
            witness = "; ".join(
                model.format_witness(e) for e in edges[:4])
            charge = edges[0]
            out.append(self.finding(
                charge.path, charge.lineno,
                f"lock-order cycle among [{names}]: opposite "
                f"acquisition orders observed — {witness}; every "
                f"thread pair running these paths can deadlock: pick "
                f"ONE job-wide order and move the minority "
                f"acquisition outside the held region (outbox "
                f"pattern) or re-order it",
                context=self._context_of(program, charge)))
        return out

    @staticmethod
    def _context_of(program, edge):
        # the charging frame's qualname: first name in the chain
        return edge.chain[0] if edge.chain else "<module>"
