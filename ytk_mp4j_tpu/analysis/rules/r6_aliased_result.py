"""R6 — leader returns an aliased slot buffer in the thread fan-out.

In the hybrid thread collectives (``ThreadCommSlave._fan_in_out``) each
thread deposits a VIEW of its caller's array into the shared ``slots``;
the ``leader`` closure's return value becomes the shared ``result``
that every thread then reads. A leader that returns ``slots[i]``
without detaching (``_detach`` / ``.copy()`` / ``dict()`` / ``list()``)
hands every thread a buffer aliasing thread *i*'s input — the next
in-place merge corrupts a sibling's data (the aliased-buffer hazard
documented on ``_detach``).

The rule inspects functions named ``leader`` whose first parameter is
the slots list, and flags returns of raw subscripts of it (directly or
through a simple local name). Slots that arrive pre-detached (the
pairwise tree reduce detaches slot 0) carry inline suppressions citing
that invariant.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules.common import walk_pruned

_DETACHERS = {"_detach", "copy", "deepcopy", "dict", "list", "array",
              "asarray", "ascontiguousarray", "_copied_map"}


def _subscripts_of(expr: ast.AST, param: str) -> bool:
    """True when ``expr`` is (or chooses between) raw ``param[...]``
    subscripts — ``slots[0]`` or ``slots[a] if c else slots[b]``."""
    if isinstance(expr, ast.Subscript):
        return isinstance(expr.value, ast.Name) and expr.value.id == param
    if isinstance(expr, ast.IfExp):
        return (_subscripts_of(expr.body, param)
                or _subscripts_of(expr.orelse, param))
    return False


class R6AliasedLeaderResult(Rule):
    rule_id = "R6"
    severity = Severity.WARNING
    title = "aliased slot returned from leader"
    description = ("fan-out leader returns slots[i] without _detach/copy "
                   "— result aliases one thread's input buffer")
    example = """\
class ThreadComm:
    def allreduce(self):
        def leader(slots):
            acc = slots[0]      # alias into another thread's slot
            return acc
"""

    def visit_FunctionDef(self, node):           # noqa: N802
        if node.name == "leader" and node.args.args:
            self._check_leader(node, node.args.args[0].arg)
        self.generic_visit_scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_leader(self, node: ast.FunctionDef, param: str):
        # names bound to raw slot subscripts (and never rebound to
        # anything detached)
        aliased: set[str] = set()
        for n in walk_pruned(node.body):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                name = n.targets[0].id
                if _subscripts_of(n.value, param) or (
                        isinstance(n.value, ast.Name)
                        and n.value.id in aliased):
                    aliased.add(name)
                else:
                    aliased.discard(name)
        for n in walk_pruned(node.body):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            v = n.value
            if isinstance(v, ast.Call) and call_name(v) in _DETACHERS:
                continue
            if _subscripts_of(v, param) or (
                    isinstance(v, ast.Name) and v.id in aliased):
                self.report(n, (
                    f"leader returns a raw '{param}[...]' slot — the "
                    f"shared result aliases one thread's input view; "
                    f"detach with _detach()/copy() (or suppress citing "
                    f"the invariant that already detached it)"))
