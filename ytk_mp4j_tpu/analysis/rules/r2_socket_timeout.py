"""R2 — blocking socket/Channel operation with no timeout and no
failure handling.

A ``recv`` / ``recv_into`` / ``accept`` / ``sendall`` on a socket or
``Channel`` with no timeout configured and no enclosing handler turns a
dead peer into a silent, undiagnosable hang (the hazard class the
paper's fail-stop model accepts only at explicitly documented points).

A call escapes when either:

- it sits inside a ``try`` whose handlers catch ``socket.timeout`` /
  ``TimeoutError`` / ``OSError`` / ``Mp4jError`` / ``Exception`` (the
  site deals with transport failure), or
- the same function configured a timeout on the same receiver earlier
  (``x.settimeout(...)`` / ``x.set_timeout(...)`` with a non-``None``
  argument).

Deliberately unbounded waits (the reference's fail-stop barrier) carry
inline suppressions stating that contract.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import (
    Rule, attr_chain, call_name, receiver_chain)
from ytk_mp4j_tpu.analysis.report import Severity

_BLOCKING = {"recv", "recv_into", "accept", "sendall"}
_HANDLED = {"timeout", "TimeoutError", "OSError", "ConnectionError",
            "Mp4jError", "Exception", "BaseException"}
_TIMEOUT_SETTERS = {"settimeout", "set_timeout"}


def _handler_names(handler: ast.ExceptHandler):
    if handler.type is None:        # bare except catches everything
        yield "BaseException"
        return
    types = (handler.type.elts
             if isinstance(handler.type, ast.Tuple) else [handler.type])
    for t in types:
        chain = attr_chain(t)
        if chain:
            yield chain[-1]


class R2UnboundedSocketOp(Rule):
    rule_id = "R2"
    severity = Severity.WARNING
    title = "unbounded socket operation"
    description = ("socket/Channel recv/accept/sendall without a timeout "
                   "or enclosing transport-failure handling")
    example = """\
class Puller:
    def pull(self):
        return self.sock.recv(1024)     # no timeout, no handler
"""

    def run(self, ctx):
        self._try_stack: list[ast.Try] = []
        self._func_stack: list[dict] = []    # per-function state
        return super().run(ctx)

    # -- structure tracking --------------------------------------------
    def visit_Try(self, node: ast.Try):      # noqa: N802
        # only the `body` is protected by the handlers; visit children
        # with the try on the stack for body, off the stack elsewhere
        self._try_stack.append(node)
        for stmt in node.body:
            self.visit(stmt)
        self._try_stack.pop()
        for part in (node.handlers, node.orelse, node.finalbody):
            for stmt in part:
                self.visit(stmt)

    def visit_FunctionDef(self, node):       # noqa: N802
        self._func_stack.append({"timeouts": []})
        try:
            self.generic_visit_scoped(node)
        finally:
            self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- the check ------------------------------------------------------
    def visit_Call(self, node: ast.Call):    # noqa: N802
        name = call_name(node)
        if name in _TIMEOUT_SETTERS and self._func_stack:
            arg = node.args[0] if node.args else None
            is_none = isinstance(arg, ast.Constant) and arg.value is None
            if not is_none:
                self._func_stack[-1]["timeouts"].append(
                    (receiver_chain(node), node.lineno))
        elif name in _BLOCKING and isinstance(node.func, ast.Attribute):
            if not self._escapes(node):
                self.report(node, (
                    f"blocking .{name}() with no timeout configured and "
                    f"no transport-failure handler: a dead peer hangs "
                    f"this call forever"))
        self.generic_visit(node)

    def _escapes(self, node: ast.Call) -> bool:
        recv = receiver_chain(node)
        if recv == ["self"]:
            # a method delegating to the object's OWN blocking wrapper
            # (Channel.recv_array -> self.recv()): the timeout
            # discipline is audited inside the wrapper, not at every
            # internal call site
            return True
        for t in self._try_stack:
            for h in t.handlers:
                if any(n in _HANDLED for n in _handler_names(h)):
                    return True
        if self._func_stack:
            for chain, lineno in self._func_stack[-1]["timeouts"]:
                if lineno > node.lineno:
                    continue
                # receiver-aware when both chains resolve; a computed
                # receiver (e.g. self._channel(p).recv()) matches any
                # earlier timeout in the function
                if recv is None or chain is None or chain == recv:
                    return True
        return False
