"""R3 — thread-group shared state mutated outside the group lock.

``_ThreadGroup`` instances (received as ``self._g`` / ``group`` /
``g``) carry state shared by every thread of a process: ``slots``,
``result``, ``max_code``, ``pending_closes``, ``closed``. Mutating any
of these outside ``with group.lock`` is a data race — unless the region
is barrier-delimited (every thread passes a barrier between the write
and any cross-thread read), which a static pass cannot prove; those
regions carry inline ``# mp4j-lint: disable=R3`` suppressions stating
the barrier argument.

The rule tracks simple local aliases (``slots = self._g.slots`` then
``slots[i] = ...``) and mutating method calls (``.append`` /
``.update`` / ...) as well as direct attribute / subscript stores.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, attr_chain
from ytk_mp4j_tpu.analysis.report import Severity

_SHARED = {"slots", "result", "max_code", "pending_closes", "closed"}
_GROUP_NAMES = {"_g", "g", "group"}
_MUTATORS = {"append", "extend", "insert", "clear", "update",
             "setdefault", "pop", "remove", "add"}


def _shared_chain(node: ast.AST) -> str | None:
    """``self._g.slots`` -> ``"slots"`` when the receiver is a thread
    group; None otherwise."""
    chain = attr_chain(node)
    if chain and len(chain) >= 2 and chain[-1] in _SHARED \
            and chain[-2] in _GROUP_NAMES:
        return chain[-1]
    return None


class R3SharedStateOutsideLock(Rule):
    rule_id = "R3"
    severity = Severity.ERROR
    title = "thread-group state outside lock"
    description = ("_ThreadGroup shared state (slots/result/max_code/...) "
                   "mutated outside the group lock or a documented "
                   "barrier region")
    example = """\
class ThreadAllreduce:
    def publish(self, value):
        self._g.result = value          # outside `with self._g.lock`
"""

    def run(self, ctx):
        self._with_lock_depth = 0
        self._aliases: list[dict[str, str]] = []   # per-function
        return super().run(ctx)

    # -- structure tracking --------------------------------------------
    def visit_With(self, node: ast.With):        # noqa: N802
        locked = any(
            (chain := attr_chain(item.context_expr)) and "lock" in chain[-1]
            for item in node.items)
        if locked:
            self._with_lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_lock_depth -= 1

    def visit_FunctionDef(self, node):           # noqa: N802
        self._aliases.append({})
        try:
            self.generic_visit_scoped(node)
        finally:
            self._aliases.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- mutation detection --------------------------------------------
    def _alias_of(self, name: str) -> str | None:
        for frame in reversed(self._aliases):
            if name in frame:
                return frame[name]
        return None

    def _target_shared(self, target: ast.AST) -> str | None:
        """Shared-state name mutated by storing to ``target``, if any."""
        if isinstance(target, ast.Attribute):
            return _shared_chain(target)
        if isinstance(target, ast.Subscript):
            base = target.value
            shared = _shared_chain(base)
            if shared:
                return shared
            if isinstance(base, ast.Name):
                return self._alias_of(base.id)
        return None

    def _flag(self, node: ast.AST, name: str, verb: str):
        if self._with_lock_depth == 0:
            self.report(node, (
                f"thread-group shared state '{name}' {verb} outside "
                f"'with group.lock' — data race unless the region is "
                f"barrier-delimited (suppress with the barrier argument "
                f"if it is)"))

    def visit_Assign(self, node: ast.Assign):    # noqa: N802
        for target in node.targets:
            shared = self._target_shared(target)
            if shared:
                self._flag(node, shared, "assigned")
            # record local aliases of shared containers
            if isinstance(target, ast.Name) and self._aliases:
                shared_src = _shared_chain(node.value)
                if shared_src:
                    self._aliases[-1][target.id] = shared_src
                else:
                    self._aliases[-1].pop(target.id, None)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):  # noqa: N802
        shared = self._target_shared(node.target)
        if shared:
            self._flag(node, shared, "updated")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):        # noqa: N802
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            base = node.func.value
            shared = _shared_chain(base)
            if not shared and isinstance(base, ast.Name):
                shared = self._alias_of(base.id)
            if shared:
                self._flag(node, shared, f"mutated via .{node.func.attr}()")
        self.generic_visit(node)
