"""R11 — wall clock used for duration/deadline measurement.

PR 3's trace plane measures phases with ``time.perf_counter`` and the
watchdogs/deadlines (PR 5) run on ``time.monotonic``; ``time.time()``
is subject to NTP steps and slews, so a duration or deadline derived
from it can jump backwards, expire instantly, or never expire — the
exact failure mode is a recovery deadline firing spuriously mid-abort
(declaring a healthy rank dead) or a phase measurement going negative
in a merged trace. The rendezvous deadline in ``comm/master.py`` had
this bug until ISSUE 6 converted it to ``monotonic``.

Wall clock remains CORRECT in two shapes, which stay quiet:

- **storage/formatting**: a timestamp written into an artifact or a
  log line (``{"wall_time": time.time()}``, ``time.localtime(now)``,
  ``now % 1`` millisecond formatting) is a point in time, not a
  measurement;
- **the trace anchor** (``obs/spans.py`` ``_epoch_wall``): exported
  Chrome-trace timestamps must be comparable ACROSS independently
  launched processes, which only the wall clock provides — spans are
  still *recorded* in perf_counter time and anchored once. This is
  arithmetic, so it fires, and it is the baselined sanctioned site.

Heuristic: in ``comm/``, ``obs/``, ``transport/`` a ``time.time()``
call (or bare ``time()`` when the module does ``from time import
time``) fires when its value enters add/subtract arithmetic or a
comparison — directly (``deadline - time.time()``, ``time.time() >
deadline``) or through a name assigned from it and used that way in
the same function scope (module-level names are tracked module-wide —
the spans anchor pattern — except in scopes that bind the same name
locally, which shadow rather than implicate it).
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule
from ytk_mp4j_tpu.analysis.report import Finding, Severity

_ARITH_OPS = (ast.Add, ast.Sub)


def _is_wall_call(node: ast.AST, bare: bool) -> bool:
    """``time.time()``; or plain ``time()`` in a module that does
    ``from time import time``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return (f.attr == "time" and isinstance(f.value, ast.Name)
                and f.value.id == "time")
    return bare and isinstance(f, ast.Name) and f.id == "time"


class R11WallClockDuration(Rule):
    rule_id = "R11"
    severity = Severity.ERROR
    title = "wall clock used for duration/deadline measurement"
    description = ("time.time() feeding duration/deadline arithmetic "
                   "is subject to NTP steps — phases must use "
                   "time.perf_counter and deadlines time.monotonic; "
                   "wall clock only at the sanctioned trace-anchor / "
                   "artifact-timestamp sites")
    example = """\
import time

def rendezvous(self):
    deadline = time.time() + self.timeout   # NTP step breaks this
    while time.time() < deadline:
        self.accept_one()
"""

    _MSG = ("wall-clock time.time() feeds duration/deadline "
            "arithmetic; use time.perf_counter (phases) or "
            "time.monotonic (deadlines) — NTP can step the wall "
            "clock mid-measurement")

    def run(self, ctx):
        self._bare = False
        # name-flow state, resolved after the walk: assignments
        # `x = time.time()` and the names that entered +/-/compare
        # expressions, keyed by enclosing scope; _local_binds tracks
        # every locally bound name (params + assignments) so a local
        # that SHADOWS a module-level name cannot implicate it
        self._assigns: list[tuple[str, str, ast.AST]] = []
        self._arith: dict[str, set[str]] = {}
        self._local_binds: dict[str, set[str]] = {}
        self._reported: set[int] = set()
        return super().run(ctx)

    def visit_Module(self, node):               # noqa: N802
        if not self.ctx.in_dirs("comm", "obs", "transport"):
            return
        self._bare = any(
            isinstance(n, ast.ImportFrom) and n.module == "time"
            and any(alias.name == "time" for alias in n.names)
            for n in ast.walk(node))
        self.generic_visit(node)
        # deferred name-flow findings: a wall-clock value that entered
        # arithmetic/comparison through its assigned name. A
        # module-level name counts in any scope that does NOT bind the
        # same name locally (the spans `_epoch_wall` anchor pattern);
        # a function-local assign counts only in its own scope.
        for name, qual, call in self._assigns:
            if qual == "<module>":
                hit = any(name in names
                          and (q == "<module>"
                               or name not in self._local_binds.get(
                                   q, ()))
                          for q, names in self._arith.items())
            else:
                hit = name in self._arith.get(qual, ())
            if hit:
                self.findings.append(Finding(
                    rule=self.rule_id, severity=self.severity,
                    path=self.ctx.path,
                    line=getattr(call, "lineno", 0),
                    col=getattr(call, "col_offset", 0) + 1,
                    message=self._MSG, context=qual))

    def _visit_def(self, node):
        self.scope.append(node.name)
        try:
            binds = self._local_binds.setdefault(self.qualname(), set())
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                binds.add(arg.arg)
            # every locally bound name shadows: plain/aug/ann assigns,
            # for targets, with ... as, walrus, unpacking, except-as
            # (pruned at nested defs — those have their own scope)
            stack = list(ast.iter_child_nodes(node))
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                          ast.Store):
                    binds.add(n.id)
                elif isinstance(n, ast.ExceptHandler) and n.name:
                    binds.add(n.name)
                stack.extend(ast.iter_child_nodes(n))
            self.generic_visit(node)
        finally:
            self.scope.pop()

    visit_FunctionDef = _visit_def              # noqa: N815
    visit_AsyncFunctionDef = _visit_def         # noqa: N815

    def visit_Lambda(self, node):               # noqa: N802
        # lambdas get a pseudo-scope: their body's arithmetic must not
        # key to the enclosing scope (a module-level lambda would key
        # to <module>, whose deferred branch never consults binds) and
        # their params must shadow like def params do. Lambdas sharing
        # an enclosing scope share the pseudo-scope — binds union, an
        # over-approximation in the quiet direction.
        self.scope.append("<lambda>")
        try:
            binds = self._local_binds.setdefault(self.qualname(), set())
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                binds.add(arg.arg)
            self.generic_visit(node)
        finally:
            self.scope.pop()

    def visit_Assign(self, node):               # noqa: N802
        if _is_wall_call(node.value, self._bare):
            qual = self.qualname()
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._assigns.append((tgt.id, qual, node.value))
        self.generic_visit(node)

    def visit_BinOp(self, node):                # noqa: N802
        if isinstance(node.op, _ARITH_OPS):
            self._note_expr(node)
        self.generic_visit(node)

    def visit_Compare(self, node):              # noqa: N802
        self._note_expr(node)
        self.generic_visit(node)

    def _note_expr(self, expr: ast.AST) -> None:
        names = self._arith.setdefault(self.qualname(), set())
        for n in self._operands(expr):
            if _is_wall_call(n, self._bare):
                if id(n) not in self._reported:   # nested BinOps
                    self._reported.add(id(n))
                    self.report(n, self._MSG)
            elif isinstance(n, ast.Name):
                names.add(n.id)

    @staticmethod
    def _operands(expr: ast.AST):
        """The expression's subtree, pruned at nested calls and
        f-strings: their INSIDES are not operands of this arithmetic
        (``time.strftime(...) + f"{ms}"`` is string formatting, not a
        measurement), while the call node itself still is one
        (``deadline - time.time()``). Arithmetic inside a pruned
        subtree is its own BinOp node and gets visited directly."""
        stack = [expr]
        while stack:
            n = stack.pop()
            yield n
            if n is not expr and isinstance(n, (ast.Call, ast.JoinedStr)):
                continue
            stack.extend(ast.iter_child_nodes(n))
