"""Rule catalogue for mp4j-lint — one module per rule.

| id | severity | hazard |
|----|----------|--------|
| R1 | error    | collective under a rank-dependent branch |
| R2 | warning  | unbounded socket/Channel recv/accept/sendall |
| R3 | error    | thread-group shared state outside the lock |
| R4 | error    | operand mismatch between paired segment transfers |
| R5 | error    | bare/swallowed exceptions in comm hot paths |
| R6 | warning  | leader returns an aliased slot (no _detach) |
| R7 | error    | mutable defaults / mutated module-level state |
| R8 | error    | chunk schedule derived from rank-local state |
| R9 | error    | pickled dict payload on a collective map path |
| R10 | error   | peer-channel I/O bypassing the epoch fence |
| R11 | error   | wall clock feeding duration/deadline arithmetic |
| R12 | error   | transport construction outside transport/ (SPI) |
| R13 | error   | raw-byte read of a possibly non-contiguous array |
| R14 | error   | telemetry artifact write skipping tmp+os.replace |
| R15 | error   | roster-derived topology cached in an attribute |
| R16 | error   | un-awaited CollectiveFuture crosses a boundary |
| R17 | error   | metric family missing from METRICS_DOC |
| R18 | error   | bare time.sleep() inside a while loop (control code) |
| R19 | error   | lock-order cycle (whole-program) |
| R20 | error   | blocking call under a held lock (whole-program) |
| R21 | error   | callback/dispatch under the minting lock (whole-program) |
| R22 | error   | transport-decision size literal outside tuning/tuner |
| R23 | error   | inconsistent lockset on a shared field (whole-program) |
| R24 | error   | resource leaked on an exception path (whole-program) |
| R25 | error   | thread started without join/daemon/stop (whole-program) |
| R26 | warning | in-loop i* submit awaited with no compute (overlap defeated) |
| R27 | warning | HTTP fetch without explicit timeout in obs/ scrape code |
| R28 | error   | serve-path wait without deadline / wall clock in serve/ |

R19-R21 and R23-R25 are
:class:`~ytk_mp4j_tpu.analysis.engine.ProgramRule` instances: they
run once over the whole indexed path set (call graph + lock model +
race/resource models) instead of file by file.
"""

from __future__ import annotations

from ytk_mp4j_tpu.analysis.rules.r1_rank_branch import (
    R1RankConditionalCollective)
from ytk_mp4j_tpu.analysis.rules.r2_socket_timeout import (
    R2UnboundedSocketOp)
from ytk_mp4j_tpu.analysis.rules.r3_lock_discipline import (
    R3SharedStateOutsideLock)
from ytk_mp4j_tpu.analysis.rules.r4_operand_pairing import (
    R4OperandPairing)
from ytk_mp4j_tpu.analysis.rules.r5_swallowed_exceptions import (
    R5SwallowedException)
from ytk_mp4j_tpu.analysis.rules.r6_aliased_result import (
    R6AliasedLeaderResult)
from ytk_mp4j_tpu.analysis.rules.r7_mutable_state import R7MutableState
from ytk_mp4j_tpu.analysis.rules.r8_chunk_schedule import (
    R8RankLocalChunkSchedule)
from ytk_mp4j_tpu.analysis.rules.r9_map_payload import (
    R9PickledMapPayload)
from ytk_mp4j_tpu.analysis.rules.r10_epoch_fence import (
    R10EpochFenceBypass)
from ytk_mp4j_tpu.analysis.rules.r11_wall_clock import (
    R11WallClockDuration)
from ytk_mp4j_tpu.analysis.rules.r12_transport_spi import (
    R12TransportSpiBypass)
from ytk_mp4j_tpu.analysis.rules.r13_digest_contiguity import (
    R13DigestContiguity)
from ytk_mp4j_tpu.analysis.rules.r14_torn_write import R14TornWrite
from ytk_mp4j_tpu.analysis.rules.r15_topology_cache import (
    R15TopologyCache)
from ytk_mp4j_tpu.analysis.rules.r16_unawaited_future import (
    R16UnawaitedFuture)
from ytk_mp4j_tpu.analysis.rules.r17_metric_doc import R17MetricDoc
from ytk_mp4j_tpu.analysis.rules.r18_sleep_loop import R18SleepLoop
from ytk_mp4j_tpu.analysis.rules.r19_lock_order import R19LockOrderCycle
from ytk_mp4j_tpu.analysis.rules.r20_blocking_under_lock import (
    R20BlockingUnderLock)
from ytk_mp4j_tpu.analysis.rules.r21_callback_under_lock import (
    R21CallbackUnderLock)
from ytk_mp4j_tpu.analysis.rules.r22_knob_literal import R22KnobLiteral
from ytk_mp4j_tpu.analysis.rules.r23_lockset_race import R23LocksetRace
from ytk_mp4j_tpu.analysis.rules.r24_resource_leak import (
    R24ResourceLeak)
from ytk_mp4j_tpu.analysis.rules.r25_thread_lifecycle import (
    R25ThreadLifecycle)
from ytk_mp4j_tpu.analysis.rules.r26_immediate_await import (
    R26ImmediateAwait)
from ytk_mp4j_tpu.analysis.rules.r27_http_timeout import (
    R27HttpNoTimeout)
from ytk_mp4j_tpu.analysis.rules.r28_serve_deadline import (
    R28ServeDeadline)

ALL_RULES = [
    R1RankConditionalCollective,
    R2UnboundedSocketOp,
    R3SharedStateOutsideLock,
    R4OperandPairing,
    R5SwallowedException,
    R6AliasedLeaderResult,
    R7MutableState,
    R8RankLocalChunkSchedule,
    R9PickledMapPayload,
    R10EpochFenceBypass,
    R11WallClockDuration,
    R12TransportSpiBypass,
    R13DigestContiguity,
    R14TornWrite,
    R15TopologyCache,
    R16UnawaitedFuture,
    R17MetricDoc,
    R18SleepLoop,
    R19LockOrderCycle,
    R20BlockingUnderLock,
    R21CallbackUnderLock,
    R22KnobLiteral,
    R23LocksetRace,
    R24ResourceLeak,
    R25ThreadLifecycle,
    R26ImmediateAwait,
    R27HttpNoTimeout,
    R28ServeDeadline,
]

RULES_BY_ID = {cls.rule_id: cls for cls in ALL_RULES}


def get_rules(select=None):
    """Fresh rule instances; ``select`` is an iterable of rule ids."""
    if select is None:
        classes = ALL_RULES
    else:
        unknown = set(select) - set(RULES_BY_ID)
        if unknown:
            raise KeyError(f"unknown rule id(s): {sorted(unknown)}")
        classes = [RULES_BY_ID[s] for s in select]
    return [cls() for cls in classes]
