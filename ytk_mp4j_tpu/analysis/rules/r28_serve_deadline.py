"""R28 — serve-path wait without a deadline / wall clock in serve/.

The inference plane (ISSUE 19) lives or dies by its tail latency: a
request admitted to the micro-batcher carries an end-to-end deadline
of a few MILLISECONDS (``MP4J_SERVE_DEADLINE_MS``), so any wait on the
serve path that can block forever converts one slow replica into an
unbounded p99 — the exact outage the chaos bench measures. Two shapes
fire, both restricted to ``serve/``:

- **unbounded wait**: a no-argument ``.wait()`` / ``.acquire()`` /
  ``.join()`` / ``.result()`` call. Every blocking point on the serve
  path must carry a ``timeout=`` (the batcher's idle wait, the
  future's result wait, the dispatch thread's close join) so a wedged
  collective surfaces as a counted timeout, not a hung frontend.
- **wall clock**: any ``time.time()`` (or bare ``time()`` under
  ``from time import time``) or ``datetime.now()`` /
  ``datetime.utcnow()`` call. R11 already rejects wall-clock
  *arithmetic* in comm/obs/transport; serve deadlines are so short
  that a single NTP slew exceeds the whole budget, so in ``serve/``
  the wall clock is banned outright — batch deadlines and latency
  observations must ride ``time.monotonic`` / ``time.perf_counter``.

Quiet shapes: a wait with any positional argument or a ``timeout=``
keyword (``fut.result(timeout)``, ``cv.wait(timeout=w)``,
``t.join(remaining)``), and string ``"".join(parts)`` — it takes an
argument, so the no-argument heuristic never sees it.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule
from ytk_mp4j_tpu.analysis.report import Severity

# blocking methods that accept a timeout and block forever without one
_WAIT_ATTRS = ("wait", "acquire", "join", "result")


def _is_wall_call(node: ast.AST, bare: bool) -> bool:
    """``time.time()``; bare ``time()`` under ``from time import
    time``; ``datetime.now()`` / ``datetime.utcnow()`` on either the
    module or the class."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "time" and isinstance(f.value, ast.Name) \
                and f.value.id == "time":
            return True
        if f.attr in ("now", "utcnow"):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "datetime":
                return True
            if isinstance(base, ast.Attribute) \
                    and base.attr == "datetime":
                return True
        return False
    return bare and isinstance(f, ast.Name) and f.id == "time"


class R28ServeDeadline(Rule):
    rule_id = "R28"
    severity = Severity.ERROR
    title = "serve-path wait without a deadline / wall clock in serve/"
    description = ("the serve path budgets milliseconds end to end: a "
                   "wait()/acquire()/join()/result() with no timeout "
                   "turns one slow replica into an unbounded p99, and "
                   "a wall-clock read (time.time / datetime.now) can "
                   "slew by more than the whole deadline — use "
                   "timeout= everywhere and time.monotonic for "
                   "deadlines")
    example_path = "ytk_mp4j_tpu/serve/example.py"
    example = """\
import time

class Batcher:
    def flush(self):
        self._ready.wait()                  # can block forever
        deadline = time.time() + 0.002      # NTP slew > budget
"""

    _MSG_WAIT = ("unbounded {name}() on the serve path — pass a "
                 "timeout so a wedged replica surfaces as a counted "
                 "timeout, not a hung frontend")
    _MSG_WALL = ("wall clock on the serve path — serve deadlines are "
                 "milliseconds, smaller than an NTP slew; use "
                 "time.monotonic (deadlines) / time.perf_counter "
                 "(latency)")

    def run(self, ctx):
        self._bare = False
        return super().run(ctx)

    def visit_Module(self, node):               # noqa: N802
        if not self.ctx.in_dirs("serve"):
            return
        self._bare = any(
            isinstance(n, ast.ImportFrom) and n.module == "time"
            and any(alias.name == "time" for alias in n.names)
            for n in ast.walk(node))
        self.generic_visit(node)

    def visit_Call(self, node):                 # noqa: N802
        if _is_wall_call(node, self._bare):
            self.report(node, self._MSG_WALL)
        else:
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _WAIT_ATTRS
                    and not node.args
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                self.report(node, self._MSG_WAIT.format(name=f.attr))
        self.generic_visit(node)
