"""R16 — a CollectiveFuture never awaited before a collective boundary.

The nonblocking API (ISSUE 11) hands out :class:`CollectiveFuture`
handles whose buffers the scheduler owns until ``wait()`` (or
``wait_all()``) resolves them. A future that is still un-awaited when
the SAME comm object enters a blocking collective, a ``barrier()``, or
``close()`` is a latent hazard: the runtime drains outstanding work at
those boundaries (so the program *happens* to be correct), but the
caller is reading or reusing a buffer whose completion it never
observed — and on backends without the drain (or after a refactor that
reorders the calls) that becomes a data race on the payload buffer.
The fix is one of: ``f.wait()`` before the boundary, ``comm.wait_all()``
(which this rule recognizes), or not holding the future at all.

Heuristic (function-local, statement order): an assignment
``f = comm.iallreduce(...)`` (any ``i*`` nonblocking method) opens a
tracked future; ``f.wait()`` / ``f.result()`` / ``f.exception()``
closes it, as does ``comm.wait_all()`` on the same receiver — and ANY
other use of ``f`` (passed to a call, stored, returned) conservatively
closes it too (the future escaped; its awaiting is someone else's
contract). A call to a blocking collective / ``barrier`` / ``close``
on the same receiver while a tracked future is open fires the rule.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import (
    Rule, attr_chain, call_name, receiver_chain)
from ytk_mp4j_tpu.analysis.report import Severity

I_METHODS = frozenset({
    "iallreduce", "ireduce_scatter", "iallgather", "igather",
    "iallreduce_map",
})
_AWAITS = frozenset({"wait", "result", "exception"})
_BLOCKING = frozenset({
    "allreduce_array", "reduce_array", "broadcast_array",
    "allgather_array", "gather_array", "scatter_array",
    "reduce_scatter_array", "allreduce_map", "allreduce_map_multi",
    "reduce_map", "broadcast_map", "gather_map", "allgather_map",
    "scatter_map", "reduce_scatter_map", "barrier", "close",
})


class R16UnawaitedFuture(Rule):
    rule_id = "R16"
    severity = Severity.ERROR
    title = "un-awaited CollectiveFuture crosses a collective boundary"
    description = ("a future from an i* nonblocking collective is "
                   "never awaited before a blocking collective, "
                   "barrier, or close on the same comm")
    example = """\
def step(comm, x):
    f = comm.iallreduce(x)
    comm.barrier()              # f never awaited before the boundary
"""

    def visit_FunctionDef(self, node):          # noqa: N802
        self._check_function(node)
        self.generic_visit_scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

    @classmethod
    def _iter_own(cls, node: ast.AST):
        """Pre-order (source-order) walk of a function's OWN body —
        nested defs/lambdas analyze on their own visit."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda,
                                  ast.ClassDef)):
                continue
            yield child
            yield from cls._iter_own(child)

    def _check_function(self, fn: ast.AST) -> None:
        # futures open in THIS function body: name -> (line, receiver)
        open_futs: dict[str, tuple[int, tuple]] = {}
        self.scope.append(getattr(fn, "name", "<anon>"))
        try:
            for stmt in self._iter_own(fn):
                if isinstance(stmt, ast.Assign):
                    self._on_assign(stmt, open_futs)
                elif isinstance(stmt, ast.Call):
                    self._on_call(stmt, open_futs)
        finally:
            self.scope.pop()

    def _on_assign(self, node: ast.Assign, open_futs) -> None:
        if len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        call = node.value
        if isinstance(call, ast.Call) \
                and call_name(call) in I_METHODS:
            recv = receiver_chain(call)
            if recv is not None:
                open_futs[node.targets[0].id] = (node.lineno,
                                                 tuple(recv))

    def _on_call(self, call: ast.Call, open_futs) -> None:
        name = call_name(call)
        # f.wait()/result()/exception() closes the future
        if name in _AWAITS and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name):
            open_futs.pop(call.func.value.id, None)
            return
        # comm.wait_all() is the collective-boundary drain: closes
        # every future opened on the same receiver
        if name == "wait_all":
            recv = receiver_chain(call)
            for f, (_ln, r) in list(open_futs.items()):
                if recv is None or tuple(recv) == r:
                    open_futs.pop(f, None)
            return
        # any OTHER use of a tracked future (argument, container,
        # attribute base) closes it conservatively — it escaped
        for arg in ast.walk(call):
            if isinstance(arg, ast.Name) and arg.id in open_futs \
                    and arg is not call.func:
                open_futs.pop(arg.id, None)
        if name in _BLOCKING:
            recv = receiver_chain(call)
            if recv is None:
                return
            for f, (ln, r) in list(open_futs.items()):
                if tuple(recv) == r:
                    self.report(call, (
                        f"future '{f}' (line {ln}) is never awaited "
                        f"before this blocking '{name}' on the same "
                        f"comm: call .wait() or "
                        f"{'.'.join(recv)}.wait_all() first, or the "
                        "buffer's completion is unobserved"))
                    open_futs.pop(f, None)
