"""R12 — transport construction outside the transport SPI.

ISSUE 7 split the socket code into a transport SPI: every concrete
channel (``TcpChannel``, ``ShmChannel``) and every raw ``socket.
socket(...)`` belongs inside ``transport/`` — the collectives, the
control plane and the observability layers all program against the
abstract :class:`~ytk_mp4j_tpu.transport.channel.Channel` contract and
acquire channels through the owning slave's fenced accessors. A raw
socket or direct channel construction elsewhere bypasses everything
the SPI composes over the contract (epoch pinning, fault hooks,
transport-tagged stats, the invalidate/deferred-close discipline) and
quietly re-couples a caller to ONE transport — exactly the special-
casing the SPI exists to end.

Sanctioned sites carry baseline entries: the rendezvous surfaces
(master listen socket + registration channel, slave listen socket,
the accept loop's handshake channel) must construct over raw sockets
because they ARE the mechanism transports are negotiated over.

Heuristic: outside ``transport/`` (and outside ``analysis/`` — the
linter's own fixtures), flag

- any call whose terminal name is ``socket`` with a dotted receiver
  ending in ``socket`` (``socket.socket(...)``) or a bare ``socket``
  name imported from the socket module;
- any call to a name ending in ``Channel`` that matches the known
  concrete channels (``TcpChannel``, ``ShmChannel``) or the legacy
  bare ``Channel``.

``connect(...)`` (the transport package's own dialer factory) is NOT
flagged: it returns a fully-constructed SPI object and is the
sanctioned way to obtain an outbound channel.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import (
    Rule, call_name, receiver_chain)
from ytk_mp4j_tpu.analysis.report import Severity

_CHANNEL_CTORS = frozenset({"Channel", "TcpChannel", "ShmChannel"})


class R12TransportSpiBypass(Rule):
    rule_id = "R12"
    severity = Severity.ERROR
    title = "transport construction outside transport/"
    description = ("raw socket.socket(...) or concrete Channel "
                   "construction outside the transport SPI bypasses "
                   "epoch pinning, fault hooks and transport-tagged "
                   "stats; acquire channels through the slave's "
                   "fenced accessors (or transport.connect)")
    example = """\
import socket

def open_side_channel(self):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    return s                    # bypasses the Channel SPI
"""

    def visit_Call(self, node: ast.Call):       # noqa: N802
        if self.ctx.in_dirs("transport", "analysis"):
            return
        name = call_name(node)
        if name == "socket":
            recv = receiver_chain(node)
            # socket.socket(...) — or socket(...) where the bare name
            # came from the socket module is indistinguishable from a
            # user callable, so only the dotted form (the repo idiom)
            # is flagged
            if recv is not None and recv[-1] == "socket":
                self.report(node, (
                    "raw socket.socket(...) outside transport/: "
                    "socket construction belongs behind the Channel "
                    "SPI (transport.tcp / transport.shm); rendezvous "
                    "surfaces are the only baselined exception"))
        elif name in _CHANNEL_CTORS:
            self.report(node, (
                f"{name}(...) constructed outside transport/: "
                "collective/control code must program against the "
                "Channel contract and acquire peers through the "
                "fenced accessors (epoch pinning, fault hooks and "
                "transport-tagged stats all hang off the SPI)"))
        self.generic_visit(node)
