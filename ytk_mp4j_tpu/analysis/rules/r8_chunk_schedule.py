"""R8 — chunk schedule derived from rank-local state.

The pipelined collective engine splits per-step segments into
``MP4J_CHUNK_BYTES`` chunks. The chunk SCHEDULE (how many chunks, what
sizes) must be a pure function of job-wide call parameters — segment
size, dtype, env thresholds — exactly like the raw/framed wire decision
(R4's contract): two peers of one exchange derive the schedule
independently, and a rank-local input (``rank``, ``vr``, a thread rank)
would make them disagree about how many transfers to expect. Unlike a
mismatched operand, a mismatched chunk count doesn't corrupt data — it
deadlocks one side waiting for a chunk the other never sends.

Heuristic: a ``for``/``while`` loop in ``comm/`` / ``transport/`` whose
header (the iterable / the condition) mentions BOTH a chunk-ish
identifier (``*chunk*`` — the engine's naming convention:
``chunk_ranges``, ``_chunk_bytes``, ``n_chunks``, ...) and a rank-ish
identifier (``rank`` / ``vr`` / ``_tr`` ..., the R1 vocabulary). Using
a rank to pick WHICH segment to move is the normal shape of the
ring/halving algorithms and is not flagged — only rank-dependence
inside the chunk-loop header itself, where it sizes the schedule.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules.common import expr_mentions_rank

_SCHEDULE_DIRS = ("comm", "transport")


def _is_chunkish(ident: str) -> bool:
    return "chunk" in ident.lower()


def _mentions_chunk(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and _is_chunkish(node.id):
            return True
        if isinstance(node, ast.Attribute) and _is_chunkish(node.attr):
            return True
    return False


class R8RankLocalChunkSchedule(Rule):
    rule_id = "R8"
    severity = Severity.ERROR
    title = "rank-local chunk schedule"
    description = ("chunk-loop trip count depends on rank-local state; "
                   "peers would disagree on the number of transfers "
                   "and deadlock")
    example = """\
def exchange(self, arr):
    for lo, hi in chunk_ranges(arr.size - self.rank, 8, CHUNK):
        self._exchange_raw(1, 1, arr[lo:hi], None)
"""

    def _check_header(self, node: ast.AST, header: ast.AST) -> None:
        if not self.ctx.in_dirs(*_SCHEDULE_DIRS):
            return
        if _mentions_chunk(header) and expr_mentions_rank(header):
            self.report(node, (
                "chunk schedule derived from rank-local state: the "
                "trip count must be a pure function of job-wide call "
                "parameters (segment size, dtype, MP4J_CHUNK_BYTES) "
                "or peers deadlock expecting different chunk counts"))

    def visit_For(self, node: ast.For):         # noqa: N802
        self._check_header(node, node.iter)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):     # noqa: N802
        self._check_header(node, node.test)
        self.generic_visit(node)
