"""R7 — mutable default arguments and module-level mutable state.

Two shapes:

- a mutable default (``def f(x, acc=[])``), anywhere: the default is
  created once and shared across calls — in a comm stack that means
  shared across ranks/threads of a process, a cross-rank state leak.
- a module-level ``{}`` / ``[]`` / ``set()`` in ``comm/`` / ``ops/`` /
  ``transport/`` that the module itself mutates: process-global state
  shared by every job and thread in the process. Read-only lookup
  tables are fine and not flagged; deliberate process-wide caches carry
  inline suppressions naming their reset path.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity

_MUTABLE_CTORS = {"list", "dict", "set", "defaultdict", "Counter",
                  "OrderedDict", "deque", "bytearray"}
_MUTATORS = {"append", "extend", "insert", "clear", "update",
             "setdefault", "pop", "popitem", "remove", "add", "discard",
             "appendleft", "sort"}
_STATE_DIRS = ("comm", "ops", "transport")


def _is_mutable_literal(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    return isinstance(expr, ast.Call) and call_name(expr) in _MUTABLE_CTORS


class R7MutableState(Rule):
    rule_id = "R7"
    severity = Severity.ERROR
    title = "shared mutable state"
    description = ("mutable default argument, or module-level mutable "
                   "container mutated at runtime in comm/ops/transport")
    example = """\
def accumulate(x, acc=[], *, opts={}):
    acc.append(x)               # shared across EVERY call
"""

    # -- mutable defaults ----------------------------------------------
    def visit_FunctionDef(self, node):           # noqa: N802
        args = node.args
        for arg, default in list(zip(reversed(args.posonlyargs + args.args),
                                     reversed(args.defaults))) + \
                list(zip(args.kwonlyargs, args.kw_defaults)):
            if default is not None and _is_mutable_literal(default):
                self.report(default, (
                    f"mutable default for parameter '{arg.arg}' is "
                    f"created once and shared across every call (and "
                    f"every rank/thread in the process)"))
        self.generic_visit_scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- module-level mutated containers -------------------------------
    def visit_Module(self, node: ast.Module):    # noqa: N802
        if self.ctx.in_dirs(*_STATE_DIRS):
            candidates: dict[str, ast.stmt] = {}
            for stmt in node.body:
                target = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    target, value = stmt.targets[0].id, stmt.value
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.value is not None:
                    target, value = stmt.target.id, stmt.value
                if target and _is_mutable_literal(value):
                    candidates[target] = stmt
            for name in self._mutated_names(node, set(candidates)):
                stmt = candidates[name]
                self.report(stmt, (
                    f"module-level mutable '{name}' is mutated at "
                    f"runtime — process-global state shared across "
                    f"jobs and threads; prefer instance state (or "
                    f"suppress naming the reset path)"))
        self.generic_visit(node)

    @staticmethod
    def _mutated_names(tree: ast.Module, names: set[str]) -> list[str]:
        hit: set[str] = set()
        for n in ast.walk(tree):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in names:
                        hit.add(t.value.id)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in names:
                        hit.add(t.value.id)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _MUTATORS \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id in names:
                hit.add(n.func.value.id)
        return sorted(hit)
