"""R4 — operand disagreement between paired segment transfers.

``_send_segment`` / ``_recv_segment`` / ``_recv_segment_into`` are the
two ends of one wire exchange: the sender frames (or raw-sends) with
its operand's dtype, the receiver sizes and decodes with its own. The
raw/framed decision and the element size are both pure functions of the
operand, so every segment call inside one collective must pass the SAME
operand expression — a mismatch means the two sides of the exchange
disagree about the bytes on the wire (silent corruption on the raw
path, shape/dtype errors on the framed one).

The rule checks each function independently: all segment-transfer call
sites in it must name one operand expression.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, call_name
from ytk_mp4j_tpu.analysis.report import Severity
from ytk_mp4j_tpu.analysis.rules.common import walk_pruned

# call name -> positional index of the operand argument
_SEGMENT_CALLS = {
    "_send_segment": 2,
    "_recv_segment": 2,
    "_recv_segment_into": 4,
}


def _operand_expr(call: ast.Call) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == "operand":
            return kw.value
    idx = _SEGMENT_CALLS[call_name(call)]
    if len(call.args) > idx:
        return call.args[idx]
    return None


class R4OperandPairing(Rule):
    rule_id = "R4"
    severity = Severity.ERROR
    title = "segment operand mismatch"
    description = ("paired _send_segment/_recv_segment call sites in one "
                   "collective pass different operands")
    example = """\
class C:
    def bcast(self, arr, operand):
        if self.rank == 0:
            self._send_segment(1, arr, operand)
        else:
            self._recv_segment_into(0, arr, 0, 8, Operands.DOUBLE)
"""

    def visit_FunctionDef(self, node):           # noqa: N802
        # own body only; nested defs are visited as their own functions
        seen: dict[str, ast.Call] = {}           # operand dump -> first call
        for n in walk_pruned(node.body):
            if isinstance(n, ast.Call) and call_name(n) in _SEGMENT_CALLS:
                operand = _operand_expr(n)
                if operand is None:
                    continue
                key = ast.dump(operand)
                if seen and key not in seen:
                    first_key, first = next(iter(seen.items()))
                    self.report(n, (
                        f"segment transfer passes operand "
                        f"{ast.unparse(operand)!r} but a paired call at "
                        f"line {first.lineno} uses "
                        f"{ast.unparse(_operand_expr(first))!r} — sender "
                        f"and receiver will disagree on the wire format"))
                seen.setdefault(key, n)
        self.generic_visit_scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef
