"""R22 — transport-decision size literal outside tuning/tuner.

The self-tuning data plane (ISSUE 15) rests on ONE premise: every
numeric threshold that shapes a transport decision — routing floors,
ring minimums, chunk bounds, buffer sizes — lives in
``utils/tuning.py`` (static knobs + shared constants) or
``utils/tuner.py`` (policy parameters), where it is validated once,
documented once, and visible to the policy core. A size literal
inlined at a decision site in ``comm/`` or ``transport/`` is KNOB
DRIFT: the day someone tunes the central constant, the inlined twin
silently disagrees — and on a wire protocol (the shm ring/carrier
routing, the handshake's ring floor) a disagreement between two ranks
is a hang, not a slowdown. This is exactly the bug class PR 15 found
in the peer handshake (a hard-coded ``4096`` mirroring the
``MP4J_SHM_RING_BYTES`` validator's floor).

Heuristic: an integer literal >= ``_SIZE_FLOOR`` (4096 — below that
the literal is a small protocol constant, not a size knob) used as a
DECISION input in ``comm/`` or ``transport/``:

- an operand of a comparison (``n >= 262144`` — the routing shape);
- an argument of ``min()``/``max()`` (the clamp shape).

Plain data arguments (``recv(65536)``, ``listen(64)``) and
assignments are not flagged — only the sites where the literal
*decides*. Sanctioned sites carry inline suppressions or baseline
entries arguing why the literal is not a knob.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule
from ytk_mp4j_tpu.analysis.report import Severity

_SIZE_FLOOR = 4096

_MSG = ("size literal {v} feeds a transport decision here: move it to "
        "utils/tuning.py (a validated knob / shared constant) or "
        "utils/tuner.py (a policy parameter) and reference it — an "
        "inlined size threshold drifts silently from the central knob "
        "it mirrors (on a wire-protocol decision, a drifted pair of "
        "ranks hangs)")


def _is_size_literal(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool)
            and node.value >= _SIZE_FLOOR)


class R22KnobLiteral(Rule):
    rule_id = "R22"
    severity = Severity.ERROR
    title = "transport-decision size literal outside tuning/tuner"
    description = ("numeric size thresholds feeding transport "
                   "decisions in comm/ or transport/ must live in "
                   "utils/tuning.py or utils/tuner.py — an inlined "
                   "literal drifts from the knob it mirrors")
    example = """\
def send_raw(self, view):
    if len(view) >= 262144:     # inlined twin of SHM_RING_MIN_BYTES
        self._ring_send(view)
    else:
        self._carrier_send(view)
"""
    example_path = "ytk_mp4j_tpu/transport/example.py"

    def _in_scope(self) -> bool:
        return self.ctx.in_dirs("comm", "transport")

    def visit_Compare(self, node):              # noqa: N802
        if self._in_scope():
            for cand in (node.left, *node.comparators):
                if _is_size_literal(cand):
                    self.report(cand, _MSG.format(v=cand.value))
        self.generic_visit(node)

    def visit_Call(self, node):                 # noqa: N802
        if (self._in_scope() and isinstance(node.func, ast.Name)
                and node.func.id in ("min", "max")):
            for cand in node.args:
                if _is_size_literal(cand):
                    self.report(cand, _MSG.format(v=cand.value))
        self.generic_visit(node)
