"""R18 — bare ``time.sleep()`` inside a ``while`` loop (comm/
resilience/obs control code).

The packages this rule covers run long-lived control loops: the
master's watchdog, the autoscaler controller (ISSUE 13), heartbeat and
sink drain threads, the progression scheduler. A loop that paces
itself with ``time.sleep()`` is deaf for the whole interval — it can
neither shut down promptly when the job ends (every sleeping thread
adds its full interval to shutdown latency) nor react to a state flip
it exists to watch (a circuit-breaker trip, a terminal abort, a stop
flag). The discipline is ``Event.wait(timeout)`` (or a ``Condition``
wait): same pacing, but the setter wakes the loop IMMEDIATELY — the
master's watchdog (``self._stop.wait(tick)``) and the autoscaler loop
are the house pattern.

Heuristic: a ``time.sleep(...)`` call lexically inside a ``while``
statement, in files under ``comm/``, ``resilience/`` or ``obs/``.
Nested function definitions reset the loop tracking (a closure's sleep
runs on its own schedule, not per-iteration of the enclosing loop).
Sanctioned sites — bounded micro-backoffs inside data-plane poll
loops that already observe the epoch fence, interactive CLI polls
whose only waker is the keyboard — carry baseline entries arguing
exactly that.
"""

from __future__ import annotations

import ast

from ytk_mp4j_tpu.analysis.engine import Rule, attr_chain
from ytk_mp4j_tpu.analysis.report import Severity

_MSG = ("time.sleep() inside a while loop: a sleeping control loop "
        "cannot shut down promptly or react to the state it watches "
        "(stop flags, breaker trips, terminal aborts) — pace the loop "
        "with Event.wait(timeout) / Condition.wait so the setter wakes "
        "it immediately (or baseline a bounded data-plane backoff)")


class R18SleepLoop(Rule):
    rule_id = "R18"
    severity = Severity.ERROR
    title = "bare time.sleep() inside a while loop"
    description = ("control loops in comm/resilience/obs must pace "
                   "with Event.wait(timeout), not time.sleep — a "
                   "sleeping controller can neither stop promptly "
                   "nor notice a trip")
    example = """\
import time

def watchdog(self):
    while not self._stop_flag:
        self._tick()
        time.sleep(0.5)         # deaf to the stop flag for 500 ms
"""

    def run(self, ctx):
        self._while_depth = 0
        return super().run(ctx)

    def visit_While(self, node):                # noqa: N802
        self._while_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._while_depth -= 1

    def _visit_func(self, node):
        # a nested def's body executes on its own schedule — the
        # enclosing loop's cadence does not apply to it
        saved, self._while_depth = self._while_depth, 0
        try:
            self.generic_visit_scoped(node)
        finally:
            self._while_depth = saved

    def visit_FunctionDef(self, node):          # noqa: N802
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):     # noqa: N802
        self._visit_func(node)

    def visit_Call(self, node):                 # noqa: N802
        if (self._while_depth
                and self.ctx.in_dirs("comm", "resilience", "obs")
                and attr_chain(node.func) == ["time", "sleep"]):
            self.report(node, _MSG)
        self.generic_visit(node)
