"""Vocabulary shared by the mp4j-lint rules: what counts as a
collective, and what counts as a rank-dependent expression, in this
codebase's idiom (slave methods ``allreduce_array``/``reduce_map``/...,
functional ops ``allreduce``/``scatter``/..., ``barrier`` /
``thread_barrier``)."""

from __future__ import annotations

import ast
from collections import Counter

from ytk_mp4j_tpu.analysis.engine import call_name

# the 7 collective families of the slave contract + the barriers
_COLLECTIVE_BASES = {
    "allreduce", "reduce", "broadcast", "allgather", "gather",
    "scatter", "reduce_scatter",
}
_COLLECTIVE_SUFFIXES = ("_array", "_map", "")
_BARRIERS = {"barrier", "thread_barrier"}

# identifiers that carry a rank: the slave API names plus the local
# spellings used by the collective algorithms (vr = virtual rank in the
# binomial/halving code, _tr = thread rank)
_RANK_EXTRA = {"vr", "_tr", "tr", "src_vr", "dst_vr"}

_DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
              ast.Lambda)


def is_collective_name(name: str | None) -> bool:
    if not name:
        return False
    if name in _BARRIERS:
        return True
    for suf in _COLLECTIVE_SUFFIXES:
        if suf and not name.endswith(suf):
            continue
        base = name[:len(name) - len(suf)] if suf else name
        if base in _COLLECTIVE_BASES:
            return True
    return False


def is_rankish_ident(ident: str) -> bool:
    return "rank" in ident.lower() or ident in _RANK_EXTRA


def expr_mentions_rank(expr: ast.AST) -> bool:
    """True when any identifier in the expression carries a rank."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and is_rankish_ident(node.id):
            return True
        if isinstance(node, ast.Attribute) and is_rankish_ident(node.attr):
            return True
    return False


def walk_pruned(roots, prune=_DEF_NODES):
    """Walk all nodes under ``roots`` without descending into nested
    function / class / lambda definitions: their code does not execute
    where it is written, so it doesn't belong to the enclosing
    statement's schedule."""
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, prune):
            continue
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def collective_calls(body) -> Counter:
    """Multiset of collective/barrier names called by a branch arm."""
    return Counter(
        name for node in walk_pruned(body)
        if isinstance(node, ast.Call)
        and is_collective_name(name := call_name(node)))
