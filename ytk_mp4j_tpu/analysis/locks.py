"""Lock model for mp4j-lint's whole-program concurrency rules
(ISSUE 14).

The package's safety rests on hand-enforced lock disciplines — "master
-> controller only", "``_tel_lock`` never nests inside
``_master_lock``", "events minted under the lock dispatch from an
outbox outside it". This module turns those review-time rules into a
checked artifact:

1. **Lock discovery** — every ``threading.Lock``/``RLock``/
   ``Condition`` assignment site becomes a lock node identified by its
   DEFINING site ``(class, attr)`` (or ``(module, name)``). Two
   instances of the same class share a node: for ordering analysis the
   conservative merge is exactly right — an order violation between
   any two instances is a violation of the class's discipline.
2. **Per-function summaries** — each function's acquisition events
   (``with``-nesting and linear ``acquire()``/``release()`` pairs),
   call sites and blocking operations, each annotated with the set of
   locks held at that point.
3. **Interprocedural propagation** — a fixpoint over the call graph
   computes, per function, every lock it may transitively acquire and
   every blocking operation it may transitively reach, each with one
   shortest witness chain.
4. **The lock-order graph** — an edge ``A -> B`` means some execution
   acquires ``B`` while holding ``A``, with a witness call chain. R19
   reports its cycles; ``mp4j-lint graph --dot`` dumps it.

Closures are summarized with an EMPTY held set (their bodies run on
their own thread/schedule, not at the definition site), and
unresolvable lock expressions or callees contribute nothing: a missed
edge can hide a finding but never invent one.
"""

from __future__ import annotations

import ast
import dataclasses

from ytk_mp4j_tpu.analysis.callgraph import (
    FunctionInfo, ProgramIndex)
from ytk_mp4j_tpu.analysis.engine import attr_chain

_LOCK_KINDS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

# -- R20 blocking vocabulary -------------------------------------------
# Channel SPI + raw socket verbs: any of these holds the calling
# thread against a peer's progress.
_CHANNEL_BLOCKERS = {
    "recv", "recv_into", "recv_obj", "recv_array", "recv_array_into",
    "recv_map_columns", "recv_raw_into", "sendall", "send_obj",
    "send_array", "send_map_columns", "send_raw", "accept", "connect",
}
# synchronization waits: Event.wait / Condition.wait(_for) / future
# wait; a wait on a HELD condition releases it for the duration and is
# exempted at charge time, every other held lock still stalls.
_WAIT_BLOCKERS = {"wait", "wait_for", "wait_all"}
_SUBPROCESS_BLOCKERS = {"run", "check_call", "check_output", "call",
                        "communicate"}
_THREADISH = ("thread", "proc", "worker", "drain", "heartbeat")

# -- R23 access vocabulary ---------------------------------------------
# attribute types that are internally synchronized (or are themselves
# the synchronization): accessing the OBJECT is safe, so these never
# become shared-field access events — the data they guard does.
_SYNC_TYPES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.Thread",
    "queue.Queue",
})
# container verbs that MUTATE their receiver: `self._peers.pop(r)` is
# a write to `_peers`, not a read
_MUTATORS = {
    "append", "extend", "insert", "pop", "remove", "clear", "sort",
    "reverse", "update", "setdefault", "add", "discard", "popitem",
    "appendleft", "popleft",
}


@dataclasses.dataclass(frozen=True)
class LockDecl:
    key: str            # "Master._lock@comm.master" / "spans._lock@obs.spans"
    kind: str           # Lock | RLock | Condition | local
    cls: str | None
    attr: str
    module: str         # dotted module id
    path: str
    lineno: int

    @property
    def display(self) -> str:
        return f"{self.cls}.{self.attr}" if self.cls else \
            f"{self.module.rsplit('.', 1)[-1]}.{self.attr}"

    @property
    def reentrant(self) -> bool:
        # threading.Condition's default internal lock is an RLock
        return self.kind in ("RLock", "Condition")


@dataclasses.dataclass
class AcqEvent:
    lock: str                    # LockDecl key
    held: tuple[str, ...]        # locks held at the acquisition
    lineno: int


@dataclasses.dataclass
class CallEvent:
    callees: tuple[str, ...]     # FunctionInfo keys (resolved)
    held: tuple[str, ...]
    lineno: int
    display: str                 # terminal callee name for messages


@dataclasses.dataclass
class BlockEvent:
    what: str                    # e.g. "socket/channel recv", "Event.wait"
    terminal: str                # the called name
    held: tuple[str, ...]
    lineno: int
    recv_lock: str | None        # lock key when the receiver IS a lock


@dataclasses.dataclass
class HookEvent:
    name: str                    # the hook-ish callable's name
    held: tuple[str, ...]
    lineno: int


@dataclasses.dataclass
class AccessEvent:
    """One read/write of an instance attribute of an index class, with
    the locks held at the site (ISSUE 16's lockset substrate)."""

    owner: str                   # receiver's ClassInfo key ("mod:Cls")
    attr: str
    write: bool
    held: tuple[str, ...]
    lineno: int


@dataclasses.dataclass
class Summary:
    func: FunctionInfo
    acquires: list[AcqEvent] = dataclasses.field(default_factory=list)
    calls: list[CallEvent] = dataclasses.field(default_factory=list)
    blockers: list[BlockEvent] = dataclasses.field(default_factory=list)
    hooks: list[HookEvent] = dataclasses.field(default_factory=list)
    accesses: list[AccessEvent] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass(frozen=True)
class Edge:
    """One observed acquisition order src -> dst with a witness."""

    src: str
    dst: str
    chain: tuple[str, ...]       # function displays, caller-first
    path: str                    # file of the charging frame
    lineno: int                  # line of the charging frame


def _is_hookish(name: str) -> bool:
    low = name.lower()
    return (low.endswith("hook") or low.endswith("callback")
            or low.endswith("_cb") or low == "cb")


class _FuncWalker:
    """Extract one function's Summary: a recursive statement walk that
    threads the held-lock tuple through ``with`` nesting and linear
    ``acquire()``/``release()`` pairs, typing locals as it goes."""

    def __init__(self, model: "LockModel", func: FunctionInfo):
        self.model = model
        self.index = model.index
        self.func = func
        self.out = Summary(func)
        self.local_types: dict[str, str] = {}
        self.local_lock_alias: dict[str, str] = {}   # name -> lock key

    def walk(self) -> Summary:
        self._stmts(self.func.node.body, ())
        return self.out

    # -- statement traversal -------------------------------------------
    def _stmts(self, body, held):
        for stmt in body:
            held = self._stmt(stmt, held)
        return held

    def _stmt(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._expr(item.context_expr, inner)
                lk = self._resolve_lock(item.context_expr)
                if lk is not None:
                    self.out.acquires.append(AcqEvent(
                        lk, inner, node.lineno))
                    if lk not in inner:
                        inner = inner + (lk,)
            self._stmts(node.body, inner)
            return held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # a closure runs on its own schedule: empty held set, but
            # its acquisitions/calls still belong to this summary so
            # thread bodies defined inline are not invisible
            self._stmts(getattr(node, "body", []), ())
            return held
        if isinstance(node, ast.Try):
            h = self._stmts(node.body, held)
            for hd in node.handlers:
                self._stmts(hd.body, held)
            self._stmts(node.orelse, h)
            return self._stmts(node.finalbody, h)
        if isinstance(node, ast.If):
            self._expr(node.test, held)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return held
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter, held)
            self._type_loop_target(node)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return held
        if isinstance(node, ast.While):
            self._expr(node.test, held)
            self._stmts(node.body, held)
            self._stmts(node.orelse, held)
            return held
        if isinstance(node, ast.Assign):
            self._expr(node.value, held)
            self._track_assign(node)
            for tgt in node.targets:
                self._assign_target(tgt, held)
            return held
        if isinstance(node, ast.AugAssign):
            self._expr(node.value, held)
            self._assign_target(node.target, held)
            return held
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._assign_target(tgt, held)
            return held
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._expr(node.value, held)
                self._assign_target(node.target, held)
            return held
        if isinstance(node, ast.Expr):
            # statement-level acquire()/release() adjusts the linear
            # held set for the REST of this statement list
            adj = self._acquire_release(node.value, held)
            if adj is not None:
                return adj
            self._expr(node.value, held)
            return held
        if isinstance(node, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(node):
                self._expr(child, held)
            return held
        # default: visit child expressions, recurse into child stmt
        # lists (Match etc.) conservatively with the same held set
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, held)
            else:
                self._expr(child, held)
        return held

    def _acquire_release(self, expr, held):
        if not isinstance(expr, ast.Call) \
                or not isinstance(expr.func, ast.Attribute) \
                or expr.func.attr not in ("acquire", "release"):
            return None
        lk = self._resolve_lock(expr.func.value)
        if lk is None:
            return None
        if expr.func.attr == "acquire":
            self.out.acquires.append(AcqEvent(lk, held, expr.lineno))
            return held if lk in held else held + (lk,)
        return tuple(h for h in held if h != lk)

    def _type_loop_target(self, node) -> None:
        # `for s in self._slots:` types s as the list's element class
        if isinstance(node.target, ast.Name):
            t = self._expr_type(node.iter)
            if t and t.startswith("list:") and len(t) > 5:
                self.local_types[node.target.id] = t[5:]

    def _track_assign(self, node: ast.Assign) -> None:
        if len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        self.local_types.pop(name, None)
        self.local_lock_alias.pop(name, None)
        value = node.value
        lk = self._resolve_lock(value, declare_local=name)
        if lk is not None:
            self.local_lock_alias[name] = lk
            return
        t = self._expr_type(value)
        if t is not None:
            self.local_types[name] = t

    def _expr_type(self, expr) -> str | None:
        t = self.index.type_of_expr(expr, self.func.module)
        if t is not None:
            return t
        ch = attr_chain(expr)
        if ch:
            if len(ch) == 1 and ch[0] in self.local_types:
                return self.local_types[ch[0]]
            return self.index.resolve_receiver_type(
                ch, self.func, self.local_types)
        if isinstance(expr, ast.Subscript):
            base_t = self._expr_type(expr.value)
            if base_t and base_t[:5] in ("list:", "dict:") \
                    and len(base_t) > 5:
                return base_t[5:]
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr == "get":
            base_t = self._expr_type(expr.func.value)
            if base_t and base_t.startswith("dict:"):
                return base_t[5:]
        if isinstance(expr, ast.IfExp):
            return (self._expr_type(expr.body)
                    or self._expr_type(expr.orelse))
        return None

    # -- expression traversal ------------------------------------------
    def _expr(self, expr, held) -> None:
        """Recursive expression walk: classify calls, and record every
        resolvable attribute read/write with the held-lock set. A
        ``wait()``/``wait_for()`` on a HELD condition RELEASES it for
        the duration, so its argument expressions (predicates, lambda
        bodies) are walked with the condition's lock removed — a site
        reached from inside the wait must not be credited with a lock
        the wait gave up (ISSUE 16)."""
        if expr is None:
            return
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(expr, ast.Lambda):
            self._expr(expr.body, held)
            return
        if isinstance(expr, ast.Call):
            self._call(expr, held)
            arg_held = self._wait_arg_held(expr, held)
            f = expr.func
            if isinstance(f, ast.Attribute):
                if f.attr in _MUTATORS \
                        and isinstance(f.value, ast.Attribute) \
                        and not self._user_method(f.value, f.attr):
                    self._access(f.value, held, write=True)
                    self._expr(f.value.value, held)
                else:
                    self._expr(f.value, held)
            elif not isinstance(f, ast.Name):
                self._expr(f, held)
            for a in expr.args:
                self._expr(a, arg_held)
            for kw in expr.keywords:
                self._expr(kw.value, arg_held)
            return
        if isinstance(expr, ast.Attribute):
            self._access(expr, held, write=False)
            self._expr(expr.value, held)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.stmt):
                self._stmt(child, held)
            elif isinstance(child, ast.AST):
                self._expr(child, held)

    def _assign_target(self, tgt, held) -> None:
        """Record write accesses for assignment/del targets: attribute
        stores, and subscript stores into an attribute container."""
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._assign_target(e, held)
        elif isinstance(tgt, ast.Starred):
            self._assign_target(tgt.value, held)
        elif isinstance(tgt, ast.Subscript):
            self._expr(tgt.slice, held)
            base = tgt.value
            while isinstance(base, ast.Subscript):
                self._expr(base.slice, held)
                base = base.value
            if isinstance(base, ast.Attribute):
                self._access(base, held, write=True)
        elif isinstance(tgt, ast.Attribute):
            self._access(tgt, held, write=True)
            self._expr(tgt.value, held)

    def _access(self, node, held, write: bool) -> None:
        """One attribute read/write, filtered down to what the lockset
        analysis can reason about: instance fields of INDEX classes.
        Locks themselves, internally-synchronized objects (events,
        queues, threads) and bound-method references are not data."""
        if not isinstance(node, ast.Attribute):
            return
        attr = node.attr
        if attr.startswith("__"):
            return
        if self._resolve_lock(node) is not None:
            return
        owner = self._expr_type(node.value)
        if not owner or ":" not in owner \
                or owner.startswith(("list:", "dict:")):
            return
        oci = self.index.classes.get(owner)
        if oci is None:
            return
        at = self.index.attr_type(oci, attr)
        if at in _SYNC_TYPES:
            return
        if self.index.lookup_method(oci, attr) is not None:
            return
        self.out.accesses.append(AccessEvent(
            owner, attr, write, held, node.lineno))

    def _user_method(self, receiver: ast.Attribute, name: str) -> bool:
        """True when ``receiver.name(...)`` resolves to a method a
        class in the index DEFINES: then the call is tracked through
        the call graph (the callee's own accesses carry the locksets)
        and the container-verb heuristic must not also charge the
        receiver field with a write — ``stats.add(...)`` mutates
        *inside* ``CommStats.add``, it does not rebind ``stats``."""
        owner = self._expr_type(receiver)
        if not owner or ":" not in owner:
            return False
        oci = self.index.classes.get(owner)
        if oci is None:
            return False
        return self.index.lookup_method(oci, name) is not None

    def _wait_arg_held(self, call: ast.Call, held):
        """Held set for a call's ARGUMENT expressions: minus the
        receiver condition for ``wait``/``wait_for`` on a held lock."""
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in ("wait",
                                                       "wait_for"):
            lk = self._resolve_lock(f.value)
            if lk is not None and lk in held:
                return tuple(h for h in held if h != lk)
        return held

    def _resolve_func_ref(self, expr) -> list[FunctionInfo]:
        """A bare function/bound-method REFERENCE (not a call):
        ``self._drained`` / ``check_fn`` -> FunctionInfo candidates."""
        ch = attr_chain(expr)
        if not ch:
            return []
        if len(ch) == 1:
            fi = self.func.module.functions.get(ch[0])
            return [fi] if fi is not None else []
        owner = self.index._owner_class(ch[:-1], self.func,
                                        self.local_types)
        if owner is not None:
            fi = self.index.lookup_method(owner, ch[-1])
            return [fi] if fi is not None else []
        return []

    def _call(self, call: ast.Call, held) -> None:
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name is None:
            return
        if name in ("acquire", "release"):
            # non-statement-level acquire/release: record the acquire
            # event (ordering) without linear tracking
            if name == "acquire":
                lk = self._resolve_lock(call.func.value) \
                    if isinstance(call.func, ast.Attribute) else None
                if lk is not None:
                    self.out.acquires.append(
                        AcqEvent(lk, held, call.lineno))
            return
        self._classify_blocking(call, name, held)
        if name == "wait_for" and call.args \
                and isinstance(call.func, ast.Attribute):
            # the predicate runs INSIDE the wait, i.e. with the
            # condition's lock re-acquired around each evaluation but
            # released between them — model the call edge with the
            # condition removed from the held set so real R23 findings
            # under the predicate are not masked by a false "held"
            lk = self._resolve_lock(call.func.value)
            inner = tuple(h for h in held if h != lk) \
                if lk is not None else held
            preds = self._resolve_func_ref(call.args[0])
            if preds:
                self.out.calls.append(CallEvent(
                    tuple(fi.key for fi in preds), inner, call.lineno,
                    preds[0].name))
        if _is_hookish(name):
            self.out.hooks.append(HookEvent(name, held, call.lineno))
        callees = self.index.resolve_call(call, self.func,
                                          self.local_types)
        if callees:
            self.out.calls.append(CallEvent(
                tuple(fi.key for fi in callees), held, call.lineno,
                name))

    def _classify_blocking(self, call, name, held) -> None:
        chain = attr_chain(call.func) or []
        recv = chain[:-1]
        if name in _CHANNEL_BLOCKERS:
            # `connect` also names non-socket verbs; require a
            # receiver for the socket-ish ones that need one
            self.out.blockers.append(BlockEvent(
                f"socket/channel {name}", name, held, call.lineno,
                None))
            return
        if name in _WAIT_BLOCKERS:
            recv_lock = self._resolve_lock(call.func.value) \
                if isinstance(call.func, ast.Attribute) else None
            self.out.blockers.append(BlockEvent(
                f"{name}() on " + (".".join(recv) if recv else "a waitable"),
                name, held, call.lineno, recv_lock))
            return
        if name == "sleep" and recv == ["time"]:
            self.out.blockers.append(BlockEvent(
                "time.sleep", name, held, call.lineno, None))
            return
        if name in _SUBPROCESS_BLOCKERS and recv == ["subprocess"]:
            self.out.blockers.append(BlockEvent(
                f"subprocess.{name}", name, held, call.lineno, None))
            return
        if name == "select" and recv in (["select"], ["selectors"]):
            self.out.blockers.append(BlockEvent(
                "select.select", name, held, call.lineno, None))
            return
        if name == "join":
            # thread/process join only: typed receivers, or names that
            # read as threads — never str.join / os.path.join
            if recv in (["os", "path"], ["posixpath"], ["ntpath"]):
                return
            t = self.index.resolve_receiver_type(
                recv, self.func, self.local_types) if recv else None
            if recv and recv[0] in self.local_types and t is None:
                t = None
            threadish = (t == "threading.Thread"
                         or any(any(p in seg.lower() for p in _THREADISH)
                                for seg in recv))
            if threadish:
                self.out.blockers.append(BlockEvent(
                    ".".join(recv) + ".join()", name, held, call.lineno,
                    None))
            return
        if name in ("get", "put"):
            t = self.index.resolve_receiver_type(
                recv, self.func, self.local_types) if recv else None
            if t == "queue.Queue":
                self.out.blockers.append(BlockEvent(
                    f"Queue.{name}", name, held, call.lineno, None))

    # -- lock resolution ------------------------------------------------
    def _resolve_lock(self, expr, declare_local: str | None = None
                      ) -> str | None:
        """Lock key for an expression, or None. ``declare_local``
        registers a fresh function-local lock for ``x = Lock()``."""
        model = self.model
        if isinstance(expr, ast.Call) and declare_local is not None:
            t = self.index.type_of_expr(expr, self.func.module)
            kind = _LOCK_KINDS.get(t or "")
            if kind:
                return model.declare_local_lock(
                    self.func, declare_local, kind, expr.lineno)
            return None
        chain = attr_chain(expr)
        if not chain:
            # subscripted/computed receivers: `self._slots[rank].lock`
            if isinstance(expr, ast.Attribute):
                t = self._expr_type(expr.value)
                if t and t[:5] not in ("list:", "dict:"):
                    oci = self.index.class_of_key(t)
                    if oci is not None:
                        for c in self.index.mro(oci):
                            lk = model.lookup(c.module.name, c.name,
                                              expr.attr)
                            if lk is not None:
                                return lk
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in self.local_lock_alias:
                return self.local_lock_alias[name]
            return model.lookup(self.func.module.name, None, name)
        if chain[0] in ("self", "cls") and self.func.cls:
            mod = self.func.module
            ci = mod.classes.get(self.func.cls)
            if ci is None:
                return None
            if len(chain) == 2:
                for c in self.index.mro(ci):
                    lk = model.lookup(c.module.name, c.name, chain[1])
                    if lk is not None:
                        return lk
                return None
            owner = self.index.resolve_receiver_type(
                chain[:-1], self.func, self.local_types)
            oci = self.index.class_of_key(owner)
            if oci is not None:
                for c in self.index.mro(oci):
                    lk = model.lookup(c.module.name, c.name, chain[-1])
                    if lk is not None:
                        return lk
            return None
        # local var receiver: slot.lock / g.lock
        t = self.index.resolve_receiver_type(
            chain[:-1], self.func, self.local_types)
        oci = self.index.class_of_key(t)
        if oci is not None:
            for c in self.index.mro(oci):
                lk = model.lookup(c.module.name, c.name, chain[-1])
                if lk is not None:
                    return lk
        # imported module's lock: spans._lock
        m = self.index._imported_module(self.func.module, chain[0])
        if m is not None and len(chain) == 2:
            return model.lookup(m.name, None, chain[1])
        return None


class LockModel:
    """Discovery + summaries + fixpoint + the lock-order graph."""

    def __init__(self, index: ProgramIndex):
        self.index = index
        self.locks: dict[str, LockDecl] = {}
        self._by_site: dict[tuple[str, str | None, str], str] = {}
        self.summaries: dict[str, Summary] = {}
        # fkey -> lock key -> ("direct", lineno) | ("via", lineno, ckey)
        self.trans_acquires: dict[str, dict[str, tuple]] = {}
        # fkey -> (terminal, recv_lock) -> BlockEvent | ("via", ln, ckey)
        self.trans_blockers: dict[str, dict[tuple, tuple]] = {}
        self.trans_hooks: dict[str, dict[str, tuple]] = {}
        self.edges: dict[tuple[str, str], Edge] = {}
        self.reentries: list[tuple[str, Edge]] = []   # (lock, witness)
        self._discover()
        for fi in index.functions.values():
            self.summaries[fi.key] = _FuncWalker(self, fi).walk()
        self._fixpoint()
        self._build_edges()

    # -- discovery ------------------------------------------------------
    def declare(self, module: str, path: str, cls: str | None, attr: str,
                kind: str, lineno: int) -> str:
        key = (f"{cls}.{attr}@{module}" if cls
               else f"{attr}@{module}")
        if key not in self.locks:
            self.locks[key] = LockDecl(
                key=key, kind=kind, cls=cls, attr=attr, module=module,
                path=path, lineno=lineno)
            self._by_site[(module, cls, attr)] = key
        return key

    def declare_local_lock(self, func: FunctionInfo, name: str,
                           kind: str, lineno: int) -> str:
        return self.declare(func.module.name, func.path,
                            func.cls, f"<{func.name}:{name}>", kind,
                            lineno)

    def lookup(self, module: str, cls: str | None,
               attr: str) -> str | None:
        return self._by_site.get((module, cls, attr))

    def _discover(self) -> None:
        for mod in self.index.modules.values():
            for node in mod.ctx.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    kind = _LOCK_KINDS.get(
                        self.index.type_of_expr(node.value, mod) or "")
                    if kind:
                        self.declare(mod.name, mod.path, None,
                                     node.targets[0].id, kind,
                                     node.lineno)
            for ci in mod.classes.values():
                for m in set(ci.methods.values()):
                    if m.cls != ci.name:
                        continue      # inherited binding
                    for sub in ast.walk(m.node):
                        if not isinstance(sub, ast.Assign) \
                                or len(sub.targets) != 1:
                            continue
                        ch = attr_chain(sub.targets[0])
                        if not ch or len(ch) != 2 or ch[0] != "self":
                            continue
                        kind = _LOCK_KINDS.get(
                            self.index.type_of_expr(sub.value, mod)
                            or "")
                        if kind:
                            self.declare(mod.name, mod.path, ci.name,
                                         ch[1], kind, sub.lineno)

    # -- fixpoint -------------------------------------------------------
    def _fixpoint(self) -> None:
        for fkey, s in self.summaries.items():
            acq = {}
            for a in s.acquires:
                acq.setdefault(a.lock, ("direct", a.lineno))
            self.trans_acquires[fkey] = acq
            blk = {}
            for b in s.blockers:
                blk.setdefault((b.terminal, b.recv_lock),
                               ("direct", b.lineno, b.what))
            self.trans_blockers[fkey] = blk
            hks = {}
            for h in s.hooks:
                hks.setdefault(h.name, ("direct", h.lineno))
            self.trans_hooks[fkey] = hks
        changed = True
        while changed:
            changed = False
            for fkey, s in self.summaries.items():
                for call in s.calls:
                    for ckey in call.callees:
                        if ckey == fkey or ckey not in self.summaries:
                            continue
                        for lk in self.trans_acquires[ckey]:
                            if lk not in self.trans_acquires[fkey]:
                                self.trans_acquires[fkey][lk] = (
                                    "via", call.lineno, ckey)
                                changed = True
                        for bk, ent in self.trans_blockers[ckey] \
                                .items():
                            if bk not in self.trans_blockers[fkey]:
                                self.trans_blockers[fkey][bk] = (
                                    "via", call.lineno, ckey, ent[2]
                                    if ent[0] == "direct" else ent[3])
                                changed = True
                        for hk in self.trans_hooks[ckey]:
                            if hk not in self.trans_hooks[fkey]:
                                self.trans_hooks[fkey][hk] = (
                                    "via", call.lineno, ckey)
                                changed = True

    def _chase(self, table, fkey, key) -> tuple[tuple[str, ...], int]:
        """Witness chain (function displays) + terminal line."""
        chain: list[str] = []
        seen = set()
        lineno = 0
        while fkey not in seen:
            seen.add(fkey)
            fi = self.index.functions[fkey]
            chain.append(fi.display)
            ent = table[fkey][key]
            lineno = ent[1]
            if ent[0] == "direct":
                break
            fkey = ent[2]
        return tuple(chain), lineno

    # -- the lock-order graph ------------------------------------------
    def _note_edge(self, src, dst, chain, path, lineno) -> None:
        if src == dst:
            decl = self.locks[dst]
            if not decl.reentrant:
                self.reentries.append((dst, Edge(
                    src, dst, chain, path, lineno)))
            return
        self.edges.setdefault((src, dst), Edge(
            src, dst, chain, path, lineno))

    def _build_edges(self) -> None:
        for fkey, s in self.summaries.items():
            fi = s.func
            for a in s.acquires:
                for held in a.held:
                    self._note_edge(held, a.lock, (fi.display,),
                                    fi.path, a.lineno)
            for call in s.calls:
                if not call.held:
                    continue
                for ckey in call.callees:
                    if ckey not in self.trans_acquires:
                        continue
                    for lk in self.trans_acquires[ckey]:
                        tail, _ = self._chase(
                            self.trans_acquires, ckey, lk)
                        for held in call.held:
                            self._note_edge(
                                held, lk, (fi.display,) + tail,
                                fi.path, call.lineno)

    def cycles(self) -> list[list[str]]:
        """SCCs of size >= 2 in the lock-order graph (Tarjan)."""
        graph: dict[str, list[str]] = {}
        for (src, dst) in self.edges:
            graph.setdefault(src, []).append(dst)
            graph.setdefault(dst, [])
        index_of: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        out: list[list[str]] = []
        counter = [0]

        def strongconnect(v):
            # iterative Tarjan: (node, child-iterator) frames
            work = [(v, iter(graph[v]))]
            index_of[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index_of:
                        index_of[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(graph[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index_of[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index_of[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))

        for v in sorted(graph):
            if v not in index_of:
                strongconnect(v)
        return sorted(out)

    def format_witness(self, edge: Edge) -> str:
        site = f"{edge.path}:{edge.lineno}"
        return (f"{self.locks[edge.src].display} -> "
                f"{self.locks[edge.dst].display} via "
                + " -> ".join(edge.chain) + f" ({site})")

    def to_dot(self) -> str:
        """The discovered lock-order graph as GraphViz DOT: nodes =
        lock attrs with their defining class/module, edges = observed
        acquisition orders with one witness call chain each. The
        README's discipline table is generated from this, not prose."""
        lines = ["digraph mp4j_lock_order {",
                 '  rankdir=LR;',
                 '  node [shape=box, fontname="monospace"];']
        used = sorted({k for e in self.edges for k in e})
        for key in used:
            d = self.locks[key]
            shape = "box" if d.kind != "Condition" else "oval"
            lines.append(
                f'  "{key}" [label="{d.display}\\n'
                f'{d.kind} @ {d.module}", shape={shape}];')
        for (src, dst), e in sorted(self.edges.items()):
            label = " -> ".join(e.chain)
            lines.append(
                f'  "{src}" -> "{dst}" '
                f'[label="{label}\\n{e.path}:{e.lineno}"];')
        lines.append("}")
        return "\n".join(lines)
