"""Findings and rendering for mp4j-lint.

A :class:`Finding` is one rule violation pinned to ``file:line:col``
with a severity and the enclosing scope (``Class.method``) — the scope
is what baseline suppressions key on, so findings survive line drift
from unrelated edits.
"""

from __future__ import annotations

import dataclasses
import enum
import json


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR"
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str            # "R1".."R7", or "E001" for parse failures
    severity: Severity
    path: str            # as given to the engine (normalized to posix)
    line: int
    col: int
    message: str
    context: str = "<module>"   # enclosing Class.func qualname

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.severity}: {self.message} "
                f"[{self.context}]")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["severity"] = str(self.severity)
        return d


def render_text(findings, suppressed_count: int = 0) -> str:
    lines = [f.format() for f in findings]
    n = len(findings)
    noun = "finding" if n == 1 else "findings"
    tail = f"{n} {noun}"
    if suppressed_count:
        tail += f" ({suppressed_count} suppressed)"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings, suppressed_count: int = 0) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "suppressed": suppressed_count,
    }, indent=2, sort_keys=True)


# SARIF severity levels for the three Severity tiers
_SARIF_LEVEL = {Severity.INFO: "note",
                Severity.WARNING: "warning",
                Severity.ERROR: "error"}

_SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                     "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(findings, rule_classes=()) -> str:
    """The findings as a SARIF 2.1.0 log (one run, driver mp4j-lint).

    ``rule_classes`` is the rule catalogue that RAN (not just the rules
    that fired): SARIF viewers use ``tool.driver.rules`` to render the
    catalogue, and an empty-results log should still carry it so "0
    findings" is distinguishable from "0 rules ran". ``ruleIndex`` on
    each result points into that array. The scope qualname the baseline
    keys on travels as a partial fingerprint, so result identity
    survives line drift exactly like baseline matching does.
    """
    rules = []
    index: dict[str, int] = {}
    for cls in rule_classes:
        index[cls.rule_id] = len(rules)
        rules.append({
            "id": cls.rule_id,
            "name": cls.title.title().replace(" ", "").replace("-", ""),
            "shortDescription": {"text": cls.title},
            "fullDescription": {"text": cls.description},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL[cls.severity]},
        })
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": max(f.col, 1)},
                },
            }],
            "partialFingerprints": {"mp4jContext/v1": f.context},
        }
        if f.rule in index:
            res["ruleIndex"] = index[f.rule]
        results.append(res)
    return json.dumps({
        "$schema": _SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mp4j-lint",
                "informationUri":
                    "https://github.com/ytk-mp4j/ytk-mp4j-tpu",
                "rules": rules,
            }},
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }, indent=2, sort_keys=True)
