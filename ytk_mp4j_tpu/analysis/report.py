"""Findings and rendering for mp4j-lint.

A :class:`Finding` is one rule violation pinned to ``file:line:col``
with a severity and the enclosing scope (``Class.method``) — the scope
is what baseline suppressions key on, so findings survive line drift
from unrelated edits.
"""

from __future__ import annotations

import dataclasses
import enum
import json


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR"
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str            # "R1".."R7", or "E001" for parse failures
    severity: Severity
    path: str            # as given to the engine (normalized to posix)
    line: int
    col: int
    message: str
    context: str = "<module>"   # enclosing Class.func qualname

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.severity}: {self.message} "
                f"[{self.context}]")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["severity"] = str(self.severity)
        return d


def render_text(findings, suppressed_count: int = 0) -> str:
    lines = [f.format() for f in findings]
    n = len(findings)
    noun = "finding" if n == 1 else "findings"
    tail = f"{n} {noun}"
    if suppressed_count:
        tail += f" ({suppressed_count} suppressed)"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings, suppressed_count: int = 0) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "suppressed": suppressed_count,
    }, indent=2, sort_keys=True)
