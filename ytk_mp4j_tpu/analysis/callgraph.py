"""Whole-package module index + conservative call graph (ISSUE 14).

mp4j-lint's per-file rules see one AST at a time; the concurrency
disciplines R19-R21 check are properties of CALL CHAINS — a lock
acquired here, a blocking call three frames deeper, a hook fired from
a helper of a helper. This module builds the shared substrate: a
package-wide index of modules, classes and functions, plus a call
graph whose edges are resolved CONSERVATIVELY. An edge exists only
when the callee is identified with confidence:

- ``self.m()`` / ``cls.m()`` through the enclosing class, its bases
  (resolved across modules) and class-attribute method bindings
  (``visit_AsyncFunctionDef = visit_FunctionDef``);
- ``f()`` through module-level functions and name-assignment aliases
  (``g = f``);
- ``mod.f()`` through ``import``/``from`` aliases when ``mod`` is in
  the index;
- ``self.attr.m()`` / ``local.m()`` through inferred attribute and
  local types: ``self._recovery = RecoveryManager(...)`` binds
  ``_recovery`` to that class, a parameter whose name matches exactly
  one index class case-insensitively (``master`` -> ``Master``) binds
  the same way, and a list attribute built from one constructor
  (``self._slots = [...]`` + ``self._slots.append(_Slot(...))``)
  types its subscripts and loop variables.

Unresolvable calls contribute NO edge: for the lock analyses a missed
edge can hide a finding but never invent one, which is the right
failure mode for a tier-1 gate.
"""

from __future__ import annotations

import ast
import dataclasses

from ytk_mp4j_tpu.analysis.engine import LintContext, attr_chain

# constructor spellings worth typing even though the classes live
# outside the index (lock discovery + blocking-receiver typing)
_BUILTIN_TYPES = {
    ("threading", "Lock"): "threading.Lock",
    ("threading", "RLock"): "threading.RLock",
    ("threading", "Condition"): "threading.Condition",
    ("threading", "Event"): "threading.Event",
    ("threading", "Semaphore"): "threading.Semaphore",
    ("threading", "BoundedSemaphore"): "threading.Semaphore",
    ("threading", "Thread"): "threading.Thread",
    ("multiprocessing", "Process"): "threading.Thread",
    ("queue", "Queue"): "queue.Queue",
    ("queue", "SimpleQueue"): "queue.Queue",
}

# container verbs on a list:/dict:-typed receiver belong to the
# container, never to the element class
_CONTAINER_METHODS = {
    "append", "extend", "insert", "pop", "clear", "remove", "sort",
    "reverse", "index", "count", "copy", "update", "setdefault",
    "get", "items", "values", "keys", "popitem", "discard", "add",
}


def module_name_for(path: str) -> str:
    """Dotted module id for a display path: anchored at the package
    root when one is present (``.../ytk_mp4j_tpu/comm/master.py`` ->
    ``ytk_mp4j_tpu.comm.master``), else the bare stem — stable however
    the linter was invoked."""
    parts = path.split("/")
    if "ytk_mp4j_tpu" in parts:
        parts = parts[parts.index("ytk_mp4j_tpu"):]
    name = "/".join(parts)
    if name.endswith(".py"):
        name = name[:-3]
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


@dataclasses.dataclass(eq=False)
class FunctionInfo:
    """One top-level or class-level ``def`` in the index."""

    key: str                    # "ytk_mp4j_tpu.comm.master:Master._serve"
    name: str
    cls: str | None             # owning class name, None for module fns
    module: "ModuleInfo"
    node: ast.FunctionDef | ast.AsyncFunctionDef

    @property
    def display(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    @property
    def path(self) -> str:
        return self.module.path


@dataclasses.dataclass(eq=False)
class ClassInfo:
    key: str                    # "ytk_mp4j_tpu.comm.master:Master"
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    bases: list[str] = dataclasses.field(default_factory=list)  # raw dotted
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # attr -> type key: a ClassInfo.key, a _BUILTIN_TYPES value, or
    # ("list", elem_key) encoded as "list:" + elem_key
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(eq=False)
class ModuleInfo:
    name: str                   # dotted id
    path: str                   # posix display path
    ctx: LintContext
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    functions: dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    imports: dict[str, str] = dataclasses.field(default_factory=dict)
    # alias -> (module dotted, original name) for `from x import y as z`
    from_names: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict)


class ProgramIndex:
    """The package seen whole: modules, classes, functions, and the
    resolution helpers the lock model and the R19-R21 rules share."""

    def __init__(self, contexts: list[LintContext]):
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # suffix -> resolved module: _module_by_suffix scans the whole
        # module table per miss, and the same few dotted names resolve
        # thousands of times across the race/resource passes
        self._suffix_cache: dict[str, "ModuleInfo | None"] = {}
        for ctx in contexts:
            self._index_module(ctx)
        self._classes_ci = self._build_ci_table()
        for mod in self.modules.values():
            for ci in mod.classes.values():
                self._infer_attr_types(ci)

    # -- construction ---------------------------------------------------
    def _index_module(self, ctx: LintContext) -> None:
        mod = ModuleInfo(name=module_name_for(ctx.path), path=ctx.path,
                         ctx=ctx)
        # a stale duplicate (same dotted id from two trees) keeps the
        # first; the lint run's path set is the source of truth
        if mod.name in self.modules:
            mod = ModuleInfo(name=mod.name + "#" + ctx.path,
                             path=ctx.path, ctx=ctx)
        self.modules[mod.name] = mod
        for node in ctx.tree.body:
            if isinstance(node, (ast.Import,)):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    mod.from_names[a.asname or a.name] = (
                        node.module, a.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(
                    key=f"{mod.name}:{node.name}", name=node.name,
                    cls=None, module=mod, node=node)
                mod.functions[node.name] = fi
                self.functions[fi.key] = fi
            elif isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Name):
                # module-level alias: g = f
                src = mod.functions.get(node.value.id)
                if src is not None:
                    mod.functions[node.targets[0].id] = src

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(key=f"{mod.name}:{node.name}", name=node.name,
                       module=mod, node=node)
        for b in node.bases:
            chain = attr_chain(b)
            if chain:
                ci.bases.append(".".join(chain))
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(
                    key=f"{mod.name}:{node.name}.{item.name}",
                    name=item.name, cls=node.name, module=mod, node=item)
                ci.methods[item.name] = fi
                self.functions[fi.key] = fi
        for item in node.body:
            # class-attribute method binding: visit_X = visit_Y
            if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name) \
                    and isinstance(item.value, ast.Name) \
                    and item.value.id in ci.methods:
                ci.methods[item.targets[0].id] = ci.methods[item.value.id]
        mod.classes[node.name] = ci
        self.classes[ci.key] = ci

    def _build_ci_table(self) -> dict[str, ClassInfo | None]:
        """Case-insensitive class-name table for parameter typing;
        ambiguous names map to None (no binding)."""
        out: dict[str, ClassInfo | None] = {}
        for ci in self.classes.values():
            k = ci.name.lower().lstrip("_")
            if k in out and out[k] is not ci:
                out[k] = None
            else:
                out[k] = ci
        return out

    # -- type inference -------------------------------------------------
    def _resolve_class_name(self, mod: ModuleInfo,
                            chain: list[str]) -> ClassInfo | None:
        """Resolve a dotted constructor name to an index class."""
        if len(chain) == 1:
            name = chain[0]
            if name in mod.classes:
                return mod.classes[name]
            if name in mod.from_names:
                src_mod, orig = mod.from_names[name]
                m = self._module_by_suffix(src_mod)
                if m is not None:
                    return m.classes.get(orig)
            return None
        if len(chain) == 2:
            m = self._imported_module(mod, chain[0])
            if m is not None:
                return m.classes.get(chain[1])
        return None

    def _module_by_suffix(self, dotted: str) -> ModuleInfo | None:
        if dotted in self.modules:
            return self.modules[dotted]
        if dotted in self._suffix_cache:
            return self._suffix_cache[dotted]
        out = None
        for name, m in self.modules.items():
            if name.endswith("." + dotted.rsplit(".", 1)[-1]) \
                    and (name == dotted or name.endswith("." + dotted)):
                out = m
                break
        self._suffix_cache[dotted] = out
        return out

    def _imported_module(self, mod: ModuleInfo,
                         alias: str) -> ModuleInfo | None:
        if alias in mod.imports:
            return self._module_by_suffix(mod.imports[alias])
        if alias in mod.from_names:
            src_mod, orig = mod.from_names[alias]
            return self._module_by_suffix(src_mod + "." + orig) \
                or self._module_by_suffix(orig)
        return None

    def type_of_expr(self, expr: ast.AST, mod: ModuleInfo) -> str | None:
        """Type key of a constructor-ish expression, or None."""
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if not chain:
                return None
            if len(chain) == 2 and tuple(chain) in _BUILTIN_TYPES:
                return _BUILTIN_TYPES[tuple(chain)]
            if len(chain) == 1 and chain[0] in ("Lock", "RLock",
                                                "Condition", "Event",
                                                "Thread", "Queue"):
                # `from threading import Lock` style
                fn = mod.from_names.get(chain[0])
                if fn and tuple([fn[0].split(".")[-1], fn[1]]) \
                        in _BUILTIN_TYPES:
                    return _BUILTIN_TYPES[(fn[0].split(".")[-1], fn[1])]
            ci = self._resolve_class_name(mod, chain)
            if ci is not None:
                return ci.key
            return None
        if isinstance(expr, (ast.List, ast.ListComp)):
            elts = (expr.elts if isinstance(expr, ast.List)
                    else [expr.elt])
            elem_keys = {self.type_of_expr(e, mod) for e in elts}
            elem_keys.discard(None)
            if len(elem_keys) == 1:
                return "list:" + elem_keys.pop()
            return "list:" if isinstance(expr, ast.List) \
                and not expr.elts else None
        return None

    def type_from_annotation(self, ann: ast.AST,
                             mod: ModuleInfo) -> str | None:
        """Type key from an annotation: ``_Slot`` -> the class,
        ``list[_Slot]`` -> ``list:<class>``, ``dict[int, _Slot]`` ->
        ``dict:<class>``, ``Optional[X]``/``X | None`` -> X."""
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            # X | None / None | X
            for side in (ann.left, ann.right):
                if not (isinstance(side, ast.Constant)
                        and side.value is None):
                    t = self.type_from_annotation(side, mod)
                    if t is not None:
                        return t
            return None
        chain = attr_chain(ann)
        if chain:
            ci = self._resolve_class_name(mod, chain)
            return ci.key if ci is not None else None
        if isinstance(ann, ast.Subscript):
            base = attr_chain(ann.value) or []
            base_name = base[-1] if base else ""
            sl = ann.slice
            if base_name in ("list", "List", "Sequence", "set",
                             "frozenset", "Set", "tuple", "Tuple"):
                t = self.type_from_annotation(sl, mod)
                return "list:" + t if t else None
            if base_name in ("dict", "Dict", "Mapping", "defaultdict"):
                if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                    t = self.type_from_annotation(sl.elts[1], mod)
                    return "dict:" + t if t else None
                return None
            if base_name == "Optional":
                return self.type_from_annotation(sl, mod)
        return None

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        """``self.X = <expr>`` sites across the class body, with the
        parameter-name heuristic and list-element typing."""
        mod = ci.module
        param_types: dict[str, dict[str, str]] = {}
        for m in set(ci.methods.values()):
            ptypes: dict[str, str] = {}
            for arg in (m.node.args.posonlyargs + m.node.args.args
                        + m.node.args.kwonlyargs):
                bound = self._classes_ci.get(arg.arg.lower().lstrip("_"))
                if bound is not None:
                    ptypes[arg.arg] = bound.key
            param_types[m.key] = ptypes
        for m in set(ci.methods.values()):
            for node in ast.walk(m.node):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    # the annotation is authoritative when it resolves
                    # (`self._slots: list[_Slot] = []`)
                    ch = attr_chain(node.target)
                    if ch and len(ch) == 2 and ch[0] == "self":
                        t = self.type_from_annotation(
                            node.annotation, mod)
                        if t is not None:
                            ci.attr_types[ch[1]] = t
                            continue
                    if node.value is None:
                        continue
                    targets = [node.target]
                else:
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "append" \
                            and len(node.args) == 1:
                        # self.X.append(C(...)) types the list elements
                        ch = attr_chain(node.func.value)
                        if ch and len(ch) == 2 and ch[0] == "self":
                            t = self.type_of_expr(node.args[0], mod)
                            if t and ci.attr_types.get(ch[1]) \
                                    in (None, "list:", "list:" + t):
                                ci.attr_types[ch[1]] = "list:" + t
                    continue
                value = node.value
                for tgt in targets:
                    ch = attr_chain(tgt)
                    if not ch or len(ch) != 2 or ch[0] != "self":
                        continue
                    attr = ch[1]
                    t = self.type_of_expr(value, mod)
                    if t is None and isinstance(value, ast.Name):
                        t = param_types[m.key].get(value.id)
                    if t is None:
                        # `self.x = None` placeholders don't clobber
                        if isinstance(value, ast.Constant) \
                                and value.value is None:
                            continue
                        # a second, untypable assignment to a typed
                        # attr makes it unknown — safety over recall
                        if attr in ci.attr_types \
                                and not ci.attr_types[attr].startswith(
                                    "list:"):
                            del ci.attr_types[attr]
                        continue
                    prev = ci.attr_types.get(attr)
                    if prev is None or prev == "list:" or prev == t:
                        ci.attr_types[attr] = t
                    elif t == "list:" and prev.startswith("list:"):
                        pass      # an empty re-init keeps the elem type
                    elif prev != t:
                        del ci.attr_types[attr]

    # -- resolution helpers ---------------------------------------------
    def mro(self, ci: ClassInfo):
        """The class and its resolvable bases, nearest first."""
        out, stack, seen = [], [ci], set()
        while stack:
            c = stack.pop(0)
            if c.key in seen:
                continue
            seen.add(c.key)
            out.append(c)
            for raw in c.bases:
                b = self._resolve_class_name(c.module, raw.split("."))
                if b is not None:
                    stack.append(b)
        return out

    def lookup_method(self, ci: ClassInfo,
                      name: str) -> FunctionInfo | None:
        for c in self.mro(ci):
            if name in c.methods:
                return c.methods[name]
        return None

    def attr_type(self, ci: ClassInfo, attr: str) -> str | None:
        for c in self.mro(ci):
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None

    def class_of_key(self, key: str | None) -> ClassInfo | None:
        if key is None:
            return None
        if key.startswith("list:"):
            key = key[5:]
        elif key.startswith("dict:"):
            key = key[5:]
        return self.classes.get(key)

    def resolve_call(self, call: ast.Call, scope: FunctionInfo,
                     local_types: dict[str, str] | None = None,
                     ) -> list[FunctionInfo]:
        """Callee candidates for one call site (empty when unknown)."""
        mod = scope.module
        local_types = local_types or {}
        f = call.func
        if isinstance(f, ast.Name):
            fi = mod.functions.get(f.id)
            if fi is not None:
                return [fi]
            if f.id in mod.from_names:
                src_mod, orig = mod.from_names[f.id]
                m = self._module_by_suffix(src_mod)
                if m is not None and orig in m.functions:
                    return [m.functions[orig]]
            return []
        chain = attr_chain(f)
        if not chain:
            return []
        recv_type = self.resolve_receiver_type(chain[:-1], scope,
                                               local_types)
        if recv_type is not None \
                and recv_type[:5] in ("list:", "dict:") \
                and chain[-1] in _CONTAINER_METHODS:
            return []     # list/dict verbs never resolve to the elems
        owner = self._owner_class(chain[:-1], scope, local_types)
        if owner is not None:
            fi = self.lookup_method(owner, chain[-1])
            return [fi] if fi is not None else []
        if len(chain) == 2:
            m = self._imported_module(mod, chain[0])
            if m is not None and chain[-1] in m.functions:
                return [m.functions[chain[-1]]]
        return []

    def _owner_class(self, recv: list[str], scope: FunctionInfo,
                     local_types: dict[str, str]) -> ClassInfo | None:
        """Class owning the method for a dotted receiver chain."""
        if not recv:
            return None
        mod = scope.module
        if recv[0] in ("self", "cls") and scope.cls:
            cur = mod.classes.get(scope.cls)
            rest = recv[1:]
        elif recv[0] in local_types:
            cur = self.class_of_key(local_types[recv[0]])
            rest = recv[1:]
        else:
            return None
        for attr in rest:
            if cur is None:
                return None
            cur = self.class_of_key(self.attr_type(cur, attr))
        return cur

    def resolve_receiver_type(self, recv: list[str], scope: FunctionInfo,
                              local_types: dict[str, str]) -> str | None:
        """Type key of a dotted receiver expression, if inferable."""
        mod = scope.module
        if not recv:
            return None
        if recv[0] in ("self", "cls") and scope.cls:
            cur: str | None = mod.classes[scope.cls].key \
                if scope.cls in mod.classes else None
            rest = recv[1:]
        elif recv[0] in local_types:
            cur = local_types[recv[0]]
            rest = recv[1:]
        else:
            return None
        for attr in rest:
            ci = self.class_of_key(cur)
            if ci is None:
                return None
            cur = self.attr_type(ci, attr)
        return cur
