"""``mp4j-lint`` — collective-protocol static analyzer CLI.

Usage::

    mp4j-lint [paths...]              # default: ytk_mp4j_tpu
    mp4j-lint --json                  # machine-readable findings
    mp4j-lint --explain R20           # catalogue entry + firing example
    mp4j-lint --strict                # stale baseline entries are findings
    mp4j-lint --prune-baseline        # rewrite the baseline minus stale rows
    mp4j-lint graph --dot             # the discovered lock-order graph
    mp4j-lint races [--dot]           # the shared-field -> lockset map
    mp4j-lint --sarif out.sarif       # SARIF 2.1.0 log for CI viewers
    mp4j-lint diff-sarif OLD NEW      # nonzero only on NEW fingerprints
    python -m ytk_mp4j_tpu.analysis ytk_mp4j_tpu/

Exit codes: 0 clean, 1 unsuppressed findings, 2 bad invocation or
unreadable baseline. By default the committed baseline
(``ytk_mp4j_tpu/analysis/baseline.toml``) is applied; ``--no-baseline``
shows everything, ``--write-baseline`` accepts the current findings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import textwrap

from ytk_mp4j_tpu.analysis import baseline as baseline_mod
from ytk_mp4j_tpu.analysis.engine import Engine, Program, ProgramRule
from ytk_mp4j_tpu.analysis.report import (render_json, render_sarif,
                                          render_text)
from ytk_mp4j_tpu.analysis.rules import ALL_RULES, RULES_BY_ID, get_rules
from ytk_mp4j_tpu.exceptions import Mp4jError

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-lint",
        description=("static analyzer for distributed-correctness hazards "
                     "in the mp4j comm stack"))
    ap.add_argument("paths", nargs="*", default=["ytk_mp4j_tpu"],
                    help="files or directories to lint "
                         "(default: ytk_mp4j_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the committed "
                         "analysis/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--select", default=None, metavar="R1,R2,...",
                    help="run only these rule ids")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--json", action="store_true",
                    help="shorthand for --format json (editors/CI)")
    ap.add_argument("--strict", action="store_true",
                    help="stale baseline entries (matching no finding) "
                         "are B001 findings — the tier-1 gate's mode")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write a baseline accepting the current "
                         "unsuppressed findings, then exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline file keeping only the "
                         "entries that still match a finding")
    ap.add_argument("--sarif", metavar="OUT.sarif", default=None,
                    help="also write the findings as a SARIF 2.1.0 "
                         "log to OUT.sarif (for CI annotation viewers)")
    ap.add_argument("--explain", metavar="RN", default=None,
                    help="print one rule's catalogue entry and a "
                         "firing example, then exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    return ap


def _build_races_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-lint races",
        description=("dump the shared-field -> lockset map discovered "
                     "by the R23 lockset analysis: every mutable field "
                     "reachable from >= 2 thread roots, which locks "
                     "its access sites hold (and how consistently), "
                     "and a witness pair for each inconsistency"))
    ap.add_argument("paths", nargs="*", default=["ytk_mp4j_tpu"])
    ap.add_argument("--dot", action="store_true",
                    help="GraphViz DOT output (default: text report)")
    ap.add_argument("-o", "--output", default=None, metavar="FILE",
                    help="write to FILE instead of stdout")
    return ap


def _build_graph_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-lint graph",
        description=("dump the whole-program lock-order graph "
                     "discovered by the R19-R21 analysis: nodes are "
                     "lock attributes with their defining class, edges "
                     "are observed acquisition orders with one witness "
                     "call chain each"))
    ap.add_argument("paths", nargs="*", default=["ytk_mp4j_tpu"])
    ap.add_argument("--dot", action="store_true",
                    help="GraphViz DOT output (default: text edges)")
    ap.add_argument("-o", "--output", default=None, metavar="FILE",
                    help="write to FILE instead of stdout")
    return ap


def _explain(rule_id: str) -> int:
    cls = RULES_BY_ID.get(rule_id)
    if cls is None:
        print(f"mp4j-lint: unknown rule id {rule_id!r} "
              f"(see --list-rules)", file=sys.stderr)
        return 2
    print(f"{cls.rule_id} ({cls.severity!s}) — {cls.title}")
    print()
    print(textwrap.fill(cls.description, width=72))
    example = getattr(cls, "example", "")
    if example:
        print("\nfiring example:\n")
        for line in example.rstrip().splitlines():
            print("    " + line)
        # show the rule actually firing on its own example — the
        # catalogue stays honest by construction (tested in tier-1)
        rule = cls()
        eng = Engine(rules=[rule])
        path = getattr(cls, "example_path",
                       "ytk_mp4j_tpu/comm/example.py")
        result = eng.lint_source(example, path)
        hits = [f for f in result.findings if f.rule == cls.rule_id]
        print("\nfires:")
        for f in hits:
            print(f"    line {f.line}: {f.message[:100]}"
                  + ("..." if len(f.message) > 100 else ""))
        if not hits:
            print("    (example did not fire — catalogue bug)")
            return 2
    return 0


def _load_program(paths, prog: str):
    contexts, errors = Engine(rules=[]).load_contexts(paths)
    for f in errors:
        print(f"mp4j-lint {prog}: skipped {f.path}: {f.message}",
              file=sys.stderr)
    if not contexts:
        print(f"mp4j-lint {prog}: no parsable files", file=sys.stderr)
        return None
    return Program(contexts)


def _emit(out: str, output: str | None) -> None:
    if output:
        tmp = output + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(out + "\n")
        os.replace(tmp, output)
        print(f"mp4j-lint: wrote {output}")
    else:
        print(out)


def _races_main(argv) -> int:
    args = _build_races_parser().parse_args(argv)
    program = _load_program(args.paths, "races")
    if program is None:
        return 2
    model = program.races
    _emit(model.to_dot() if args.dot else model.to_text(), args.output)
    return 0


def _graph_main(argv) -> int:
    args = _build_graph_parser().parse_args(argv)
    program = _load_program(args.paths, "graph")
    if program is None:
        return 2
    model = program.locks
    if args.dot:
        out = model.to_dot()
    else:
        lines = [f"{len(model.locks)} locks, {len(model.edges)} "
                 f"order edges, {len(model.cycles())} cycle(s)"]
        for (_s, _d), e in sorted(model.edges.items()):
            lines.append("  " + model.format_witness(e))
        for scc in model.cycles():
            lines.append("  CYCLE: " + " <-> ".join(
                model.locks[k].display for k in scc))
        out = "\n".join(lines)
    _emit(out, args.output)
    return 0


def _build_diff_sarif_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-lint diff-sarif",
        description=("compare two SARIF logs by result fingerprint "
                     "and exit nonzero ONLY when NEW carries findings "
                     "whose partialFingerprints are absent from OLD — "
                     "the ratchet CI gate: pre-existing findings never "
                     "block, line drift never false-alarms (the "
                     "fingerprint is the scope qualname, not a line "
                     "number)"))
    ap.add_argument("old", help="baseline SARIF log")
    ap.add_argument("new", help="candidate SARIF log")
    return ap


def _sarif_result_keys(doc) -> dict[tuple, dict]:
    """Identity map of a SARIF log's results: ``(ruleId, artifact
    uri, sorted partialFingerprints) -> result``. Line numbers are
    deliberately NOT part of the key."""
    out: dict[tuple, dict] = {}
    for run in doc.get("runs") or []:
        for res in run.get("results") or []:
            locs = res.get("locations") or [{}]
            uri = (locs[0].get("physicalLocation") or {}) \
                .get("artifactLocation", {}).get("uri", "")
            fp = tuple(sorted(
                (res.get("partialFingerprints") or {}).items()))
            out.setdefault((res.get("ruleId"), uri, fp), res)
    return out


def _diff_sarif_main(argv) -> int:
    args = _build_diff_sarif_parser().parse_args(argv)
    docs = []
    for path in (args.old, args.new):
        try:
            with open(path, encoding="utf-8") as fh:
                docs.append(json.load(fh))
        except (OSError, ValueError) as e:
            print(f"mp4j-lint diff-sarif: unreadable {path}: {e}",
                  file=sys.stderr)
            return 2
    old_keys = set(_sarif_result_keys(docs[0]))
    fresh = [(k, r) for k, r in _sarif_result_keys(docs[1]).items()
             if k not in old_keys]
    for (rule, uri, _fp), res in fresh:
        region = (res.get("locations") or [{}])[0] \
            .get("physicalLocation", {}).get("region", {})
        msg = res.get("message", {}).get("text", "")
        print(f"NEW {rule} {uri}:{region.get('startLine', 0)} {msg}")
    print(f"mp4j-lint diff-sarif: {len(fresh)} new finding(s)")
    return 1 if fresh else 0


def _baseline_header(path: str) -> str | None:
    """The leading comment block of the committed baseline, preserved
    across --prune-baseline rewrites."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    head: list[str] = []
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            head.append(line)
        else:
            break
    while head and not head[-1].strip():
        head.pop()
    return "\n".join(head) if head else None


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "graph":
        return _graph_main(argv[1:])
    if argv and argv[0] == "races":
        return _races_main(argv[1:])
    if argv and argv[0] == "diff-sarif":
        return _diff_sarif_main(argv[1:])
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            scope = ("whole-program"
                     if issubclass(cls, ProgramRule) else "per-file")
            print(f"{cls.rule_id}  {cls.severity!s:7s} [{scope}] "
                  f"{cls.title}: {cls.description}")
        return 0
    if args.explain:
        return _explain(args.explain.strip())

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        rules = get_rules(select)
    except KeyError as e:
        print(f"mp4j-lint: {e.args[0]}", file=sys.stderr)
        return 2

    bl = None
    if args.write_baseline:
        # regeneration must see EVERY finding, or entries the current
        # baseline already suppresses would be silently dropped
        args.no_baseline = True
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            bl = baseline_mod.load(args.baseline)
        except (Mp4jError, OSError) as e:
            print(f"mp4j-lint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
    if args.prune_baseline and bl is None:
        print("mp4j-lint: --prune-baseline needs a readable baseline",
              file=sys.stderr)
        return 2

    eng = Engine(rules=rules, baseline=bl,
                 strict_baseline=args.strict,
                 baseline_path=args.baseline)
    result = eng.lint_paths(args.paths)

    if args.write_baseline:
        text = baseline_mod.render(result.findings,
                                   reason="accepted by --write-baseline")
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"mp4j-lint: wrote {len(result.findings)} suppression(s) "
              f"to {args.write_baseline}")
        return 0

    if args.prune_baseline:
        # only entries PROVABLY stale for this run are dropped: their
        # rule ran and their file was in scope — `--select R18
        # --prune-baseline` or a single-file path keeps every entry it
        # could not judge (code-review finding)
        stale_ids = {id(e) for e in eng.stale_entries(
            eng.last_linted_paths)}
        kept = [e for e in bl.entries if id(e) not in stale_ids]
        stale = len(bl.entries) - len(kept)
        text = baseline_mod.render_entries(
            kept, header=_baseline_header(args.baseline))
        tmp = args.baseline + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, args.baseline)
        print(f"mp4j-lint: pruned {stale} stale entr"
              f"{'y' if stale == 1 else 'ies'}, kept {len(kept)} "
              f"in {args.baseline}")
        return 0

    if args.sarif:
        sarif = render_sarif(
            result.findings, [type(r) for r in rules])
        tmp = args.sarif + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(sarif + "\n")
        os.replace(tmp, args.sarif)
        print(f"mp4j-lint: wrote SARIF log {args.sarif}",
              file=sys.stderr)

    if args.format == "json" or args.json:
        print(render_json(result.findings, len(result.suppressed)))
    else:
        print(render_text(result.findings, len(result.suppressed)))
        if bl is not None and not args.strict:
            for e in bl.unused():
                print(f"note: unused baseline suppression "
                      f"({e.rule} {e.file} {e.context})", file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
