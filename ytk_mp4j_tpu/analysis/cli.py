"""``mp4j-lint`` — collective-protocol static analyzer CLI.

Usage::

    mp4j-lint [paths...]              # default: ytk_mp4j_tpu
    python -m ytk_mp4j_tpu.analysis ytk_mp4j_tpu/

Exit codes: 0 clean, 1 unsuppressed findings, 2 bad invocation or
unreadable baseline. By default the committed baseline
(``ytk_mp4j_tpu/analysis/baseline.toml``) is applied; ``--no-baseline``
shows everything, ``--write-baseline`` accepts the current findings.
"""

from __future__ import annotations

import argparse
import os
import sys

from ytk_mp4j_tpu.analysis import baseline as baseline_mod
from ytk_mp4j_tpu.analysis.engine import Engine
from ytk_mp4j_tpu.analysis.report import render_json, render_text
from ytk_mp4j_tpu.analysis.rules import ALL_RULES, get_rules
from ytk_mp4j_tpu.exceptions import Mp4jError

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-lint",
        description=("static analyzer for distributed-correctness hazards "
                     "in the mp4j comm stack"))
    ap.add_argument("paths", nargs="*", default=["ytk_mp4j_tpu"],
                    help="files or directories to lint "
                         "(default: ytk_mp4j_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the committed "
                         "analysis/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--select", default=None, metavar="R1,R2,...",
                    help="run only these rule ids")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write a baseline accepting the current "
                         "unsuppressed findings, then exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    return ap


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.rule_id}  {cls.severity!s:7s} {cls.title}: "
                  f"{cls.description}")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        rules = get_rules(select)
    except KeyError as e:
        print(f"mp4j-lint: {e.args[0]}", file=sys.stderr)
        return 2

    bl = None
    if args.write_baseline:
        # regeneration must see EVERY finding, or entries the current
        # baseline already suppresses would be silently dropped
        args.no_baseline = True
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            bl = baseline_mod.load(args.baseline)
        except (Mp4jError, OSError) as e:
            print(f"mp4j-lint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    result = Engine(rules=rules, baseline=bl).lint_paths(args.paths)

    if args.write_baseline:
        text = baseline_mod.render(result.findings,
                                   reason="accepted by --write-baseline")
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"mp4j-lint: wrote {len(result.findings)} suppression(s) "
              f"to {args.write_baseline}")
        return 0

    if args.format == "json":
        print(render_json(result.findings, len(result.suppressed)))
    else:
        print(render_text(result.findings, len(result.suppressed)))
        if bl is not None:
            for e in bl.unused():
                print(f"note: unused baseline suppression "
                      f"({e.rule} {e.file} {e.context})", file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
