"""``python -m ytk_mp4j_tpu.obs`` — the mp4j-scope CLI."""

import sys

from ytk_mp4j_tpu.obs.cli import main

sys.exit(main())
