"""Live metrics plane: counters, gauges, log-scale histograms, rates.

This module is the measurement substrate of ISSUE 6's monitoring layer,
sitting one level above :mod:`ytk_mp4j_tpu.utils.stats` (which keeps
per-collective lifetime totals): it adds the quantities totals cannot
answer —

- **histograms** with fixed log2-scale buckets: per-collective-family
  latency (``latency/<family>``, seconds) and wire frame sizes
  (``frame_bytes``), cheap enough to stay default-on (one lock + two
  integer bumps per observation; ``MP4J_METRICS=0`` turns every
  observe into a no-op);
- **delta shipping**: :func:`diff_snapshot` / :func:`fold_snapshot`
  turn cumulative registry snapshots into bounded heartbeat payloads —
  a slave ships only what changed since its last beat, the master
  folds deltas back into a rolling cumulative view (counters and
  bucket counts are additive, so out-of-order folds are harmless);
- **rate windows**: :class:`RateWindow` keeps a bounded ring of
  ``(time, cumulative totals)`` interval snapshots so rates (GB/s,
  collectives/s, keys/s) are derivable over a sliding
  ``MP4J_METRICS_WINDOW_SECS`` window instead of diluted lifetime
  averages;
- **rendering**: :func:`to_prometheus` serializes the master's metrics
  document (see ``Master.metrics_doc``) as Prometheus text-format 0.0.4
  — the same document serves as the JSON schema.

Histogram bucket layout: ``n`` log2 buckets above ``lo`` plus one
overflow bucket. Bucket ``0`` holds values ``<= lo``; bucket ``i``
holds ``(lo * 2**(i-1), lo * 2**i]``; bucket ``n`` holds everything
above ``lo * 2**(n-1)`` (rendered as ``le="+Inf"``). Quantile
estimates return the UPPER edge of the bucket containing the
nearest-rank order statistic, so an estimate is exact to one bucket
(a factor of 2) by construction — the property the tier-1 tests pin
against ``numpy.percentile``.

Everything here is deliberately import-light (stdlib only): ``utils.
stats`` feeds it from the comm hot path, and the ``mp4j-scope`` CLI
consumes it offline.
"""

from __future__ import annotations

import collections
import math
import threading

from ytk_mp4j_tpu.utils import tuning

# Canonical bucket layouts (job-wide constants, like the stats schema:
# the master folds per-rank histograms bucket-wise, which is only
# meaningful when every rank uses the identical layout).
LATENCY_LO = 1e-6          # 1 us .. ~34 s in 36 log2 buckets
LATENCY_BUCKETS = 36
FRAME_LO = 64.0            # 64 B .. ~4.3 GB in 27 log2 buckets
FRAME_BUCKETS = 27

# ----------------------------------------------------------------------
# THE metric catalogue (mp4j-lint R17 doc-drift guard): every metric
# family — registry-internal flat names AND Prometheus series the
# /metrics endpoint renders — must have a one-line entry here. A
# ``<segment>`` marks a dynamic label segment (R17 prefix-matches it).
# Registering or rendering a family absent from this table is a lint
# error: an undocumented series is invisible to the operators the
# metrics plane exists for.
# ----------------------------------------------------------------------
METRICS_DOC: dict[str, str] = {
    # -- registry families (flat names inside MetricsRegistry) --------
    "latency/<family>": "per-collective-family latency histogram "
                        "(log2 buckets, seconds; ISSUE 6)",
    "frame_bytes": "wire frame size histogram, untagged transports "
                   "(log2 buckets, bytes)",
    "frame_bytes/<transport>": "wire frame size histogram per "
                               "transport (tcp/shm; ISSUE 7)",
    "sink/bytes": "bytes the durable sink made safe on disk "
                  "(ISSUE 9)",
    "sink/records": "telemetry records the durable sink wrote",
    "sink/dropped_records": "telemetry records the sink LOST (ring "
                            "overflow, full disk, encode poison) — "
                            "nonzero means an outage, never noise",
    "sink/lag_secs": "seconds between the sink's last two drains",
    "sink/dir_bytes": "bytes currently on disk in the rank's segment "
                      "dir (bounded by MP4J_SINK_BYTES)",
    "async/outstanding": "nonblocking collectives queued + in flight "
                         "on this rank's scheduler (ISSUE 11)",
    "tuner/decisions": "per-link tuner decisions APPLIED at collective "
                       "boundaries on this rank (ISSUE 15)",
    # -- serve plane (ISSUE 19) — the latency/serve_request histogram
    # rides the latency/<family> row above
    "serve/requests": "requests the serve frontend completed",
    "serve/batches": "micro-batches dispatched",
    "serve/batch_full": "batches dispatched because max_batch filled",
    "serve/batch_deadline": "batches dispatched at the accumulation "
                            "deadline (MP4J_SERVE_DEADLINE_MS)",
    "serve/cache_hits": "hot-key cache row hits",
    "serve/cache_misses": "hot-key cache row misses (pulled over the "
                          "columnar map plane)",
    "serve/cache_stale": "cached rows dropped past the staleness "
                         "bound (MP4J_SERVE_STALE_VERSIONS)",
    "serve/cache_rows": "rows resident in the hot-key cache now",
    "serve/pull_rows": "rows pulled from the sharded table",
    "serve/degraded_batches": "batches delivered with an incomplete "
                              "contributor set (replacement warming "
                              "up / out-of-vocabulary rows) — "
                              "delivered, not hung, but say so",
    "serve/qps": "serve requests per second (sliding window)",
    "serve/worker_rounds": "serve rounds a worker rank answered",
    # -- Prometheus series (the /metrics endpoint) --------------------
    "mp4j_ranks_reporting": "ranks whose heartbeats the master holds",
    "mp4j_slave_num": "the job's configured rank count",
    "mp4j_calls_total": "collective calls per rank and family",
    "mp4j_bytes_sent_total": "payload bytes sent per rank and family",
    "mp4j_bytes_recv_total": "payload bytes received per rank/family",
    "mp4j_chunks_total": "pipeline chunks exchanged per rank/family",
    "mp4j_keys_total": "map entries encoded columnar per rank/family",
    "mp4j_retries_total": "epoch-fenced retry rounds per rank/family",
    "mp4j_reconnects_total": "peer re-dials during recovery",
    "mp4j_aborts_seen_total": "abort rounds this rank tore down for",
    "mp4j_wire_bytes_tcp_total": "wire bytes moved over TCP",
    "mp4j_wire_bytes_shm_total": "wire bytes moved over shm rings",
    "mp4j_phase_seconds_total": "busy seconds per rank, family and "
                                "phase (wire/reduce/serialize)",
    "mp4j_rank_seq": "per-rank outermost collective sequence number",
    "mp4j_heartbeat_age_seconds": "seconds since each rank's last "
                                  "heartbeat arrived",
    "mp4j_rank_<rate>": "per-rank sliding-window rates "
                        "(bytes/collectives/keys per second)",
    "mp4j_cluster_<rate>": "cluster sliding-window rates",
    "mp4j_audit_divergences_total": "cross-rank digest divergences "
                                    "flagged (ISSUE 8)",
    "mp4j_audit_verified_seqs": "collective ordinals verified "
                                "bit-identical across ranks",
    "mp4j_audit_verified_seq_watermark": "highest cross-rank-verified "
                                         "ordinal (the known-good "
                                         "watermark)",
    "mp4j_replacements_total": "dead ranks replaced from warm spares "
                               "(ISSUE 10)",
    "mp4j_shrinks_total": "shrink rounds survived",
    "mp4j_spares_available": "idle warm spares registered now",
    "mp4j_sink_bytes_total": "durable-sink bytes per rank + cluster",
    "mp4j_sink_records_total": "durable-sink records per rank",
    "mp4j_sink_dropped_records_total": "durable-sink records LOST per "
                                       "rank — alert on growth",
    "mp4j_sink_lag_seconds": "per-rank sink drain lag",
    "mp4j_outstanding_collectives": "nonblocking collectives in "
                                    "flight per rank + cluster",
    "mp4j_collective_latency_seconds": "cluster latency histogram per "
                                       "collective family",
    "mp4j_frame_bytes": "cluster wire frame size histogram "
                        "(transport-labelled)",
    # -- health plane (ISSUE 12) --------------------------------------
    "mp4j_rank_health_state": "per-rank health verdict (0 HEALTHY, "
                              "1 DEGRADED, 2 SUSPECT, "
                              "3 EVICT_RECOMMENDED, 4 DEAD)",
    "mp4j_alerts_total": "health alerts emitted per rank and "
                         "detector — any growth is a story",
    "mp4j_evict_recommended": "ranks the health plane currently "
                              "recommends evicting (it never acts)",
    "mp4j_straggler_onsets_total": "straggler onsets the online "
                                   "dominator detected (ISSUE 9's "
                                   "offline onset events, live)",
    "mp4j_critpath_dominator": "per-rank share of recently attributed "
                               "ordinals this rank gated (sliding "
                               "window)",
    # -- autoscaler (ISSUE 13) ------------------------------------------
    "mp4j_autoscale_actions_total": "autoscaler actions DISPATCHED, "
                                    "by action (evict_replace / "
                                    "provision / grow) — alert on "
                                    "unexpected growth",
    "mp4j_autoscale_tripped": "1 when the autoscaler's circuit "
                              "breaker tripped it back to "
                              "recommend-only (two consecutive "
                              "failed actions)",
    # -- serve plane (ISSUE 19) -----------------------------------------
    "mp4j_serve_requests_total": "serve requests completed per rank "
                                 "(+ cluster total)",
    "mp4j_serve_batches_total": "serve micro-batches dispatched per "
                                "rank (+ cluster total)",
    "mp4j_serve_cache_hits_total": "serve hot-key cache hits per rank "
                                   "(+ cluster total)",
    "mp4j_serve_cache_misses_total": "serve hot-key cache misses per "
                                     "rank (+ cluster total)",
    "mp4j_serve_degraded_batches_total": "serve batches delivered "
                                         "degraded per rank (+ "
                                         "cluster total)",
    "mp4j_serve_qps": "cluster serve requests per second (frontend "
                      "sliding window)",
    # -- self-tuning data plane (ISSUE 15) ------------------------------
    "mp4j_tuner_decisions_total": "per-link tuner decisions applied "
                                  "per rank (+ cluster total)",
    "mp4j_tuner_demotions_total": "fenced host-leader demotions the "
                                  "master's tuner controller "
                                  "dispatched",
    "mp4j_tuner_tripped": "1 when an audit divergence tripped the "
                          "tuner back to static defaults (latched "
                          "for the job)",
}


def bucket_edges(lo: float, n: int) -> list[float]:
    """The ``n`` finite upper edges ``[lo, 2*lo, ..., lo * 2**(n-1)]``
    (the overflow bucket's edge is +Inf)."""
    return [lo * 2.0 ** i for i in range(n)]


def bucket_index(value: float, lo: float, n: int) -> int:
    """Index of the bucket holding ``value`` (0..n, where n is the
    overflow bucket). Exact at the edges by construction: the log2
    guess is fixed up so ``value <= lo * 2**idx`` and
    ``value > lo * 2**(idx-1)`` always hold."""
    if value <= lo:
        return 0
    idx = int(math.ceil(math.log2(value / lo)))
    while idx < n and value > lo * 2.0 ** idx:
        idx += 1
    while idx > 1 and value <= lo * 2.0 ** (idx - 1):
        idx -= 1
    return min(max(idx, 0), n)


def _new_hist(lo: float, n: int) -> dict:
    return {"lo": lo, "n": n, "counts": [0] * (n + 1),
            "count": 0, "sum": 0.0}


def hist_quantile(h: dict, q: float) -> float:
    """Nearest-rank quantile estimate: the UPPER edge of the bucket
    containing the ``ceil(q * count)``-th smallest observation (so the
    true order statistic is within one bucket below the estimate).
    Empty histogram -> 0.0; overflow bucket -> +Inf (the histogram
    only knows the value exceeded its largest edge)."""
    count = h["count"]
    if count <= 0:
        return 0.0
    target = max(1, math.ceil(min(max(q, 0.0), 1.0) * count))
    cum = 0
    for i, c in enumerate(h["counts"]):
        cum += c
        if cum >= target:
            if i >= h["n"]:
                return math.inf
            return h["lo"] * 2.0 ** i if i else h["lo"]
    return math.inf


class MetricsRegistry:
    """Cheap thread-safe registry of counters, gauges and fixed
    log2-bucket histograms. All names are flat strings; histogram
    families encode their one label in the name (``latency/<family>``)
    — the renderer splits it back out. Disabled (``MP4J_METRICS=0``)
    every mutator is a single flag check."""

    def __init__(self, enabled: bool | None = None):
        self._enabled = (tuning.metrics_enabled() if enabled is None
                         else bool(enabled))
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict] = {}

    @property
    def enabled(self) -> bool:
        return self._enabled

    def inc(self, name: str, value: float = 1) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        if not self._enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float, lo: float, n: int) -> None:
        if not self._enabled:
            return
        idx = bucket_index(value, lo, n)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _new_hist(lo, n)
            h["counts"][idx] += 1
            h["count"] += 1
            h["sum"] += value

    def snapshot(self) -> dict:
        """Deep copy: ``{"counters": {...}, "gauges": {...},
        "histograms": {name: {lo, n, counts, count, sum}}}``."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: {**h, "counts": list(h["counts"])}
                               for k, h in self._hists.items()},
            }


def _empty_snapshot() -> dict:
    return {"counters": {}, "gauges": {}, "histograms": {}}


def diff_snapshot(cur: dict, prev: dict) -> dict:
    """``cur - prev`` over registry snapshots, pruned: unchanged
    counters/histograms are dropped so a heartbeat's payload is
    bounded by what actually happened since the last beat, not by
    every metric ever seen (satellite of ISSUE 6). Gauges are
    last-value semantics and always ship whole."""
    out = _empty_snapshot()
    pc = prev.get("counters", {})
    for k, v in cur.get("counters", {}).items():
        d = v - pc.get(k, 0)
        if d:
            out["counters"][k] = d
    out["gauges"] = dict(cur.get("gauges", {}))
    ph = prev.get("histograms", {})
    for k, h in cur.get("histograms", {}).items():
        p = ph.get(k)
        if p is None:
            if h["count"]:
                out["histograms"][k] = {**h, "counts": list(h["counts"])}
            continue
        if h["count"] == p["count"]:
            continue
        out["histograms"][k] = {
            "lo": h["lo"], "n": h["n"],
            "counts": [a - b for a, b in zip(h["counts"], p["counts"])],
            "count": h["count"] - p["count"],
            "sum": h["sum"] - p["sum"],
        }
    return out


def fold_snapshot(agg: dict, delta: dict) -> dict:
    """Fold a delta (or a whole snapshot) into a cumulative aggregate;
    returns a NEW snapshot (inputs untouched). Counters and bucket
    counts add; gauges take the delta's value."""
    out = {
        "counters": dict(agg.get("counters", {})),
        "gauges": dict(agg.get("gauges", {})),
        "histograms": {k: {**h, "counts": list(h["counts"])}
                       for k, h in agg.get("histograms", {}).items()},
    }
    for k, v in delta.get("counters", {}).items():
        out["counters"][k] = out["counters"].get(k, 0) + v
    out["gauges"].update(delta.get("gauges", {}))
    for k, h in delta.get("histograms", {}).items():
        a = out["histograms"].get(k)
        if a is None or a["lo"] != h["lo"] or a["n"] != h["n"]:
            # unseen family (or a layout change across versions):
            # the delta becomes the aggregate
            out["histograms"][k] = {**h, "counts": list(h["counts"])}
            continue
        a["counts"] = [x + y for x, y in zip(a["counts"], h["counts"])]
        a["count"] += h["count"]
        a["sum"] += h["sum"]
    return out


class RateWindow:
    """Bounded ring of ``(t, cumulative totals)`` interval snapshots;
    rates are ``(newest - oldest) / dt`` over the points still inside
    the window — a sliding-window derivative, immune to the lifetime
    dilution a totals/uptime quotient suffers. Not thread-safe: the
    owner (the master, under its lock) serializes access."""

    def __init__(self, window_secs: float, maxlen: int = 512):
        self.window = float(window_secs)
        # minimum spacing between RETAINED points: notes arriving
        # faster than window/(maxlen/2) replace the newest point
        # instead of appending, so the deque always spans the full
        # window no matter the note rate — the master feeds the
        # cluster window once per heartbeat PER RANK, which at fleet
        # size would otherwise shrink the effective window to
        # maxlen/(2N) beats with no warning
        self._min_dt = self.window / (maxlen / 2)
        self._points: collections.deque = collections.deque(maxlen=maxlen)

    def note(self, t: float, totals: dict[str, float]) -> None:
        pts = self._points
        if len(pts) >= 2 and t - pts[-2][0] < self._min_dt:
            pts[-1] = (t, dict(totals))     # coalesce: keep freshest
        else:
            pts.append((t, dict(totals)))
        cutoff = t - self.window
        while len(pts) > 2 and pts[0][0] < cutoff:
            pts.popleft()

    def rates(self) -> dict[str, float]:
        """``{key}_per_sec`` for every key in the newest totals; 0.0
        until the window holds two points."""
        if len(self._points) < 2:
            keys = self._points[-1][1] if self._points else {}
            return {f"{k}_per_sec": 0.0 for k in keys}
        t0, first = self._points[0]
        t1, last = self._points[-1]
        dt = t1 - t0
        if dt <= 0:
            return {f"{k}_per_sec": 0.0 for k in last}
        return {f"{k}_per_sec": (last.get(k, 0) - first.get(k, 0)) / dt
                for k in last}


# ----------------------------------------------------------------------
# Prometheus text-format rendering (the /metrics endpoint)
# ----------------------------------------------------------------------
_STATS_COUNTER_KEYS = ("calls", "bytes_sent", "bytes_recv", "chunks",
                       "keys", "retries", "reconnects", "aborts_seen",
                       "wire_bytes_tcp", "wire_bytes_shm")
_STATS_PHASE_KEYS = ("wire_seconds", "reduce_seconds",
                     "serialize_seconds")


def _esc(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _hist_lines(out: list[str], metric: str, labels: str, h: dict) -> None:
    cum = 0
    edges = bucket_edges(h["lo"], h["n"])
    sep = "," if labels else ""
    for i, c in enumerate(h["counts"]):
        cum += c
        le = _fmt(edges[i]) if i < h["n"] else "+Inf"
        out.append(f'{metric}_bucket{{{labels}{sep}le="{le}"}} {cum}')
    out.append(f"{metric}_sum{{{labels}}} {_fmt(float(h['sum']))}"
               if labels else f"{metric}_sum {_fmt(float(h['sum']))}")
    out.append(f"{metric}_count{{{labels}}} {h['count']}"
               if labels else f"{metric}_count {h['count']}")


def to_prometheus(doc: dict) -> str:
    """Render a master metrics document (``Master.metrics_doc``) as
    Prometheus text format 0.0.4: per-rank and cluster-aggregate
    counter series, cluster-folded latency/frame histograms, and the
    windowed rate gauges. Every metric family is emitted as ONE
    contiguous block (the format requires it — strict parsers like
    promtool reject a family that reappears after another metric), so
    samples are collected per family first and ranks vary inside the
    block."""
    whos = [*sorted(doc.get("ranks", {}), key=int)]
    stats_of = {r: doc["ranks"][r].get("stats", {}) for r in whos}
    stats_of["cluster"] = doc.get("cluster", {}).get("stats", {})

    out: list[str] = []
    out.append("# TYPE mp4j_ranks_reporting gauge")
    out.append(f"mp4j_ranks_reporting {len(whos)}")
    out.append("# TYPE mp4j_slave_num gauge")
    out.append(f"mp4j_slave_num {doc.get('slave_num', 0)}")

    for key in _STATS_COUNTER_KEYS:
        block = []
        for who in [*whos, "cluster"]:
            for family in sorted(stats_of[who]):
                v = stats_of[who][family].get(key, 0)
                if v:
                    block.append(
                        f'mp4j_{key}_total{{rank="{_esc(who)}",'
                        f'collective="{_esc(family)}"}} '
                        f"{_fmt(float(v))}")
        if block:
            out.append(f"# TYPE mp4j_{key}_total counter")
            out.extend(block)
    phase_block = []
    for who in [*whos, "cluster"]:
        for family in sorted(stats_of[who]):
            for key in _STATS_PHASE_KEYS:
                v = stats_of[who][family].get(key, 0.0)
                if v:
                    phase_block.append(
                        f'mp4j_phase_seconds_total{{rank="{_esc(who)}",'
                        f'collective="{_esc(family)}",'
                        f'phase="{key[:-len("_seconds")]}"}} '
                        f"{_fmt(float(v))}")
    if phase_block:
        out.append("# TYPE mp4j_phase_seconds_total counter")
        out.extend(phase_block)

    out.append("# TYPE mp4j_rank_seq gauge")
    for r in whos:
        prog = doc["ranks"][r].get("progress", {})
        out.append(f'mp4j_rank_seq{{rank="{_esc(r)}"}} '
                   f"{prog.get('seq', 0)}")
    out.append("# TYPE mp4j_heartbeat_age_seconds gauge")
    for r in whos:
        out.append(f'mp4j_heartbeat_age_seconds{{rank="{_esc(r)}"}} '
                   f"{_fmt(float(doc['ranks'][r].get('age', 0.0)))}")
    # per-rank rate gauges, one family (= one rate key) per block
    rate_keys = sorted({k for r in whos
                        for k in doc["ranks"][r].get("rates", {})})
    for k in rate_keys:
        out.append(f"# TYPE mp4j_rank_{k} gauge")
        for r in whos:
            rates = doc["ranks"][r].get("rates", {})
            if k in rates:
                out.append(f'mp4j_rank_{k}{{rank="{_esc(r)}"}} '
                           f"{_fmt(float(rates[k]))}")

    for k, v in sorted(doc.get("cluster", {}).get("rates", {}).items()):
        out.append(f"# TYPE mp4j_cluster_{k} gauge")
        out.append(f"mp4j_cluster_{k} {_fmt(float(v))}")

    # audit plane (ISSUE 8): divergence counter + verification
    # watermark — present whenever the master carries an auditor (the
    # series stay at 0 unless slaves run MP4J_AUDIT=verify|capture,
    # so dashboards can alert on `> 0` unconditionally)
    audit = doc.get("cluster", {}).get("audit")
    if audit is not None:
        out.append("# TYPE mp4j_audit_divergences_total counter")
        out.append("mp4j_audit_divergences_total "
                   f"{int(audit.get('divergences', 0))}")
        out.append("# TYPE mp4j_audit_verified_seqs gauge")
        out.append("mp4j_audit_verified_seqs "
                   f"{int(audit.get('verified_total', 0))}")
        out.append("# TYPE mp4j_audit_verified_seq_watermark gauge")
        out.append("mp4j_audit_verified_seq_watermark "
                   f"{int(audit.get('verified_seq', 0))}")

    # elastic membership (ISSUE 10): replacement/shrink counters and
    # the warm-spare gauge — present whenever the master carries a
    # membership log (they stay 0 for non-elastic jobs, so dashboards
    # can alert on growth unconditionally)
    ms = doc.get("cluster", {}).get("membership")
    if ms is not None:
        out.append("# TYPE mp4j_replacements_total counter")
        out.append(
            f"mp4j_replacements_total {int(ms.get('replacements', 0))}")
        out.append("# TYPE mp4j_shrinks_total counter")
        out.append(f"mp4j_shrinks_total {int(ms.get('shrinks', 0))}")
        out.append("# TYPE mp4j_spares_available gauge")
        out.append(
            f"mp4j_spares_available {int(ms.get('spares_available', 0))}")

    # durable-sink series (ISSUE 9): per-rank registry counters named
    # sink/<what> plus the drain-lag gauge; a cluster total per
    # counter so dashboards can alert on drop growth fleet-wide. The
    # series exist whenever a rank arms MP4J_SINK_DIR and stay absent
    # otherwise (no zero-noise for sinkless jobs).
    for key, metric in (("sink/bytes", "mp4j_sink_bytes_total"),
                        ("sink/records", "mp4j_sink_records_total"),
                        ("sink/dropped_records",
                         "mp4j_sink_dropped_records_total")):
        block = []
        total = 0.0
        for r in whos:
            v = doc["ranks"][r].get("counters", {}).get(key)
            if v:
                total += v
                block.append(f'{metric}{{rank="{_esc(r)}"}} '
                             f"{_fmt(float(v))}")
        if block:
            block.append(f'{metric}{{rank="cluster"}} '
                         f"{_fmt(float(total))}")
            out.append(f"# TYPE {metric} counter")
            out.extend(block)
    lag_block = []
    for r in whos:
        g = doc["ranks"][r].get("gauges", {}).get("sink/lag_secs")
        if g is not None:
            lag_block.append(
                f'mp4j_sink_lag_seconds{{rank="{_esc(r)}"}} '
                f"{_fmt(float(g))}")
    if lag_block:
        out.append("# TYPE mp4j_sink_lag_seconds gauge")
        out.extend(lag_block)

    # nonblocking-collective gauge (ISSUE 11): how many collectives
    # each rank's scheduler currently holds outstanding, plus a
    # cluster sum; present only for ranks that went async (no
    # zero-noise for fully blocking jobs)
    out_block = []
    total_out = 0.0
    for r in whos:
        g = doc["ranks"][r].get("gauges", {}).get("async/outstanding")
        if g is not None:
            total_out += float(g)
            out_block.append(
                f'mp4j_outstanding_collectives{{rank="{_esc(r)}"}} '
                f"{_fmt(float(g))}")
    if out_block:
        out_block.append(
            f'mp4j_outstanding_collectives{{rank="cluster"}} '
            f"{_fmt(total_out)}")
        out.append("# TYPE mp4j_outstanding_collectives gauge")
        out.extend(out_block)

    # health plane (ISSUE 12): per-rank verdict gauge, per-(rank,
    # detector) alert counter, the evict recommendation count, and the
    # online dominator's onset counter + window-share gauge — present
    # whenever the master runs the health engine (MP4J_HEALTH=1, the
    # default), absent entirely when disabled (no zero-noise)
    hl = doc.get("cluster", {}).get("health")
    if hl is not None:
        out.append("# TYPE mp4j_rank_health_state gauge")
        for r, e in sorted((hl.get("ranks") or {}).items(),
                           key=lambda kv: int(kv[0])):
            out.append(f'mp4j_rank_health_state{{rank="{_esc(r)}"}} '
                       f"{int(e.get('state_code', 0))}")
        alert_block = []
        for r, e in sorted((hl.get("ranks") or {}).items(),
                           key=lambda kv: int(kv[0])):
            for det, n in sorted((e.get("alerts") or {}).items()):
                if n:
                    alert_block.append(
                        f'mp4j_alerts_total{{rank="{_esc(r)}",'
                        f'detector="{_esc(det)}"}} {int(n)}')
        if alert_block:
            out.append("# TYPE mp4j_alerts_total counter")
            out.extend(alert_block)
        out.append("# TYPE mp4j_evict_recommended gauge")
        out.append(f"mp4j_evict_recommended "
                   f"{len(hl.get('evict_recommended') or ())}")
        dom = hl.get("dominator") or {}
        out.append("# TYPE mp4j_straggler_onsets_total counter")
        out.append(f"mp4j_straggler_onsets_total "
                   f"{int(dom.get('onsets', 0))}")
        shares = dom.get("shares") or {}
        if shares:
            out.append("# TYPE mp4j_critpath_dominator gauge")
            for r, s in sorted(shares.items(),
                               key=lambda kv: int(kv[0])):
                out.append(
                    f'mp4j_critpath_dominator{{rank="{_esc(r)}"}} '
                    f"{_fmt(float(s))}")

    # self-tuning data plane (ISSUE 15): per-rank applied-decision
    # counters (from the slave registry's tuner/decisions) plus the
    # master controller's demotion counter and trip gauge — present
    # whenever the master runs with MP4J_TUNER != off
    tun_block = []
    tun_total = 0.0
    for r in whos:
        v = doc["ranks"][r].get("counters", {}).get("tuner/decisions")
        if v:
            tun_total += v
            tun_block.append(
                f'mp4j_tuner_decisions_total{{rank="{_esc(r)}"}} '
                f"{_fmt(float(v))}")
    if tun_block:
        tun_block.append(
            f'mp4j_tuner_decisions_total{{rank="cluster"}} '
            f"{_fmt(tun_total)}")
        out.append("# TYPE mp4j_tuner_decisions_total counter")
        out.extend(tun_block)
    tun = doc.get("cluster", {}).get("tuner")
    if tun is not None:
        out.append("# TYPE mp4j_tuner_demotions_total counter")
        out.append(f"mp4j_tuner_demotions_total "
                   f"{int(tun.get('demotions', 0))}")
        out.append("# TYPE mp4j_tuner_tripped gauge")
        out.append(f"mp4j_tuner_tripped "
                   f"{1 if tun.get('tripped') else 0}")

    # serve plane (ISSUE 19): per-rank request/batch/cache counters
    # (frontend families, worker rounds fold into the same names) plus
    # the frontend's sliding-window QPS gauge — present only for
    # serving jobs (no zero-noise for pure training jobs)
    for key, metric in (
            ("serve/requests", "mp4j_serve_requests_total"),
            ("serve/batches", "mp4j_serve_batches_total"),
            ("serve/cache_hits", "mp4j_serve_cache_hits_total"),
            ("serve/cache_misses", "mp4j_serve_cache_misses_total"),
            ("serve/degraded_batches",
             "mp4j_serve_degraded_batches_total")):
        block = []
        total = 0.0
        for r in whos:
            v = doc["ranks"][r].get("counters", {}).get(key)
            if v:
                total += v
                block.append(f'{metric}{{rank="{_esc(r)}"}} '
                             f"{_fmt(float(v))}")
        if block:
            block.append(f'{metric}{{rank="cluster"}} '
                         f"{_fmt(float(total))}")
            out.append(f"# TYPE {metric} counter")
            out.extend(block)
    srv = doc.get("cluster", {}).get("serve")
    if srv is not None and srv.get("active"):
        out.append("# TYPE mp4j_serve_qps gauge")
        out.append(f"mp4j_serve_qps {_fmt(float(srv.get('qps', 0.0)))}")

    # autoscaler (ISSUE 13): per-action dispatch counters + the
    # circuit-breaker gauge — present whenever the master runs a
    # controller (MP4J_AUTOSCALE=observe|act), absent entirely when
    # off (no zero-noise; `off` is today's behavior bit-for-bit)
    asc = doc.get("cluster", {}).get("autoscale")
    if asc is not None:
        out.append("# TYPE mp4j_autoscale_actions_total counter")
        for action, n in sorted((asc.get("actions") or {}).items()):
            out.append(
                f'mp4j_autoscale_actions_total{{action="{_esc(action)}"'
                f"}} {int(n)}")
        out.append("# TYPE mp4j_autoscale_tripped gauge")
        out.append(f"mp4j_autoscale_tripped "
                   f"{1 if asc.get('tripped') else 0}")

    out.append("# TYPE mp4j_collective_latency_seconds histogram")
    hists = doc.get("cluster", {}).get("histograms", {})
    for name in sorted(hists):
        h = hists[name]
        if name.startswith("latency/"):
            _hist_lines(out, "mp4j_collective_latency_seconds",
                        f'collective="{_esc(name[len("latency/"):])}"', h)
    out.append("# TYPE mp4j_frame_bytes histogram")
    for name in sorted(hists):
        # transport-labelled families (frame_bytes/tcp, frame_bytes/
        # shm — ISSUE 7) next to the legacy unlabelled series, all one
        # contiguous mp4j_frame_bytes block
        if name == "frame_bytes":
            _hist_lines(out, "mp4j_frame_bytes", "", hists[name])
        elif name.startswith("frame_bytes/"):
            _hist_lines(
                out, "mp4j_frame_bytes",
                f'transport="{_esc(name[len("frame_bytes/"):])}"',
                hists[name])
    return "\n".join(out) + "\n"
