"""Postmortem flight recorder: per-rank crash bundles + merged report.

A ``Mp4jFatalError`` used to leave nothing on disk: the job's spans,
stats and recovery history died with the processes, and debugging a
production incident meant reproducing it. With ``MP4J_POSTMORTEM_DIR``
set, every rank that reaches a terminal abort dumps a **bundle** before
it raises (hooked into the recovery engine's fatal fan-out, so the
survivors of a dead rank all dump), and the master writes a cluster
**manifest**; ``mp4j-scope postmortem <dir>`` merges them into one
report that names the dead and lagging ranks.

Bundle layout (``<dir>/rank_NNNN/``)::

    trace.json      span ring as Chrome-trace JSON (load in Perfetto)
    stats.json      {"rank", "reason", "epoch", "progress", "stats"}
    metrics.json    histogram/counter registry snapshot (obs.metrics)
    recovery.json   {"epoch", "events": [[mono_ts, kind, detail], ...]}
    complete.json   completeness marker, written LAST: a bundle without
                    it was torn mid-dump and the report says so

Master manifest (``<dir>/manifest.json``)::

    {"slave_num", "reason", "departed": {rank: why},
     "diagnosis": [...], "table": {rank: progress+age}, "wall_time"}

Everything here is best-effort by design — the job is already dying;
a full disk must never turn a clean ``Mp4jFatalError`` into something
worse. Writers catch ``OSError`` at the call site.
"""

from __future__ import annotations

import json
import os
import time

from ytk_mp4j_tpu.obs import spans, telemetry
from ytk_mp4j_tpu.obs.critpath import fmt_wall as _fmt_wall

_BUNDLE_FILES = ("trace.json", "stats.json", "metrics.json",
                 "recovery.json", "audit.json", "sink.json")


def bundle_dir(root: str, rank: int) -> str:
    return os.path.join(root, f"rank_{rank:04d}")


def write_bundle(root: str, rank: int, *, reason: str, progress: dict,
                 stats: dict, metrics: dict, epoch: int,
                 events: list | None = None,
                 audit: dict | None = None,
                 sink: dict | None = None) -> str:
    """Write one rank's postmortem bundle; returns the bundle dir.
    The ``complete.json`` marker goes last so a reader can distinguish
    a finished bundle from one torn by the dying process, and every
    file lands via tmp + ``os.replace`` (mp4j-lint R14) so a crash
    mid-dump can never leave a syntactically truncated JSON
    masquerading as a complete one — ``complete.json``-last used to be
    the ONLY guard. ``audit`` (ISSUE 8) is the rank's audit-ring dump
    — the record ring that makes the bundle replayable offline
    (``mp4j-scope replay``); ``sink`` (ISSUE 9) is the durable sink's
    status record pointing the report at full-job segment history."""
    d = bundle_dir(root, rank)
    os.makedirs(d, exist_ok=True)
    spans.export_chrome_trace(os.path.join(d, "trace.json"))
    _dump(d, "stats.json", {"rank": rank, "reason": reason,
                            "epoch": epoch, "progress": progress,
                            "stats": stats})
    _dump(d, "metrics.json", metrics)
    _dump(d, "recovery.json", {"epoch": epoch,
                               "events": list(events or [])})
    if audit is not None:
        _dump(d, "audit.json", audit)
    if sink is not None:
        _dump(d, "sink.json", sink)
    _dump(d, "complete.json", {
        "rank": rank, "files": list(_BUNDLE_FILES),
        # wall clock: a postmortem artifact's timestamp must be
        # human-meaningful across hosts, not a per-process counter
        # mp4j-lint: disable=R11 (artifact timestamp, not a duration)
        "wall_time": time.time()})
    return d


def _dump(d: str, name: str, obj) -> None:
    """Atomic bundle-file write (tmp + ``os.replace``): the visible
    path only ever holds a complete JSON document — a crash between
    write and replace leaves the tmp file, never a torn artifact."""
    path = os.path.join(d, name)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


def write_master_manifest(root: str, *, slave_num: int, reason: str,
                          table: dict, departed: dict,
                          diagnosis: list[str],
                          audit: dict | None = None,
                          sink_dir: str | None = None,
                          membership: dict | None = None,
                          health: dict | None = None,
                          autoscale: dict | None = None) -> str:
    """The master's cluster-level half of the recorder: who the job
    thought was alive, why it died, and the final heartbeat table
    (fresh — the slaves' fatal-path telemetry flush lands before the
    closing manifest refresh). ``audit`` (ISSUE 8) carries the
    cluster audit status — the last cross-rank-verified collective
    ordinal is the report's known-good watermark; ``sink_dir``
    (ISSUE 9) names the job's durable-sink root so the merged report
    can join full-job segment history; ``membership`` (ISSUE 10)
    records the elastic mode, spare availability and full
    replacement/shrink history so the report covers every roster the
    job ever ran under; ``health`` (ISSUE 12) freezes the health
    plane's final verdicts — per-rank state, the first-degradation
    event and the recent alert tail — so the report can answer *what
    degraded first, when, and which detector saw it*; ``autoscale``
    (ISSUE 13) freezes the controller's ledger — actions taken,
    would-be actions observed, circuit-breaker state — so the report
    shows what the autopilot DID about the degradation it saw."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, "manifest.json")
    _dump(root, "manifest.json", {
        "slave_num": slave_num,
        "reason": reason,
        "departed": {str(r): why for r, why in departed.items()},
        "diagnosis": list(diagnosis),
        "audit": audit,
        "sink_dir": sink_dir or None,
        "membership": membership,
        "health": health,
        "autoscale": autoscale,
        "table": {str(r): t for r, t in table.items()},
        # mp4j-lint: disable=R11 (artifact timestamp, not a duration)
        "wall_time": time.time(),
    })
    return path


# ----------------------------------------------------------------------
# merged report (the ``mp4j-scope postmortem`` command)
# ----------------------------------------------------------------------

def load_bundles(root: str) -> dict[int, dict]:
    """Read every COMPLETE bundle under ``root``; returns
    ``{rank: {"stats": ..., "recovery": ..., "metrics": ...,
    "complete": ..., "torn": bool}}`` (torn bundles appear with
    whatever files survived and ``torn=True``)."""
    out: dict[int, dict] = {}
    for name in sorted(os.listdir(root)):
        if not name.startswith("rank_"):
            continue
        try:
            rank = int(name[len("rank_"):])
        except ValueError:
            continue
        d = os.path.join(root, name)
        entry: dict = {"torn": not os.path.exists(
            os.path.join(d, "complete.json"))}
        for fname in _BUNDLE_FILES + ("complete.json",):
            p = os.path.join(d, fname)
            if os.path.exists(p):
                try:
                    with open(p, encoding="utf-8") as fh:
                        entry[fname.rsplit(".", 1)[0]] = json.load(fh)
                except (OSError, json.JSONDecodeError):
                    entry["torn"] = True
        out[rank] = entry
    return out


def merge_report(root: str) -> str:
    """One report from a postmortem directory: names the dead rank(s)
    (no bundle / departed per the manifest), the lagging rank(s)
    (behind the max collective sequence number), the cluster skew
    table, and each rank's last position."""
    manifest = None
    mpath = os.path.join(root, "manifest.json")
    if os.path.exists(mpath):
        with open(mpath, encoding="utf-8") as fh:
            manifest = json.load(fh)
    bundles = load_bundles(root)
    if manifest is None and not bundles:
        raise ValueError(f"{root}: no postmortem bundles or manifest")

    slave_num = (manifest["slave_num"] if manifest
                 else (max(bundles) + 1 if bundles else 0))
    lines = [f"postmortem report: {root}"]
    if manifest:
        lines.append(f"reason: {manifest.get('reason')}")
    lines.append(f"bundles: {len(bundles)}/{slave_num} ranks"
                 + (" (+" + ", ".join(
                     f"rank {r} TORN" for r in sorted(bundles)
                     if bundles[r]["torn"]) + ")"
                    if any(b["torn"] for b in bundles.values()) else ""))

    departed = {int(r): why for r, why in
                (manifest.get("departed") or {}).items()} if manifest \
        else {}
    # dead = left no bundle at all. A rank that dumped and THEN closed
    # nonzero (every survivor of a fatal does) is a casualty, not the
    # cause — the manifest's departed map only supplies the "why" for
    # the ranks that never wrote.
    dead = sorted(set(range(slave_num)) - set(bundles))
    for r in dead:
        why = departed.get(r, "no postmortem bundle written")
        lines.append(f"DEAD rank {r}: {why}")

    # membership history (ISSUE 10): every replacement/shrink the job
    # survived before it finally died — a postmortem that omits them
    # would blame rank ids that belonged to different processes over
    # the job's lifetime
    ms = (manifest or {}).get("membership") or {}
    if ms.get("replacements") or ms.get("shrinks"):
        lines.append(
            f"membership: mode={ms.get('mode')}, "
            f"{ms.get('replacements', 0)} replacement(s), "
            f"{ms.get('shrinks', 0)} shrink(s), "
            f"{ms.get('spares_available', 0)} spare(s) left")
        for ev in ms.get("events") or []:
            if ev.get("kind") == "replace":
                lines.append(
                    f"membership event: rank {ev.get('rank')} REPLACED "
                    f"from spare #{ev.get('spare')} @ epoch "
                    f"{ev.get('epoch')} ({ev.get('why')})")
            else:
                lines.append(
                    f"membership event: SHRUNK, dropped "
                    f"{ev.get('dead')} @ epoch {ev.get('epoch')} "
                    f"({ev.get('why')})")

    # health timeline (ISSUE 12): what degraded first, when, and which
    # detector saw it — the manifest froze the engine's final verdicts
    # at abort time (the durable sink join below carries the FULL
    # alert history when the job ran with a sink)
    health = (manifest or {}).get("health") or {}
    if health.get("ranks"):
        verdicts = ", ".join(
            f"rank {r}: {e.get('state')}"
            for r, e in sorted(health["ranks"].items(), key=lambda kv:
                               int(kv[0]))
            if e.get("state") != "HEALTHY")
        lines.append("health verdicts at abort time: "
                     + (verdicts or "all reporting ranks HEALTHY"))
        fd = health.get("first_degraded")
        if fd:
            lines.append(
                f"health: first degradation was rank {fd.get('rank')} "
                f"-> {fd.get('to')} via {fd.get('detector')} at "
                f"{_fmt_wall(fd.get('wall'))}"
                + (f" (collective #{fd['seq']})" if fd.get("seq")
                   else "") + f": {fd.get('msg', '')}")
        for ev in health.get("last_alerts") or []:
            lines.append(
                f"health alert: rank {ev.get('rank')} "
                f"{ev.get('from')} -> {ev.get('to')} "
                f"({ev.get('detector')}) at "
                f"{_fmt_wall(ev.get('wall'))}: "
                f"{ev.get('msg', '')}")
        evict = health.get("evict_recommended") or []
        if evict:
            lines.append(
                f"health: EVICT was recommended for rank(s) "
                f"{', '.join(map(str, evict))} before the fatal")

    # autoscaler actions (ISSUE 13): what the autopilot did (or would
    # have done) about the degradation the health section describes —
    # a postmortem that shows verdicts without actions can't tell a
    # controller that failed to act from one that was never armed
    asc = (manifest or {}).get("autoscale") or {}
    if asc:
        lines.append(
            f"autoscaler: mode={asc.get('mode')}"
            + (" TRIPPED (recommend-only)" if asc.get("tripped")
               else "")
            + f", actions {asc.get('actions')}, "
            f"budget {asc.get('budget', {}).get('used', 0)}/"
            f"{asc.get('budget', {}).get('limit', 0)}")
        if asc.get("tripped"):
            lines.append(
                f"autoscaler: breaker tripped: {asc.get('tripped_why')}")
        for ev in asc.get("events") or []:
            lines.append(
                f"autoscaler event: {ev.get('event')} "
                f"{ev.get('action')}"
                + (f" rank {ev['rank']}"
                   if ev.get("rank") is not None else "")
                + f" at {_fmt_wall(ev.get('wall'))}: "
                  f"{ev.get('msg', '')}")

    # known-good watermark (ISSUE 8): the last collective ordinal the
    # master cross-rank-verified before the fatal — everything up to
    # it is PROVEN bit-identical across ranks, so the search space for
    # "when did it go wrong" starts there, not at step 0
    audit = (manifest or {}).get("audit") or {}
    if audit.get("verified_seq"):
        lines.append(
            f"known-good watermark: collective #{audit['verified_seq']} "
            "was the last cross-rank-verified seq before the fatal "
            f"({audit.get('verified_total', 0)} seq(s) verified, "
            f"{audit.get('divergences', 0)} divergence(s))")
    elif audit:
        lines.append(
            "known-good watermark: none — no collective was cross-rank-"
            "verified before the fatal (audit mode below 'verify', or "
            "the job died before the first complete round)")
    for d in audit.get("last_divergences") or []:
        lines.append(f"audit divergence: {d.get('msg')}")

    # sequence-number lag across the bundles that exist
    table = {}
    for r, b in sorted(bundles.items()):
        prog = (b.get("stats") or {}).get("progress") or {}
        table[r] = {"seq": int(prog.get("seq", 0)),
                    "current": prog.get("current"),
                    "last": prog.get("last"),
                    "phase": prog.get("phase"),
                    "current_secs": float(prog.get("current_secs", 0.0)),
                    "age": 0.0}
    if table:
        lines.append("")
        lines.extend(telemetry.render_diagnosis(table, slave_num))
        per_rank = {r: (b.get("stats") or {}).get("stats") or {}
                    for r, b in bundles.items()}
        skew = telemetry.cluster_skew(
            {r: s for r, s in per_rank.items() if s})
        if skew:
            lines.append("")
            lines.append(telemetry.format_skew(skew))
    for r, b in sorted(bundles.items()):
        ev = (b.get("recovery") or {}).get("events") or []
        if ev:
            tail = "; ".join(f"{kind}({detail})" if detail else kind
                             for _, kind, detail in ev[-6:])
            lines.append(f"rank {r} recovery log (last "
                         f"{min(len(ev), 6)}): {tail}")
    if manifest and manifest.get("diagnosis"):
        lines.append("")
        lines.append("master diagnosis at abort time:")
        lines.extend(f"  {ln}" for ln in manifest["diagnosis"])

    # durable-sink join (ISSUE 9): when the job ran with the streaming
    # sink, the report gains FULL-JOB history — critical-path
    # dominators and straggler onset over every ordinal the segments
    # kept, not just the ring tails the bundles froze
    sink_root = (manifest or {}).get("sink_dir")
    if not sink_root:
        for b in bundles.values():
            root_hint = (b.get("sink") or {}).get("root")
            if root_hint:
                sink_root = root_hint
                break
    if sink_root and os.path.isdir(sink_root):
        try:
            from ytk_mp4j_tpu.obs import critpath, sink as sink_mod
            analysis = critpath.analyze(sink_mod.load_job(sink_root))
            lines.append("")
            lines.append("durable sink (full-job history):")
            lines.extend("  " + ln for ln in critpath.format_report(
                analysis, sink_root).splitlines())
        except Exception as e:      # torn segments must not kill the
            # postmortem path they exist to enrich
            lines.append(f"durable sink at {sink_root}: unreadable "
                         f"({e!r})")
    return "\n".join(lines)
