"""Cluster telemetry: heartbeat schema, cross-rank skew, hang diagnosis.

Pure functions over per-rank telemetry records — the stateful consumers
are the master (``comm/master.py``, live heartbeat table) and the
``mp4j-scope`` CLI (post-hoc per-rank ``comm.stats()`` dumps); both
share this implementation. Deliberately imports nothing from ``comm``.

Heartbeat schema (one ``TELEMETRY`` message, slave -> master)::

    {"progress": {"seq": int,          # collectives ENTERED so far
                  "current": str|None, # collective in flight, if any
                  "last": str|None,    # last collective completed
                  "phase": str|None,   # last phase booked (wire/...)
                  "current_secs": float},  # time inside `current`
     "stats": {collective: {calls, bytes_sent, bytes_recv, chunks,
                            wire_seconds, reduce_seconds,
                            serialize_seconds}}}

``seq`` is the per-slave monotonically increasing collective sequence
number (bumped by ``CommStats.begin`` on every outermost collective
call), the quantity hang diagnosis compares across ranks: in a correct
SPMD schedule every rank runs the same collective sequence, so a rank
whose ``seq`` trails the cluster maximum is the rank everyone else is
waiting for.
"""

from __future__ import annotations

import statistics
import time

from ytk_mp4j_tpu.obs.health import SHORT_BY_NAME as _STATE_SHORT

_PHASES = ("wire_seconds", "reduce_seconds", "serialize_seconds")


def busy_seconds(entry: dict[str, float]) -> float:
    """A rank's total busy time for one collective family (phase times
    are busy, possibly overlapping, times — see utils.stats)."""
    return float(sum(entry.get(p, 0.0) for p in _PHASES))


def cluster_skew(per_rank: dict[int, dict[str, dict[str, float]]]
                 ) -> dict[str, dict]:
    """Cross-rank skew per collective family.

    ``per_rank`` maps rank -> ``comm.stats()`` snapshot. Returns, per
    collective name seen on any rank::

        {"ranks": int,                 # ranks reporting this family
         "calls": int,                 # max calls any rank made
         "bytes": int,                 # total wire bytes, all ranks
         "busy_min"/"busy_median"/"busy_max": float,
         "stragglers": [rank, ...]}    # ranks at busy_max (ties kept)

    A straggler here is the rank spending the most busy time in the
    family — on a balanced workload that is noise, on a skewed one it
    names who the other ranks waited for.
    """
    names: set[str] = set()
    for snap in per_rank.values():
        names.update(snap)
    out: dict[str, dict] = {}
    for name in names:
        rows = {r: snap[name] for r, snap in per_rank.items()
                if name in snap}
        busys = {r: busy_seconds(e) for r, e in rows.items()}
        bmax = max(busys.values())
        out[name] = {
            "ranks": len(rows),
            "calls": int(max(e.get("calls", 0) for e in rows.values())),
            "bytes": int(sum(e.get("bytes_sent", 0)
                             + e.get("bytes_recv", 0)
                             for e in rows.values())),
            "busy_min": min(busys.values()),
            "busy_median": statistics.median(busys.values()),
            "busy_max": bmax,
            "stragglers": sorted(r for r, b in busys.items()
                                 if b >= bmax and bmax > 0),
        }
    return out


def format_skew(skew: dict[str, dict]) -> str:
    """Human-readable skew table (the ``mp4j-scope report`` view)."""
    if not skew:
        return "(no telemetry)"
    w = max(len(n) for n in skew)
    lines = [f"{'collective':<{w}}  ranks  calls      MB  "
             f"busy min/med/max (s)  stragglers"]
    for name in sorted(skew):
        s = skew[name]
        lines.append(
            f"{name:<{w}}  {s['ranks']:>5d}  {s['calls']:>5d}  "
            f"{s['bytes'] / 1e6:>6.2f}  "
            f"{s['busy_min']:>6.3f}/{s['busy_median']:>6.3f}/"
            f"{s['busy_max']:>6.3f}  "
            f"{','.join(map(str, s['stragglers'])) or '-'}")
    return "\n".join(lines)


def format_live(doc: dict) -> str:
    """The ``mp4j-scope live`` frame: one view of a master metrics
    document (``Master.metrics_doc`` / the ``/metrics.json``
    endpoint) — cluster rates, then one row per rank with throughput,
    current collective, sequence lag, retry count, health verdict and
    heartbeat age. Stragglers (the busy-max ranks of any collective
    family, same rule as :func:`cluster_skew`) are marked ``*``; ranks
    behind the max sequence number show their lag. The whole table
    stays within 120 columns. A rank whose heartbeat is older than 2x
    the heartbeat period renders ``stale`` in its derived rate column
    — the master's rate window freezes at the last fold, and a wedged
    rank must not display a healthy-looking throughput (ISSUE 12)."""
    ranks = doc.get("ranks", {})
    cl = doc.get("cluster", {})
    rates = cl.get("rates", {})
    head = (f"mp4j live — {len(ranks)}/{doc.get('slave_num', '?')} "
            f"ranks reporting | "
            f"{rates.get('bytes_per_sec', 0.0) / 1e9:.3f} GB/s | "
            f"{rates.get('collectives_per_sec', 0.0):.1f} coll/s | "
            f"{rates.get('keys_per_sec', 0.0):.0f} keys/s "
            f"(window {doc.get('window_secs', 0):.0f}s)")
    audit = cl.get("audit") or {}
    if audit.get("rank_seq") or audit.get("divergences"):
        # the audit plane only reports under MP4J_AUDIT=verify|capture;
        # a nonzero divergence count is the headline of the whole view
        head += (f"\naudit: verified through collective "
                 f"#{audit.get('verified_seq', 0)}, "
                 f"{audit.get('divergences', 0)} divergence(s)")
        if audit.get("divergences"):
            last = (audit.get("last_divergences") or [{}])[-1]
            head += f"\n  last: {last.get('msg', '?')}"
    # elastic membership (ISSUE 10): the spares line + event headline;
    # absent entirely for non-elastic jobs with no spares registered
    ms = cl.get("membership") or {}
    badges = {str(r): b for r, b in (ms.get("badges") or {}).items()}
    if (ms.get("mode", "off") != "off" or ms.get("spares_total")
            or ms.get("replacements") or ms.get("shrinks")):
        head += (f"\nmembership: mode={ms.get('mode', 'off')} | "
                 f"spares {ms.get('spares_available', 0)}/"
                 f"{ms.get('spares_total', 0)} available | "
                 f"{ms.get('replacements', 0)} replacement(s), "
                 f"{ms.get('shrinks', 0)} shrink(s)")
        events = ms.get("events") or []
        if events:
            ev = events[-1]
            if ev.get("kind") == "replace":
                head += (f"\n  last: rank {ev.get('rank')} REPLACED "
                         f"from spare #{ev.get('spare')} @ epoch "
                         f"{ev.get('epoch')}")
            else:
                head += (f"\n  last: SHRUNK, dropped {ev.get('dead')} "
                         f"@ epoch {ev.get('epoch')}")
    # health head-line (ISSUE 12): only when the plane has something
    # to say — any alert ever, or any rank off HEALTHY right now
    hl = cl.get("health") or {}
    hl_states = {r: e.get("state", "HEALTHY")
                 for r, e in (hl.get("ranks") or {}).items()}
    if hl.get("alerts_total") or any(s != "HEALTHY"
                                     for s in hl_states.values()):
        bad = ", ".join(f"rank {r} {s}" for r, s in
                        sorted(hl_states.items(), key=lambda kv:
                               int(kv[0])) if s != "HEALTHY")
        head += (f"\nhealth: {hl.get('alerts_total', 0)} alert(s)"
                 + (f" | {bad}" if bad else " | all HEALTHY again"))
        evict = hl.get("evict_recommended") or []
        if evict:
            head += (" | EVICT recommended: "
                     + ",".join(map(str, evict)))
        last = hl.get("last_alerts") or []
        if last:
            ev = last[-1]
            head += (f"\n  last: rank {ev.get('rank')} "
                     f"{ev.get('from')}->{ev.get('to')} "
                     f"({ev.get('detector')}) "
                     f"{str(ev.get('msg', ''))[:60]}")
    # serve head-line (ISSUE 19): the inference plane's QPS / tail
    # latency / cache hit-rate / degraded tally; absent entirely for
    # training jobs (no serve/* counters anywhere in the registry)
    sv = cl.get("serve") or {}
    if sv.get("active"):
        hr = sv.get("hit_rate")
        head += (f"\nserve: {sv.get('qps', 0.0):.1f} QPS | "
                 f"p50 {sv.get('p50_ms', 0.0):.2f}ms "
                 f"p99 {sv.get('p99_ms', 0.0):.2f}ms | "
                 f"{sv.get('requests', 0)} req in "
                 f"{sv.get('batches', 0)} batch(es) | cache "
                 + (f"{100.0 * hr:.0f}% hit" if hr is not None
                    else "off")
                 + (f" | {sv['degraded_batches']} DEGRADED"
                    if sv.get("degraded_batches") else ""))
    # autoscaler head-line (ISSUE 13): mode, trip state, action tally;
    # absent entirely when MP4J_AUTOSCALE=off (no controller exists)
    asc = cl.get("autoscale") or {}
    if asc:
        acted = sum((asc.get("actions") or {}).values())
        would = sum((asc.get("observed") or {}).values())
        head += (f"\nautoscale: mode={asc.get('mode')}"
                 + (" TRIPPED" if asc.get("tripped") else "")
                 + f" | {acted} action(s)"
                 + (f", {would} observed" if would else "")
                 + f" | budget {asc.get('budget', {}).get('used', 0)}"
                 f"/{asc.get('budget', {}).get('limit', 0)}")
        events = asc.get("events") or []
        if events:
            ev = events[-1]
            head += (f"\n  last: {ev.get('event')} "
                     f"{ev.get('action')} "
                     f"{str(ev.get('msg', ''))[:60]}")
    if not ranks:
        return head + "\n(no rank telemetry yet)"
    skew = cluster_skew({int(r): info.get("stats", {})
                         for r, info in ranks.items()
                         if info.get("stats")})
    stragglers = {r for s in skew.values() for r in s["stragglers"]}
    max_seq = max(info.get("progress", {}).get("seq", 0)
                  for info in ranks.values())
    hb_secs = float(doc.get("hb_secs") or 0.0)
    lines = [head,
             f"{'rank':>4} {'seq':>5} {'lag':>3} {'ep':>2}  "
             f"{'state':<32} {'MB/s':>8} {'shm%':>4} {'ovl%':>4} "
             f"{'aud':>5} {'sink':>6} {'rtry':>4} {'health':>6}  "
             f"{'roster':<8}  hb age"]
    for r in sorted(ranks, key=int):
        info = ranks[r]
        prog = info.get("progress", {})
        seq = prog.get("seq", 0)
        lag = max_seq - seq
        age = float(info.get("age", 0.0))
        if prog.get("current"):
            state = (f"in {prog['current']} "
                     f"({prog.get('current_secs', 0.0):.1f}s"
                     + (f", {prog['phase']}" if prog.get("phase")
                        else "") + ")")
        elif prog.get("last"):
            state = f"idle after {prog['last']}"
        else:
            state = "idle"
        retries = sum(int(e.get("retries", 0))
                      for e in info.get("stats", {}).values())
        # which plane the bytes rode (ISSUE 7): shm share of the
        # transport-tagged wire bytes; "-" before any tagged byte moved
        shm_b = sum(e.get("wire_bytes_shm", 0)
                    for e in info.get("stats", {}).values())
        tagged = shm_b + sum(e.get("wire_bytes_tcp", 0)
                             for e in info.get("stats", {}).values())
        shm_pct = f"{100.0 * shm_b / tagged:.0f}" if tagged else "-"
        # overlap column (ISSUE 11): of the wall time this rank had
        # nonblocking collectives in flight, the fraction where >= 2
        # overlapped — the scheduler's ovl% headline; "-" until the
        # rank submits any i* work
        asy = info.get("stats", {}).get("<async>", {})
        inflight = asy.get("async_inflight", 0.0)
        ovl_pct = (f"{100.0 * asy.get('async_overlap', 0.0) / inflight:.0f}"
                   if inflight else "-")
        # audit column (ISSUE 8): the rank's last audited collective
        # ordinal; "-" until the rank ships audit records
        aud = info.get("audit_seq", 0)
        # sink column (ISSUE 9): MB the rank's durable sink has made
        # safe, with a ! marker when it is dropping records; "-" only
        # when the sink is truly disarmed (no bytes AND no drops — a
        # full disk writes nothing but drops plenty, and rendering
        # that as disarmed would hide exactly the failure the marker
        # exists for)
        sink_b = info.get("counters", {}).get("sink/bytes", 0)
        sink_drop = info.get("counters", {}).get(
            "sink/dropped_records", 0)
        sink_col = (f"{sink_b / 1e6:.1f}M" + ("!" if sink_drop else "")
                    if sink_b or sink_drop else "-")
        mark = "*" if int(r) in stragglers else " "
        # epoch + roster badge (ISSUE 10): which recovery epoch the
        # rank runs at, and whether its id was REPLACED from a spare
        # or SHRUNK into a new number this job
        epoch = prog.get("epoch") or 0
        badge = badges.get(str(r), "-")
        # health column (ISSUE 12): the rank's current verdict, "-"
        # when the master runs without the health plane
        health_col = _STATE_SHORT.get(hl_states.get(str(r)), "-")
        # stale-heartbeat annotation (ISSUE 12 satellite): the rate
        # column is DERIVED from the rank's last fold — render the
        # fact that it is history, not throughput, once the beat is
        # 2x the heartbeat period late
        stale = hb_secs > 0 and age > 2.0 * hb_secs
        mbs = ("stale" if stale else
               f"{info.get('rates', {}).get('bytes_per_sec', 0.0) / 1e6:.2f}")
        lines.append(
            f"{mark}{r:>3} {seq:>5} {lag if lag else '-':>3} "
            f"{epoch if epoch else '-':>2}  "
            f"{state:<32.32} "
            f"{mbs:>8} "
            f"{shm_pct:>4} "
            f"{ovl_pct:>4} "
            f"{aud if aud else '-':>5} "
            f"{sink_col:>6} "
            f"{retries:>4} "
            f"{health_col:>6}  "
            f"{badge:<8.8}  {age:.1f}s")
    return "\n".join(lines)


def _health_tally(ladder: dict[str, int]) -> str:
    """Compress a health-ladder tally (``{"HEALTHY": 3, "DEGRADED":
    1}``) into the fleet table's cell: ``3H1D``; ``-`` when the job
    reports no health plane."""
    if not ladder:
        return "-"
    order = {"HEALTHY": 0, "SUSPECT": 1, "DEGRADED": 2, "CRITICAL": 3}
    parts = []
    for name in sorted(ladder, key=lambda n: order.get(n, 9)):
        parts.append(f"{ladder[name]}{_STATE_SHORT.get(name, name[:1])}")
    return "".join(parts)


def _fleet_state_cell(state: str, age: float) -> str:
    """``LIVE`` / ``STALE(4.2s)`` / ``GONE(44s)`` — a non-LIVE row
    always says how old its facts are."""
    if state == "LIVE":
        return "LIVE"
    return f"{state}({age:.0f}s)" if age >= 9.5 else \
        f"{state}({age:.1f}s)"


def format_fleet(model: dict) -> str:
    """The ``mp4j-scope fleet`` frame: one view of a fleet model
    (:func:`ytk_mp4j_tpu.obs.fleet.fold_fleet`) — the aggregate
    head-line, one row per job (identity, staleness state, ranks,
    rates, retries, health-ladder tally, roster generation), then one
    block per SHARED host fingerprint with each co-resident job's
    ranks / wire bytes / live rate / slow-link verdicts, and a
    ``CONTENTION`` line per flagged host. Pure over the model dict."""
    agg = model.get("aggregate") or {}
    jobs = model.get("jobs") or {}
    head = (f"mp4j fleet — {agg.get('live', 0)}/{agg.get('jobs', 0)} "
            f"job(s) LIVE | {agg.get('ranks', 0)} ranks | "
            f"{agg.get('bytes_per_sec', 0.0) / 1e9:.3f} GB/s | "
            f"{agg.get('collectives_per_sec', 0.0):.1f} coll/s")
    lines = [head,
             f"{'job':<10} {'state':<12} {'ranks':>6} {'MB/s':>8} "
             f"{'coll/s':>7} {'QPS':>7} {'rtry':>4} {'health':>7} "
             f"{'gen':>3}  url"]
    for key in sorted(jobs):
        st = jobs[key]
        s = st.get("summary")
        cell = _fleet_state_cell(st.get("state") or "?",
                                 float(st.get("age", 0.0)))
        if s is None:
            lines.append(f"{'-':<10} {cell:<12} {'-':>6} {'-':>8} "
                         f"{'-':>7} {'-':>7} {'-':>4} {'-':>7} "
                         f"{'-':>3}  {st.get('url', key)} "
                         f"(never scraped)")
            continue
        ranks_cell = f"{s['ranks_reporting']}/{s['slave_num']}"
        # serve jobs read distinctly from batch jobs (ISSUE 19): the
        # QPS cell is a number only when the job runs the inference
        # plane; "-" for pure training jobs
        sv = s.get("serve")
        qps_cell = f"{sv['qps']:.1f}" if sv else "-"
        lines.append(
            f"{(s['job_id'] or '-'):<10.10} {cell:<12} "
            f"{ranks_cell:>6} "
            f"{s['bytes_per_sec'] / 1e6:>8.2f} "
            f"{s['collectives_per_sec']:>7.1f} "
            f"{qps_cell:>7} "
            f"{s['retries']:>4d} "
            f"{_health_tally(s['health']['states']):>7} "
            f"{s['roster_gen']:>3d}  {st.get('url', key)}")
    hosts = model.get("hosts") or {}
    for fp in model.get("shared_hosts") or []:
        lines.append(f"shared host {fp}:")
        for jid in sorted(hosts.get(fp, {}).get("jobs", {})):
            j = hosts[fp]["jobs"][jid]
            ranks = ",".join(map(str, j["ranks"]))
            slow = ",".join(j["slow_links"]) or "-"
            lines.append(
                f"  job {jid:<10.10} ranks [{ranks}]  "
                f"{j['wire_bytes'] / 1e6:.2f} MB wire  "
                f"{j['bytes_per_sec'] / 1e6:.2f} MB/s  "
                f"slow links: {slow}")
    for c in model.get("contention") or []:
        verdicts = "; ".join(f"{j}: {','.join(v)}"
                             for j, v in c["slow"].items())
        lines.append(
            f"CONTENTION host {c['host_fp']}: "
            f"{', '.join(c['jobs'])} busy simultaneously, "
            f"each holding slow-link verdicts ({verdicts})")
    return "\n".join(lines)


def _wall_hms(wall) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(wall)))
    except (TypeError, ValueError, OverflowError, OSError):
        return "??:??:??"


def format_fleet_report(report: dict) -> str:
    """The ``mp4j-scope fleet-report`` view: jobs ever seen with their
    last-known state, the merged event timeline (job up/stale/gone/
    restart, health transitions, autoscaler actions, contention
    on/off) and contention episodes, from
    :func:`ytk_mp4j_tpu.obs.fleet.fleet_report`'s dict. Pure."""
    lines = [f"fleet report — {report.get('snapshots', 0)} "
             f"snapshot(s), {len(report.get('events') or [])} "
             f"event(s), {report.get('segments', 0)} segment(s), "
             f"{report.get('torn', 0)} torn tail(s)"]
    jobs = report.get("jobs") or {}
    if jobs:
        lines.append("jobs:")
        for key in sorted(jobs):
            j = jobs[key]
            lines.append(
                f"  job {(j.get('job_id') or '-'):<10} "
                f"{(j.get('state') or '?'):<6} "
                f"{j.get('slave_num', '?')} rank(s)  "
                f"gen {j.get('roster_gen', '?')}  {j.get('url', key)}")
    events = report.get("events") or []
    if events:
        lines.append("timeline:")
        for ev in events:
            lines.append(f"  {_wall_hms(ev.get('wall'))}  "
                         f"{ev.get('kind', '?'):<14} "
                         f"{ev.get('msg', '')}")
    else:
        lines.append("timeline: (no events recorded)")
    eps = report.get("episodes") or []
    if eps:
        lines.append("contention episodes:")
        for ep in eps:
            onset = ep.get("onset_wall")
            clear = ep.get("clear_wall")
            span = (f"{_wall_hms(onset)}..{_wall_hms(clear)} "
                    f"({float(clear) - float(onset):.1f}s)"
                    if clear is not None
                    else f"{_wall_hms(onset)}.. (unresolved at end "
                         "of history)")
            lines.append(f"  host {ep.get('host_fp')}: {span}")
    return "\n".join(lines)


def render_diagnosis(table: dict[int, dict], slave_num: int) -> list[str]:
    """Render a hang/straggler diagnosis from the master's heartbeat
    table.

    ``table`` maps rank -> ``{"seq", "current", "last", "phase",
    "age"}`` (``age`` = seconds since that rank's last heartbeat
    arrived). Returns log lines: the cluster's max sequence number,
    then one line per rank — laggards (seq behind the max) with their
    lag, where they last were, and how stale their heartbeat is — and a
    closing line naming the likely stuck rank(s).
    """
    if not table:
        return [f"no telemetry received from any of the {slave_num} "
                "rank(s) — cannot localize the hang (heartbeats "
                "disabled? MP4J_HEARTBEAT_SECS=0)"]
    max_seq = max(t["seq"] for t in table.values())
    lines = [f"cluster diagnosis: max collective seq {max_seq}, "
             f"{len(table)}/{slave_num} ranks reporting"]
    stuck: list[int] = []
    for rank in range(slave_num):
        t = table.get(rank)
        if t is None:
            stuck.append(rank)
            lines.append(f"rank {rank}: NO heartbeat ever received")
            continue
        lag = max_seq - t["seq"]
        if t.get("current"):
            where = (f"stuck in '{t['current']}'"
                     + (f" (phase {t['phase']})" if t.get("phase")
                        else "")
                     + f" for {t.get('current_secs', 0.0):.1f}s")
        elif t.get("last"):
            where = f"idle after '{t['last']}'"
        else:
            where = "no collective entered yet"
        mark = f"lag {lag}" if lag > 0 else "up to date"
        lines.append(
            f"rank {rank}: seq {t['seq']} ({mark}), {where}; "
            f"last heartbeat {t.get('age', 0.0):.1f}s ago")
        if lag > 0:
            stuck.append(rank)
    if stuck:
        lines.append(
            f"likely stuck rank(s): {', '.join(map(str, stuck))} — "
            "behind the cluster schedule; the other ranks' bounded "
            "waits expired waiting for them")
    else:
        lines.append(
            "all reporting ranks are at the same sequence number — "
            "the stall is inside one collective (rank skew or a dead "
            "transport), not a mismatched schedule")
    return lines
