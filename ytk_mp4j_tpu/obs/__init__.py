"""mp4j-scope — cluster-wide observability (ISSUE 3 + ISSUE 6).

Layers on top of the PR-2 measurement substrate:

- :mod:`ytk_mp4j_tpu.obs.spans` — a bounded in-process span ring fed by
  the always-on :class:`~ytk_mp4j_tpu.utils.stats.CommStats` phase
  counters and the ``trace.traced`` collective wrappers; exported as
  Chrome-trace/Perfetto JSON (``trace.export_chrome_trace``).
- :mod:`ytk_mp4j_tpu.obs.telemetry` — pure functions over per-rank
  telemetry: heartbeat progress records, cross-rank skew aggregation
  (``cluster_skew``) and hang diagnosis rendering
  (``render_diagnosis``). The master (``comm/master.py``) is the stateful
  consumer; this module deliberately imports nothing from ``comm`` so
  the CLI and the master share one implementation without a cycle.
- :mod:`ytk_mp4j_tpu.obs.metrics` — the live metrics plane (ISSUE 6):
  counters/gauges/log2-bucket histograms, heartbeat delta shipping,
  sliding rate windows, and the Prometheus renderer behind the
  master's ``MP4J_METRICS_PORT`` endpoint.
- :mod:`ytk_mp4j_tpu.obs.postmortem` — the flight recorder (ISSUE 6):
  per-rank crash bundles on any terminal abort
  (``MP4J_POSTMORTEM_DIR``), the master manifest, and the merged
  report behind ``mp4j-scope postmortem``.
- :mod:`ytk_mp4j_tpu.obs.benchdiff` — the perf regression gate behind
  ``mp4j-scope bench-diff`` (ISSUE 6): per-metric budgets over
  ``bench.py`` JSON outputs.
- :mod:`ytk_mp4j_tpu.obs.sink` — mp4j-trail (ISSUE 9): the durable
  streaming telemetry sink draining the span/metrics/audit/recovery
  rings into crc-framed rotating segment files (``MP4J_SINK_DIR``,
  per-rank budget, torn-tail-tolerant reader).
- :mod:`ytk_mp4j_tpu.obs.critpath` — cross-rank per-collective
  timeline reconstruction over sink segments with critical-path
  dominator attribution, per-phase wait decomposition and
  straggler-onset trend detection (``mp4j-scope analyze``/``tail``).
- :mod:`ytk_mp4j_tpu.obs.health` — mp4j-health (ISSUE 12): the
  streaming health plane interpreting the other three — rolling
  per-rank baselines, a detector set (online critpath dominance,
  latency drift, storms, sink outages, backlog growth, heartbeat
  flapping, audit escalation) and the per-rank hysteresis verdict
  machine behind ``Master.health_status()``, the ``alerts`` sink
  records and ``mp4j-scope health``.
- :mod:`ytk_mp4j_tpu.obs.cli` — the ``mp4j-scope`` CLI: merge per-rank
  Chrome-trace files into one timeline; render the cross-rank skew
  table from per-rank ``comm.stats()`` JSON dumps; ``live`` /
  ``postmortem`` / ``replay`` / ``analyze`` / ``tail`` / ``health`` /
  ``bench-diff``.
"""
