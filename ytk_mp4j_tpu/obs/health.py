"""mp4j-health — streaming anomaly detection and per-rank verdicts.

The repo measures three telemetry planes — mp4j-scope time spans
(ISSUE 3/9), the metrics volume plane (ISSUE 6) and the audit content
plane (ISSUE 8) — but until this module nothing *interpreted* them.
This is the health plane: it folds every heartbeat into rolling
per-rank baselines, runs a detector set over the deltas, and drives a
per-rank hysteresis state machine whose verdicts are the decision
substrate the elastic autoscaler (ROADMAP) consumes — this plane
RECOMMENDS, it never acts.

State machine (per rank)::

    HEALTHY -> DEGRADED -> SUSPECT -> EVICT_RECOMMENDED
       ^          |           |              |
       +---- hysteresis: one level down per CLEAR_FOLDS clean folds
    DEAD  (from the existing liveness path; replacement resets)

Escalation is pressure-driven: each detector hit adds its severity to
a per-(rank, detector) leaky pressure counter (capped, halved on clean
folds); max pressure >= :data:`TH_DEGRADED` targets DEGRADED,
>= :data:`TH_SUSPECT` targets SUSPECT, and the machine climbs ONE
level per fold so a single noisy beat can never catapult a rank. Two
signals jump the ladder: an audit divergence naming the rank (content
corruption — straight to SUSPECT) and the dominator streak
(``MP4J_HEALTH_DOMINATOR_ORDINALS`` consecutive slow ordinals gated by
one rank — the ROADMAP's eviction contract — straight to
EVICT_RECOMMENDED, with SUSPECT forced at half the streak). Stepping
DOWN requires :data:`CLEAR_FOLDS` consecutive clean folds per level —
the hysteresis that keeps an intermittent straggler from flapping.

Detector set (each a pure function over snapshot deltas — tests drive
them without sockets):

- ``dominator`` — online port of :mod:`critpath`'s blame attribution:
  slaves fold their own span-ring delta into per-ordinal cells
  (:class:`SpanFolder`) and ship them on the heartbeat; the engine
  attributes each ordinal once every live rank's cell arrived
  (:func:`critpath.attribute` on the live deltas) and tracks both the
  sliding-window dominance share and the consecutive-ordinal streak.
  A dominance hit requires the ordinal to be SLOW against the rolling
  duration baseline (:data:`DOM_SLOW_FACTOR`) — a topology-biased but
  fast dominator on a healthy grid must stay quiet.
- ``latency_drift`` — per-family latency vs the rank's OWN baseline:
  EWMA of the per-fold mean plus the log2-histogram mean-bucket index;
  drift = mean above baseline by ``MP4J_HEALTH_DRIFT_PCT`` *and* the
  bucket index shifted a full log2 bucket (the histogram confirmation
  that defeats mean-only noise), two folds in a row.
- ``storm`` — retry/reconnect/abort counters: a leaky accumulator over
  the stats deltas; one clean recovery round never fires, a storm does.
- ``sink_drop`` — the durable sink is dropping records (full disk,
  dead drain): the ``sink/dropped_records`` counter moved.
- ``backlog`` — ``async/outstanding`` growing monotonically across
  folds: the scheduler is falling behind its submissions.
- ``hb_flap`` — heartbeat inter-arrival jitter: a beat landing far
  outside the rank's own EWMA gap (and the configured period).
- ``audit`` — divergence escalation: the cluster auditor named this
  rank in a divergence (minority output, wire pair, schedule).

Verdict transitions are emitted as structured **alert events** — into
the master log, pushed to the subject rank's recovery log and durable
sink (the ``alerts`` record kind in :mod:`sink`), exported as
Prometheus series (``mp4j_rank_health_state``, ``mp4j_alerts_total``,
``mp4j_evict_recommended``, ``mp4j_straggler_onsets_total``,
``mp4j_critpath_dominator``), surfaced via ``Master.health_status()``
(the operator hook a future autoscaler calls), the ``health`` column
in ``mp4j-scope live``, the ``mp4j-scope health`` subcommand, and the
postmortem report's health timeline.

Everything here is deliberately import-light (stdlib +
:mod:`critpath`/:mod:`spans`) and lock-free: the engine is owned by
the master and called under the master's lock; the slave-side pieces
(:class:`SpanFolder`, :class:`AlertLog`) carry their own tiny locks.
"""

from __future__ import annotations

import collections
import threading
import time

from ytk_mp4j_tpu.obs import critpath, spans

# ---------------------------------------------------------------------
# states
# ---------------------------------------------------------------------
HEALTHY = 0
DEGRADED = 1
SUSPECT = 2
EVICT_RECOMMENDED = 3
DEAD = 4
STATE_NAMES = {HEALTHY: "HEALTHY", DEGRADED: "DEGRADED",
               SUSPECT: "SUSPECT",
               EVICT_RECOMMENDED: "EVICT_RECOMMENDED", DEAD: "DEAD"}
# compact forms for the 6-char `mp4j-scope live` column, keyed both
# ways (the live view holds state NAMES from the metrics doc)
STATE_SHORT = {HEALTHY: "ok", DEGRADED: "DEGR", SUSPECT: "SUSP",
               EVICT_RECOMMENDED: "EVICT", DEAD: "DEAD"}
SHORT_BY_NAME = {STATE_NAMES[c]: s for c, s in STATE_SHORT.items()}

DETECTORS = ("dominator", "latency_drift", "storm", "sink_drop",
             "backlog", "hb_flap", "audit", "liveness")

# ---------------------------------------------------------------------
# hysteresis constants
# ---------------------------------------------------------------------
# pressure thresholds: DEGRADED needs two ordinary (sev-1) hits close
# together, SUSPECT needs sustained hitting — a single noisy fold can
# never leave HEALTHY
TH_DEGRADED = 2.0
TH_SUSPECT = 5.0
PRESSURE_CAP = 10.0
# consecutive clean folds required to step DOWN one level (and the
# streak must re-earn each level) — the anti-flap hysteresis
CLEAR_FOLDS = 3
# folds a per-family latency baseline learns before drift can fire
WARMUP_FOLDS = 5
# consecutive drifting folds after which the baseline ADOPTS the new
# level — a legitimate workload change (bigger payloads) must become
# the new normal instead of flagging forever
DRIFT_ADAPT_FOLDS = 64
# dominance noise gates: the share window must hold this many
# attributed ordinals before a share hit can fire, and a dominated
# ordinal only counts as gating when its duration exceeds the rolling
# baseline by this factor (one log2 bucket, the drift philosophy) —
# a topology-biased dominator on a fast healthy grid stays quiet
DOM_MIN_FILL = 16
DOM_SLOW_FACTOR = 2.0
# minimum per-fold histogram observations before a drift comparison
# is statistically worth making
DRIFT_MIN_COUNT = 4
# storm accumulator: fires at this many recovery events net of decay
# (one clean retry round is 1-2 events — never a storm)
STORM_THRESHOLD = 3.0
# backlog: consecutive growing folds before the scheduler counts as
# falling behind
BACKLOG_FOLDS = 3
# heartbeat flap: a gap this multiple of the larger of (configured
# period, own EWMA gap) is a flap
FLAP_FACTOR = 4.0
# pending-ordinal bound: cells wait here for the last rank's heartbeat;
# a dead/wedged rank must not grow this forever
MAX_PENDING_CELLS = 2048

_PHASES = ("wire", "reduce", "serialize")


def _wall() -> float:
    # alert/baseline timestamps are ARTIFACT timestamps (rendered in
    # timelines next to sink records, compared across hosts), not
    # duration arithmetic
    # mp4j-lint: disable=R11 (artifact timestamp, not a duration)
    return time.time()


# ---------------------------------------------------------------------
# slave side: span-ring delta -> per-ordinal cells on the heartbeat
# ---------------------------------------------------------------------
class SpanFolder:
    """Folds this rank's span-ring delta into COMPLETED per-ordinal
    cells for the heartbeat's ``health_delta`` — the live-delta feed
    the engine's online dominator attribution consumes.

    A cell is the same shape :mod:`critpath` reconstructs offline::

        {"seq", "family", "t0" (wall), "dur",
         "phases": {"wire","reduce","serialize"},
         "links": {peer: {"secs", "transport", "bytes"}}}

    Phase spans land in the ring before their collective span, so a
    beat may catch an ordinal's phases without its collective span —
    those cells stay pending until the collective span arrives (or the
    pending bound evicts them: an aborted attempt's phases never
    complete). The per-beat cell count is capped (``max_cells``) with
    overflow counted, never silent — the payload-boundedness rule
    every heartbeat delta follows."""

    def __init__(self, rank: int, max_cells: int = 128,
                 max_pending: int = 512):
        self._rank = int(rank)
        self._cur = spans.oldest_cursor()
        self._pending: dict[int, dict] = {}
        self._max_cells = int(max_cells)
        self._max_pending = int(max_pending)
        self._lock = threading.Lock()
        self.dropped = 0            # lifetime, for status/debugging

    def _cell(self, seq: int) -> dict:
        return self._pending.setdefault(seq, {
            "seq": seq, "family": None, "t0": None, "dur": 0.0,
            "phases": dict.fromkeys(_PHASES, 0.0), "links": {}})

    def take(self) -> dict | None:
        """The heartbeat increment: ``{"cells": [...], "dropped": n}``
        or None when nothing completed since the last beat."""
        with self._lock:
            self._cur, items, ring_dropped = spans.take_since(self._cur)
            done: list[dict] = []
            for s in items:
                try:
                    name, cat, t0, dur, pid, _tid, args = s
                except (TypeError, ValueError):
                    continue
                if pid != self._rank:
                    continue
                args = args or {}
                seq = int(args.get("seq") or 0)
                if not seq:
                    continue
                if cat == "collective":
                    c = self._cell(seq)
                    c["family"] = name
                    c["t0"] = round(spans.to_wall(t0), 6)
                    c["dur"] = round(float(dur), 9)
                    self._pending.pop(seq, None)
                    done.append(c)
                elif cat == "phase" and name in _PHASES:
                    c = self._cell(seq)
                    c["phases"][name] = round(
                        c["phases"][name] + float(dur), 9)
                    if name == "wire" and args.get("peer") is not None:
                        link = c["links"].setdefault(
                            int(args["peer"]),
                            {"secs": 0.0, "transport": None, "bytes": 0})
                        link["secs"] = round(
                            link["secs"] + float(dur), 9)
                        if args.get("transport"):
                            link["transport"] = args["transport"]
                        link["bytes"] += int(args.get("bytes_sent") or 0) \
                            + int(args.get("bytes_recv") or 0)
            dropped = ring_dropped
            # bound the pending table: an aborted attempt's phases
            # never see their collective span — evict oldest ordinals
            while len(self._pending) > self._max_pending:
                self._pending.pop(min(self._pending), None)
                dropped += 1
            # bound the beat: ship the NEWEST completed cells (the
            # engine's window wants recency; old cells would only
            # re-open already-attributed ordinals)
            if len(done) > self._max_cells:
                dropped += len(done) - self._max_cells
                done = done[-self._max_cells:]
            self.dropped += dropped
            if not done and not dropped:
                return None
            return {"cells": done, "dropped": dropped}


class AlertLog:
    """Bounded per-rank alert-event log (the slave-side landing pad
    for the master's health-alert pushes). The durable sink drains it
    with the shared cursor-delta read (:func:`spans.ring_delta`) into
    the ``alerts`` record kind."""

    def __init__(self, maxlen: int = 512):
        self._events: collections.deque = collections.deque(
            maxlen=maxlen)
        self._count = 0
        self._lock = threading.Lock()

    def note(self, event: dict) -> None:
        with self._lock:
            self._events.append(dict(event))
            self._count += 1

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def events_since(self, cursor: int) -> tuple[int, list[dict], int]:
        with self._lock:
            return spans.ring_delta(self._events, self._count, cursor)


# ---------------------------------------------------------------------
# pure detector functions (each owns one small baseline dict)
# ---------------------------------------------------------------------
def detect_latency_drift(base: dict, hist_delta: dict,
                         drift_pct: float) -> tuple[int, str] | None:
    """One family's per-fold latency delta vs this rank's own EWMA
    baseline. ``base`` holds ``{"ewma", "ewma_bucket", "n", "arm",
    "driftn"}`` and is mutated in place; ``hist_delta`` is a metrics
    histogram delta (``{"lo", "n", "counts", "count", "sum"}``).

    Fires (sev, msg) when the fold's mean exceeds the baseline by
    ``drift_pct`` percent AND the mean log2-bucket index shifted at
    least one full bucket (2x) — both, two folds in a row. The
    baseline only learns from NON-drifting folds, so a degraded rank
    keeps firing instead of normalizing its own slowdown; after
    :data:`DRIFT_ADAPT_FOLDS` consecutive drifting folds the new
    level is adopted as the new normal."""
    count = int(hist_delta.get("count") or 0)
    if count < DRIFT_MIN_COUNT:
        return None
    mean = float(hist_delta.get("sum") or 0.0) / count
    counts = hist_delta.get("counts") or []
    occupied = sum(i * c for i, c in enumerate(counts))
    bucket = occupied / count
    if base.get("n", 0) < WARMUP_FOLDS:
        _learn(base, mean, bucket)
        return None
    factor = 1.0 + drift_pct / 100.0
    drifting = (mean > base["ewma"] * factor
                and bucket >= base["ewma_bucket"] + 1.0)
    if not drifting:
        base["arm"] = 0
        base["driftn"] = 0
        _learn(base, mean, bucket)
        return None
    base["driftn"] = base.get("driftn", 0) + 1
    if base["driftn"] >= DRIFT_ADAPT_FOLDS:
        # the new normal: adopt it and go quiet
        base.update(ewma=mean, ewma_bucket=bucket, n=WARMUP_FOLDS,
                    arm=0, driftn=0)
        return None
    base["arm"] = base.get("arm", 0) + 1
    if base["arm"] < 2:
        return None                 # first drifting fold only arms
    sev = 2 if mean > base["ewma"] * factor * 2.0 else 1
    return (sev, f"latency {mean * 1e3:.2f}ms vs baseline "
                 f"{base['ewma'] * 1e3:.2f}ms "
                 f"(+{(mean / base['ewma'] - 1) * 100:.0f}%, "
                 f"{bucket - base['ewma_bucket']:.1f} log2 buckets)")


def _learn(base: dict, mean: float, bucket: float,
           alpha: float = 0.2) -> None:
    n = base.get("n", 0)
    if n == 0:
        base["ewma"] = mean
        base["ewma_bucket"] = bucket
    else:
        base["ewma"] += alpha * (mean - base["ewma"])
        base["ewma_bucket"] += alpha * (bucket - base["ewma_bucket"])
    base["n"] = n + 1


def detect_storm(base: dict, events: float) -> tuple[int, str] | None:
    """Retry/reconnect/abort storm: a leaky accumulator (halved each
    fold) over the fold's recovery-event count. One clean recovery
    round (1-2 events) never reaches :data:`STORM_THRESHOLD`."""
    acc = base.get("acc", 0.0) * 0.5 + float(events)
    base["acc"] = acc
    if acc < STORM_THRESHOLD:
        return None
    sev = 2 if acc >= 2 * STORM_THRESHOLD else 1
    return (sev, f"recovery storm: {acc:.1f} weighted "
                 "retry/reconnect/abort events in the window")


def detect_sink_drop(base: dict, dropped_delta: float
                     ) -> tuple[int, str] | None:
    """The durable sink dropped records since the last fold — a full
    disk or dead drain thread is a telemetry OUTAGE, exactly the
    healthy-looking-dead state the sink's ``!`` marker exists for."""
    if dropped_delta <= 0:
        return None
    base["total"] = base.get("total", 0.0) + dropped_delta
    return (1, f"durable sink dropping records "
               f"(+{int(dropped_delta)} this fold, "
               f"{int(base['total'])} total)")


def detect_backlog(base: dict, outstanding: float | None
                   ) -> tuple[int, str] | None:
    """``mp4j_outstanding_collectives`` growing monotonically across
    :data:`BACKLOG_FOLDS` folds: the nonblocking scheduler is falling
    behind its submissions instead of oscillating with the workload."""
    if outstanding is None:
        return None
    prev = base.get("prev")
    if prev is not None and outstanding > prev:
        base["grow"] = base.get("grow", 0) + 1
    elif prev is not None and outstanding < prev:
        base["grow"] = 0
    base["prev"] = outstanding
    if base.get("grow", 0) < BACKLOG_FOLDS:
        return None
    return (1, f"outstanding-collective backlog growing "
               f"{base['grow']} folds straight "
               f"(now {outstanding:.0f})")


def detect_hb_flap(base: dict, gap: float | None, hb_secs: float
                   ) -> tuple[int, str] | None:
    """Heartbeat inter-arrival jitter: this beat arrived after a gap
    far outside both the configured period and the rank's own EWMA
    gap — the rank is wedging and recovering, not beating steadily."""
    if gap is None:
        return None
    ewma = base.get("ewma")
    hit = None
    floor = max(hb_secs, 0.05)
    if base.get("n", 0) >= WARMUP_FOLDS:
        bound = FLAP_FACTOR * max(floor, ewma)
        if gap > bound:
            hit = (1, f"heartbeat gap {gap:.2f}s vs expected "
                      f"~{max(floor, ewma):.2f}s (flapping)")
    if hit is None:
        # learn only steady gaps — a flap must not inflate its own
        # baseline out of detectability
        base["ewma"] = (gap if ewma is None
                        else ewma + 0.2 * (gap - ewma))
        base["n"] = base.get("n", 0) + 1
    return hit


# ---------------------------------------------------------------------
# per-rank verdict record
# ---------------------------------------------------------------------
class _RankHealth:
    __slots__ = ("state", "since_wall", "since_seq", "pressure",
                 "clean", "dirty", "alerts", "lat", "links", "hb",
                 "storm", "sink", "backlog", "last_seq", "why")

    def __init__(self):
        self.state = HEALTHY
        self.since_wall = _wall()
        self.since_seq = 0
        self.pressure: dict[str, float] = {}
        self.clean = 0              # consecutive clean folds
        self.dirty = False          # hit since this rank's last fold
        self.alerts: dict[str, int] = {}   # detector -> alerts emitted
        self.lat: dict[str, dict] = {}     # family -> drift baseline
        self.links: dict[int, dict] = {}   # peer -> {"ewma_gbs", "n"}
        self.hb: dict = {}
        self.storm: dict = {}
        self.sink: dict = {}
        self.backlog: dict = {}
        self.last_seq = 0
        self.why = ""               # last transition's message


class HealthEngine:
    """The master-owned streaming health engine (module docstring).
    Single-threaded by contract: every method is called under the
    master's lock, right where the heartbeat folds — the engine itself
    takes no locks."""

    def __init__(self, slave_num: int, *, enabled: bool = True,
                 window: int = 64, dominator_ordinals: int = 500,
                 drift_pct: float = 100.0, hb_secs: float = 0.5):
        self.slave_num = int(slave_num)
        self.enabled = bool(enabled)
        self.window = int(window)
        self.dominator_ordinals = int(dominator_ordinals)
        self.drift_pct = float(drift_pct)
        self.hb_secs = float(hb_secs)
        self._ranks: dict[int, _RankHealth] = {}
        # online dominator state
        self._cells: dict[int, dict[int, dict]] = {}   # seq -> rank -> cell
        self._dom_recent: collections.deque = collections.deque(
            maxlen=max(self.window, 1))    # (seq, dominator, slow)
        # cause-aware dominator rows (ISSUE 15): the tuner's leader-
        # demotion policy needs the CAUSE ("link->K over tcp") next to
        # the dominator, which the share window above deliberately
        # drops — a parallel bounded deque of dicts keeps the two
        # consumers decoupled
        self._dom_rows: collections.deque = collections.deque(
            maxlen=max(self.window, 1))
        # _dom_rows crosses lock domains: folds append under the
        # master's lock, the tuner controller snapshots under its own
        # — a private lock makes the rows' discipline self-contained
        self._dom_lock = threading.Lock()
        self._streak_rank: int | None = None
        self._streak = 0
        self._dur_ewma = 0.0
        self._dur_n = 0
        self._attributed = 0
        self._cells_dropped = 0
        self._onsets = 0
        self._onset_active: dict[int, bool] = {}
        # alert plumbing
        self._alerts: collections.deque = collections.deque(maxlen=64)
        self._alert_seq = 0
        self.alerts_total = 0
        self.first_degraded: dict | None = None
        self._arrival: dict[int, float] = {}    # rank -> mono arrival

    # -- fold entry points ---------------------------------------------
    def fold(self, rank: int, payload: dict, now: float,
             live: set[int]) -> list[dict]:
        """Fold one heartbeat (called from the master's telemetry
        fold). ``now`` is monotonic; returns newly emitted alert
        events."""
        if not self.enabled:
            return []
        rank = int(rank)
        rec = self._ranks.setdefault(rank, _RankHealth())
        if rec.state == DEAD:
            return []               # zombie beat after declaration
        hits: dict[int, list[tuple[str, int, str]]] = {rank: []}
        own = hits[rank]
        progress = payload.get("progress") or {}
        rec.last_seq = int(progress.get("seq") or rec.last_seq)

        # heartbeat inter-arrival
        last = self._arrival.get(rank)
        self._arrival[rank] = now
        gap = (now - last) if last is not None else None
        hit = detect_hb_flap(rec.hb, gap, self.hb_secs)
        if hit:
            own.append(("hb_flap", *hit))

        # stats delta: recovery storms
        sd = payload.get("stats_delta") or {}
        events = sum(float(e.get(k, 0) or 0)
                     for e in sd.values() if isinstance(e, dict)
                     for k in ("retries", "reconnects", "aborts_seen"))
        hit = detect_storm(rec.storm, events)
        if hit:
            own.append(("storm", *hit))

        # metrics delta: latency drift per family, sink drops, backlog
        md = payload.get("metrics_delta") or {}
        for name, h in (md.get("histograms") or {}).items():
            if not name.startswith("latency/"):
                continue
            fam = name[len("latency/"):]
            hit = detect_latency_drift(
                rec.lat.setdefault(fam, {}), h, self.drift_pct)
            if hit:
                own.append(("latency_drift", hit[0],
                            f"{fam}: {hit[1]}"))
        drops = float((md.get("counters") or {}).get(
            "sink/dropped_records", 0) or 0)
        hit = detect_sink_drop(rec.sink, drops)
        if hit:
            own.append(("sink_drop", *hit))
        hit = detect_backlog(
            rec.backlog,
            (md.get("gauges") or {}).get("async/outstanding"))
        if hit:
            own.append(("backlog", *hit))

        # the online dominator: fold this rank's cells, attribute what
        # completed (hits may target OTHER ranks), track baselines
        floors: dict[int, int] = {}
        alerts: list[dict] = []
        self._fold_cells(rank, payload.get("health_delta"), live,
                         hits, floors, alerts)

        for r, rhits in hits.items():
            alerts.extend(self._apply(r, rhits, floors.get(r),
                                      own_fold=(r == rank)))
        return alerts

    def note_audit(self, entries: list[dict], live: set[int]
                   ) -> list[dict]:
        """Audit-divergence escalation: each divergence naming ranks
        forces those ranks at least to SUSPECT — content corruption
        outranks every latency signal."""
        if not self.enabled:
            return []
        alerts: list[dict] = []
        for e in entries or ():
            for r in e.get("ranks") or ():
                r = int(r)
                if live and r not in live:
                    continue
                alerts.extend(self._apply(
                    r, [("audit", 3,
                         f"audit divergence at collective "
                         f"#{e.get('seq')}: {e.get('msg', '')[:160]}")],
                    SUSPECT, own_fold=False))
        return alerts

    def note_dead(self, rank: int, why: str) -> list[dict]:
        """The liveness path declared ``rank`` dead — the one verdict
        this engine does not decide itself, recorded so the health
        plane tells one coherent story."""
        if not self.enabled:
            return []
        rec = self._ranks.setdefault(int(rank), _RankHealth())
        if rec.state == DEAD:
            return []
        old = rec.state
        rec.state = DEAD
        rec.since_wall = _wall()
        rec.why = why
        ev = self._emit(int(rank), "liveness", old, DEAD,
                        f"declared dead: {why}", rec)
        return [ev]

    def note_replacement(self, rank: int) -> list[dict]:
        """A spare was adopted into ``rank``: the verdict, pressures
        and baselines belonged to the dead occupant — the joiner
        starts HEALTHY with fresh baselines."""
        if not self.enabled:
            return []
        rec = self._ranks.get(int(rank))
        old = rec.state if rec is not None else HEALTHY
        self._ranks[int(rank)] = _RankHealth()
        self._arrival.pop(int(rank), None)
        if old == HEALTHY:
            return []
        ev = self._emit(int(rank), "liveness", old, HEALTHY,
                        "replaced from a warm spare — fresh baselines",
                        self._ranks[int(rank)])
        return [ev]

    def note_grow(self, slave_num: int) -> None:
        """The roster GREW (ISSUE 13): widen the expected rank count.
        Ordinals completed before the growth can never collect the
        joiners' cells — drop them (counted, never silent) so they
        don't jam the pending table until the cap prunes them; the
        joiners' verdicts start HEALTHY lazily on their first fold."""
        self.slave_num = int(slave_num)
        self._cells_dropped += sum(len(c) for c in self._cells.values())
        self._cells.clear()

    def note_shrink(self, slave_num: int,
                    mapping: dict[int, int]) -> None:
        """The roster renumbered: remap verdicts, drop the dead, and
        drop pending cells (they are keyed by OLD ranks; the retried
        ordinals' fresh cells arrive under the new numbering)."""
        self.slave_num = int(slave_num)
        self._ranks = {mapping[r]: rec for r, rec in self._ranks.items()
                       if r in mapping}
        self._arrival = {mapping[r]: t for r, t in self._arrival.items()
                         if r in mapping}
        self._onset_active = {mapping[r]: a for r, a
                              in self._onset_active.items()
                              if r in mapping}
        self._cells_dropped += sum(len(c) for c in self._cells.values())
        self._cells.clear()
        self._dom_recent.clear()
        self._streak_rank, self._streak = None, 0

    # -- the online dominator ------------------------------------------
    def _fold_cells(self, rank: int, delta: dict | None,
                    live: set[int], hits: dict, floors: dict,
                    out: list[dict]) -> None:
        if not delta:
            return
        rec = self._ranks.setdefault(rank, _RankHealth())
        self._cells_dropped += int(delta.get("dropped") or 0)
        for cell in delta.get("cells") or ():
            seq = int(cell.get("seq") or 0)
            if not seq:
                continue
            links = {int(p): lk for p, lk
                     in (cell.get("links") or {}).items()}
            # rolling per-link wire GB/s baseline (status evidence for
            # the autoscaler: which link a slow rank is slow ON)
            for peer, lk in links.items():
                secs = float(lk.get("secs") or 0.0)
                if secs > 0 and lk.get("bytes"):
                    gbs = float(lk["bytes"]) / secs / 1e9
                    base = rec.links.setdefault(
                        peer, {"ewma_gbs": gbs, "n": 0})
                    base["ewma_gbs"] += 0.2 * (gbs - base["ewma_gbs"])
                    base["n"] += 1
            self._cells.setdefault(seq, {})[rank] = {
                "family": cell.get("family"),
                "t0": cell.get("t0"),
                "dur": float(cell.get("dur") or 0.0),
                "phases": {p: float((cell.get("phases") or {})
                                    .get(p, 0.0)) for p in _PHASES},
                "links": links,
            }
        need = len(live) if live else self.slave_num
        for seq in sorted(self._cells):
            if len(self._cells[seq]) < need:
                continue
            rows = critpath.attribute({seq: self._cells.pop(seq)})
            if rows:
                self._note_row(rows[0], hits, floors, out)
        # bound pending: a wedged rank's missing cells must not grow
        # this forever — evict oldest (counted, never silent)
        while len(self._cells) > MAX_PENDING_CELLS:
            victim = min(self._cells)
            self._cells_dropped += len(self._cells.pop(victim))

    def _note_row(self, row: dict, hits: dict, floors: dict,
                  out: list[dict]) -> None:
        self._attributed += 1
        dom = int(row["dominator"])
        dur = float(row["dur"])
        slow = (self._dur_n >= DOM_MIN_FILL
                and dur > self._dur_ewma * DOM_SLOW_FACTOR)
        if not slow:
            # baseline learns only non-gating ordinals after warmup,
            # so a persistent straggler cannot normalize itself
            self._dur_ewma = (dur if self._dur_n == 0 else
                              self._dur_ewma
                              + 0.05 * (dur - self._dur_ewma))
            self._dur_n += 1
        self._dom_recent.append((int(row["seq"]), dom, slow))
        with self._dom_lock:
            self._dom_rows.append({"seq": int(row["seq"]), "dom": dom,
                                   "cause": row.get("cause") or "?",
                                   "slow": slow})
        if slow and dom == self._streak_rank:
            self._streak += 1
        elif slow:
            self._streak_rank, self._streak = dom, 1
        else:
            self._streak_rank, self._streak = None, 0

        # the streak trigger stands on its own (the ROADMAP contract:
        # N consecutive gated ordinals => evictable) — it must not
        # wait for the window share to qualify; slowness is already
        # baked in (only slow dominated rows extend the streak)
        floor = None
        sev = 1
        cause = row.get("cause") or "?"
        if self._streak >= self.dominator_ordinals:
            floor, sev = EVICT_RECOMMENDED, 2
        elif self._streak >= max(self.dominator_ordinals // 2, 2):
            floor, sev = SUSPECT, 2
        if floor is not None:
            floors[dom] = max(floors.get(dom, 0), floor)
        win = self._dom_recent
        dom_rows = [s for _, d, s in win if d == dom]
        share = len(dom_rows) / len(win)
        slow_share = (sum(dom_rows) / len(dom_rows)) if dom_rows else 0
        qualified = (len(win) >= DOM_MIN_FILL
                     and share >= critpath.ONSET_SHARE
                     and slow_share >= 0.5)
        if qualified or floor is not None:
            msg = (f"critical-path dominator: {share * 100:.0f}% of "
                   f"the last {len(win)} ordinal(s), cause {cause}, "
                   f"streak {self._streak}")
            if floor == EVICT_RECOMMENDED:
                msg += (f" >= MP4J_HEALTH_DOMINATOR_ORDINALS="
                        f"{self.dominator_ordinals}")
            hits.setdefault(dom, []).append(("dominator", sev, msg))
        if qualified and not self._onset_active.get(dom):
            self._onset_active[dom] = True
            self._onsets += 1
            dom_rec = self._ranks.setdefault(dom, _RankHealth())
            dom_rec.alerts["dominator"] = \
                dom_rec.alerts.get("dominator", 0) + 1
            out.append(self._push_alert({
                "rank": dom, "detector": "dominator",
                "kind": "onset",
                "from": STATE_NAMES[self._state_of(dom)],
                "to": STATE_NAMES[self._state_of(dom)],
                "seq": int(row["seq"]),
                "msg": f"straggler onset at collective "
                       f"#{row['seq']}: {msg}"}))
        # re-arm every rank that dropped well below the threshold
        counts: dict[int, int] = {}
        for _, d, _s in win:
            counts[d] = counts.get(d, 0) + 1
        for r in list(self._onset_active):
            if (self._onset_active[r]
                    and counts.get(r, 0) / len(win)
                    < critpath.ONSET_SHARE / 2):
                self._onset_active[r] = False

    def _state_of(self, rank: int) -> int:
        rec = self._ranks.get(rank)
        return rec.state if rec is not None else HEALTHY

    # -- hysteresis state machine --------------------------------------
    def _apply(self, rank: int, rhits: list, floor: int | None,
               own_fold: bool) -> list[dict]:
        rec = self._ranks.setdefault(rank, _RankHealth())
        if rec.state == DEAD:
            return []
        if rhits:
            rec.dirty = True
            rec.clean = 0
            for det, sev, _msg in rhits:
                rec.pressure[det] = min(
                    PRESSURE_CAP, rec.pressure.get(det, 0.0) + sev)
        elif own_fold:
            # this rank's own fold with no hit from any source since
            # its previous fold: decay toward recovery
            if rec.dirty:
                rec.dirty = False
            else:
                rec.clean += 1
                for det in list(rec.pressure):
                    rec.pressure[det] *= 0.5
                    if rec.pressure[det] < 0.25:
                        del rec.pressure[det]

        maxp = max(rec.pressure.values(), default=0.0)
        target = HEALTHY
        if maxp >= TH_DEGRADED:
            target = DEGRADED
        if maxp >= TH_SUSPECT:
            target = SUSPECT
        if floor:
            target = max(target, floor)

        alerts: list[dict] = []
        if target > rec.state:
            # jump straight to a forced floor (audit, dominator
            # streak); pressure-driven escalation climbs ONE level per
            # fold so a single noisy beat can never catapult a rank
            new = max(rec.state + 1, floor or 0)
            new = min(new, target)
            det, msg = self._dominant(rec, rhits)
            alerts.append(self._transition(rank, rec, new, det, msg))
        elif (target < rec.state and rec.clean >= CLEAR_FOLDS
              and not floor):
            new = rec.state - 1
            rec.clean = 0           # re-earn each level down
            alerts.append(self._transition(
                rank, rec, new, "recovery",
                f"{CLEAR_FOLDS} clean folds — stepping down"))
        return alerts

    @staticmethod
    def _dominant(rec: _RankHealth, rhits: list) -> tuple[str, str]:
        """The detector (and message) a transition is attributed to:
        the loudest hit THIS fold, else the highest-pressure one."""
        if rhits:
            det, _sev, msg = max(rhits, key=lambda h: h[1])
            return det, msg
        if rec.pressure:
            det = max(rec.pressure, key=rec.pressure.get)
            return det, f"sustained {det} pressure"
        return "recovery", ""

    def _transition(self, rank: int, rec: _RankHealth, new: int,
                    det: str, msg: str) -> dict:
        old = rec.state
        rec.state = new
        rec.since_wall = _wall()
        rec.since_seq = rec.last_seq
        rec.why = msg
        return self._emit(rank, det, old, new, msg, rec)

    def _emit(self, rank: int, det: str, old: int, new: int,
              msg: str, rec: _RankHealth) -> dict:
        ev = {"rank": rank, "detector": det, "kind": "state",
              "from": STATE_NAMES[old], "to": STATE_NAMES[new],
              "seq": rec.last_seq, "msg": msg}
        self._push_alert(ev)
        if new > old and old == HEALTHY and self.first_degraded is None:
            self.first_degraded = {
                "rank": rank, "detector": det, "wall": ev["wall"],
                "seq": rec.last_seq, "to": STATE_NAMES[new],
                "msg": msg}
        # EVERY emitted alert counts in mp4j_alerts_total{rank,
        # detector} — liveness (DEAD/replacement) included, so the
        # per-detector counters always sum to alerts_total
        rec.alerts[det] = rec.alerts.get(det, 0) + 1
        return ev

    def _push_alert(self, ev: dict) -> dict:
        self._alert_seq += 1
        self.alerts_total += 1
        ev.setdefault("id", self._alert_seq)
        ev.setdefault("wall", _wall())
        self._alerts.append(ev)
        return ev

    # -- the operator hook ---------------------------------------------
    def dominator_rows(self) -> list[dict]:
        """The recent cause-aware attribution rows ``[{seq, dom,
        cause, slow}]`` (bounded by the window) — the evidence the
        master's tuner controller feeds
        :func:`ytk_mp4j_tpu.utils.tuner.decide_leaders` (ISSUE 15)."""
        with self._dom_lock:
            return list(self._dom_rows)

    def dominator_shares(self) -> dict[int, float]:
        """Sliding-window dominance share per rank (the
        ``mp4j_critpath_dominator`` gauge)."""
        win = self._dom_recent
        if not win:
            return {}
        counts: dict[int, int] = {}
        for _, d, _s in win:
            counts[d] = counts.get(d, 0) + 1
        return {r: c / len(win) for r, c in sorted(counts.items())}

    def status(self) -> dict:
        """The health document — ``Master.health_status()``, the
        metrics doc's ``cluster.health`` section, the postmortem
        manifest. This is the contract the future elastic autoscaler
        reads: ``evict_recommended`` lists the ranks this plane
        RECOMMENDS replacing (it never acts), each with the detector
        evidence behind the verdict."""
        ranks = {}
        for r in sorted(self._ranks):
            rec = self._ranks[r]
            ranks[str(r)] = {
                "state": STATE_NAMES[rec.state],
                "state_code": rec.state,
                "since_wall": rec.since_wall,
                "since_seq": rec.since_seq,
                "why": rec.why,
                "pressure": {d: round(p, 2)
                             for d, p in sorted(rec.pressure.items())},
                "alerts": dict(sorted(rec.alerts.items())),
                "links_gbs": {str(p): round(b["ewma_gbs"], 4)
                              for p, b in sorted(rec.links.items())},
            }
        return {
            "enabled": self.enabled,
            "window": self.window,
            "dominator_ordinals": self.dominator_ordinals,
            "ranks": ranks,
            "evict_recommended": sorted(
                r for r, rec in self._ranks.items()
                if rec.state == EVICT_RECOMMENDED),
            "dominator": {
                "shares": {str(r): round(s, 3) for r, s
                           in self.dominator_shares().items()},
                "streak_rank": self._streak_rank,
                "streak": self._streak,
                "attributed": self._attributed,
                "cells_dropped": self._cells_dropped,
                "onsets": self._onsets,
            },
            "alerts_total": self.alerts_total,
            "first_degraded": self.first_degraded,
            "last_alerts": list(self._alerts)[-8:],
        }


# ---------------------------------------------------------------------
# rendering (the `mp4j-scope health` subcommand + postmortem section)
# ---------------------------------------------------------------------
_fmt_wall = critpath.fmt_wall


def format_alert(ev: dict) -> str:
    if ev.get("kind") == "autoscale":
        # an autoscaler action event (ISSUE 13) — rides the same
        # alert pipe so timelines interleave actions with verdicts
        return (f"{_fmt_wall(ev.get('wall'))}  autoscaler "
                f"{ev.get('event')} {ev.get('action')}"
                + (f" rank {ev['rank']}"
                   if ev.get("rank") is not None else "")
                + f": {ev.get('msg', '')}")
    if ev.get("kind") == "tuner":
        # a self-tuning data-plane event (ISSUE 15: leader demotion,
        # audit trip) — same pipe, same timelines
        return (f"{_fmt_wall(ev.get('wall'))}  tuner "
                f"{ev.get('event')}"
                + (f" rank {ev['rank']}"
                   if ev.get("rank") is not None else "")
                + f": {ev.get('msg', '')}")
    if ev.get("kind") == "onset":
        return (f"{_fmt_wall(ev.get('wall'))}  rank {ev.get('rank')} "
                f"ONSET ({ev.get('detector')}): {ev.get('msg', '')}")
    return (f"{_fmt_wall(ev.get('wall'))}  rank {ev.get('rank')} "
            f"{ev.get('from')} -> {ev.get('to')} "
            f"({ev.get('detector')}"
            + (f", collective #{ev['seq']}" if ev.get("seq") else "")
            + f"): {ev.get('msg', '')}")


def format_status(health: dict) -> str:
    """Current verdicts from a live master's health document (the
    ``mp4j-scope health URL`` view)."""
    if not health:
        return "(no health plane — master runs MP4J_HEALTH=0?)"
    lines = [f"mp4j health — {len(health.get('ranks', {}))} rank(s), "
             f"{health.get('alerts_total', 0)} alert(s), "
             f"window {health.get('window')} ordinal(s)"]
    ranks = health.get("ranks") or {}
    if ranks:
        lines.append(f"  {'rank':>4}  {'state':<18}  {'since':<23}  "
                     "evidence")
        for r in sorted(ranks, key=int):
            e = ranks[r]
            evidence = ", ".join(
                f"{d}={p}" for d, p in (e.get("pressure") or {}).items()) \
                or e.get("why") or "-"
            lines.append(f"  {r:>4}  {e.get('state', '?'):<18}  "
                         f"{_fmt_wall(e.get('since_wall')):<23}  "
                         f"{evidence}")
    evict = health.get("evict_recommended") or []
    if evict:
        lines.append(f"EVICT RECOMMENDED: rank(s) "
                     f"{', '.join(map(str, evict))} — the autoscaler "
                     "hook (health_status()) carries the evidence")
    dom = health.get("dominator") or {}
    if dom.get("shares"):
        share_s = ", ".join(f"rank {r}: {s * 100:.0f}%"
                            for r, s in dom["shares"].items())
        lines.append(f"dominator window: {share_s} "
                     f"({dom.get('attributed', 0)} ordinal(s) "
                     f"attributed, {dom.get('onsets', 0)} onset(s))")
    fd = health.get("first_degraded")
    if fd:
        lines.append(
            f"first degradation: rank {fd.get('rank')} -> "
            f"{fd.get('to')} via {fd.get('detector')} at "
            f"{_fmt_wall(fd.get('wall'))} (collective "
            f"#{fd.get('seq')})")
    for ev in health.get("last_alerts") or []:
        lines.append("  " + format_alert(ev))
    return "\n".join(lines)


def format_history(alerts: list[dict], ranks: list[int] | None = None
                   ) -> str:
    """Verdict history from durable-sink ``alerts`` records (the
    ``mp4j-scope health DIR`` view): the full transition timeline,
    the first-degradation headline, and each rank's final verdict."""
    if not alerts:
        return ("(no health alerts in the sink — the job stayed "
                "HEALTHY, or ran MP4J_HEALTH=0)")
    alerts = sorted(alerts, key=lambda e: (e.get("wall") or 0,
                                           e.get("id") or 0))
    lines = [f"health timeline — {len(alerts)} alert(s)"]
    first = next((e for e in alerts
                  if e.get("kind") == "state"
                  and e.get("from") == "HEALTHY"), None)
    if first is not None:
        lines.append(
            f"first degradation: rank {first.get('rank')} -> "
            f"{first.get('to')} via {first.get('detector')} at "
            f"{_fmt_wall(first.get('wall'))}"
            + (f" (collective #{first['seq']})"
               if first.get("seq") else ""))
    for ev in alerts:
        lines.append("  " + format_alert(ev))
    final: dict[int, str] = {}
    for ev in alerts:
        if ev.get("kind") == "state":
            final[int(ev["rank"])] = ev.get("to", "?")
    for r in ranks or []:
        final.setdefault(int(r), "HEALTHY")
    if final:
        lines.append("final verdicts: " + ", ".join(
            f"rank {r}: {s}" for r, s in sorted(final.items())))
    return "\n".join(lines)
