"""Bounded span ring + Chrome-trace export.

Every phase event the always-on :class:`~ytk_mp4j_tpu.utils.stats.
CommStats` books (wire/reduce/serialize, at chunk granularity) and
every outermost collective call the ``trace.traced`` wrapper times is
also appended here as a *span*: ``(name, category, start, duration,
rank, thread, args)``. The ring is bounded (``MP4J_SPAN_RING`` entries,
default 65536; 0 disables) so a long job keeps a sliding window of the
most recent activity at a fixed memory cost, and appending is one
O(1) ``deque.append`` — cheap enough to stay default-on.

:func:`export_chrome_trace` renders the ring as trace-event JSON
(``{"traceEvents": [...]}``, complete-event ``"ph": "X"`` records with
``ts``/``dur`` in microseconds, ``pid`` = mp4j rank, ``tid`` = a small
per-process thread id), loadable in ``chrome://tracing`` or Perfetto.
Multi-process jobs export one file per rank; ``mp4j-scope merge``
combines them into a single timeline (ranks keep distinct pids).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any

from ytk_mp4j_tpu.utils import tuning

_lock = threading.Lock()
# Trace timebase: spans are recorded in perf_counter time (cheap,
# monotone) but EXPORTED anchored to the wall clock — perf_counter
# epochs are per-process, so independently launched ranks would
# otherwise shift by their launch skew in a merged timeline. Residual
# cross-host skew is whatever NTP leaves (ms-scale), fine for eyeballs.
_epoch = time.perf_counter()
_epoch_wall = time.time()
_capacity = tuning.span_ring_capacity()
_ring: collections.deque = collections.deque(maxlen=max(_capacity, 1))
_enabled = _capacity > 0
_tids: dict[int, int] = {}        # thread ident -> small stable tid
# total spans ever appended — the durable sink's delta cursor (ISSUE
# 9): take_since(cursor) derives (new records, ring-overflow drops)
# from (count, ring length, cursor) alone, so readers never consume
# the ring. Appends bump it under _lock so (ring, count) stay
# consistent for the cursor math.
_count = 0


def enabled() -> bool:
    return _enabled


def configure(capacity: int) -> None:
    """Resize (and clear) the ring; 0 disables recording. Mainly for
    tests and embedding applications — jobs configure via
    ``MP4J_SPAN_RING``."""
    global _ring, _capacity, _enabled
    with _lock:
        _capacity = capacity
        _enabled = capacity > 0
        _ring = collections.deque(maxlen=max(capacity, 1))


def clear() -> None:
    with _lock:
        _ring.clear()


def _append(item: tuple) -> None:
    global _count
    with _lock:
        _ring.append(item)
        _count += 1


def ring_delta(ring, count: int, cursor: int
               ) -> tuple[int, list, int]:
    """``(count, new_items, dropped)`` — THE cursor-delta read every
    bounded-ring source shares (span ring here, the audit record ring,
    the recovery event log): items appended since ``cursor`` that are
    still in the ring, plus how many already fell off (reported, never
    silently lost). A cursor ahead of ``count`` (ring reconfigured/
    cleared) resets cleanly. The caller holds its own lock.

    Cost is O(new items), not O(ring): reversed(deque) iterates from
    the right, so a near-current reader over a full 65536-entry ring
    copies only its delta — appenders sharing the caller's lock must
    never stall behind a full-ring copy."""
    new = count - min(cursor, count)
    avail = min(new, len(ring))
    if not avail:
        return count, [], new
    items = list(itertools.islice(reversed(ring), avail))
    items.reverse()
    return count, items, new - avail


def take_since(cursor: int) -> tuple[int, list[tuple], int]:
    """``(new_cursor, spans, dropped)`` — every span appended since
    ``cursor`` that is still in the ring (:func:`ring_delta` under the
    span lock). Non-destructive: any number of readers keep
    independent cursors."""
    with _lock:
        return ring_delta(_ring, _count, cursor)


def oldest_cursor() -> int:
    """The earliest cursor :func:`take_since` can still serve in full
    — a reader attaching mid-process (the durable sink of a slave
    constructed after other slaves already ran in this process)
    starts here so pre-attachment history is neither replayed nor
    misreported as dropped."""
    with _lock:
        return _count - len(_ring)


def to_wall(t0: float) -> float:
    """A span's ``perf_counter`` timestamp anchored to the wall clock
    — the same anchoring :func:`export_chrome_trace` applies, shared
    so the durable sink writes cross-rank-comparable timestamps."""
    return t0 - _epoch + _epoch_wall


def _tid() -> int:
    ident = threading.get_ident()
    tid = _tids.get(ident)
    if tid is None:
        with _lock:
            tid = _tids.setdefault(ident, len(_tids))
    return tid


def record(name: str, cat: str, t0: float, dur: float,
           pid: int | None, args: dict[str, Any] | None = None) -> None:
    """Append one complete span (``t0`` in ``time.perf_counter``
    seconds). Bounded ring: the oldest span falls off when full."""
    if not _enabled:
        return
    _append((name, cat, t0, dur, pid or 0, _tid(), args))


def phase(name: str, seconds: float, pid: int | None, collective: str,
          seq: int, **extra) -> None:
    """A phase span (wire/reduce/serialize) booked after the fact: the
    caller measured ``seconds`` ending now, so the span's start is
    reconstructed as ``now - seconds``."""
    if not _enabled:
        return
    end = time.perf_counter()
    args: dict[str, Any] = {"collective": collective, "seq": seq}
    for k, v in extra.items():
        if v is not None:
            args[k] = v
    _append((name, "phase", end - seconds, seconds, pid or 0,
             _tid(), args))


def mark(name: str, pid: int | None, **args: Any) -> None:
    """A zero-duration recovery event (abort announced, retry started,
    terminal abort) — renders as an instant tick on the rank's
    timeline, so ``mp4j-scope`` traces show exactly where a job
    recovered (ISSUE 5)."""
    if not _enabled:
        return
    _append((name, "recovery", time.perf_counter(), 0.0, pid or 0,
             _tid(), {k: v for k, v in args.items()
                      if v is not None} or None))


def collective(name: str, t0: float, dur: float, pid: int | None,
               seq: int) -> None:
    """The outermost collective-call span (emitted by trace.traced)."""
    if not _enabled:
        return
    _append((name, "collective", t0, dur, pid or 0, _tid(),
             {"seq": seq}))


def snapshot() -> list[tuple]:
    with _lock:
        return list(_ring)


def export_chrome_trace(path: str) -> int:
    """Write the ring as trace-event JSON; returns the event count.

    Events are globally sorted by start time, so ``ts`` is monotone
    non-decreasing on every (pid, tid) track — the invariant the tier-1
    schema test asserts and Perfetto's importer expects.
    """
    events = []
    for name, cat, t0, dur, pid, tid, args in sorted(
            snapshot(), key=lambda s: s[2]):
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round((t0 - _epoch + _epoch_wall) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        events.append(ev)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    _atomic_dump(path, doc)
    return len(events)


def _atomic_dump(path: str, doc) -> None:
    """Tmp-file + ``os.replace`` write: a crash mid-dump leaves either
    the previous file or the complete new one, never a syntactically
    truncated JSON masquerading as a trace (mp4j-lint R14)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)


def merge_chrome_traces(out_path: str, in_paths: list[str]) -> int:
    """Merge per-rank Chrome-trace files into one timeline (ranks keep
    their pids; events re-sorted by ``ts`` so every track stays
    monotone). Accepts both the object form (``{"traceEvents": [...]}``)
    and the bare-array form of the trace-event format."""
    merged: list[dict] = []
    for p in in_paths:
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        merged.extend(events)
    merged.sort(key=lambda e: (e.get("ts", 0)))
    _atomic_dump(out_path, {"traceEvents": merged,
                            "displayTimeUnit": "ms"})
    return len(merged)
